"""TF op mapping rules — the long tail of the reference ruleset.

Covers the remaining `inputFrameworkOpName` entries of
`nd4j/samediff-import/samediff-import-tensorflow/src/main/resources/
tensorflow-mapping-ruleset.pbtxt` beyond the core set in ``mappings.py``:
linalg, scatter/segment, image, random, quantization, bitwise, 3-D
conv/pool, block RNN cells, and loss ops.  Shape-ish constant inputs fold
to static kwargs (XLA wants static shapes); genuinely dynamic-output ops
(Unique, Where, ListDiff, ...) are documented exemptions in
``coverage.py`` rather than silent failures.
"""
from __future__ import annotations

import numpy as np

from ..ir import IRNode, ImportContext, ImportException, mapper
from .mappings import TF, _ins, _conv_attrs, _simple, _dtype_name


def _const_i(ctx, name):
    return int(np.asarray(ctx.const_value(name)))


def _const_f(ctx, name):
    return float(np.asarray(ctx.const_value(name)))


def _const_list(ctx, name):
    return [int(v) for v in np.atleast_1d(np.asarray(ctx.const_value(name)))]


def _attr_scalar(v, default=None):
    return default if v is None else (v.decode() if isinstance(v, bytes)
                                      else v)


def _port_consumed(ctx, node, port):
    t = f"{node.name}:{port}"
    return any(t in n.inputs for n in ctx.graph.nodes)


def _emit_fn(ctx, fn, inputs, out_tensor, label, needs_key=False, **kwargs):
    """Record a non-registry callable (arg-order adapter) as a graph node."""
    out = ctx.sd._record_fn(fn, list(inputs), label=label,
                            out_name=out_tensor.replace(":", "_"),
                            needs_key=needs_key, **kwargs)
    ctx.bind(out_tensor, out)
    return out


def _reg_fn(name):
    from ...ops.registry import OpRegistry
    return OpRegistry.get().lookup(name).fn


# -- simple elementwise / linalg aliases ----------------------------------
for _tf, _op in [
    ("AccumulateNV2", "mergeadd"),
    ("BitwiseAnd", "bitwise_and"), ("BitwiseOr", "bitwise_or"),
    ("BitwiseXor", "bitwise_xor"), ("Invert", "toggle_bits"),
    ("LeftShift", "shift_bits"), ("RightShift", "rshift_bits"),
    ("IsFinite", "isfinite"), ("IsInf", "isinf"), ("IsNan", "isnan"),
    ("Igamma", "igamma"), ("Igammac", "igammac"), ("Betainc", "betainc"),
    ("Polygamma", "polygamma"), ("Zeta", "zeta"),
    ("Cholesky", "cholesky"),
    ("MatrixInverse", "matrix_inverse"),
    ("BatchMatrixInverse", "matrix_inverse"),
    ("MatrixDeterminant", "matrix_determinant"),
    ("BatchMatrixDeterminant", "matrix_determinant"),
    ("MatrixDiag", "matrix_diag"), ("MatrixDiagPart", "matrix_diag_part"),
    ("MatrixSetDiag", "matrix_set_diag"),
    ("BatchMatrixSetDiag", "matrix_set_diag"),
    ("Diag", "diag"), ("DiagPart", "diag_part"),
    ("HSVToRGB", "hsv_to_rgb"), ("RGBToHSV", "rgb_to_hsv"),
    ("ClipByValue", "clip_by_value"),
    ("Cross", "cross"),
]:
    _simple(_tf, _op)


@mapper(TF, "CheckNumericsV2", "Copy", "CopyHost", "DeepCopy")
def _identity_like(node, ctx):
    src = node.inputs[0]
    if src in ctx.const_np:
        ctx.const_np[node.outputs[0]] = ctx.const_np[src]
    else:
        ctx.bind(node.outputs[0], ctx.get(src), aval=ctx.aval(src))


@mapper(TF, "Assert")
def _assert(node, ctx):
    pass  # graph-mode assertion; XLA graphs carry no side effects


@mapper(TF, "Assign")
def _assign(node, ctx):
    # frozen inference graphs keep Assign only as an init artifact; its
    # value output aliases the assigned value (reference maps it to identity)
    src = node.inputs[1] if len(node.inputs) > 1 else node.inputs[0]
    if src in ctx.const_np:
        ctx.const_np[node.outputs[0]] = ctx.const_np[src]
    else:
        ctx.bind(node.outputs[0], ctx.get(src), aval=ctx.aval(src))


@mapper(TF, "ApproximateEqual")
def _approx_equal(node, ctx):
    a, b = _ins(node, ctx)
    tol = float(node.attrs.get("tolerance", 1e-5))
    d = ctx.emit("subtract", [a, b], f"{node.name}__d")
    ad = ctx.emit("abs", [d], f"{node.name}__ad")
    t = ctx.sd.constant(np.float32(tol), f"{node.name}__tol")
    ctx.emit("less", [ad, t], node.outputs[0])


# -- shape/layout ---------------------------------------------------------
@mapper(TF, "BroadcastTo")
def _broadcast_to(node, ctx):
    x = ctx.get(node.inputs[0])
    shape = tuple(_const_list(ctx, node.inputs[1]))
    ctx.emit("broadcast_to", [x], node.outputs[0], shape=shape)


@mapper(TF, "BroadcastArgs")
def _broadcast_args(node, ctx):
    s0 = tuple(_const_list(ctx, node.inputs[0]))
    s1 = tuple(_const_list(ctx, node.inputs[1]))
    ctx.const_np[node.outputs[0]] = np.asarray(
        np.broadcast_shapes(s0, s1), np.int32)


@mapper(TF, "ShapeN")
def _shape_n(node, ctx):
    for i, src in enumerate(node.inputs):
        a = ctx.aval(src)
        if a is None:
            raise ImportException(
                f"ShapeN({src!r}) needs a static input shape")
        val = np.asarray(a.shape, np.int32)
        ctx.const_np[f"{node.name}:{i}"] = val
        if i == 0:
            ctx.const_np[node.outputs[0]] = val


@mapper(TF, "Empty")
def _empty(node, ctx):
    shape = tuple(_const_list(ctx, node.inputs[0]))
    ctx.const_np[node.outputs[0]] = np.zeros(
        shape, np.dtype(_dtype_name(node.attrs.get("dtype"))))


@mapper(TF, "DepthToSpace", "SpaceToDepth")
def _depth_space(node, ctx):
    op = ("depth_to_space" if node.op_type == "DepthToSpace"
          else "space_to_depth")
    df = _attr_scalar(node.attrs.get("data_format"), "NHWC")
    ctx.emit(op, _ins(node, ctx), node.outputs[0],
             block_size=int(node.attrs.get("block_size", 2)),
             data_format=df)


@mapper(TF, "BatchToSpaceND", "BatchToSpace")
def _batch_to_space(node, ctx):
    x = ctx.get(node.inputs[0])
    if node.op_type == "BatchToSpace":  # block_size attr, crops input
        bs = int(node.attrs.get("block_size", 2))
        block = [bs, bs]
        crops = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    else:
        block = _const_list(ctx, node.inputs[1])
        crops = np.asarray(ctx.const_value(node.inputs[2])).tolist()
    ctx.emit("batch_to_space", [x], node.outputs[0], block_shape=block,
             crops=crops)


@mapper(TF, "SpaceToBatchND", "SpaceToBatch")
def _space_to_batch(node, ctx):
    x = ctx.get(node.inputs[0])
    if node.op_type == "SpaceToBatch":
        bs = int(node.attrs.get("block_size", 2))
        block = [bs, bs]
        pads = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    else:
        block = _const_list(ctx, node.inputs[1])
        pads = np.asarray(ctx.const_value(node.inputs[2])).tolist()
    ctx.emit("space_to_batch", [x], node.outputs[0], block_shape=block,
             paddings=pads)


@mapper(TF, "ReverseV2")
def _reverse_v2(node, ctx):
    x = ctx.get(node.inputs[0])
    dims = _const_list(ctx, node.inputs[1])
    ctx.emit("reverse", [x], node.outputs[0], dims=tuple(dims))


@mapper(TF, "ReverseSequence")
def _reverse_sequence(node, ctx):
    x, lens = _ins(node, ctx)
    ctx.emit("reverse_sequence", [x, lens], node.outputs[0],
             seq_axis=int(node.attrs.get("seq_dim", 0)),
             batch_axis=int(node.attrs.get("batch_dim", 0)))


@mapper(TF, "Roll")
def _roll(node, ctx):
    x = ctx.get(node.inputs[0])
    shift = _const_list(ctx, node.inputs[1])
    axis = _const_list(ctx, node.inputs[2])
    ctx.emit("roll", [x], node.outputs[0],
             shift=shift if len(shift) > 1 else shift[0],
             axis=axis if len(axis) > 1 else axis[0])


@mapper(TF, "ParallelConcat")
def _parallel_concat(node, ctx):
    ctx.emit("concat", _ins(node, ctx), node.outputs[0], axis=0)


@mapper(TF, "Cumprod")
def _cumprod(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = _const_i(ctx, node.inputs[1])
    ctx.emit("cumprod", [x], node.outputs[0], axis=axis,
             exclusive=bool(node.attrs.get("exclusive", False)),
             reverse=bool(node.attrs.get("reverse", False)))


@mapper(TF, "LinSpace")
def _lin_space(node, ctx):
    start = _const_f(ctx, node.inputs[0])
    stop = _const_f(ctx, node.inputs[1])
    num = _const_i(ctx, node.inputs[2])
    ctx.const_np[node.outputs[0]] = np.linspace(
        start, stop, num, dtype=np.float32)


@mapper(TF, "Bincount")
def _bincount(node, ctx):
    # Bincount(arr, size, weights): output length == size (static const).
    # Weights may be a runtime tensor; only *emptiness* must be static.
    arr = ctx.get(node.inputs[0])
    size = _const_i(ctx, node.inputs[1])
    ins = [arr]
    out_dtype = None
    if len(node.inputs) > 2:
        w_aval = ctx.aval(node.inputs[2])
        if w_aval is None:
            raise ImportException(
                "Bincount: cannot determine statically whether the weights "
                f"input {node.inputs[2]!r} is empty (unknown shape); TF "
                "treats empty weights as unweighted, which changes semantics")
        if int(np.prod(w_aval.shape)) > 0:
            ins.append(ctx.get(node.inputs[2]))
        else:
            # empty weights: unweighted counting, but the output dtype
            # still follows T (the weights dtype)
            out_dtype = np.dtype(w_aval.dtype).name
    if out_dtype is not None and not np.issubdtype(
            np.dtype(out_dtype), np.integer):
        cnt = ctx.emit("bincount", ins, f"{node.name}__counts",
                       minlength=size, maxlength=size)
        ctx.emit("cast", [cnt], node.outputs[0], dtype=out_dtype)
        return
    ctx.emit("bincount", ins, node.outputs[0], minlength=size,
             maxlength=size)


@mapper(TF, "HistogramFixedWidth")
def _histogram(node, ctx):
    x = ctx.get(node.inputs[0])
    lo, hi = (float(v) for v in
              np.asarray(ctx.const_value(node.inputs[1])).ravel()[:2])
    nbins = _const_i(ctx, node.inputs[2]) if len(node.inputs) > 2 else 100
    hist = _reg_fn("histogram_fixed_width")
    _emit_fn(ctx, lambda v: hist(v, (lo, hi), nbins), [x],
             node.outputs[0], "histogram_fixed_width")


@mapper(TF, "Bitcast")
def _bitcast(node, ctx):
    ctx.emit("bitcast", _ins(node, ctx), node.outputs[0],
             dtype=_dtype_name(node.attrs.get("type")))


@mapper(TF, "CompareAndBitpack")
def _compare_bitpack(node, ctx):
    ctx.emit("compare_and_bitpack", _ins(node, ctx), node.outputs[0])


# -- linalg multi-output --------------------------------------------------
@mapper(TF, "LogMatrixDeterminant")
def _log_matrix_det(node, ctx):
    x = ctx.get(node.inputs[0])
    det = ctx.emit("matrix_determinant", [x], f"{node.name}__det")
    ctx.emit("sign", [det], node.outputs[0])
    ad = ctx.emit("abs", [det], f"{node.name}__absdet")
    ctx.emit("log", [ad], f"{node.name}:1")


@mapper(TF, "Lu")
def _lu(node, ctx):
    x = ctx.get(node.inputs[0])
    outs = [node.outputs[0], f"{node.name}:1"]
    ctx.emit_multi("lu", [x], outs)


@mapper(TF, "Svd")
def _svd(node, ctx):
    x = ctx.get(node.inputs[0])
    full = bool(node.attrs.get("full_matrices", False))
    if not bool(node.attrs.get("compute_uv", True)):
        # registry svd(compute_uv=False) returns s only
        ctx.emit("svd", [x], node.outputs[0], full_matrices=full,
                 compute_uv=False)
        return
    # registry order (u, s, vh); TF order (s, u, v) with v un-transposed
    tmp = [f"{node.name}__u", f"{node.name}__s", f"{node.name}__vh"]
    u, s, vh = ctx.emit_multi("svd", [x], tmp, full_matrices=full)
    ctx.bind(node.outputs[0], s, aval=ctx.aval(tmp[1]))
    ctx.bind(f"{node.name}:1", u, aval=ctx.aval(tmp[0]))
    rank = len(ctx.aval(node.inputs[0]).shape) \
        if ctx.aval(node.inputs[0]) else 2
    perm = list(range(rank - 2)) + [rank - 1, rank - 2]
    ctx.emit("transpose", [vh], f"{node.name}:2", axes=tuple(perm))


@mapper(TF, "MatrixSolve")
def _matrix_solve(node, ctx):
    a, b = _ins(node, ctx)
    ctx.emit("solve", [a, b], node.outputs[0],
             adjoint=bool(node.attrs.get("adjoint", False)))


@mapper(TF, "MatrixTriangularSolve")
def _triangular_solve(node, ctx):
    a, b = _ins(node, ctx)
    ctx.emit("triangular_solve", [a, b], node.outputs[0],
             lower=bool(node.attrs.get("lower", True)),
             adjoint=bool(node.attrs.get("adjoint", False)))


@mapper(TF, "MatrixBandPart")
def _band_part(node, ctx):
    x = ctx.get(node.inputs[0])
    lo = _const_i(ctx, node.inputs[1])
    hi = _const_i(ctx, node.inputs[2])
    ctx.emit("matrix_band_part", [x], node.outputs[0], num_lower=lo,
             num_upper=hi)


# -- scatter / segment ----------------------------------------------------
@mapper(TF, "ScatterNd")
def _scatter_nd(node, ctx):
    idx, upd = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    shape = tuple(_const_list(ctx, node.inputs[2]))
    ctx.emit("scatter_nd", [idx, upd], node.outputs[0], shape=shape)


for _tf, _op in [
    ("ScatterAdd", "scatter_add"), ("ScatterSub", "scatter_sub"),
    ("ScatterMul", "scatter_mul"), ("ScatterDiv", "scatter_div"),
    ("ScatterMax", "scatter_max"), ("ScatterMin", "scatter_min"),
    ("ScatterUpdate", "scatter_upd"),
    ("ScatterNdAdd", "scatter_nd_add"), ("ScatterNdSub", "scatter_nd_sub"),
    ("ScatterNdUpdate", "scatter_nd_update"),
    ("TensorScatterAdd", "scatter_nd_add"),
    ("TensorScatterSub", "scatter_nd_sub"),
    ("TensorScatterUpdate", "scatter_nd_update"),
    ("TensorScatterMax", "scatter_nd_max"),
    ("TensorScatterMin", "scatter_nd_min"),
]:
    _simple(_tf, _op)


def _segment(tf_name, op_name, unsorted=False):
    @mapper(TF, tf_name)
    def _m(node, ctx, _op=op_name, _uns=unsorted):
        data, ids = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
        if _uns:
            n = _const_i(ctx, node.inputs[2])
        else:
            # sorted Segment*: output rows = max(id)+1, data-dependent
            # unless the ids are graph constants (the usual export shape)
            ids_np = ctx.maybe_const(node.inputs[1])
            if ids_np is None:
                raise ImportException(
                    f"{tf_name} {node.name!r}: segment_ids must be graph "
                    f"constants (output shape is data-dependent)")
            n = int(np.max(ids_np)) + 1
        ctx.emit(_op, [data, ids], node.outputs[0], num_segments=n)
    return _m


for _tf, _op in [("SegmentMax", "segment_max"), ("SegmentMean", "segment_mean"),
                 ("SegmentMin", "segment_min"), ("SegmentProd", "segment_prod"),
                 ("SegmentSum", "segment_sum")]:
    _segment(_tf, _op)
for _tf, _op in [("UnsortedSegmentMax", "unsorted_segment_max"),
                 ("UnsortedSegmentMin", "unsorted_segment_min"),
                 ("UnsortedSegmentProd", "unsorted_segment_prod"),
                 ("UnsortedSegmentSum", "unsorted_segment_sum")]:
    _segment(_tf, _op, unsorted=True)


@mapper(TF, "DynamicPartition")
def _dynamic_partition(node, ctx):
    # partition sizes are data-dependent; static only when the partition
    # vector is a graph constant — then each partition is a static gather
    parts_np = ctx.maybe_const(node.inputs[1])
    if parts_np is None:
        raise ImportException(
            f"DynamicPartition {node.name!r}: partitions must be graph "
            f"constants (output shapes are data-dependent)")
    x = ctx.get(node.inputs[0])
    n = int(node.attrs.get("num_partitions", 1))
    flat = np.asarray(parts_np).ravel()
    for i in range(n):
        sel = np.nonzero(flat == i)[0].astype(np.int32)
        idx = ctx.sd.constant(sel, f"{node.name}__idx{i}")
        out = node.outputs[0] if i == 0 else f"{node.name}:{i}"
        ctx.emit("gather", [x, idx], out, axis=0)


@mapper(TF, "DynamicStitch", "ParallelDynamicStitch")
def _dynamic_stitch(node, ctx):
    n = len(node.inputs) // 2
    stitch = _reg_fn("dynamic_stitch")

    def fn(*args, _n=n, _stitch=stitch):
        return _stitch(list(args[:_n]), list(args[_n:]))

    _emit_fn(ctx, fn, [ctx.get(i) for i in node.inputs], node.outputs[0],
             "dynamic_stitch")


# -- image ----------------------------------------------------------------
def _resize(tf_name, op_name):
    @mapper(TF, tf_name)
    def _m(node, ctx, _op=op_name):
        x = ctx.get(node.inputs[0])
        size = _const_list(ctx, node.inputs[1])
        ctx.emit(_op, [x], node.outputs[0], size=tuple(size),
                 align_corners=bool(node.attrs.get("align_corners", False)),
                 half_pixel_centers=bool(
                     node.attrs.get("half_pixel_centers", False)))
    return _m


for _tf, _op in [("ResizeArea", "resize_area"),
                 ("ResizeBicubic", "resize_bicubic"),
                 ("ResizeBilinear", "resize_bilinear"),
                 ("ResizeNearestNeighbor", "resize_nearest_neighbor")]:
    _resize(_tf, _op)


@mapper(TF, "CropAndResize")
def _crop_and_resize(node, ctx):
    img, boxes, box_ind = (ctx.get(node.inputs[i]) for i in range(3))
    crop_size = tuple(_const_list(ctx, node.inputs[3]))
    method = _attr_scalar(node.attrs.get("method"), "bilinear")
    ctx.emit("crop_and_resize", [img, boxes, box_ind], node.outputs[0],
             crop_size=crop_size, method=method,
             extrapolation_value=float(
                 node.attrs.get("extrapolation_value", 0.0)))


@mapper(TF, "ExtractImagePatches")
def _extract_patches(node, ctx):
    x = ctx.get(node.inputs[0])
    pad = _attr_scalar(node.attrs.get("padding"), "VALID")
    ks = [int(v) for v in node.attrs.get("ksizes", [1, 1, 1, 1])]
    st = [int(v) for v in node.attrs.get("strides", [1, 1, 1, 1])]
    rt = [int(v) for v in node.attrs.get("rates", [1, 1, 1, 1])]
    ctx.emit("extract_image_patches", [x], node.outputs[0],
             ksizes=ks[1:3], strides=st[1:3], rates=rt[1:3], padding=pad)


@mapper(TF, "AdjustContrastv2")
def _adjust_contrast(node, ctx):
    x, f = _ins(node, ctx)
    ctx.emit("adjust_contrast", [x, f], node.outputs[0])


@mapper(TF, "AdjustHue")
def _adjust_hue(node, ctx):
    x, d = _ins(node, ctx)
    ctx.emit("adjust_hue", [x, d], node.outputs[0])


@mapper(TF, "AdjustSaturation")
def _adjust_saturation(node, ctx):
    x, f = _ins(node, ctx)
    ctx.emit("adjust_saturation", [x, f], node.outputs[0])


@mapper(TF, "DrawBoundingBoxesV2", "DrawBoundingBoxes")
def _draw_boxes(node, ctx):
    ctx.emit("draw_bounding_boxes", _ins(node, ctx), node.outputs[0])


@mapper(TF, "NonMaxSuppression", "NonMaxSuppressionV2",
        "NonMaxSuppressionV3")
def _nms(node, ctx):
    boxes, scores = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    max_out = _const_i(ctx, node.inputs[2])
    if node.op_type == "NonMaxSuppression":
        iou = float(node.attrs.get("iou_threshold", 0.5))
    else:
        iou = _const_f(ctx, node.inputs[3])
    score = -np.inf
    if node.op_type == "NonMaxSuppressionV3" and len(node.inputs) > 4:
        score = _const_f(ctx, node.inputs[4])
    ctx.emit("non_max_suppression", [boxes, scores], node.outputs[0],
             max_output_size=max_out, iou_threshold=iou,
             score_threshold=score)


@mapper(TF, "NonMaxSuppressionV4")
def _nms_v4(node, ctx):
    # static-shape NMS: indices padded to max_output_size with -1 plus a
    # valid-count output — TF's pad_to_max_output_size=True contract
    boxes, scores = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    max_out = _const_i(ctx, node.inputs[2])
    iou = _const_f(ctx, node.inputs[3])
    score = _const_f(ctx, node.inputs[4]) if len(node.inputs) > 4 else -np.inf
    idx = ctx.emit("non_max_suppression", [boxes, scores],
                   f"{node.name}__rawidx",
                   max_output_size=max_out, iou_threshold=iou,
                   score_threshold=score)
    import jax as _jax
    zero = ctx.sd.constant(np.int32(0), f"{node.name}__zero")
    # register the scalar's aval so downstream emits keep static shapes
    ctx.bind(f"{node.name}__zero", zero,
             aval=_jax.ShapeDtypeStruct((), np.int32))
    valid = ctx.emit("greater_equal", [idx, zero], f"{node.name}__valid")
    vi = ctx.emit("cast", [valid], f"{node.name}__vi", dtype="int32")
    ctx.emit("reduce_sum", [vi], f"{node.name}:1")
    # TF pads with 0, not -1 (gather with padded indices must hit row 0,
    # not wrap to the last row as a negative index would under JAX)
    ctx.emit("maximum", [idx, zero], node.outputs[0])


@mapper(TF, "NonMaxSuppressionWithOverlaps")
def _nms_overlaps(node, ctx):
    ov, scores = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    max_out = _const_i(ctx, node.inputs[2])
    thr = _const_f(ctx, node.inputs[3])
    score = _const_f(ctx, node.inputs[4]) if len(node.inputs) > 4 else -np.inf
    ctx.emit("non_max_suppression_overlaps", [ov, scores], node.outputs[0],
             max_output_size=max_out, overlap_threshold=thr,
             score_threshold=score)


# -- quantization ---------------------------------------------------------
def _nudged_range(mn, mx, num_bits, narrow_range):
    """TF's quantization-range nudge, in float32 exactly like the kernel
    (fake_quant_ops_functor.h) — the f32 rounding of min/scale decides
    whether a half-integer zero point nudges up or down, so this must NOT
    run through XLA's reciprocal-multiply lowering."""
    qmin = np.float32(1.0 if narrow_range else 0.0)
    qmax = np.float32(2 ** int(num_bits) - 1)
    mn, mx = np.float32(mn), np.float32(mx)
    scale = (mx - mn) / (qmax - qmin)
    zp = qmin - mn / scale
    # std::round = half-away-from-zero (zp >= qmin >= 0 here), NOT
    # numpy's round-half-to-even
    nzp = np.float32(qmin if zp < qmin else qmax if zp > qmax
                     else np.floor(zp + np.float32(0.5)))
    return ((qmin - nzp) * scale, (qmax - nzp) * scale, scale)


def _emit_fake_quant_static(ctx, node, x, mn, mx):
    nmin, nmax, scale = _nudged_range(
        mn, mx, int(node.attrs.get("num_bits", 8)),
        bool(node.attrs.get("narrow_range", False)))
    inv = np.float32(1.0) / scale

    def fn(v, _nmin=nmin, _nmax=nmax, _scale=scale, _inv=inv):
        import jax.numpy as jnp
        clamped = jnp.clip(v, _nmin, _nmax)
        return jnp.round((clamped - _nmin) * _inv) * _scale + _nmin

    _emit_fn(ctx, fn, [x], node.outputs[0], "fake_quant")


@mapper(TF, "FakeQuantWithMinMaxArgs")
def _fake_quant_args(node, ctx):
    x = ctx.get(node.inputs[0])
    _emit_fake_quant_static(ctx, node, x,
                            float(node.attrs.get("min", -6.0)),
                            float(node.attrs.get("max", 6.0)))


@mapper(TF, "FakeQuantWithMinMaxVars", "FakeQuantWithMinMaxVarsPerChannel")
def _fake_quant_vars(node, ctx):
    mn = ctx.maybe_const(node.inputs[1])
    mx = ctx.maybe_const(node.inputs[2])
    if node.op_type == "FakeQuantWithMinMaxVars" and mn is not None \
            and mx is not None and np.asarray(mn).ndim == 0:
        _emit_fake_quant_static(ctx, node, ctx.get(node.inputs[0]),
                                float(mn), float(mx))
        return
    op = ("fake_quant_with_min_max_vars"
          if node.op_type == "FakeQuantWithMinMaxVars"
          else "fake_quant_with_min_max_vars_per_channel")
    ctx.emit(op, _ins(node, ctx), node.outputs[0],
             num_bits=int(node.attrs.get("num_bits", 8)),
             narrow_range=bool(node.attrs.get("narrow_range", False)))


# -- topk / selection -----------------------------------------------------
@mapper(TF, "TopK", "TopKV2")
def _top_k(node, ctx):
    x = ctx.get(node.inputs[0])
    if node.op_type == "TopKV2":
        k = _const_i(ctx, node.inputs[1])
    else:
        k = int(node.attrs.get("k", 1))
    outs = [node.outputs[0], f"{node.name}:1"]
    ctx.emit_multi("top_k", [x], outs, k=k,
                   sorted=bool(node.attrs.get("sorted", True)))


@mapper(TF, "InTopK", "InTopKV2")
def _in_top_k(node, ctx):
    pred, targ = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    if node.op_type == "InTopKV2":
        k = _const_i(ctx, node.inputs[2])
    else:
        k = int(node.attrs.get("k", 1))
    ctx.emit("in_top_k", [pred, targ], node.outputs[0], k=k)


@mapper(TF, "NthElement")
def _nth_element(node, ctx):
    x = ctx.get(node.inputs[0])
    n = _const_i(ctx, node.inputs[1])
    ctx.emit("nth_element", [x], node.outputs[0], n=n,
             reverse=bool(node.attrs.get("reverse", False)))


# -- nn: conv3d / pool3d / misc -------------------------------------------
@mapper(TF, "Conv3D")
def _conv3d(node, ctx):
    x, w = _ins(node, ctx)
    df, strides, dil, padding = _conv_attrs(node, n=3)
    ctx.emit("conv3d", [x, w], node.outputs[0], strides=strides,
             padding=padding, dilation=dil, data_format=df)


@mapper(TF, "MaxPool3D", "AvgPool3D")
def _pool3d(node, ctx):
    x = ctx.get(node.inputs[0])
    df = _attr_scalar(node.attrs.get("data_format"), "NDHWC")
    ks = node.attrs.get("ksize", [1] * 5)
    st = node.attrs.get("strides", [1] * 5)
    if df.startswith("NC"):
        kernel, strides = ks[2:5], st[2:5]
    else:
        kernel, strides = ks[1:4], st[1:4]
    ctx.emit("maxpool3d" if node.op_type == "MaxPool3D" else "avgpool3d",
             [x], node.outputs[0], kernel=tuple(int(k) for k in kernel),
             strides=tuple(int(s) for s in strides),
             padding=_attr_scalar(node.attrs.get("padding"), "VALID"),
             data_format=df)


@mapper(TF, "MaxPoolV2")
def _maxpool_v2(node, ctx):
    x = ctx.get(node.inputs[0])
    ks = _const_list(ctx, node.inputs[1])
    st = _const_list(ctx, node.inputs[2])
    df = _attr_scalar(node.attrs.get("data_format"), "NHWC")
    if df.startswith("NC"):
        kernel, strides = ks[2:4], st[2:4]
    else:
        kernel, strides = ks[1:3], st[1:3]
    ctx.emit("maxpool2d", [x], node.outputs[0], kernel=tuple(kernel),
             strides=tuple(strides),
             padding=_attr_scalar(node.attrs.get("padding"), "VALID"),
             data_format=df)


@mapper(TF, "MaxPoolWithArgmax")
def _maxpool_argmax(node, ctx):
    x = ctx.get(node.inputs[0])
    ks = [int(v) for v in node.attrs.get("ksize", [1, 2, 2, 1])]
    st = [int(v) for v in node.attrs.get("strides", ks)]
    outs = [node.outputs[0], f"{node.name}:1"]
    ctx.emit_multi("max_pool_with_argmax", [x], outs,
                   kernel=tuple(ks[1:3]), strides=tuple(st[1:3]),
                   padding=_attr_scalar(node.attrs.get("padding"), "VALID"))


@mapper(TF, "Conv2DBackpropInput")
def _conv2d_backprop_input(node, ctx):
    out_shape = tuple(_const_list(ctx, node.inputs[0]))
    w, g = ctx.get(node.inputs[1]), ctx.get(node.inputs[2])
    df, strides, _dil, padding = _conv_attrs(node)
    deconv = _reg_fn("deconv2d_tf")
    _emit_fn(ctx, lambda ww, gg: deconv(out_shape, ww, gg, strides=strides,
                                        padding=padding, data_format=df),
             [w, g], node.outputs[0], "deconv2d_tf")


@mapper(TF, "Dilation2D")
def _dilation2d(node, ctx):
    x, w = _ins(node, ctx)
    st = [int(v) for v in node.attrs.get("strides", [1, 1, 1, 1])]
    rt = [int(v) for v in node.attrs.get("rates", [1, 1, 1, 1])]
    ctx.emit("dilation2d", [x, w], node.outputs[0],
             strides=tuple(st[1:3]), rates=tuple(rt[1:3]),
             padding=_attr_scalar(node.attrs.get("padding"), "SAME"))


@mapper(TF, "LRN")
def _lrn(node, ctx):
    ctx.emit("lrn", _ins(node, ctx), node.outputs[0],
             depth_radius=int(node.attrs.get("depth_radius", 5)),
             bias=float(node.attrs.get("bias", 1.0)),
             alpha=float(node.attrs.get("alpha", 1.0)),
             beta=float(node.attrs.get("beta", 0.5)))


# -- losses ---------------------------------------------------------------
@mapper(TF, "SoftmaxCrossEntropyWithLogits")
def _softmax_xent(node, ctx):
    logits, labels = _ins(node, ctx)
    ctx.emit("softmax_cross_entropy_loss_with_logits", [logits, labels],
             node.outputs[0])
    if _port_consumed(ctx, node, 1):
        # backprop output: softmax(logits) - labels
        sm = ctx.emit("softmax", [logits], f"{node.name}__sm")
        ctx.emit("subtract", [sm, labels], f"{node.name}:1")


@mapper(TF, "SparseSoftmaxCrossEntropyWithLogits")
def _sparse_softmax_xent(node, ctx):
    logits, labels = _ins(node, ctx)  # TF input order: features, labels
    ctx.emit("sparse_softmax_cross_entropy_loss_with_logits",
             [labels, logits], node.outputs[0])
    if _port_consumed(ctx, node, 1):
        a = ctx.aval(node.inputs[0])  # features [B, C]
        if a is None:
            raise ImportException(
                f"{node.name}: backprop output needs static logits shape")
        sm = ctx.emit("softmax", [logits], f"{node.name}__sm")
        oh = ctx.emit("onehot", [labels], f"{node.name}__oh",
                      depth=int(a.shape[-1]))
        ctx.emit("subtract", [sm, oh], f"{node.name}:1")


@mapper(TF, "CTCLoss")
def _ctc_loss(node, ctx):
    # inputs: logits [T,B,C], labels_indices [N,2], labels_values [N],
    # sequence_length [B]; sparse labels must be graph constants
    logits = ctx.get(node.inputs[0])
    idx = np.asarray(ctx.const_value(node.inputs[1]))
    vals = np.asarray(ctx.const_value(node.inputs[2]))
    seq_len = ctx.get(node.inputs[3])
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException("CTCLoss needs a static logits shape")
    T, B, C = a.shape
    lab_lens = np.zeros(B, np.int32)
    for b_i, _t in idx:
        lab_lens[int(b_i)] += 1
    maxlen = max(1, int(lab_lens.max()))
    dense = np.zeros((B, maxlen), np.int32)
    for (b_i, t_i), v in zip(idx, vals):
        dense[int(b_i), int(t_i)] = int(v)
    labels = ctx.sd.constant(dense, f"{node.name}__labels")
    lab_len_v = ctx.sd.constant(lab_lens, f"{node.name}__lab_lens")
    ctx.emit("ctc_loss", [labels, logits, lab_len_v, seq_len],
             node.outputs[0], blank_index=C - 1)
    if _port_consumed(ctx, node, 1):
        raise ImportException(
            f"CTCLoss {node.name!r}: gradient output consumption is not "
            f"supported at import (use jax.grad on the imported graph)")


# -- block RNN cells ------------------------------------------------------
@mapper(TF, "LSTMBlockCell")
def _lstm_block_cell(node, ctx):
    # TF inputs: x, cs_prev, h_prev, w, wci, wcf, wco, b
    x, cs, h, w, wci, wcf, wco, b = (ctx.get(i) for i in node.inputs)
    outs = [node.outputs[0]] + [f"{node.name}:{i}" for i in range(1, 7)]
    peephole = bool(node.attrs.get("use_peephole", False))
    ins = [x, h, cs, w, b] + ([wci, wcf, wco] if peephole else [])
    ctx.emit_multi("lstmBlockCell", ins, outs,
                   forget_bias=float(node.attrs.get("forget_bias", 1.0)),
                   clip_value=max(0.0,
                                  float(node.attrs.get("cell_clip", 0.0))))


@mapper(TF, "BlockLSTM", "BlockLSTMV2")
def _block_lstm(node, ctx):
    # TF inputs: seq_len_max, x, cs_prev, h_prev, w, wci, wcf, wco, b;
    # outputs (i, cs, f, o, ci, co, h) full sequences — h (:6) and cs (:1)
    # are the consumed ones in practice; gate traces aren't exposed by the
    # fused scan, so refuse loudly if a gate port is consumed.
    _seq, x, cs, h, w, wci, wcf, wco, b = (ctx.get(i) for i in node.inputs)
    for port in (0, 2, 3, 4, 5):
        if _port_consumed(ctx, node, port):
            raise ImportException(
                f"{node.op_type} {node.name!r}: per-gate sequence output "
                f":{port} is not exposed by the fused TPU scan")
    peephole = bool(node.attrs.get("use_peephole", False))
    fb = 1.0 if node.op_type == "BlockLSTMV2" else \
        float(node.attrs.get("forget_bias", 1.0))
    ins = [x, h, cs, w, b] + ([wci, wcf, wco] if peephole else [])
    tmp = [f"{node.name}__hseq", f"{node.name}__hlast",
           f"{node.name}__clast"]
    h_seq, _hl, _cl = ctx.emit_multi(
        "lstmBlock", ins, tmp, forget_bias=fb,
        clip_value=max(0.0, float(node.attrs.get("cell_clip", 0.0))),
        time_major=True)
    ctx.bind(f"{node.name}:6", h_seq, aval=ctx.aval(tmp[0]))
    if _port_consumed(ctx, node, 1):
        raise ImportException(
            f"{node.op_type} {node.name!r}: cell-state sequence output :1 "
            f"is not exposed by the fused TPU scan")


@mapper(TF, "GRUBlockCell")
def _gru_block_cell(node, ctx):
    # TF inputs: x, h_prev, w_ru, w_c, b_ru, b_c; outputs (r, u, c, h)
    x, h, w_ru, w_c, b_ru, b_c = (ctx.get(i) for i in node.inputs)
    outs = [node.outputs[0]] + [f"{node.name}:{i}" for i in range(1, 4)]
    ctx.emit_multi("gru_block_cell", [x, h, w_ru, w_c, b_ru, b_c], outs)


# -- random ---------------------------------------------------------------
def _random_shape(ctx, name):
    return tuple(_const_list(ctx, name))


@mapper(TF, "RandomUniform", "StatelessRandomUniform")
def _random_uniform(node, ctx):
    shape = _random_shape(ctx, node.inputs[0])
    ctx.emit("randomuniform", [], node.outputs[0], needs_key=True,
             shape=shape)


@mapper(TF, "RandomUniformInt")
def _random_uniform_int(node, ctx):
    shape = _random_shape(ctx, node.inputs[0])
    lo = _const_i(ctx, node.inputs[1])
    hi = _const_i(ctx, node.inputs[2])
    u = ctx.emit("randomuniform", [], f"{node.name}__u", needs_key=True,
                 shape=shape, minval=float(lo), maxval=float(hi))
    f = ctx.emit("Floor", [u], f"{node.name}__f")
    ctx.emit("cast", [f], node.outputs[0], dtype="int32")


@mapper(TF, "RandomStandardNormal")
def _random_normal(node, ctx):
    shape = _random_shape(ctx, node.inputs[0])
    ctx.emit("random_normal", [], node.outputs[0], needs_key=True,
             shape=shape)


@mapper(TF, "RandomGamma")
def _random_gamma(node, ctx):
    shape = _random_shape(ctx, node.inputs[0])
    g = _reg_fn("random_gamma")
    _emit_fn(ctx, lambda alpha, key: g(key, shape, alpha),
             [ctx.get(node.inputs[1])], node.outputs[0], "random_gamma",
             needs_key=True)


@mapper(TF, "RandomPoisson", "RandomPoissonV2")
def _random_poisson(node, ctx):
    shape = _random_shape(ctx, node.inputs[0])
    p = _reg_fn("random_poisson")
    _emit_fn(ctx, lambda lam, key: p(key, shape, lam),
             [ctx.get(node.inputs[1])], node.outputs[0], "random_poisson",
             needs_key=True)


@mapper(TF, "RandomShuffle")
def _random_shuffle(node, ctx):
    s = _reg_fn("random_shuffle")
    _emit_fn(ctx, lambda x, key: s(key, x), [ctx.get(node.inputs[0])],
             node.outputs[0], "random_shuffle", needs_key=True)


@mapper(TF, "RandomCrop")
def _random_crop(node, ctx):
    size = tuple(_const_list(ctx, node.inputs[1]))
    c = _reg_fn("random_crop")
    _emit_fn(ctx, lambda x, key: c(key, x, size), [ctx.get(node.inputs[0])],
             node.outputs[0], "random_crop", needs_key=True)


@mapper(TF, "Multinomial")
def _multinomial(node, ctx):
    n = _const_i(ctx, node.inputs[1])
    m = _reg_fn("random_multinomial")
    _emit_fn(ctx, lambda logits, key: m(key, logits, n),
             [ctx.get(node.inputs[0])], node.outputs[0], "multinomial",
             needs_key=True)
