"""TF op -> registered-op mapping rules.

Reference: the declarative mapping rules + per-op hooks of
`nd4j/samediff-import/samediff-import-tensorflow/src/main/resources/` and
`TensorflowOpDeclarations.kt`; legacy `TFGraphMapper.java` op switch.

Each rule maps one TF node onto a registered op (a pure jax fn), folding
shape-ish constant inputs (perms, axes, reshape targets) into static kwargs
so the resulting SameDiff graph is fully static for XLA.
"""
from __future__ import annotations

import numpy as np

from ..ir import IRNode, ImportContext, ImportException, mapper
from .parser import _np_dtype
from .slicing import build_index_spec

TF = "tensorflow"


def _ins(node: IRNode, ctx: ImportContext):
    return [ctx.get(i) for i in node.inputs]


def _dtype_name(attr) -> str:
    if isinstance(attr, tuple) and attr[0] == "dtype":
        d = _np_dtype(attr[1])
        return "bfloat16" if getattr(d, "__name__", "") == "bfloat16" \
            else np.dtype(d).name
    return "float32"


def _simple(tf_name: str, op_name: str):
    @mapper(TF, tf_name)
    def _m(node, ctx, _op=op_name):
        ctx.emit(_op, _ins(node, ctx), node.outputs[0])
    return _m


# -- elementwise binary ---------------------------------------------------
for _tf, _op in [
    ("Add", "add"), ("AddV2", "add"), ("Sub", "subtract"),
    ("Mul", "multiply"), ("Div", "divide"), ("RealDiv", "divide"),
    ("DivNoNan", "divide_no_nan"), ("Pow", "Pow"),
    ("Maximum", "maximum"), ("Minimum", "minimum"),
    ("FloorDiv", "floordiv"), ("FloorMod", "floormod"), ("Mod", "mod"),
    ("SquaredDifference", "squaredsubtract"), ("Atan2", "atan2"),
    ("TruncateDiv", "truncatediv"),
    ("Greater", "greater"), ("GreaterEqual", "greater_equal"),
    ("Less", "less"), ("LessEqual", "less_equal"),
    ("Equal", "equals"), ("NotEqual", "not_equals"),
    ("LogicalAnd", "boolean_and"), ("LogicalOr", "boolean_or"),
]:
    _simple(_tf, _op)

# -- elementwise unary ----------------------------------------------------
for _tf, _op in [
    ("Tanh", "tanh"), ("Sigmoid", "sigmoid"), ("Relu", "relu"),
    ("Relu6", "relu6"), ("Elu", "elu"), ("Selu", "selu"),
    ("Softplus", "softplus"), ("Softsign", "softsign"),
    ("Exp", "exp"), ("Expm1", "expm1"), ("Log", "log"), ("Log1p", "log1p"),
    ("Sqrt", "sqrt"), ("Rsqrt", "rsqrt"), ("Square", "square"),
    ("Neg", "neg"), ("Abs", "abs"), ("Erf", "erf"), ("Erfc", "erfc"),
    ("Floor", "floor"), ("Ceil", "ceil"), ("Round", "round"),
    ("Rint", "rint"), ("Sign", "sign"), ("Reciprocal", "reciprocal"),
    ("Inv", "reciprocal"), ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
    ("Asin", "asin"), ("Acos", "acos"), ("Atan", "atan"),
    ("Sinh", "sinh"), ("Cosh", "cosh"), ("Asinh", "asinh"),
    ("Acosh", "acosh"), ("Atanh", "atanh"), ("LogicalNot", "boolean_not"),
    ("Digamma", "digamma"), ("Lgamma", "lgamma"),
    ("ZerosLike", "zeros_as"), ("OnesLike", "ones_as"),
    ("Softmax", "softmax"), ("LogSoftmax", "log_softmax"),
    ("Mish", "mish"), ("L2Loss", "l2_loss"),
]:
    _simple(_tf, _op)

_simple("Select", "select")
_simple("SelectV2", "select")
_simple("AddN", "mergeadd")
_simple("InvertPermutation", "invert_permutation")


# -- identity-like: alias the input variable ------------------------------
@mapper(TF, "Identity", "Snapshot", "StopGradient", "PreventGradient",
        "CheckNumerics", "EnsureShape", "Enter", "Exit")
def _identity(node, ctx):
    src = node.inputs[0]
    if src in ctx.const_np:
        ctx.const_np[node.outputs[0]] = ctx.const_np[src]
    else:
        ctx.bind(node.outputs[0], ctx.get(src), aval=ctx.aval(src))


@mapper(TF, "IdentityN")
def _identity_n(node, ctx):
    for i, src in enumerate(node.inputs):
        out = f"{node.name}:{i}"
        if src in ctx.const_np:
            ctx.const_np[out] = ctx.const_np[src]
        else:
            ctx.bind(out, ctx.get(src), aval=ctx.aval(src))


@mapper(TF, "NoOp")
def _noop(node, ctx):
    pass


# -- TF1 cond (frameless Switch/Merge) ------------------------------------
# While-loop frames are consumed by while_frames.py before mapping, so any
# Switch/Merge reaching these rules belongs to a tf.cond region. XLA
# computes both branches anyway (no frames), so Switch passes its value
# through on both ports and Merge becomes an elementwise select on the
# Switch predicate — exact for the side-effect-free graphs freezing
# produces.

@mapper(TF, "Switch")
def _switch(node, ctx):
    v = ctx.get(node.inputs[0])
    ctx.bind(f"{node.name}:0", v, aval=ctx.aval(node.inputs[0]))
    ctx.bind(f"{node.name}:1", v, aval=ctx.aval(node.inputs[0]))
    ctx.bind(node.outputs[0], v, aval=ctx.aval(node.inputs[0]))


def _trace_switch_port(ctx, tensor):
    """Which Switch port (0=false, 1=true) a tensor derives from, and the
    Switch's predicate. Stops at intervening Merge nodes (an inner cond's
    output is branch *data* for the outer cond, not its routing)."""
    seen = set()
    stack = [tensor]
    while stack:
        t = stack.pop()
        if t in seen:
            continue
        seen.add(t)
        prod = ctx.producer(t)
        if prod is None:
            base = t.split(":")[0]
            prod = ctx.producer(base + ":0")
        if prod is None:
            continue
        if prod.op_type == "Switch":
            port = int(t.split(":")[1]) if ":" in t else 0
            return port, prod.inputs[1]
        if prod.op_type == "Merge":
            continue  # frame boundary of an inner cond
        stack.extend(prod.inputs)
    return None, None


@mapper(TF, "Merge")
def _merge(node, ctx):
    if len(node.inputs) != 2:
        raise ImportException(
            f"Merge {node.name!r}: {len(node.inputs)}-way merges (tf.case) "
            f"are not supported")
    for n in ctx.graph.nodes:
        if f"{node.name}:1" in n.inputs:
            raise ImportException(
                f"Merge {node.name!r}: its value_index output is consumed "
                f"by {n.name!r} — runtime branch indices are not "
                f"representable in a frameless lowering")
    a_port, a_pred = _trace_switch_port(ctx, node.inputs[0])
    b_port, b_pred = _trace_switch_port(ctx, node.inputs[1])
    if a_pred is not None and b_pred is not None and a_pred != b_pred:
        raise ImportException(
            f"Merge {node.name!r}: inputs route through different "
            f"predicates ({a_pred!r} vs {b_pred!r})")
    pred = a_pred if a_pred is not None else b_pred
    # a branch with no data-path Switch (e.g. a constant branch) infers
    # the complementary port
    if a_port is None and b_port is not None:
        a_port = 1 - b_port
    if b_port is None and a_port is not None:
        b_port = 1 - a_port
    if pred is None or a_port == b_port:
        raise ImportException(
            f"Merge {node.name!r}: cannot identify its cond branches "
            f"(ports {a_port}/{b_port}) — unsupported control-flow shape")
    true_t = node.inputs[0] if a_port == 1 else node.inputs[1]
    false_t = node.inputs[1] if a_port == 1 else node.inputs[0]
    ctx.emit("select", [ctx.get(pred), ctx.get(true_t), ctx.get(false_t)],
             node.outputs[0])


# -- matmul family --------------------------------------------------------
@mapper(TF, "MatMul")
def _matmul(node, ctx):
    ctx.emit("matmul", _ins(node, ctx), node.outputs[0],
             transpose_a=bool(node.attrs.get("transpose_a", False)),
             transpose_b=bool(node.attrs.get("transpose_b", False)))


@mapper(TF, "BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(node, ctx):
    ctx.emit("matmul", _ins(node, ctx), node.outputs[0],
             transpose_a=bool(node.attrs.get("adj_x", False)),
             transpose_b=bool(node.attrs.get("adj_y", False)))


@mapper(TF, "Einsum")
def _einsum(node, ctx):
    ctx.emit("einsum", _ins(node, ctx), node.outputs[0],
             equation=node.attrs.get("equation"))


@mapper(TF, "BiasAdd")
def _biasadd(node, ctx):
    ctx.emit("biasadd", _ins(node, ctx), node.outputs[0],
             nchw=node.attrs.get("data_format") == "NCHW")


# -- reductions -----------------------------------------------------------
def _reduction(tf_name: str, op_name: str):
    @mapper(TF, tf_name)
    def _m(node, ctx, _op=op_name):
        x = ctx.get(node.inputs[0])
        axes = ctx.const_value(node.inputs[1]) if len(node.inputs) > 1 else None
        dims = tuple(int(a) for a in np.atleast_1d(axes)) \
            if axes is not None else None
        ctx.emit(_op, [x], node.outputs[0], dims=dims,
                 keep_dims=bool(node.attrs.get("keep_dims", False)))
    return _m


for _tf, _op in [("Mean", "reduce_mean"), ("Sum", "reduce_sum"),
                 ("Max", "reduce_max"), ("Min", "reduce_min"),
                 ("Prod", "reduce_prod"), ("All", "reduce_all"),
                 ("Any", "reduce_any"),
                 ("EuclideanNorm", "reduce_norm2")]:
    _reduction(_tf, _op)


@mapper(TF, "ArgMax", "ArgMin")
def _argminmax(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = int(np.asarray(ctx.const_value(node.inputs[1]))) \
        if len(node.inputs) > 1 else 0
    ctx.emit("argmax" if node.op_type == "ArgMax" else "argmin",
             [x], node.outputs[0], dims=axis)


@mapper(TF, "Cumsum")
def _cumsum(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = int(np.asarray(ctx.const_value(node.inputs[1])))
    ctx.emit("cumsum", [x], node.outputs[0], axis=axis,
             exclusive=bool(node.attrs.get("exclusive", False)),
             reverse=bool(node.attrs.get("reverse", False)))


# -- shape manipulation ---------------------------------------------------
@mapper(TF, "Reshape")
def _reshape(node, ctx):
    x = ctx.get(node.inputs[0])
    shape = [int(s) for s in np.asarray(ctx.const_value(node.inputs[1]))]
    ctx.emit("reshape", [x], node.outputs[0], shape=tuple(shape))


@mapper(TF, "Transpose")
def _transpose(node, ctx):
    x = ctx.get(node.inputs[0])
    perm = tuple(int(p) for p in np.asarray(ctx.const_value(node.inputs[1])))
    ctx.emit("transpose", [x], node.outputs[0], axes=perm)


@mapper(TF, "ExpandDims")
def _expand_dims(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = int(np.asarray(ctx.const_value(node.inputs[1])))
    ctx.emit("expand_dims", [x], node.outputs[0], axis=axis)


@mapper(TF, "Squeeze")
def _squeeze(node, ctx):
    x = ctx.get(node.inputs[0])
    dims = node.attrs.get("squeeze_dims") or node.attrs.get("axis")
    axis = tuple(int(d) for d in dims) if dims else None
    ctx.emit("squeeze", [x], node.outputs[0], axis=axis)


@mapper(TF, "ConcatV2")
def _concat_v2(node, ctx):
    xs = [ctx.get(i) for i in node.inputs[:-1]]
    axis = int(np.asarray(ctx.const_value(node.inputs[-1])))
    ctx.emit("concat", xs, node.outputs[0], axis=axis)


@mapper(TF, "Concat")
def _concat(node, ctx):
    axis = int(np.asarray(ctx.const_value(node.inputs[0])))
    xs = [ctx.get(i) for i in node.inputs[1:]]
    ctx.emit("concat", xs, node.outputs[0], axis=axis)


@mapper(TF, "Pack")
def _pack(node, ctx):
    ctx.emit("stack", _ins(node, ctx), node.outputs[0],
             axis=int(node.attrs.get("axis", 0)))


@mapper(TF, "Unpack")
def _unpack(node, ctx):
    num = int(node.attrs.get("num", 1))
    outs = [f"{node.name}:{i}" for i in range(num)]
    ctx.emit_multi("unstack", _ins(node, ctx), outs,
                   axis=int(node.attrs.get("axis", 0)))


@mapper(TF, "Split")
def _split(node, ctx):
    axis = int(np.asarray(ctx.const_value(node.inputs[0])))
    x = ctx.get(node.inputs[1])
    num = int(node.attrs.get("num_split", 1))
    outs = [f"{node.name}:{i}" for i in range(num)]
    ctx.emit_multi("split", [x], outs, num=num, axis=axis)


@mapper(TF, "SplitV")
def _split_v(node, ctx):
    x = ctx.get(node.inputs[0])
    sizes = [int(s) for s in np.asarray(ctx.const_value(node.inputs[1]))]
    axis = int(np.asarray(ctx.const_value(node.inputs[2])))
    outs = [f"{node.name}:{i}" for i in range(len(sizes))]
    ctx.emit_multi("split_v", [x], outs, sizes=sizes, axis=axis)


@mapper(TF, "StridedSlice")
def _strided_slice(node, ctx):
    x = ctx.get(node.inputs[0])
    begin = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    end = np.asarray(ctx.const_value(node.inputs[2])).tolist()
    strides = np.asarray(ctx.const_value(node.inputs[3])).tolist() \
        if len(node.inputs) > 3 else None
    a = ctx.aval(node.inputs[0])
    spec = build_index_spec(
        begin, end, strides,
        begin_mask=int(node.attrs.get("begin_mask", 0)),
        end_mask=int(node.attrs.get("end_mask", 0)),
        ellipsis_mask=int(node.attrs.get("ellipsis_mask", 0)),
        new_axis_mask=int(node.attrs.get("new_axis_mask", 0)),
        shrink_axis_mask=int(node.attrs.get("shrink_axis_mask", 0)),
        rank=len(a.shape) if a is not None else None)
    ctx.emit("tf_strided_slice", [x], node.outputs[0], spec=spec)


@mapper(TF, "Slice")
def _slice(node, ctx):
    x = ctx.get(node.inputs[0])
    begin = [int(b) for b in np.asarray(ctx.const_value(node.inputs[1]))]
    size = [int(s) for s in np.asarray(ctx.const_value(node.inputs[2]))]
    ctx.emit("slice", [x], node.outputs[0], begin=begin, size=size)


@mapper(TF, "GatherV2", "Gather")
def _gather(node, ctx):
    params = ctx.get(node.inputs[0])
    indices = ctx.get(node.inputs[1])
    axis = 0
    if node.op_type == "GatherV2" and len(node.inputs) > 2:
        axis = int(np.asarray(ctx.const_value(node.inputs[2])))
    if int(node.attrs.get("batch_dims", 0)) != 0:
        raise ImportException("GatherV2 batch_dims != 0 not supported")
    ctx.emit("gather", [params, indices], node.outputs[0], axis=axis)


@mapper(TF, "GatherNd")
def _gather_nd(node, ctx):
    ctx.emit("gather_nd", _ins(node, ctx), node.outputs[0])


@mapper(TF, "OneHot")
def _onehot(node, ctx):
    indices = ctx.get(node.inputs[0])
    depth = int(np.asarray(ctx.const_value(node.inputs[1])))
    on = float(np.asarray(ctx.const_value(node.inputs[2])))
    off = float(np.asarray(ctx.const_value(node.inputs[3])))
    ctx.emit("onehot", [indices], node.outputs[0], depth=depth, on_value=on,
             off_value=off, axis=int(node.attrs.get("axis", -1)))


@mapper(TF, "Fill")
def _fill(node, ctx):
    dims = [int(d) for d in np.asarray(ctx.const_value(node.inputs[0]))]
    value = ctx.get(node.inputs[1])
    ctx.emit("broadcast_to", [value], node.outputs[0], shape=tuple(dims))


@mapper(TF, "Tile")
def _tile(node, ctx):
    x = ctx.get(node.inputs[0])
    reps = [int(r) for r in np.asarray(ctx.const_value(node.inputs[1]))]
    ctx.emit("tile", [x], node.outputs[0], reps=reps)


@mapper(TF, "Pad", "PadV2", "MirrorPad")
def _pad(node, ctx):
    x = ctx.get(node.inputs[0])
    paddings = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    cval = 0
    if node.op_type == "PadV2" and len(node.inputs) > 2:
        cval = float(np.asarray(ctx.const_value(node.inputs[2])))
    mode = node.attrs.get("mode", "CONSTANT") \
        if node.op_type == "MirrorPad" else "CONSTANT"
    ctx.emit("pad", [x], node.outputs[0], paddings=paddings, mode=mode,
             constant_values=cval)


@mapper(TF, "Cast")
def _cast(node, ctx):
    ctx.emit("cast", _ins(node, ctx), node.outputs[0],
             dtype=_dtype_name(node.attrs.get("DstT")))


@mapper(TF, "Shape", "Size", "Rank")
def _shape_of(node, ctx):
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException(
            f"{node.op_type}({node.inputs[0]!r}) needs a static input shape; "
            f"pass concrete input_shapes to the importer")
    if node.op_type == "Shape":
        val = np.asarray(a.shape, np.int32)
    elif node.op_type == "Size":
        val = np.asarray(int(np.prod(a.shape)), np.int32)
    else:
        val = np.asarray(len(a.shape), np.int32)
    ctx.const_np[node.outputs[0]] = val


@mapper(TF, "Range")
def _range(node, ctx):
    start = float(np.asarray(ctx.const_value(node.inputs[0])))
    limit = float(np.asarray(ctx.const_value(node.inputs[1])))
    delta = float(np.asarray(ctx.const_value(node.inputs[2])))
    ctx.const_np[node.outputs[0]] = np.arange(start, limit, delta,
                                              dtype=np.int32
                                              if all(float(v).is_integer()
                                                     for v in (start, limit,
                                                               delta))
                                              else np.float32)


# -- nn -------------------------------------------------------------------
@mapper(TF, "FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(node, ctx):
    x, scale, offset, mean, var = _ins(node, ctx)
    outs = ctx.sd._record(
        "fused_batch_norm", [x, scale, offset, mean, var], n_outputs=3,
        out_name=node.name.replace(":", "_"),
        eps=float(node.attrs.get("epsilon", 1e-3)),
        training=bool(node.attrs.get("is_training", False)),
        data_format=node.attrs.get("data_format", "NHWC"))
    ctx.bind(node.outputs[0], outs[0])
    ctx.bind(f"{node.name}:1", outs[1])
    ctx.bind(f"{node.name}:2", outs[2])


@mapper(TF, "LeakyRelu")
def _leaky_relu(node, ctx):
    ctx.emit("leakyrelu", _ins(node, ctx), node.outputs[0],
             alpha=float(node.attrs.get("alpha", 0.2)))


def _conv_attrs(node, n=2):
    df = node.attrs.get("data_format", "NHWC")
    strides = node.attrs.get("strides", [1] * (n + 2))
    dilations = node.attrs.get("dilations", [1] * (n + 2))
    if df.startswith("NC"):
        s, d = strides[2:2 + n], dilations[2:2 + n]
    else:
        s, d = strides[1:1 + n], dilations[1:1 + n]
    padding = node.attrs.get("padding", "SAME")
    if isinstance(padding, bytes):
        padding = padding.decode()
    return df, tuple(int(v) for v in s), tuple(int(v) for v in d), padding


@mapper(TF, "Conv2D")
def _conv2d(node, ctx):
    x, w = _ins(node, ctx)
    df, strides, dil, padding = _conv_attrs(node)
    ctx.emit("conv2d", [x, w], node.outputs[0], strides=strides,
             padding=padding, dilation=dil, data_format=df)


@mapper(TF, "DepthwiseConv2dNative")
def _depthwise(node, ctx):
    x, w = _ins(node, ctx)
    df, strides, dil, padding = _conv_attrs(node)
    ctx.emit("depthwise_conv2d", [x, w], node.outputs[0], strides=strides,
             padding=padding, dilation=dil, data_format=df)


@mapper(TF, "MaxPool", "AvgPool")
def _pool(node, ctx):
    x = ctx.get(node.inputs[0])
    df = node.attrs.get("data_format", "NHWC")
    ks = node.attrs.get("ksize", [1, 1, 1, 1])
    st = node.attrs.get("strides", [1, 1, 1, 1])
    if df.startswith("NC"):
        kernel, strides = ks[2:4], st[2:4]
    else:
        kernel, strides = ks[1:3], st[1:3]
    padding = node.attrs.get("padding", "VALID")
    if isinstance(padding, bytes):
        padding = padding.decode()
    kw = {}
    if node.op_type == "AvgPool":
        # TF average pooling ALWAYS excludes padded cells from the divisor
        kw["include_pad"] = False
    ctx.emit("maxpool2d" if node.op_type == "MaxPool" else "avgpool2d",
             [x], node.outputs[0], kernel=tuple(int(k) for k in kernel),
             strides=tuple(int(s) for s in strides), padding=padding,
             data_format=df, **kw)
