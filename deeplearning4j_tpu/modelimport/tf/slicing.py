"""TF StridedSlice mask resolution -> static index spec.

TF's StridedSlice carries five bitmasks (begin/end/ellipsis/new_axis/
shrink_axis). The reference resolves these at execution time
(`libnd4j/include/ops/declarable/generic/shape/strided_slice.cpp`); on TPU we
resolve them at *import* time against the static input shape and emit the
serializable `tf_strided_slice` op.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple


def build_index_spec(begin: Sequence[int], end: Sequence[int],
                     strides: Sequence[int], begin_mask: int = 0,
                     end_mask: int = 0, ellipsis_mask: int = 0,
                     new_axis_mask: int = 0, shrink_axis_mask: int = 0,
                     rank: int = None) -> List[Tuple]:
    """Resolve masks into a spec of ("slice",b,e,s)/("int",i)/("newaxis",)/
    ("all",) entries consumable by the `tf_strided_slice` op (and by numpy
    for constant folding)."""
    n = len(begin)
    spec: List[Tuple] = []
    # count real (non-new-axis, non-ellipsis) entries to size the ellipsis
    real = sum(1 for i in range(n)
               if not (new_axis_mask >> i) & 1 and not (ellipsis_mask >> i) & 1)
    for i in range(n):
        if (ellipsis_mask >> i) & 1:
            fill = (rank - real) if rank is not None else 0
            spec.extend([("all",)] * max(fill, 0))
            continue
        if (new_axis_mask >> i) & 1:
            spec.append(("newaxis",))
            continue
        if (shrink_axis_mask >> i) & 1:
            spec.append(("int", int(begin[i])))
            continue
        b = None if (begin_mask >> i) & 1 else int(begin[i])
        e = None if (end_mask >> i) & 1 else int(end[i])
        s = int(strides[i]) if strides is not None else 1
        if b is None and e is None and s == 1:
            spec.append(("all",))
        else:
            spec.append(("slice", b, e, s))
    return spec


def apply_spec_np(x, spec):
    idx = []
    for entry in spec:
        kind = entry[0]
        if kind == "slice":
            idx.append(slice(entry[1], entry[2], entry[3]))
        elif kind == "int":
            idx.append(int(entry[1]))
        elif kind == "newaxis":
            idx.append(None)
        else:
            idx.append(slice(None))
    return x[tuple(idx)]
