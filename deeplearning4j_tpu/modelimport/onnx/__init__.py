from .importer import OnnxImporter, import_onnx_model

__all__ = ["OnnxImporter", "import_onnx_model"]
