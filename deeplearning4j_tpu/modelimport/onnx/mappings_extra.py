"""ONNX op mapping rules — long tail of the reference ruleset.

Covers the remaining `inputFrameworkOpName` entries of
`nd4j/samediff-import/samediff-import-onnx/src/main/resources/
onnx-mapping-ruleset.pbtxt` beyond the core set in ``mappings.py``.
Dynamic-output ops (NonZero, the Sequence* family) and subgraph control
flow (If, Loop) are documented exemptions in ``coverage.py``.
"""
from __future__ import annotations

import numpy as np

from ..ir import IRNode, ImportContext, ImportException, mapper
from .mappings import ONNX, _ins, _simple


def _axes_arg(node, ctx, input_idx=1):
    """axes from attr (opset<13/18) or constant input (newer opsets)."""
    axes = node.attrs.get("axes")
    if axes is None and len(node.inputs) > input_idx and \
            node.inputs[input_idx]:
        axes = np.asarray(ctx.const_value(node.inputs[input_idx])).tolist()
    return tuple(int(a) for a in axes) if axes else None


def _emit_fn(ctx, fn, inputs, out_tensor, label, needs_key=False, **kwargs):
    out = ctx.sd._record_fn(fn, list(inputs), label=label,
                            out_name=out_tensor.replace(":", "_"),
                            needs_key=needs_key, **kwargs)
    ctx.bind(out_tensor, out)
    return out


def _reg_fn(name):
    from ...ops.registry import OpRegistry
    return OpRegistry.get().lookup(name).fn


for _ox, _op in [
    ("Det", "matrix_determinant"),
    ("PRelu", "prelu"),
    ("GatherND", "gather_nd"),
]:
    _simple(_ox, _op)


@mapper(ONNX, "HardSigmoid")
def _hard_sigmoid(node, ctx):
    # ONNX: max(0, min(1, alpha*x + beta)), default alpha=0.2 — NOT the
    # alpha=1/6 of jax.nn.hard_sigmoid, so compose explicitly
    alpha = float(node.attrs.get("alpha", 0.2))
    beta = float(node.attrs.get("beta", 0.5))

    def fn(x, _a=alpha, _b=beta):
        import jax.numpy as jnp
        return jnp.clip(_a * x + _b, 0.0, 1.0)

    _emit_fn(ctx, fn, [ctx.get(node.inputs[0])], node.outputs[0],
             "hard_sigmoid")


@mapper(ONNX, "AliasWithName", "Placeholder")
def _alias(node, ctx):
    src = node.inputs[0]
    if src in ctx.const_np:
        ctx.const_np[node.outputs[0]] = ctx.const_np[src]
    else:
        ctx.bind(node.outputs[0], ctx.get(src), aval=ctx.aval(src))


@mapper(ONNX, "CumSum")
def _cumsum(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = int(np.asarray(ctx.const_value(node.inputs[1])))
    ctx.emit("cumsum", [x], node.outputs[0], axis=axis,
             exclusive=bool(node.attrs.get("exclusive", 0)),
             reverse=bool(node.attrs.get("reverse", 0)))


@mapper(ONNX, "DepthToSpace")
def _depth_to_space(node, ctx):
    x = ctx.get(node.inputs[0])
    mode = node.attrs.get("mode", "DCR")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode != "DCR":
        raise ImportException("DepthToSpace mode=CRD is unsupported")
    ctx.emit("depth_to_space", [x], node.outputs[0],
             block_size=int(node.attrs.get("blocksize", 2)),
             data_format="NCHW")


@mapper(ONNX, "SpaceToDepth")
def _space_to_depth(node, ctx):
    x = ctx.get(node.inputs[0])
    ctx.emit("space_to_depth", [x], node.outputs[0],
             block_size=int(node.attrs.get("blocksize", 2)),
             data_format="NCHW")


@mapper(ONNX, "GlobalMaxPool")
def _global_max_pool(node, ctx):
    x = ctx.get(node.inputs[0])
    a = ctx.aval(node.inputs[0])
    ndim = len(a.shape) if a is not None else 4
    ctx.emit("reduce_max", [x], node.outputs[0],
             dims=tuple(range(2, ndim)), keep_dims=True)


@mapper(ONNX, "IsInf")
def _isinf(node, ctx):
    pos = bool(node.attrs.get("detect_positive", 1))
    neg = bool(node.attrs.get("detect_negative", 1))
    x = ctx.get(node.inputs[0])
    if pos and neg:
        ctx.emit("isinf", [x], node.outputs[0])
        return
    inf = ctx.sd.constant(np.float32(np.inf if pos else -np.inf),
                          f"{node.name}__inf")
    ctx.emit("equals", [x, inf], node.outputs[0])


_simple("IsNaN", "isnan")


@mapper(ONNX, "LRN")
def _lrn(node, ctx):
    size = int(node.attrs.get("size", 5))
    # ONNX normalizes alpha by window size and runs over the NCHW channel
    # axis; the TF-style registry op uses raw alpha over the LAST axis
    lrn = _reg_fn("lrn")
    dr = (size - 1) // 2
    bias = float(node.attrs.get("bias", 1.0))
    alpha = float(node.attrs.get("alpha", 1e-4)) / size
    beta = float(node.attrs.get("beta", 0.75))

    def fn(x, _lrn=lrn):
        import jax.numpy as jnp
        t = jnp.moveaxis(x, 1, -1)
        return jnp.moveaxis(_lrn(t, dr, bias, alpha, beta), -1, 1)

    _emit_fn(ctx, fn, [ctx.get(node.inputs[0])], node.outputs[0], "lrn")


@mapper(ONNX, "NonMaxSuppression")
def _nms(node, ctx):
    # inputs: boxes [B,N,4] (y1,x1,y2,x2), scores [B,C,N], then const
    # max_output_boxes_per_class, iou_threshold, score_threshold.
    # Static-shape lowering: single batch/class only (the common detection
    # head export), indices padded with -1.
    a = ctx.aval(node.inputs[0])
    sa = ctx.aval(node.inputs[1])
    if a is None or sa is None:
        raise ImportException("NonMaxSuppression needs static shapes")
    if a.shape[0] != 1 or sa.shape[1] != 1:
        raise ImportException(
            "NonMaxSuppression: only batch=1, classes=1 supported "
            f"(got boxes {a.shape}, scores {sa.shape})")
    boxes, scores = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    max_out = int(np.asarray(ctx.const_value(node.inputs[2]))) \
        if len(node.inputs) > 2 and node.inputs[2] else 0
    iou = float(np.asarray(ctx.const_value(node.inputs[3]))) \
        if len(node.inputs) > 3 and node.inputs[3] else 0.0
    score = float(np.asarray(ctx.const_value(node.inputs[4]))) \
        if len(node.inputs) > 4 and node.inputs[4] else -np.inf
    nms = _reg_fn("non_max_suppression")

    def fn(b, s, _nms=nms, _mo=max_out, _iou=iou, _sc=score):
        import jax.numpy as jnp
        idx = _nms(b[0], s[0, 0], _mo, _iou, _sc)  # [max_out], -1 padded
        z = jnp.zeros_like(idx)
        return jnp.stack([z, z, idx], axis=-1)  # [max_out, 3]

    _emit_fn(ctx, fn, [boxes, scores], node.outputs[0], "onnx_nms")


@mapper(ONNX, "RandomNormal", "RandomUniform")
def _random(node, ctx):
    shape = tuple(int(s) for s in node.attrs.get("shape", ()))
    if node.op_type == "RandomNormal":
        ctx.emit("random_normal", [], node.outputs[0], needs_key=True,
                 shape=shape, mean=float(node.attrs.get("mean", 0.0)),
                 stddev=float(node.attrs.get("scale", 1.0)))
    else:
        ctx.emit("randomuniform", [], node.outputs[0], needs_key=True,
                 shape=shape, minval=float(node.attrs.get("low", 0.0)),
                 maxval=float(node.attrs.get("high", 1.0)))


@mapper(ONNX, "Range")
def _range(node, ctx):
    start = np.asarray(ctx.const_value(node.inputs[0]))
    limit = np.asarray(ctx.const_value(node.inputs[1]))
    delta = np.asarray(ctx.const_value(node.inputs[2]))
    ctx.const_np[node.outputs[0]] = np.arange(
        start.item(), limit.item(), delta.item(), dtype=start.dtype)


@mapper(ONNX, "ReduceL1", "ReduceL2", "ReduceLogSumExp")
def _reduce_extra(node, ctx):
    op = {"ReduceL1": "reduce_norm1", "ReduceL2": "reduce_norm2",
          "ReduceLogSumExp": "reduce_logsumexp"}[node.op_type]
    x = ctx.get(node.inputs[0])
    ctx.emit(op, [x], node.outputs[0], dims=_axes_arg(node, ctx),
             keep_dims=bool(node.attrs.get("keepdims", 1)))


@mapper(ONNX, "Resize", "ResizeNearest")
def _resize(node, ctx):
    x = ctx.get(node.inputs[0])
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException("Resize needs a static input shape")
    # opset>=11 inputs: X, roi, scales, sizes
    sizes = None
    if len(node.inputs) > 3 and node.inputs[3]:
        sizes = [int(s) for s in np.asarray(ctx.const_value(node.inputs[3]))]
    elif len(node.inputs) > 2 and node.inputs[2]:
        scales = np.asarray(ctx.const_value(node.inputs[2]))
        if scales.size:
            sizes = [int(round(d * s)) for d, s in zip(a.shape, scales)]
    elif "scales" in node.attrs:  # legacy Upsample-style
        sizes = [int(round(d * s))
                 for d, s in zip(a.shape, node.attrs["scales"])]
    if sizes is None:
        raise ImportException("Resize: need constant scales or sizes")
    mode = node.attrs.get("mode", "nearest")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    method = {"nearest": "nearest", "linear": "bilinear",
              "cubic": "bicubic"}.get(mode, "nearest")
    if node.op_type == "ResizeNearest":
        method = "nearest"
    # NCHW input: spatial sizes are the trailing dims
    hw = sizes[2:] if len(sizes) == len(a.shape) else sizes
    perm_in = (0, 2, 3, 1)
    perm_out = (0, 3, 1, 2)
    t = ctx.emit("transpose", [x], f"{node.name}__nhwc", axes=perm_in)
    r = ctx.emit("image_resize", [t], f"{node.name}__r", size=tuple(hw),
                 method=method)
    ctx.emit("transpose", [r], node.outputs[0], axes=perm_out)


@mapper(ONNX, "ScatterND")
def _scatter_nd(node, ctx):
    data, idx, upd = (ctx.get(i) for i in node.inputs[:3])
    red = node.attrs.get("reduction", "none")
    red = red.decode() if isinstance(red, bytes) else red
    op = {"none": "scatter_nd_update", "add": "scatter_nd_add",
          "mul": None, "max": "scatter_nd_max",
          "min": "scatter_nd_min"}.get(red)
    if op is None:
        raise ImportException(f"ScatterND reduction={red!r} unsupported")
    ctx.emit(op, [data, idx, upd], node.outputs[0])


@mapper(ONNX, "ScatterElements", "Scatter")
def _scatter_elements(node, ctx):
    data, idx, upd = (ctx.get(i) for i in node.inputs[:3])
    axis = int(node.attrs.get("axis", 0))
    red = node.attrs.get("reduction", "none")
    red = red.decode() if isinstance(red, bytes) else red
    method = {"none": "set", "add": "add", "mul": "multiply",
              "max": "max", "min": "min"}.get(red)
    if method is None:
        raise ImportException(
            f"ScatterElements reduction={red!r} unsupported")

    def fn(d, i, u, _axis=axis, _m=method):
        import jax.numpy as jnp
        grids = list(jnp.meshgrid(*[jnp.arange(s) for s in i.shape],
                                  indexing="ij"))
        grids[_axis] = i
        return getattr(d.at[tuple(grids)], _m)(u)

    _emit_fn(ctx, fn, [data, idx, upd], node.outputs[0], "scatter_elements")


@mapper(ONNX, "Size")
def _size(node, ctx):
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException("Size needs a static input shape")
    ctx.const_np[node.outputs[0]] = np.asarray(
        int(np.prod(a.shape)), np.int64)


@mapper(ONNX, "TopK")
def _top_k(node, ctx):
    x = ctx.get(node.inputs[0])
    if len(node.inputs) > 1 and node.inputs[1]:
        k = int(np.asarray(ctx.const_value(node.inputs[1])))
    else:
        k = int(node.attrs.get("k", 1))
    axis = int(node.attrs.get("axis", -1))
    largest = bool(node.attrs.get("largest", 1))
    srt = bool(node.attrs.get("sorted", 1))
    a = ctx.aval(node.inputs[0])
    rank = len(a.shape) if a is not None else 2
    if axis < 0:
        axis += rank
    tk = _reg_fn("top_k")

    def fn(v, _k=k, _axis=axis, _rank=rank, _largest=largest, _srt=srt):
        import jax.numpy as jnp
        moved = jnp.moveaxis(v, _axis, -1)
        vals, idx = tk(moved if _largest else -moved, _k, sorted=_srt)
        if not _largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, _axis),
                jnp.moveaxis(idx, -1, _axis).astype(jnp.int64))

    out = ctx.sd._record_fn(fn, [x], label="onnx_topk", n_outputs=2,
                            out_names=[o.replace(":", "_")
                                       for o in node.outputs[:2]])
    for t, v in zip(node.outputs, out):
        ctx.bind(t, v)


@mapper(ONNX, "RoiAlign")
def _roi_align(node, ctx):
    # crop_and_resize-based RoiAlign (avg mode): bilinear-sample an
    # output_h*s x output_w*s grid per ROI, then average-pool s x s blocks
    x, rois = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    batch_idx = ctx.get(node.inputs[2])
    oh = int(node.attrs.get("output_height", 1))
    ow = int(node.attrs.get("output_width", 1))
    s = max(1, int(node.attrs.get("sampling_ratio", 1)))
    scale = float(node.attrs.get("spatial_scale", 1.0))
    mode = node.attrs.get("mode", "avg")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if mode != "avg":
        raise ImportException("RoiAlign mode=max is unsupported")
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException("RoiAlign needs a static input shape")
    H, W = a.shape[2], a.shape[3]
    car = _reg_fn("crop_and_resize")

    def fn(feat, boxes, bidx, _oh=oh, _ow=ow, _s=s, _sc=scale, _H=H, _W=W):
        import jax.numpy as jnp
        nhwc = jnp.transpose(feat, (0, 2, 3, 1))
        x1, y1, x2, y2 = (boxes[:, 0] * _sc, boxes[:, 1] * _sc,
                          boxes[:, 2] * _sc, boxes[:, 3] * _sc)
        # crop_and_resize wants normalized [y1, x1, y2, x2]
        nb = jnp.stack([y1 / (_H - 1), x1 / (_W - 1),
                        y2 / (_H - 1), x2 / (_W - 1)], axis=1)
        crops = car(nhwc, nb, bidx.astype(jnp.int32),
                    (_oh * _s, _ow * _s))
        r = crops.reshape(crops.shape[0], _oh, _s, _ow, _s, crops.shape[-1])
        return jnp.transpose(r.mean(axis=(2, 4)), (0, 3, 1, 2))

    _emit_fn(ctx, fn, [x, rois, batch_idx], node.outputs[0], "roi_align")
