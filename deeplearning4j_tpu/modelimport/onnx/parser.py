"""ONNX ModelProto (.onnx) wire-format parser -> IRGraph.

Parses the public onnx.proto schema with `protoio.py` — no onnx runtime
required. Reference counterpart: the shaded ONNX protos consumed by
`nd4j/samediff-import/samediff-import-onnx/.../OnnxFrameworkImporter.kt`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import protoio as pio
from ..ir import IRGraph, IRNode, ImportException

# onnx TensorProto.DataType -> numpy
_ONNX_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 8: object, 9: np.bool_, 10: np.float16,
    11: np.float64, 12: np.uint32, 13: np.uint64,
}


def _np_dtype(onnx_enum: int):
    if onnx_enum == 16:  # BFLOAT16
        import ml_dtypes
        return ml_dtypes.bfloat16
    try:
        return _ONNX_DTYPES[onnx_enum]
    except KeyError:
        raise ImportException(f"unsupported ONNX dtype enum {onnx_enum}")


def parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    """TensorProto: dims=1 data_type=2 float_data=4 int32_data=5
    string_data=6 int64_data=7 name=8 raw_data=9 double_data=10
    uint64_data=11."""
    f = pio.decode(buf)
    dims = pio.ints(f, 1)
    dtype = _np_dtype(pio.first(f, 2, 1))
    name = pio.as_str(pio.first(f, 8))
    raw = pio.first(f, 9)
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype)
    elif dtype == np.float32:
        arr = np.asarray(pio.floats(f, 4), np.float32)
    elif dtype == np.float64:
        arr = np.asarray(pio.doubles(f, 10), np.float64)
    elif dtype == np.int64:
        arr = np.asarray(pio.ints(f, 7), np.int64)
    elif dtype in (np.uint64, np.uint32):
        arr = np.asarray(pio.ints(f, 11, signed=False), dtype)
    elif dtype == object:
        arr = np.asarray([s.decode("utf-8", "replace")
                          for s in pio.all_(f, 6)], object)
    else:  # int32-packed family (int8/16/32, uint8/16, bool, fp16)
        vals = pio.ints(f, 5)
        if dtype == np.float16:
            arr = np.asarray(vals, np.uint16).view(np.float16)
        else:
            arr = np.asarray(vals, dtype)
    return name, arr.reshape([int(d) for d in dims])


def _parse_shape(buf: bytes) -> Optional[Tuple]:
    """TensorShapeProto: dim=1 {dim_value=1, dim_param=2}."""
    f = pio.decode(buf)
    dims = []
    for d in pio.all_(f, 1):
        df = pio.decode(d)
        if 1 in df:
            dims.append(pio.as_int64(pio.first(df, 1)))
        else:
            dims.append(None)  # symbolic dim_param
    return tuple(dims)


def _parse_value_info(buf: bytes):
    """ValueInfoProto -> (name, shape, np_dtype)."""
    f = pio.decode(buf)
    name = pio.as_str(pio.first(f, 1))
    shape, dtype = None, np.float32
    tbuf = pio.first(f, 2)
    if tbuf is not None:
        tf_ = pio.decode(tbuf)
        tens = pio.first(tf_, 1)  # TypeProto.tensor_type
        if tens is not None:
            ttf = pio.decode(tens)
            dtype = _np_dtype(pio.first(ttf, 1, 1))
            sbuf = pio.first(ttf, 2)
            if sbuf is not None:
                shape = _parse_shape(sbuf)
    return name, shape, dtype


def parse_attr(buf: bytes) -> Tuple[str, Any]:
    """AttributeProto: name=1 f=2 i=3 s=4 t=5 g=6 floats=7 ints=8
    strings=9 type=20."""
    f = pio.decode(buf)
    name = pio.as_str(pio.first(f, 1))
    atype = pio.first(f, 20)
    if atype == 1 or (atype is None and 2 in f):
        return name, pio.as_float32(pio.first(f, 2))
    if atype == 2 or (atype is None and 3 in f):
        return name, pio.as_int64(pio.first(f, 3))
    if atype == 3 or (atype is None and 4 in f):
        return name, pio.as_str(pio.first(f, 4))
    if atype == 4 or (atype is None and 5 in f):
        return name, parse_tensor(pio.first(f, 5))[1]
    if atype == 5 or (atype is None and 6 in f):
        return name, ("graph", pio.first(f, 6))
    if atype == 6 or 7 in f:
        return name, pio.floats(f, 7)
    if atype == 7 or 8 in f:
        return name, pio.ints(f, 8)
    if atype == 8 or 9 in f:
        return name, [s.decode("utf-8", "replace") for s in pio.all_(f, 9)]
    return name, None


def parse_model(data: bytes,
                input_shapes: Optional[Dict[str, Tuple]] = None) -> IRGraph:
    """ModelProto bytes -> IRGraph (graph=7, opset_import=8)."""
    m = pio.decode(data)
    gbuf = pio.first(m, 7)
    if gbuf is None:
        raise ImportException("not an ONNX ModelProto (no graph field)")
    g = pio.decode(gbuf)
    input_shapes = input_shapes or {}

    initializers: Dict[str, np.ndarray] = {}
    for t in pio.all_(g, 5):
        name, arr = parse_tensor(t)
        initializers[name] = arr

    inputs: Dict[str, Any] = {}
    for vi in pio.all_(g, 11):
        name, shape, dtype = _parse_value_info(vi)
        if name in initializers:   # opset<9 lists initializers as inputs
            continue
        if name in input_shapes:
            shape = input_shapes[name]
        dtype_name = "float32" if dtype == object else np.dtype(dtype).name
        inputs[name] = (shape, dtype_name)

    outputs = [_parse_value_info(vi)[0] for vi in pio.all_(g, 12)]

    nodes: List[IRNode] = []
    for i, nb in enumerate(pio.all_(g, 1)):
        nf = pio.decode(nb)
        op_type = pio.as_str(pio.first(nf, 4))
        name = pio.as_str(pio.first(nf, 3)) or f"{op_type}_{i}"
        node_in = [pio.as_str(s) for s in pio.all_(nf, 1)]
        node_out = [pio.as_str(s) for s in pio.all_(nf, 2)]
        attrs = dict(parse_attr(a) for a in pio.all_(nf, 5))
        # empty-string inputs are positional "absent optional" markers —
        # kept so mappers can interpret positions (e.g. Clip(x, min, max))
        nodes.append(IRNode(name=name, op_type=op_type, inputs=node_in,
                            outputs=node_out, attrs=attrs))
    return IRGraph(framework="onnx", nodes=nodes, initializers=initializers,
                   inputs=inputs, outputs=outputs)
