"""ONNX op -> registered-op mapping rules.

Reference: `nd4j/samediff-import/samediff-import-onnx/src/main/kotlin/org/nd4j/
samediff/frameworkimport/onnx/definitions/OnnxOpDeclarations.kt` (the
declarative per-op rules) — rebuilt here against jax-level registered ops.
"""
from __future__ import annotations

import numpy as np

from ..ir import IRNode, ImportContext, ImportException, mapper
from .parser import _np_dtype

ONNX = "onnx"


def _ins(node: IRNode, ctx: ImportContext):
    return [ctx.get(i) if i else None for i in node.inputs]


def _simple(onnx_name: str, op_name: str):
    @mapper(ONNX, onnx_name)
    def _m(node, ctx, _op=op_name):
        ctx.emit(_op, [ctx.get(i) for i in node.inputs if i],
                 node.outputs[0])
    return _m


for _ox, _op in [
    ("Add", "add"), ("Sub", "subtract"), ("Mul", "multiply"),
    ("Div", "divide"), ("Pow", "Pow"), ("Sqrt", "sqrt"), ("Exp", "exp"),
    ("Log", "log"), ("Tanh", "tanh"), ("Sigmoid", "sigmoid"),
    ("Relu", "relu"), ("Erf", "erf"), ("Neg", "neg"), ("Abs", "abs"),
    ("Floor", "floor"), ("Ceil", "ceil"), ("Round", "round"),
    ("Reciprocal", "reciprocal"), ("Sign", "sign"), ("Softplus", "softplus"),
    ("Softsign", "softsign"), ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
    ("Asin", "asin"), ("Acos", "acos"), ("Atan", "atan"), ("Sinh", "sinh"),
    ("Cosh", "cosh"), ("Asinh", "asinh"), ("Acosh", "acosh"),
    ("Atanh", "atanh"), ("Not", "boolean_not"), ("And", "boolean_and"),
    ("Or", "boolean_or"), ("Xor", "boolean_xor"),
    ("Equal", "equals"), ("Greater", "greater"),
    ("GreaterOrEqual", "greater_equal"), ("Less", "less"),
    ("LessOrEqual", "less_equal"), ("Max", "maximum"), ("Min", "minimum"),
    ("Mod", "mod"), ("Where", "select"), ("MatMul", "matmul"),
    ("Mish", "mish"), ("HardSwish", "hardswish"),
]:
    _simple(_ox, _op)

_simple("Sum", "mergeadd")
_simple("Mean", "mergeavg")


@mapper(ONNX, "Identity", "Dropout")
def _identity(node, ctx):
    # Dropout at inference = identity (mask output, if requested, unused)
    src = node.inputs[0]
    if src in ctx.const_np:
        ctx.const_np[node.outputs[0]] = ctx.const_np[src]
    else:
        ctx.bind(node.outputs[0], ctx.get(src), aval=ctx.aval(src))


@mapper(ONNX, "Constant")
def _constant(node, ctx):
    val = node.attrs.get("value")
    if val is None:
        if "value_float" in node.attrs:
            val = np.float32(node.attrs["value_float"])
        elif "value_int" in node.attrs:
            val = np.int64(node.attrs["value_int"])
        elif "value_floats" in node.attrs:
            val = np.asarray(node.attrs["value_floats"], np.float32)
        elif "value_ints" in node.attrs:
            val = np.asarray(node.attrs["value_ints"], np.int64)
        else:
            raise ImportException(f"Constant node {node.name!r} without value")
    ctx.const_np[node.outputs[0]] = np.asarray(val)


@mapper(ONNX, "ConstantOfShape")
def _const_of_shape(node, ctx):
    shape = [int(s) for s in np.asarray(ctx.const_value(node.inputs[0]))]
    val = node.attrs.get("value")
    fill = np.asarray(val).ravel()[0] if val is not None else np.float32(0)
    ctx.const_np[node.outputs[0]] = np.full(shape, fill)


@mapper(ONNX, "Gemm")
def _gemm(node, ctx):
    a, b = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    c = ctx.get(node.inputs[2]) if len(node.inputs) > 2 and node.inputs[2] \
        else None
    alpha = float(node.attrs.get("alpha", 1.0))
    beta = float(node.attrs.get("beta", 1.0))
    out = ctx.emit("matmul", [a, b], node.outputs[0] + "/mm",
                   transpose_a=bool(node.attrs.get("transA", 0)),
                   transpose_b=bool(node.attrs.get("transB", 0)),
                   alpha=alpha)
    if c is not None:
        scaled = ctx.sd._record("multiply", [c, ctx.sd.constant(
            np.float32(beta), node.name + "/beta")]) if beta != 1.0 else c
        ctx.emit("add", [out, scaled], node.outputs[0])
    else:
        ctx.bind(node.outputs[0], out)


@mapper(ONNX, "Conv")
def _conv(node, ctx):
    x = ctx.get(node.inputs[0])
    w_name = node.inputs[1]
    w_np = ctx.maybe_const(w_name)
    group = int(node.attrs.get("group", 1))
    strides = tuple(int(s) for s in node.attrs.get("strides", [1, 1]))
    dilations = tuple(int(d) for d in node.attrs.get("dilations", [1, 1]))
    pads = node.attrs.get("pads")
    auto_pad = node.attrs.get("auto_pad", "NOTSET")
    if pads is not None and any(int(p) for p in pads):
        n = len(pads) // 2
        padding = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    elif auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    else:
        padding = "VALID"
    if w_np is None:
        raise ImportException("Conv weights must be an initializer")
    if w_np.ndim != 4:
        raise ImportException("only 2-D Conv supported")
    # ONNX weights OIHW -> our HWIO
    if group == 1:
        w = ctx.sd.constant(np.transpose(w_np, (2, 3, 1, 0)),
                            w_name.replace(":", "_") + "_hwio")
        opn, kw = "conv2d", {}
    elif group == w_np.shape[0] and w_np.shape[1] == 1:
        # depthwise: OIHW [C*M,1,kh,kw] -> HWIO-style [kh,kw,C,M]
        c = group
        m = w_np.shape[0] // c
        w_d = np.transpose(
            w_np.reshape(c, m, 1, *w_np.shape[2:]), (3, 4, 0, 1))
        w = ctx.sd.constant(w_d, w_name.replace(":", "_") + "_dw")
        opn, kw = "depthwise_conv2d", {}
    else:
        # grouped conv: OIHW [O, In/g, kh, kw] -> HWIO [kh, kw, In/g, O],
        # lowered via lax feature_group_count (conv_ops.conv2d groups=)
        w = ctx.sd.constant(np.transpose(w_np, (2, 3, 1, 0)),
                            w_name.replace(":", "_") + "_hwio")
        opn, kw = "conv2d", {"groups": group}
    bias = ctx.get(node.inputs[2]) if len(node.inputs) > 2 and \
        node.inputs[2] else None
    ctx.emit(opn, [x, w, bias], node.outputs[0], strides=strides,
             padding=padding, dilation=dilations, data_format="NCHW", **kw)


@mapper(ONNX, "ConvTranspose")
def _conv_transpose(node, ctx):
    x = ctx.get(node.inputs[0])
    w_np = ctx.maybe_const(node.inputs[1])
    if w_np is None:
        raise ImportException("ConvTranspose weights must be an initializer")
    if w_np.ndim != 4:
        raise ImportException("only 2-D ConvTranspose supported")
    if int(node.attrs.get("group", 1)) != 1:
        raise ImportException("grouped ConvTranspose unsupported")
    if node.attrs.get("output_shape"):
        raise ImportException(
            "ConvTranspose output_shape attribute unsupported; express the "
            "crop via pads")
    if any(int(p) for p in node.attrs.get("output_padding", [])):
        raise ImportException("ConvTranspose output_padding unsupported")
    strides = tuple(int(s) for s in node.attrs.get("strides", [1, 1]))
    dil = tuple(int(d) for d in node.attrs.get("dilations", [1, 1]))
    pads = [int(p) for p in node.attrs.get("pads", [0, 0, 0, 0])]
    auto_pad = node.attrs.get("auto_pad", "NOTSET")
    if isinstance(auto_pad, bytes):
        auto_pad = auto_pad.decode()
    if auto_pad in ("SAME_UPPER", "SAME_LOWER") and not any(pads):
        # SAME: output = in*stride; crop the (dil*(k-1)+1-s) surplus,
        # extra on the end for SAME_UPPER, the start for SAME_LOWER
        kh, kw = w_np.shape[2], w_np.shape[3]
        tot = [max(dil[0] * (kh - 1) + 1 - strides[0], 0),
               max(dil[1] * (kw - 1) + 1 - strides[1], 0)]
        if auto_pad == "SAME_UPPER":
            pads = [tot[0] // 2, tot[1] // 2,
                    tot[0] - tot[0] // 2, tot[1] - tot[1] // 2]
        else:
            pads = [tot[0] - tot[0] // 2, tot[1] - tot[1] // 2,
                    tot[0] // 2, tot[1] // 2]
    # ONNX weights [Cin, Cout, kH, kW] -> deconv2d [kH, kW, outC, inC]
    w = ctx.sd.constant(np.transpose(w_np, (2, 3, 1, 0)),
                        node.inputs[1].replace(":", "_") + "_hwoi")
    bias = ctx.get(node.inputs[2]) if len(node.inputs) > 2 and \
        node.inputs[2] else None
    if not any(pads):
        ctx.emit("deconv2d", [x, w, bias], node.outputs[0],
                 strides=strides, padding="VALID", dilation=dil,
                 data_format="NCHW")
        return
    # ONNX pads CROP the full (VALID) transposed output:
    #   out = (in-1)*stride + dil*(k-1) + 1 - pad_begin - pad_end
    full = ctx.emit("deconv2d", [x, w, bias], f"{node.name}__full",
                    strides=strides, padding="VALID", dilation=dil,
                    data_format="NCHW")
    ax = ctx.aval(node.inputs[0])
    if ax is None or ax.shape[2] is None or ax.shape[3] is None:
        raise ImportException(
            "ConvTranspose with pads needs static spatial input dims to "
            "crop")
    ih, iw = ax.shape[2], ax.shape[3]
    kh, kw = w_np.shape[2], w_np.shape[3]
    hh = (ih - 1) * strides[0] + dil[0] * (kh - 1) + 1
    ww = (iw - 1) * strides[1] + dil[1] * (kw - 1) + 1
    # -1 = rest-of-dim: batch/channel stay symbolic-friendly
    ctx.emit("slice", [full], node.outputs[0],
             begin=(0, 0, pads[0], pads[1]),
             size=(-1, -1, hh - pads[0] - pads[2],
                   ww - pads[1] - pads[3]))


@mapper(ONNX, "MaxPool", "AveragePool")
def _pool(node, ctx):
    x = ctx.get(node.inputs[0])
    if int(node.attrs.get("ceil_mode", 0)):
        raise ImportException(f"{node.op_type} ceil_mode=1 unsupported "
                              "(floor-mode output grid only)")
    if any(int(d) != 1 for d in node.attrs.get("dilations", [])):
        raise ImportException(f"{node.op_type} with dilations unsupported")
    kernel = tuple(int(k) for k in node.attrs.get("kernel_shape", [2, 2]))
    strides = tuple(int(s) for s in node.attrs.get("strides", kernel))
    pads = node.attrs.get("pads")
    if pads is not None and any(int(p) for p in pads):
        n = len(pads) // 2
        padding = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    elif node.attrs.get("auto_pad", "NOTSET") in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    else:
        padding = "VALID"
    kw = {}
    if node.op_type == "AveragePool":
        # ONNX default count_include_pad=0: padded cells do NOT count in
        # the divisor (cross-checked against TF SAME avg-pool)
        kw["include_pad"] = bool(node.attrs.get("count_include_pad", 0))
    ctx.emit("maxpool2d" if node.op_type == "MaxPool" else "avgpool2d",
             [x], node.outputs[0], kernel=kernel, strides=strides,
             padding=padding, data_format="NCHW", **kw)


@mapper(ONNX, "GlobalAveragePool")
def _gap(node, ctx):
    x = ctx.get(node.inputs[0])
    a = ctx.aval(node.inputs[0])
    ndim = len(a.shape) if a is not None else 4
    ctx.emit("reduce_mean", [x], node.outputs[0],
             dims=tuple(range(2, ndim)), keep_dims=True)


@mapper(ONNX, "BatchNormalization")
def _bn(node, ctx):
    x, scale, b, mean, var = _ins(node, ctx)[:5]
    ctx.emit("batchnorm", [x, mean, var, scale, b], node.outputs[0],
             eps=float(node.attrs.get("epsilon", 1e-5)), axis=1)


@mapper(ONNX, "LayerNormalization")
def _ln(node, ctx):
    x, scale = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    b = ctx.get(node.inputs[2]) if len(node.inputs) > 2 and node.inputs[2] \
        else None
    ctx.emit("layer_norm", [x, scale, b], node.outputs[0],
             axis=int(node.attrs.get("axis", -1)),
             eps=float(node.attrs.get("epsilon", 1e-5)))


@mapper(ONNX, "Reshape")
def _reshape(node, ctx):
    x = ctx.get(node.inputs[0])
    shape = [int(s) for s in np.asarray(ctx.const_value(node.inputs[1]))]
    a = ctx.aval(node.inputs[0])
    if a is not None:  # ONNX: 0 means "copy input dim"
        shape = [a.shape[i] if s == 0 and i < len(a.shape) else s
                 for i, s in enumerate(shape)]
    ctx.emit("reshape", [x], node.outputs[0], shape=tuple(shape))


@mapper(ONNX, "Flatten")
def _flatten(node, ctx):
    x = ctx.get(node.inputs[0])
    ctx.emit("flatten_2d", [x], node.outputs[0],
             axis=int(node.attrs.get("axis", 1)))


@mapper(ONNX, "Transpose")
def _transpose(node, ctx):
    x = ctx.get(node.inputs[0])
    perm = node.attrs.get("perm")
    ctx.emit("transpose", [x], node.outputs[0],
             axes=tuple(int(p) for p in perm) if perm else None)


@mapper(ONNX, "Concat")
def _concat(node, ctx):
    ctx.emit("concat", [ctx.get(i) for i in node.inputs], node.outputs[0],
             axis=int(node.attrs.get("axis", 0)))


@mapper(ONNX, "Split")
def _split(node, ctx):
    x = ctx.get(node.inputs[0])
    axis = int(node.attrs.get("axis", 0))
    sizes = node.attrs.get("split")
    if sizes is None and len(node.inputs) > 1 and node.inputs[1]:
        sizes = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    if sizes is not None:
        ctx.emit_multi("split_v", [x], node.outputs,
                       sizes=[int(s) for s in sizes], axis=axis)
    else:
        ctx.emit_multi("split", [x], node.outputs, num=len(node.outputs),
                       axis=axis)


@mapper(ONNX, "Squeeze", "Unsqueeze")
def _squeeze(node, ctx):
    x = ctx.get(node.inputs[0])
    axes = node.attrs.get("axes")
    if axes is None and len(node.inputs) > 1 and node.inputs[1]:
        axes = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    if node.op_type == "Squeeze":
        ctx.emit("squeeze", [x], node.outputs[0],
                 axis=tuple(int(a) for a in axes) if axes else None)
    else:
        out = x
        for j, a in enumerate(sorted(int(a) for a in axes)):
            last = j == len(axes) - 1
            t = node.outputs[0] if last else f"{node.outputs[0]}/ed{j}"
            out = ctx.emit("expand_dims", [out], t, axis=a)


@mapper(ONNX, "Gather")
def _gather(node, ctx):
    params, indices = ctx.get(node.inputs[0]), ctx.get(node.inputs[1])
    ctx.emit("gather", [params, indices], node.outputs[0],
             axis=int(node.attrs.get("axis", 0)))


@mapper(ONNX, "Slice")
def _slice(node, ctx):
    x = ctx.get(node.inputs[0])
    if len(node.inputs) > 1:  # opset >= 10: starts/ends/axes/steps inputs
        starts = np.asarray(ctx.const_value(node.inputs[1])).tolist()
        ends = np.asarray(ctx.const_value(node.inputs[2])).tolist()
        axes = np.asarray(ctx.const_value(node.inputs[3])).tolist() \
            if len(node.inputs) > 3 and node.inputs[3] else \
            list(range(len(starts)))
        steps = np.asarray(ctx.const_value(node.inputs[4])).tolist() \
            if len(node.inputs) > 4 and node.inputs[4] else [1] * len(starts)
    else:  # opset 1: attributes
        starts = node.attrs["starts"]
        ends = node.attrs["ends"]
        axes = node.attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    a = ctx.aval(node.inputs[0])
    rank = len(a.shape) if a is not None else max(int(ax) for ax in axes) + 1
    spec = [("all",)] * rank
    intmax = 1 << 62
    for s, e, ax, st in zip(starts, ends, axes, steps):
        s, e, st = int(s), int(e), int(st)
        spec[int(ax)] = ("slice",
                         None if abs(s) >= intmax else s,
                         None if abs(e) >= intmax else e, st)
    ctx.emit("tf_strided_slice", [x], node.outputs[0], spec=spec)


@mapper(ONNX, "Softmax", "LogSoftmax")
def _softmax(node, ctx):
    x = ctx.get(node.inputs[0])
    ctx.emit("softmax" if node.op_type == "Softmax" else "log_softmax",
             [x], node.outputs[0], axis=int(node.attrs.get("axis", -1)))


@mapper(ONNX, "ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin",
        "ReduceProd")
def _reduce(node, ctx):
    op = {"ReduceMean": "reduce_mean", "ReduceSum": "reduce_sum",
          "ReduceMax": "reduce_max", "ReduceMin": "reduce_min",
          "ReduceProd": "reduce_prod"}[node.op_type]
    x = ctx.get(node.inputs[0])
    axes = node.attrs.get("axes")
    if axes is None and len(node.inputs) > 1 and node.inputs[1]:
        axes = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    ctx.emit(op, [x], node.outputs[0],
             dims=tuple(int(a) for a in axes) if axes else None,
             keep_dims=bool(node.attrs.get("keepdims", 1)))


@mapper(ONNX, "ArgMax", "ArgMin")
def _argminmax(node, ctx):
    x = ctx.get(node.inputs[0])
    ctx.emit("argmax" if node.op_type == "ArgMax" else "argmin",
             [x], node.outputs[0], dims=int(node.attrs.get("axis", 0)),
             keep_dims=bool(node.attrs.get("keepdims", 1)))


@mapper(ONNX, "Cast")
def _cast(node, ctx):
    to = _np_dtype(int(node.attrs.get("to", 1)))
    name = "bfloat16" if getattr(to, "__name__", "") == "bfloat16" \
        else np.dtype(to).name
    ctx.emit("cast", [ctx.get(node.inputs[0])], node.outputs[0], dtype=name)


@mapper(ONNX, "Clip")
def _clip(node, ctx):
    x = ctx.get(node.inputs[0])
    lo = node.attrs.get("min")
    hi = node.attrs.get("max")
    if lo is None and len(node.inputs) > 1 and node.inputs[1]:
        lo = float(np.asarray(ctx.const_value(node.inputs[1])))
    if hi is None and len(node.inputs) > 2 and node.inputs[2]:
        hi = float(np.asarray(ctx.const_value(node.inputs[2])))
    ctx.emit("clipbyvalue", [x], node.outputs[0],
             clip_min=-np.inf if lo is None else float(lo),
             clip_max=np.inf if hi is None else float(hi))


@mapper(ONNX, "LeakyRelu")
def _leaky(node, ctx):
    ctx.emit("leakyrelu", [ctx.get(node.inputs[0])], node.outputs[0],
             alpha=float(node.attrs.get("alpha", 0.01)))


@mapper(ONNX, "Elu")
def _elu(node, ctx):
    ctx.emit("elu", [ctx.get(node.inputs[0])], node.outputs[0])


@mapper(ONNX, "Selu")
def _selu(node, ctx):
    ctx.emit("selu", [ctx.get(node.inputs[0])], node.outputs[0])


@mapper(ONNX, "Gelu")
def _gelu(node, ctx):
    ctx.emit("gelu", [ctx.get(node.inputs[0])], node.outputs[0],
             approximate=node.attrs.get("approximate") == "tanh")


@mapper(ONNX, "Expand")
def _expand(node, ctx):
    x = ctx.get(node.inputs[0])
    shape = [int(s) for s in np.asarray(ctx.const_value(node.inputs[1]))]
    a = ctx.aval(node.inputs[0])
    if a is not None:
        # ONNX Expand uses numpy broadcasting: result dim = max(in, target)
        in_shape = (1,) * (len(shape) - len(a.shape)) + tuple(a.shape)
        shape = [max(i_, s) for i_, s in zip(in_shape, shape)]
    ctx.emit("broadcast_to", [x], node.outputs[0], shape=tuple(shape))


@mapper(ONNX, "Tile")
def _tile(node, ctx):
    x = ctx.get(node.inputs[0])
    reps = [int(r) for r in np.asarray(ctx.const_value(node.inputs[1]))]
    ctx.emit("tile", [x], node.outputs[0], reps=reps)


@mapper(ONNX, "Pad")
def _pad(node, ctx):
    x = ctx.get(node.inputs[0])
    pads = node.attrs.get("pads")
    if pads is None and len(node.inputs) > 1 and node.inputs[1]:
        pads = np.asarray(ctx.const_value(node.inputs[1])).tolist()
    n = len(pads) // 2
    paddings = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    mode = node.attrs.get("mode", "constant").upper()
    cval = 0.0
    if len(node.inputs) > 2 and node.inputs[2]:
        cval = float(np.asarray(ctx.const_value(node.inputs[2])))
    ctx.emit("pad", [x], node.outputs[0], paddings=paddings,
             mode="CONSTANT" if mode == "CONSTANT" else mode,
             constant_values=cval)


@mapper(ONNX, "Shape")
def _shape(node, ctx):
    a = ctx.aval(node.inputs[0])
    if a is None:
        raise ImportException(f"Shape({node.inputs[0]!r}) needs static shape")
    ctx.const_np[node.outputs[0]] = np.asarray(a.shape, np.int64)


@mapper(ONNX, "Einsum")
def _einsum(node, ctx):
    ctx.emit("einsum", [ctx.get(i) for i in node.inputs], node.outputs[0],
             equation=node.attrs.get("equation"))


@mapper(ONNX, "GRU")
def _gru(node, ctx):
    """ONNX GRU -> gru_onnx (reference gruCell semantics,
    `libnd4j/include/ops/declarable/headers/recurrent.h`). Both
    linear_before_reset conventions are supported (torch exports 1).

    Layout: X [T, B, In]; W [1, 3H, In] (z, r, h); R [1, 3H, H]; B [1, 6H].
    Outputs: Y [T, 1, B, H], Y_h [1, B, H]."""
    if node.attrs.get("direction", "forward") != "forward":
        raise ImportException("only forward ONNX GRU supported")
    for attr in ("activations", "activation_alpha", "activation_beta",
                 "clip"):
        if node.attrs.get(attr):
            raise ImportException(f"ONNX GRU attr {attr!r} not supported")
    if int(node.attrs.get("layout", 0)) != 0:
        raise ImportException("ONNX GRU layout=1 (batch-major) not "
                              "supported; export with layout=0")
    if len(node.inputs) > 4 and node.inputs[4]:
        raise ImportException("ONNX GRU sequence_lens not supported")
    H = int(node.attrs["hidden_size"])
    lbr = int(node.attrs.get("linear_before_reset", 0))
    w_np = ctx.const_value(node.inputs[1])[0]     # [3H, In]
    r_np = ctx.const_value(node.inputs[2])[0]     # [3H, H]
    b_np = ctx.const_value(node.inputs[3])[0] if len(node.inputs) > 3 and \
        node.inputs[3] else np.zeros(6 * H, np.float32)
    h0 = None
    if len(node.inputs) > 5 and node.inputs[5]:   # initial_h [1, B, H]
        h0 = ctx.sd._record("squeeze", [ctx.get(node.inputs[5])], axis=0)
    w = ctx.sd.constant(w_np, node.name + "_w")
    r = ctx.sd.constant(r_np, node.name + "_r")
    b = ctx.sd.constant(b_np, node.name + "_b")
    x = ctx.get(node.inputs[0])
    gru_in = [x, w, r, b]
    if h0 is not None:
        gru_in.append(h0)
    h_seq, h_last = ctx.sd._record(
        "gru_onnx", gru_in, n_outputs=2,
        out_name=node.name.replace(":", "_"), linear_before_reset=lbr,
        time_major=True)
    outs = node.outputs
    if len(outs) > 0 and outs[0]:
        ctx.emit("expand_dims", [h_seq], outs[0], axis=1)
    if len(outs) > 1 and outs[1]:
        ctx.emit("expand_dims", [h_last], outs[1], axis=0)


@mapper(ONNX, "LSTM")
def _lstm(node, ctx):
    """ONNX LSTM -> lstmLayer. ONNX gate order is [i, o, f, c]; the
    registered op uses [i, f, g(c), o] — weight blocks are reordered at
    import (weights must be initializers, as exported models' are).

    Layout: X [T, B, In]; W [1, 4H, In]; R [1, 4H, H]; B [1, 8H].
    Outputs: Y [T, 1, B, H], Y_h [1, B, H], Y_c [1, B, H]."""
    if node.attrs.get("direction", "forward") != "forward":
        raise ImportException("only forward ONNX LSTM supported")
    for attr in ("activations", "activation_alpha", "activation_beta",
                 "clip", "input_forget"):
        if node.attrs.get(attr):
            raise ImportException(f"ONNX LSTM attr {attr!r} not supported")
    if int(node.attrs.get("layout", 0)) != 0:
        raise ImportException("ONNX LSTM layout=1 (batch-major) not "
                              "supported; export with layout=0")
    if len(node.inputs) > 4 and node.inputs[4]:
        raise ImportException("ONNX LSTM sequence_lens not supported")
    if len(node.inputs) > 7 and node.inputs[7]:
        raise ImportException("ONNX LSTM peepholes (P) not supported")
    H = int(node.attrs["hidden_size"])
    w_np = ctx.const_value(node.inputs[1])[0]     # [4H, In]
    r_np = ctx.const_value(node.inputs[2])[0]     # [4H, H]
    b_np = ctx.const_value(node.inputs[3])[0] if len(node.inputs) > 3 and \
        node.inputs[3] else np.zeros(8 * H, np.float32)
    h0 = c0 = None
    if len(node.inputs) > 5 and node.inputs[5]:   # initial_h [1, B, H]
        h0 = ctx.sd._record("squeeze", [ctx.get(node.inputs[5])], axis=0)
    if len(node.inputs) > 6 and node.inputs[6]:   # initial_c
        c0 = ctx.sd._record("squeeze", [ctx.get(node.inputs[6])], axis=0)

    def reorder(m):  # [4H, ...] blocks [i,o,f,c] -> [i,f,c,o]
        i, o, f, c = np.split(m, 4, axis=0)
        return np.concatenate([i, f, c, o], axis=0)

    wx = ctx.sd.constant(reorder(w_np).T, node.name + "_wx")   # [In, 4H]
    wh = ctx.sd.constant(reorder(r_np).T, node.name + "_wh")   # [H, 4H]
    bias = ctx.sd.constant(
        reorder((b_np[:4 * H] + b_np[4 * H:]).reshape(4, H)).reshape(-1),
        node.name + "_b")
    x = ctx.get(node.inputs[0])
    lstm_in = [x, wx, wh, bias]
    if h0 is not None or c0 is not None:
        lstm_in += [h0, c0]
    h_seq, h_last, c_last = ctx.sd._record(
        "lstmLayer", lstm_in, n_outputs=3,
        out_name=node.name.replace(":", "_"), time_major=True)
    # ONNX inserts a num_directions axis
    outs = node.outputs
    if len(outs) > 0 and outs[0]:
        ctx.emit("expand_dims", [h_seq], outs[0], axis=1)
    if len(outs) > 1 and outs[1]:
        ctx.emit("expand_dims", [h_last], outs[1], axis=0)
    if len(outs) > 2 and outs[2]:
        ctx.emit("expand_dims", [c_last], outs[2], axis=0)
