"""ONNX ModelProto -> SameDiff importer.

Reference: `nd4j/samediff-import/samediff-import-onnx/.../
OnnxFrameworkImporter.kt` over `ImportGraph.kt:218`.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...autodiff.samediff import SameDiff
from ..ir import ImportContext, ImportException, get_mapper
from ..tf.importer import ImportedGraph, _toposort
from . import mappings  # noqa: F401 — registers the mapping rules
from . import mappings_extra  # noqa: F401 — long-tail ruleset coverage
from .parser import parse_model


class OnnxImporter:
    """Import an ONNX model (.onnx file or bytes)."""

    def __init__(self, model, input_shapes: Optional[Dict[str, Tuple]] = None):
        if isinstance(model, (str, os.PathLike)):
            with open(model, "rb") as f:
                model = f.read()
        self.graph = parse_model(model, input_shapes=input_shapes)

    def import_graph(self, sd: Optional[SameDiff] = None,
                     import_weights_as_variables: bool = False
                     ) -> ImportedGraph:
        g = self.graph
        unmapped = sorted({n.op_type for n in g.nodes
                           if get_mapper("onnx", n.op_type) is None})
        if unmapped:
            from ..ir import unmapped_error
            raise unmapped_error("onnx", unmapped)
        ctx = ImportContext(g, sd, import_weights_as_variables)
        inputs = {}
        for name, (shape, dtype) in g.inputs.items():
            if shape is None or any(s is None for s in shape):
                raise ImportException(
                    f"ONNX input {name!r} has dynamic shape {shape}; pass "
                    f"concrete input_shapes")
            v = ctx.sd.placeholder(name.replace(":", "_"), shape=shape,
                                   dtype=dtype)
            ctx.bind(name, v)
            inputs[name] = v.name

        known = set(g.initializers) | set(g.inputs)
        for node in _toposort(g.nodes, known):
            get_mapper("onnx", node.op_type)(node, ctx)

        outputs = {}
        for t in g.outputs:
            if t in ctx.vars or t in ctx.const_np:
                outputs[t] = ctx.get(t).name
        return ImportedGraph(ctx.sd, ctx, inputs, outputs)


def import_onnx_model(model, input_shapes=None,
                      import_weights_as_variables: bool = False
                      ) -> ImportedGraph:
    return OnnxImporter(model, input_shapes).import_graph(
        import_weights_as_variables=import_weights_as_variables)
