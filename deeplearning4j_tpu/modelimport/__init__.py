"""Model import: foreign-framework graphs/models -> SameDiff / DL4J nets.

Reference: `nd4j/samediff-import/` (Kotlin IR + declarative mapping rules,
`ImportGraph.kt:68,218`), `deeplearning4j/deeplearning4j-modelimport/`
(Keras h5, `KerasModel.java:639`), and the legacy `org/nd4j/imports/`
`TFGraphMapper` (901 lines).

TPU-native redesign: the reference maps foreign ops onto its own op
descriptors via protobuf IR (`org/nd4j/ir`). Here every foreign node maps
onto a registered op in `ops.registry` (a pure jax function), so an
imported graph *is* a SameDiff graph and compiles whole-program under jit
like any native graph. Parsing uses a self-contained protobuf wire-format
decoder (`protoio.py`) — no tensorflow/onnx runtime dependency.
"""
from .ir import IRGraph, IRNode, ImportContext, ImportException
from .tf.importer import TFGraphImporter, import_tf_graph
from .onnx.importer import OnnxImporter, import_onnx_model
from .keras.importer import (KerasModelImport, import_keras_model_and_weights,
                             import_keras_sequential_model_and_weights)

__all__ = [
    "IRGraph", "IRNode", "ImportContext", "ImportException",
    "TFGraphImporter", "import_tf_graph",
    "OnnxImporter", "import_onnx_model",
    "KerasModelImport", "import_keras_model_and_weights",
    "import_keras_sequential_model_and_weights",
]
