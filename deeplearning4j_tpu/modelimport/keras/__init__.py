from .importer import (KerasModelImport, import_keras_model_and_weights,
                       import_keras_sequential_model_and_weights,
                       register_lambda)

__all__ = ["KerasModelImport", "import_keras_model_and_weights",
           "import_keras_sequential_model_and_weights", "register_lambda"]
