"""Keras HDF5 model import -> MultiLayerNetwork / ComputationGraph.

Reference: `deeplearning4j/deeplearning4j-modelimport/src/main/java/org/
deeplearning4j/nn/modelimport/keras/KerasModelImport.java:45-151` (entry
points), `KerasModel.java:639` (getComputationGraph),
`KerasSequentialModel.java` (-> MultiLayerNetwork), and the 62 layer
adapters under `keras/layers/**`.

Handles both Keras 2 and Keras 3 legacy-h5 flavors (model_config JSON +
model_weights groups). Data-format note: Keras is channels-last (NHWC);
this framework's conv stack is NCHW like the reference DL4J — the importer
converts kernels (HWIO is shared) and reorders Flatten->Dense kernels from
(h,w,c) to (c,h,w) row order, the same fixup the reference applies via
KerasFlatten's preprocessor.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...nn.conf import layers as L
from ...nn.conf import layers_extra as LX
from ...nn.conf.config import (InputType, MultiLayerConfiguration,
                               NeuralNetConfiguration)
from ...nn.graph.computation_graph import ComputationGraph
from ...nn.graph.vertices import ElementWiseVertex, MergeVertex
from ...nn.multilayer import MultiLayerNetwork
from ..ir import ImportException

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "softmax": "softmax", "sigmoid": "sigmoid", "tanh": "tanh",
    "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid",
    "swish": "swish", "silu": "swish", "gelu": "gelu", "mish": "mish",
    "exponential": "exp", "leaky_relu": "leakyrelu",
}


def _act(name) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):  # serialized Activation object
        name = name.get("config", {}).get("name", "linear")
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ImportException(f"unsupported Keras activation {name!r}")


def _pair(v):
    return tuple(int(x) for x in v) if isinstance(v, (list, tuple)) \
        else (int(v), int(v))


def _keras_shape_to_input_type(shape) -> Optional[Tuple[int, ...]]:
    """Keras shape (no batch) -> InputType tuple. NHWC -> (C,H,W);
    [T, F] -> (F, T); [F] -> (F,)."""
    if shape is None:
        return None
    dims = [d for d in shape]
    if len(dims) == 4:
        d, h, w, c = dims
        return InputType.convolutional3d(d, h, w, c)
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(f, t if t is not None else -1)
    if len(dims) == 1:
        return InputType.feed_forward(dims[0]) if dims[0] else None
    return None


class _Adapted:
    """One imported layer: our config + a weight-mapping function."""

    def __init__(self, layer: Optional[L.Layer],
                 set_weights: Optional[Callable] = None):
        self.layer = layer
        self.set_weights = set_weights  # (weights, in_type) -> params dict


def _dense_adapter(cfg, keras_in_shape):
    units = int(cfg["units"])
    use_bias = bool(cfg.get("use_bias", True))
    layer = L.DenseLayer(n_out=units, activation=_act(cfg.get("activation")),
                         has_bias=use_bias, name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel = np.asarray(weights[0])
        # Flatten-after-conv fixup: Keras flattens (..., c) channels-last,
        # ours (c, ...) channels-first (2-D and 3-D conv activations)
        if keras_in_shape is not None and len(keras_in_shape) in (3, 4) and \
                kernel.shape[0] == int(np.prod(keras_in_shape)):
            nd = len(keras_in_shape)
            k = kernel.reshape(*keras_in_shape, units)
            kernel = np.moveaxis(k, nd - 1, 0).reshape(-1, units)
        p = {"W": jnp.asarray(kernel)}
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _conv2d_adapter(cfg, depthwise=False):
    strides = _pair(cfg.get("strides", (1, 1)))
    dilation = _pair(cfg.get("dilation_rate", (1, 1)))
    padding = "SAME" if cfg.get("padding", "valid") == "same" else "VALID"
    use_bias = bool(cfg.get("use_bias", True))
    act = _act(cfg.get("activation"))
    if depthwise:
        mult = int(cfg.get("depth_multiplier", 1))
        layer = L.DepthwiseConvolution2D(
            n_out=0, depth_multiplier=mult,
            kernel_size=_pair(cfg["kernel_size"]), stride=strides,
            padding=padding, dilation=dilation, activation=act,
            has_bias=use_bias, name=cfg.get("name"))
    else:
        layer = L.ConvolutionLayer(
            n_out=int(cfg["filters"]), kernel_size=_pair(cfg["kernel_size"]),
            stride=strides, padding=padding, dilation=dilation,
            activation=act, has_bias=use_bias, name=cfg.get("name"))

    def set_weights(weights, in_type):
        p = {"W": jnp.asarray(np.asarray(weights[0]))}  # HWIO both sides
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _pool2d_adapter(cfg, pool_type):
    pool = _pair(cfg.get("pool_size", (2, 2)))
    strides = _pair(cfg.get("strides") or cfg.get("pool_size", (2, 2)))
    padding = "SAME" if cfg.get("padding", "valid") == "same" else "VALID"
    return _Adapted(L.SubsamplingLayer(
        pooling_type=pool_type, kernel_size=pool, stride=strides,
        padding=padding, avg_include_pad=False,  # keras/TF semantics
        name=cfg.get("name")))


def _bn_adapter(cfg):
    scale = bool(cfg.get("scale", True))
    center = bool(cfg.get("center", True))
    layer = L.BatchNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                                 decay=float(cfg.get("momentum", 0.99)),
                                 use_gamma_beta=True, name=cfg.get("name"))

    def set_weights(weights, in_type):
        w = [np.asarray(a) for a in weights]
        i = 0
        gamma = w[i] if scale else None
        i += 1 if scale else 0
        beta = w[i] if center else None
        i += 1 if center else 0
        mean, var = w[i], w[i + 1]
        c = mean.shape[0]
        return {"gamma": jnp.asarray(gamma if gamma is not None
                                     else np.ones(c, np.float32)),
                "beta": jnp.asarray(beta if beta is not None
                                    else np.zeros(c, np.float32)),
                "state_mean": jnp.asarray(mean),
                "state_var": jnp.asarray(var)}

    return _Adapted(layer, set_weights)


def _embedding_adapter(cfg):
    layer = L.EmbeddingSequenceLayer(n_in=int(cfg["input_dim"]),
                                     n_out=int(cfg["output_dim"]),
                                     name=cfg.get("name"))

    def set_weights(weights, in_type):
        return {"W": jnp.asarray(np.asarray(weights[0]))}

    return _Adapted(layer, set_weights)


def _lstm_adapter(cfg):
    units = int(cfg["units"])
    if _act(cfg.get("activation", "tanh")) != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        # only the tanh/sigmoid kernel exists (nn/conf/layers.py LSTM ->
        # recurrent.lstm_layer); importing anything else would silently
        # compute different outputs
        raise ImportException(
            f"Keras LSTM with activation={cfg.get('activation')!r} / "
            f"recurrent_activation={cfg.get('recurrent_activation')!r} is "
            f"not supported (only tanh/sigmoid)")
    layer = L.LSTM(n_out=units, activation="tanh",
                   return_sequence=bool(cfg.get("return_sequences", False)),
                   name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel, rec, bias = [np.asarray(a) for a in weights[:3]]
        # Keras gate order [i, f, c, o] == ours — direct copy
        return {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec),
                "b": jnp.asarray(bias)}

    return _Adapted(layer, set_weights)


def _gru_adapter(cfg):
    """Keras GRU: gate columns (z, r, h). reset_after=True (the default,
    CuDNN convention) maps to GRUResetAfter / the gru_onnx kernel;
    reset_after=False maps to the fused-gate GRU layer."""
    units = int(cfg["units"])
    if _act(cfg.get("activation", "tanh")) != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise ImportException("Keras GRU with non-default activations is "
                              "not supported")
    reset_after = bool(cfg.get("reset_after", True))
    ret_seq = bool(cfg.get("return_sequences", False))
    if reset_after:
        inner = LX.GRUResetAfter(n_out=units, name=cfg.get("name"))
    else:
        inner = LX.GRU(n_out=units, name=cfg.get("name"))
    layer = inner if ret_seq else LX.LastTimeStep(underlying=inner,
                                                  name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel, rec = np.asarray(weights[0]), np.asarray(weights[1])
        bias = np.asarray(weights[2]) if len(weights) > 2 else None
        H = units
        if reset_after:
            w = kernel.T                      # [3H, In], rows z|r|h
            r = rec.T
            if bias is None:
                b = np.zeros(6 * H, np.float32)
            elif bias.ndim == 2:              # [2, 3H]: input + recurrent
                b = np.concatenate([bias[0], bias[1]])
            else:
                b = np.concatenate([bias, np.zeros(3 * H, bias.dtype)])
            return {"W": jnp.asarray(w), "R": jnp.asarray(r),
                    "b": jnp.asarray(b)}
        kz, kr, kh = np.split(kernel, 3, axis=1)
        rz, rr, rh = np.split(rec, 3, axis=1)
        if bias is None:
            bias = np.zeros(3 * H, np.float32)
        bz, br, bh = np.split(bias.reshape(-1)[:3 * H], 3)
        w_ru = np.concatenate([np.concatenate([kr, kz], 1),
                               np.concatenate([rr, rz], 1)], 0)
        w_c = np.concatenate([kh, rh], 0)
        return {"Wru": jnp.asarray(w_ru), "Wc": jnp.asarray(w_c),
                "bru": jnp.asarray(np.concatenate([br, bz])),
                "bc": jnp.asarray(bh)}

    return _Adapted(layer, set_weights)


def _bidirectional_adapter(cfg):
    inner_spec = cfg.get("layer", {})
    inner_cls = inner_spec.get("class_name")
    inner_cfg = dict(inner_spec.get("config", {}))
    mode = {"concat": "concat", "sum": "add", "mul": "mul",
            "ave": "ave", None: "concat"}.get(cfg.get("merge_mode",
                                                      "concat"))
    if mode is None:
        raise ImportException(
            f"Bidirectional merge_mode={cfg.get('merge_mode')!r} "
            f"unsupported")
    inner = _adapt_layer(inner_cls, inner_cfg, None)
    layer = L.Bidirectional(fwd=inner.layer, mode=mode,
                            name=cfg.get("name"))

    def set_weights(weights, in_type):
        half = len(weights) // 2
        return {"fwd": inner.set_weights(weights[:half], in_type),
                "bwd": inner.set_weights(weights[half:], in_type)}

    return _Adapted(layer, set_weights)


def _time_distributed_adapter(cfg):
    inner_spec = cfg.get("layer", {})
    if inner_spec.get("class_name") != "Dense":
        raise ImportException("TimeDistributed only supports Dense "
                              f"(got {inner_spec.get('class_name')!r})")
    inner = _dense_adapter(dict(inner_spec.get("config", {})), None)
    layer = LX.TimeDistributed(underlying=inner.layer, name=cfg.get("name"))
    return _Adapted(layer, inner.set_weights)


def _conv1d_adapter(cfg):
    pad = cfg.get("padding", "valid")
    layer = L.Convolution1DLayer(
        n_out=int(cfg["filters"]), kernel_size=int(_pair(cfg["kernel_size"])[0]),
        stride=int(_pair(cfg.get("strides", 1))[0]),
        dilation=int(_pair(cfg.get("dilation_rate", 1))[0]),
        padding={"same": "SAME", "causal": "CAUSAL"}.get(pad, "VALID"),
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)), name=cfg.get("name"))

    def set_weights(weights, in_type):
        p = {"W": jnp.asarray(np.asarray(weights[0]))}  # [k, in, out] shared
        if cfg.get("use_bias", True):
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _conv3d_adapter(cfg):
    layer = LX.Convolution3D(
        n_out=int(cfg["filters"]),
        kernel_size=tuple(int(k) for k in cfg["kernel_size"]),
        stride=tuple(int(s) for s in cfg.get("strides", (1, 1, 1))),
        padding="SAME" if cfg.get("padding", "valid") == "same" else "VALID",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)), name=cfg.get("name"))

    def set_weights(weights, in_type):
        p = {"W": jnp.asarray(np.asarray(weights[0]))}  # DHWIO both sides
        if cfg.get("use_bias", True):
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _separable_conv2d_adapter(cfg):
    use_bias = bool(cfg.get("use_bias", True))
    layer = L.SeparableConvolution2D(
        n_out=int(cfg["filters"]), kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", (1, 1))),
        padding="SAME" if cfg.get("padding", "valid") == "same" else "VALID",
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        activation=_act(cfg.get("activation")), has_bias=use_bias,
        name=cfg.get("name"))

    def set_weights(weights, in_type):
        p = {"Wd": jnp.asarray(np.asarray(weights[0])),
             "Wp": jnp.asarray(np.asarray(weights[1]))}
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[2]))
        return p

    return _Adapted(layer, set_weights)


def _conv2d_transpose_adapter(cfg):
    use_bias = bool(cfg.get("use_bias", True))
    layer = L.DeconvolutionLayer(
        n_out=int(cfg["filters"]), kernel_size=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", (1, 1))),
        padding="SAME" if cfg.get("padding", "valid") == "same" else "VALID",
        activation=_act(cfg.get("activation")), has_bias=use_bias,
        name=cfg.get("name"))

    def set_weights(weights, in_type):
        # keras kernel is [kh, kw, out, in] — ours too
        p = {"W": jnp.asarray(np.asarray(weights[0]))}
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _locally_connected2d_adapter(cfg):
    if int(cfg.get("implementation", 1)) not in (1, 2, 3):
        raise ImportException("unknown LocallyConnected2D implementation")
    use_bias = bool(cfg.get("use_bias", True))
    kh, kw = _pair(cfg["kernel_size"])
    layer = LX.LocallyConnected2D(
        n_out=int(cfg["filters"]), kernel_size=(kh, kw),
        stride=_pair(cfg.get("strides", (1, 1))),
        activation=_act(cfg.get("activation")), has_bias=use_bias,
        name=cfg.get("name"))

    def set_weights(weights, in_type):
        k = np.asarray(weights[0])        # [P, kh*kw*in, out] (keras order)
        P, _, out = k.shape
        c = k.shape[1] // (kh * kw)
        # keras flattens patches (kh, kw, c); ours are channel-major (c,kh,kw)
        k = k.reshape(P, kh, kw, c, out).transpose(0, 3, 1, 2, 4) \
            .reshape(P, c * kh * kw, out)
        p = {"W": jnp.asarray(k)}
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]).reshape(P, out))
        return p

    return _Adapted(layer, set_weights)


def _prelu_adapter(cfg):
    layer = LX.PReLULayer(name=cfg.get("name"))

    def set_weights(weights, in_type):
        alpha = np.asarray(weights[0])
        if alpha.ndim > 1 and alpha.size == alpha.shape[-1]:
            alpha = alpha.reshape(-1)       # shared over all but channels
        elif alpha.ndim > 1:
            # per-position alpha: keras holds it channels-last (the
            # batchless input shape); our activations are channels-first
            alpha = np.moveaxis(alpha, -1, 0)
        return {"alpha": jnp.asarray(alpha)}

    return _Adapted(layer, set_weights)


def _layer_norm_adapter(cfg):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        if len(axis) != 1:
            raise ImportException("multi-axis LayerNormalization "
                                  "unsupported")
        axis = axis[0]
    if int(axis) not in (-1,):
        raise ImportException("only axis=-1 LayerNormalization supported")
    layer = LX.LayerNormalizationLayer(eps=float(cfg.get("epsilon", 1e-3)),
                                       name=cfg.get("name"))

    def set_weights(weights, in_type):
        ws = [np.asarray(a) for a in weights]
        if bool(cfg.get("scale", True)):
            gamma, rest = ws[0], ws[1:]
        else:
            gamma, rest = np.ones(ws[0].shape[0], np.float32), ws
        beta = rest[0] if rest and bool(cfg.get("center", True)) \
            else np.zeros(gamma.shape[0], np.float32)
        return {"gamma": jnp.asarray(gamma), "beta": jnp.asarray(beta)}

    return _Adapted(layer, set_weights)


def _cropping_tuple(val, n):
    """Keras cropping/padding spec -> flat per-side tuple of length 2n."""
    if isinstance(val, int):
        return (val, val) * n
    val = list(val)
    if all(isinstance(v, int) for v in val):
        if len(val) == n:          # symmetric per-dim
            return tuple(x for v in val for x in (v, v))
        return tuple(int(v) for v in val)  # already per-side (1-D case)
    return tuple(int(x) for pair in val for x in pair)


def _simple_rnn_adapter(cfg):
    units = int(cfg["units"])
    inner = L.SimpleRnn(n_out=units,
                        activation=_act(cfg.get("activation", "tanh")),
                        name=cfg.get("name"))
    # keras return_sequences=False -> last timestep only (same wrapping the
    # GRU adapter applies)
    layer = inner if bool(cfg.get("return_sequences", False)) \
        else LX.LastTimeStep(underlying=inner, name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel, rec, bias = [np.asarray(a) for a in weights[:3]]
        return {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec),
                "b": jnp.asarray(bias)}

    return _Adapted(layer, set_weights)


def _adapt_layer(class_name: str, cfg: Dict[str, Any],
                 keras_in_shape) -> Optional[_Adapted]:
    """One Keras layer -> framework layer + weight mapper.

    Returns None for layers that vanish (InputLayer, Flatten — handled by
    automatic preprocessors like the reference's KerasFlatten)."""
    if class_name in ("InputLayer", "Flatten"):
        return None
    if class_name == "Dense":
        return _dense_adapter(cfg, keras_in_shape)
    if class_name == "Conv2D":
        return _conv2d_adapter(cfg)
    if class_name == "DepthwiseConv2D":
        return _conv2d_adapter(cfg, depthwise=True)
    if class_name == "MaxPooling2D":
        return _pool2d_adapter(cfg, "max")
    if class_name == "AveragePooling2D":
        return _pool2d_adapter(cfg, "avg")
    if class_name == "GlobalAveragePooling2D":
        return _Adapted(L.GlobalPoolingLayer(pooling_type="avg",
                                             name=cfg.get("name")))
    if class_name == "GlobalMaxPooling2D":
        return _Adapted(L.GlobalPoolingLayer(pooling_type="max",
                                             name=cfg.get("name")))
    if class_name == "BatchNormalization":
        return _bn_adapter(cfg)
    if class_name == "Dropout":
        return _Adapted(L.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                                       name=cfg.get("name")))
    if class_name == "Activation":
        return _Adapted(L.ActivationLayer(
            activation=_act(cfg.get("activation")), name=cfg.get("name")))
    if class_name == "LeakyReLU":
        return _Adapted(L.ActivationLayer(activation="leakyrelu",
                                          name=cfg.get("name")))
    if class_name == "ReLU":
        return _Adapted(L.ActivationLayer(activation="relu",
                                          name=cfg.get("name")))
    if class_name == "ELU":
        return _Adapted(L.ActivationLayer(activation="elu",
                                          name=cfg.get("name")))
    if class_name == "Embedding":
        return _embedding_adapter(cfg)
    if class_name == "LSTM":
        return _lstm_adapter(cfg)
    if class_name == "SimpleRNN":
        return _simple_rnn_adapter(cfg)
    if class_name == "ZeroPadding2D":
        padding = _cropping_tuple(cfg.get("padding", (1, 1)), 2)
        return _Adapted(L.ZeroPaddingLayer(padding=padding,
                                           name=cfg.get("name")))
    if class_name == "GRU":
        return _gru_adapter(cfg)
    if class_name == "Bidirectional":
        return _bidirectional_adapter(cfg)
    if class_name == "TimeDistributed":
        return _time_distributed_adapter(cfg)
    if class_name == "Conv1D":
        return _conv1d_adapter(cfg)
    if class_name == "Conv3D":
        return _conv3d_adapter(cfg)
    if class_name == "SeparableConv2D":
        return _separable_conv2d_adapter(cfg)
    if class_name == "Conv2DTranspose":
        return _conv2d_transpose_adapter(cfg)
    if class_name in ("LocallyConnected2D",):
        return _locally_connected2d_adapter(cfg)
    if class_name == "PReLU":
        return _prelu_adapter(cfg)
    if class_name == "LayerNormalization":
        return _layer_norm_adapter(cfg)
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        if cfg.get("padding", "valid") == "same":
            raise ImportException(f"{class_name} padding='same' unsupported")
        pool = cfg.get("pool_size", 2)
        pool = int(pool[0]) if isinstance(pool, (list, tuple)) else int(pool)
        st = cfg.get("strides") or pool
        st = int(st[0]) if isinstance(st, (list, tuple)) else int(st)
        return _Adapted(LX.Subsampling1DLayer(
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=pool, stride=st, name=cfg.get("name")))
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        return _Adapted(LX.Subsampling3DLayer(
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=tuple(int(k) for k in cfg.get("pool_size",
                                                      (2, 2, 2))),
            stride=tuple(int(s) for s in (cfg.get("strides") or
                                          cfg.get("pool_size", (2, 2, 2)))),
            padding="SAME" if cfg.get("padding", "valid") == "same"
            else "VALID", avg_include_pad=False,  # keras/TF semantics
            name=cfg.get("name")))
    if class_name in ("GlobalAveragePooling1D", "GlobalAveragePooling3D"):
        return _Adapted(L.GlobalPoolingLayer(pooling_type="avg",
                                             name=cfg.get("name")))
    if class_name in ("GlobalMaxPooling1D", "GlobalMaxPooling3D"):
        return _Adapted(L.GlobalPoolingLayer(pooling_type="max",
                                             name=cfg.get("name")))
    if class_name == "UpSampling1D":
        return _Adapted(LX.Upsampling1D(size=int(cfg.get("size", 2)),
                                        name=cfg.get("name")))
    if class_name == "UpSampling2D":
        if cfg.get("interpolation", "nearest") != "nearest":
            raise ImportException("UpSampling2D interpolation must be "
                                  "'nearest'")
        return _Adapted(L.Upsampling2D(size=_pair(cfg.get("size", (2, 2))),
                                       name=cfg.get("name")))
    if class_name == "UpSampling3D":
        return _Adapted(LX.Upsampling3D(
            size=tuple(int(s) for s in cfg.get("size", (2, 2, 2))),
            name=cfg.get("name")))
    if class_name == "Cropping1D":
        return _Adapted(LX.Cropping1D(
            cropping=_cropping_tuple(cfg.get("cropping", (1, 1)), 1),
            name=cfg.get("name")))
    if class_name == "Cropping2D":
        return _Adapted(LX.Cropping2D(
            cropping=_cropping_tuple(cfg.get("cropping", (1, 1)), 2),
            name=cfg.get("name")))
    if class_name == "Cropping3D":
        return _Adapted(LX.Cropping3D(
            cropping=_cropping_tuple(cfg.get("cropping", (1, 1, 1)), 3),
            name=cfg.get("name")))
    if class_name == "ZeroPadding1D":
        return _Adapted(LX.ZeroPadding1DLayer(
            padding=_cropping_tuple(cfg.get("padding", (1, 1)), 1),
            name=cfg.get("name")))
    if class_name == "ZeroPadding3D":
        return _Adapted(LX.ZeroPadding3DLayer(
            padding=_cropping_tuple(cfg.get("padding", (1, 1, 1)), 3),
            name=cfg.get("name")))
    if class_name in ("SpatialDropout1D", "SpatialDropout2D",
                      "SpatialDropout3D"):
        return _Adapted(LX.SpatialDropout(rate=float(cfg.get("rate", 0.5)),
                                          name=cfg.get("name")))
    if class_name == "GaussianDropout":
        return _Adapted(LX.GaussianDropout(rate=float(cfg.get("rate", 0.5)),
                                           name=cfg.get("name")))
    if class_name == "GaussianNoise":
        return _Adapted(LX.GaussianNoise(stddev=float(cfg.get("stddev",
                                                              0.1)),
                                         name=cfg.get("name")))
    if class_name == "AlphaDropout":
        return _Adapted(LX.AlphaDropout(rate=float(cfg.get("rate", 0.5)),
                                        name=cfg.get("name")))
    if class_name == "RepeatVector":
        return _Adapted(LX.RepeatVector(n=int(cfg.get("n", 1)),
                                        name=cfg.get("name")))
    if class_name == "Softmax":
        return _Adapted(L.ActivationLayer(activation="softmax",
                                          name=cfg.get("name")))
    if class_name == "Permute":
        # Keras dims are 1-indexed over feature dims and stated in the
        # NHWC-style layout; applied on our NCHW-ordered activations the
        # same index permutation holds for the 3-D (RNN/2-D) cases we map
        return _Adapted(LX.PermuteLayer(
            dims=tuple(int(d) for d in cfg.get("dims", (1,))),
            name=cfg.get("name")))
    if class_name == "Reshape":
        target = _resolve_reshape(cfg.get("target_shape", ()),
                                  keras_in_shape)
        if len(target) == 3:
            h, w, c = target
            if h == 1 and w == 1:
                # keras (1, 1, C) is NHWC; the runtime is NCHW. With 1x1
                # spatial dims the element order is identical, so the
                # SE-block pattern (GlobalPool -> Reshape -> 1x1 Conv)
                # maps exactly
                target = (c, 1, 1)
            else:
                raise ImportException(
                    "Reshape to a conv tensor with non-1x1 spatial dims "
                    "is unsupported (NHWC/NCHW element order differs)")
        return _Adapted(LX.ReshapeLayer(target_shape=target,
                                        name=cfg.get("name")))
    if class_name == "Masking":
        # emits the timestep keep-mask; MultiLayerNetwork threads it into
        # downstream RNN layers (Keras semantics: masked steps carry state
        # and repeat the previous output) and a temporal loss head —
        # reference KerasMasking.java + per-layer mask propagation
        return _Adapted(LX.MaskLayer(
            mask_value=float(cfg.get("mask_value", 0.0)),
            name=cfg.get("name")))
    if class_name == "LocallyConnected1D":
        if cfg.get("padding", "valid") != "valid":
            raise ImportException("LocallyConnected1D padding must be "
                                  "'valid'")
        ks = cfg.get("kernel_size", 3)
        ks = int(ks[0]) if isinstance(ks, (list, tuple)) else int(ks)
        st = cfg.get("strides", 1)
        st = int(st[0]) if isinstance(st, (list, tuple)) else int(st)
        layer = LX.LocallyConnected1D(
            n_out=int(cfg["filters"]), kernel_size=ks, stride=st,
            activation=_act(cfg.get("activation")),
            has_bias=bool(cfg.get("use_bias", True)), name=cfg.get("name"))

        def lc1d_weights(weights, in_type):
            # keras kernel (ot, ks*F, o) flattens patches k-major/f-minor;
            # our layer consumes conv_general_dilated_patches order
            # (c-major, k-minor) — permute the middle axis accordingly
            k = np.asarray(weights[0])
            ot, kf, o = k.shape
            f = kf // ks
            k = k.reshape(ot, ks, f, o).transpose(0, 2, 1, 3).reshape(
                ot, kf, o)
            p = {"W": jnp.asarray(k)}
            if layer.has_bias:
                p["b"] = jnp.asarray(np.asarray(weights[1]))
            return p

        return _Adapted(layer, lc1d_weights)
    if class_name == "SpaceToDepth":
        return _Adapted(LX.SpaceToDepthLayer(
            block_size=int(cfg.get("block_size", 2)), name=cfg.get("name")))
    if class_name == "Rescaling":
        sc, off = cfg.get("scale", 1.0), cfg.get("offset", 0.0)
        if isinstance(sc, (list, tuple)) or isinstance(off, (list, tuple)):
            raise ImportException(
                "Rescaling with per-element scale/offset is unsupported "
                "(NHWC->NCHW broadcast would need layout tracking)")
        return _Adapted(LX.RescaleLayer(scale=float(sc), offset=float(off),
                                        name=cfg.get("name")))
    if class_name == "Normalization":
        if cfg.get("invert"):
            raise ImportException("Normalization(invert=True) unsupported")
        axis = cfg.get("axis")
        axis = list(axis) if isinstance(axis, (list, tuple)) else [axis]
        if axis not in ([3], [-1]):
            raise ImportException(
                f"Normalization over axis {axis} unsupported (only the "
                f"channels axis)")
        layer = LX.ChannelNormalizationLayer(name=cfg.get("name"))

        def norm_weights(weights, in_type):
            # h5 weights: [mean (C,), variance (C,), count ()]
            return {"mean": jnp.asarray(np.asarray(weights[0]).ravel()),
                    "variance": jnp.asarray(
                        np.asarray(weights[1]).ravel())}

        return _Adapted(layer, norm_weights)
    if class_name == "Lambda":
        fn = _LAMBDA_REGISTRY.get(cfg.get("name"))
        if fn is None:
            raise ImportException(
                f"Keras Lambda layer {cfg.get('name')!r} requires "
                "register_lambda(name, layer) before import (reference "
                "KerasLayer.registerLambdaLayer)")
        return _Adapted(fn() if callable(fn) and not isinstance(fn, L.Layer)
                        else fn)
    raise ImportException(f"unsupported Keras layer type {class_name!r}")


#: name -> Layer (or zero-arg factory) for Lambda layers, mirroring the
#: reference's KerasLayer.registerLambdaLayer custom-layer hook
_LAMBDA_REGISTRY: Dict[str, Any] = {}


def register_lambda(name: str, layer_or_factory) -> None:
    """Register the implementation for a Keras Lambda layer by name."""
    _LAMBDA_REGISTRY[name] = layer_or_factory


# ---------------------------------------------------------------- h5 I/O
def _read_h5(path):
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ImportException(
                "h5 file has no model_config attr (weights-only file?); "
                "use import with a separate config JSON")
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        config = json.loads(raw)
        weights: Dict[str, List[np.ndarray]] = {}
        mw = f["model_weights"] if "model_weights" in f else f
        layer_names = [n.decode() if isinstance(n, bytes) else n
                       for n in mw.attrs.get("layer_names", list(mw.keys()))]
        for lname in layer_names:
            if lname not in mw:
                continue
            grp = mw[lname]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in grp.attrs.get("weight_names", [])]
            ws = []
            if wnames:
                for wn in wnames:
                    ws.append(np.array(grp[wn]))
            else:
                def visit(name, obj):
                    import h5py as _h
                    if isinstance(obj, _h.Dataset):
                        ws.append(np.array(obj))
                grp.visititems(visit)
            if ws:
                weights[lname] = ws
    return config, weights


def _layer_entries(model_cfg: Dict) -> List[Dict]:
    cfg = model_cfg.get("config", model_cfg)
    return cfg["layers"]


def _resolve_reshape(target, in_shape):
    """Resolve a keras Reshape target with one -1 against the input size."""
    target = [int(s) for s in target]
    if -1 in target and in_shape is not None:
        known = int(np.prod([s for s in target if s != -1]))
        total = int(np.prod(in_shape))
        target[target.index(-1)] = total // max(known, 1)
    return tuple(target)


def _keras_out_shape(class_name, cfg, in_shape):
    """Track Keras-side (channels-last, batchless) shapes for weight fixups."""
    if in_shape is None:
        return None
    if class_name == "Dense":
        return (int(cfg["units"]),)
    if class_name == "Conv2D":
        h, w, c = in_shape
        sh, sw = _pair(cfg.get("strides", (1, 1)))
        kh, kw = _pair(cfg["kernel_size"])
        if cfg.get("padding", "valid") == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (oh, ow, int(cfg["filters"]))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        h, w, c = in_shape
        ph, pw = _pair(cfg.get("pool_size", (2, 2)))
        st = cfg.get("strides") or (ph, pw)
        sh, sw = _pair(st)
        if cfg.get("padding", "valid") == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - ph) // sh + 1, (w - pw) // sw + 1
        return (oh, ow, c)
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
        return (in_shape[-1],)
    if class_name == "Flatten":
        return (int(np.prod(in_shape)),)
    if class_name == "Reshape":
        return _resolve_reshape(cfg.get("target_shape", ()), in_shape)
    if class_name == "SpaceToDepth":
        h, w, c = in_shape
        s = int(cfg.get("block_size", 2))
        return (h // s, w // s, c * s * s)
    if class_name == "Permute":
        dims = tuple(int(d) for d in cfg.get("dims", ()))
        return tuple(in_shape[d - 1] for d in dims)
    if class_name == "Masking":
        return tuple(in_shape)
    if class_name in ("Rescaling", "Normalization"):
        return tuple(in_shape)
    if class_name == "LocallyConnected1D":
        t = in_shape[0]
        ks = cfg.get("kernel_size", 3)
        ks = int(ks[0]) if isinstance(ks, (list, tuple)) else int(ks)
        st = cfg.get("strides", 1)
        st = int(st[0]) if isinstance(st, (list, tuple)) else int(st)
        return ((t - ks) // st + 1, int(cfg["filters"]))
    if class_name == "Embedding":
        return tuple(in_shape) + (int(cfg["output_dim"]),)
    if class_name in ("LSTM", "GRU", "SimpleRNN"):
        units = int(cfg["units"])
        return (in_shape[0], units) if cfg.get("return_sequences") \
            else (units,)
    if class_name == "Bidirectional":
        inner_cfg = cfg.get("layer", {}).get("config", {})
        units = int(inner_cfg.get("units", 0))
        if cfg.get("merge_mode", "concat") == "concat":
            units *= 2
        return (in_shape[0], units) if inner_cfg.get("return_sequences") \
            else (units,)
    if class_name == "TimeDistributed":
        inner_cfg = cfg.get("layer", {}).get("config", {})
        return (in_shape[0], int(inner_cfg.get("units", in_shape[-1])))
    if class_name == "Conv1D":
        t, f = in_shape
        k = _pair(cfg["kernel_size"])[0]
        s = _pair(cfg.get("strides", 1))[0]
        d = _pair(cfg.get("dilation_rate", 1))[0]
        if cfg.get("padding", "valid") in ("same", "causal"):
            ot = -(-t // s)
        else:
            ot = (t - d * (k - 1) - 1) // s + 1
        return (ot, int(cfg["filters"]))
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        t, f = in_shape
        pool = cfg.get("pool_size", 2)
        pool = int(pool[0]) if isinstance(pool, (list, tuple)) else int(pool)
        st = cfg.get("strides") or pool
        st = int(st[0]) if isinstance(st, (list, tuple)) else int(st)
        return ((t - pool) // st + 1, f)
    if class_name in ("GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        return (in_shape[-1],)
    if class_name in ("SeparableConv2D", "Conv2DTranspose"):
        h, w, c = in_shape
        sh, sw = _pair(cfg.get("strides", (1, 1)))
        kh, kw = _pair(cfg["kernel_size"])
        same = cfg.get("padding", "valid") == "same"
        if class_name == "Conv2DTranspose":
            oh = h * sh if same else sh * (h - 1) + kh
            ow = w * sw if same else sw * (w - 1) + kw
        elif same:
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (oh, ow, int(cfg["filters"]))
    if class_name == "UpSampling2D":
        h, w, c = in_shape
        sh, sw = _pair(cfg.get("size", (2, 2)))
        return (h * sh, w * sw, c)
    if class_name == "Cropping2D":
        h, w, c = in_shape
        t, b, l, r = _cropping_tuple(cfg.get("cropping", (1, 1)), 2)
        return (h - t - b, w - l - r, c)
    if class_name == "RepeatVector":
        return (int(cfg.get("n", 1)), in_shape[0])
    if class_name == "Conv3D":
        d, h, w, c = in_shape
        kd, kh, kw = (int(k) for k in cfg["kernel_size"])
        sd, sh, sw = (int(s) for s in cfg.get("strides", (1, 1, 1)))
        if cfg.get("padding", "valid") == "same":
            od, oh, ow = -(-d // sd), -(-h // sh), -(-w // sw)
        else:
            od, oh, ow = ((d - kd) // sd + 1, (h - kh) // sh + 1,
                          (w - kw) // sw + 1)
        return (od, oh, ow, int(cfg["filters"]))
    if class_name in ("MaxPooling3D", "AveragePooling3D"):
        d, h, w, c = in_shape
        ps = cfg.get("pool_size", (2, 2, 2))
        ps = (ps,) * 3 if isinstance(ps, int) else tuple(int(p) for p in ps)
        st = cfg.get("strides") or ps
        st = (st,) * 3 if isinstance(st, int) else tuple(int(s) for s in st)
        if cfg.get("padding", "valid") == "same":
            return (-(-d // st[0]), -(-h // st[1]), -(-w // st[2]), c)
        return ((d - ps[0]) // st[0] + 1, (h - ps[1]) // st[1] + 1,
                (w - ps[2]) // st[2] + 1, c)
    if class_name == "ZeroPadding2D":
        h, w, c = in_shape
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and \
                isinstance(pad[0], (list, tuple)):
            return (h + pad[0][0] + pad[0][1], w + pad[1][0] + pad[1][1], c)
        ph, pw = _pair(pad)
        return (h + 2 * ph, w + 2 * pw, c)
    return in_shape  # shape-preserving (BN, Dropout, Activation...)


def _input_shape_of(entries) -> Optional[Tuple]:
    for e in entries:
        cfg = e.get("config", {})
        if e["class_name"] == "InputLayer":
            shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
            if shape:
                return tuple(shape[1:])
        bis = cfg.get("batch_input_shape") or cfg.get("batch_shape")
        if bis:
            return tuple(bis[1:])
    return None


#: keras layers whose 2-D (T, F) output we hold as [B, F, T] on device
_TEMPORAL_LAYERS = frozenset((
    "Embedding", "LSTM", "GRU", "SimpleRNN", "Bidirectional", "Conv1D",
    "MaxPooling1D", "AveragePooling1D", "UpSampling1D", "Cropping1D",
    "ZeroPadding1D", "LocallyConnected1D", "SpatialDropout1D",
    "TimeDistributed", "RepeatVector", "Masking"))


class KerasModelImport:
    """Entry points mirroring the reference KerasModelImport API."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path, input_shape: Optional[Tuple] = None) -> MultiLayerNetwork:
        config, weights = _read_h5(path)
        if config["class_name"] not in ("Sequential",):
            raise ImportException(
                f"not a Sequential model ({config['class_name']}); use "
                f"import_keras_model_and_weights")
        entries = _layer_entries(config)
        keras_shape = input_shape or _input_shape_of(entries)
        if keras_shape is None:  # keras 3 Sequential: build_input_shape
            bis = config.get("config", {}).get("build_input_shape")
            if bis:
                keras_shape = tuple(bis[1:])
        if keras_shape is None:
            raise ImportException("could not determine input shape; pass "
                                  "input_shape=")

        lb = NeuralNetConfiguration.builder().list()
        in_type = _keras_shape_to_input_type(keras_shape)
        lb.set_input_type(in_type)
        adapted: List[Tuple[int, _Adapted, Tuple]] = []
        cur = tuple(keras_shape)
        conv_src = None  # pre-Flatten conv shape for Dense-kernel reordering
        # True while our runtime layout is [B,F,T] against keras' [B,T,F]
        # (every temporal layer); Reshape/Permute outputs are keras-identical
        transposed = len(cur) == 2
        idx = 0
        mask_alive = False  # a Masking layer's keep-mask is in flight
        for e in entries:
            cls, cfg = e["class_name"], e.get("config", {})
            if cls == "Masking":
                mask_alive = True
            elif mask_alive:
                if cls in ("GlobalAveragePooling1D", "GlobalMaxPooling1D"):
                    # keras pooling CONSUMES the mask (masked steps
                    # excluded); our pooling layers don't — refuse rather
                    # than silently diverge from the golden
                    raise ImportException(
                        f"{cls} downstream of Masking consumes the "
                        "timestep mask in keras; mask threading covers RNN "
                        "layers only — pool after an RNN with "
                        "return_sequences=False, or drop the Masking layer")
                if (cls in ("LSTM", "GRU", "SimpleRNN")
                        and not cfg.get("return_sequences", False)):
                    mask_alive = False  # consumed by last-step selection
            if cls == "Flatten" and cur is not None and len(cur) in (3, 4):
                conv_src = cur
            if cls == "Flatten" and cur is not None and len(cur) == 2:
                if any(s is None for s in cur):
                    raise ImportException(
                        "Flatten on a variable-length sequence is "
                        "unsupported (timestep dim is None)")
                # keras flattens [B,T,F]; our tensor may be [B,F,T] — line
                # the axes up first so element order matches the golden
                if transposed:
                    lb.layer(LX.PermuteLayer(dims=(2, 1)))
                    idx += 1
                lb.layer(LX.ReshapeLayer(
                    target_shape=(int(np.prod(cur)),), name=cfg.get("name")))
                idx += 1
                cur = (int(np.prod(cur)),)
                transposed = False
                continue
            if cls in ("Reshape", "Permute"):
                if cur is None:
                    raise ImportException(
                        f"{cls} with unknown input shape is unsupported")
                if len(cur) >= 3:
                    # conv activations are NCHW vs keras NHWC — a literal
                    # transpose/reshape would reorder different axes than
                    # keras did, so refuse rather than silently diverge
                    raise ImportException(
                        f"{cls} on a conv tensor is unsupported (runtime "
                        "layout differs from keras); insert Flatten or "
                        "GlobalPooling first")
                if transposed:
                    # align the [B,F,T] runtime tensor with keras' [B,T,F]
                    # before applying the keras-specified transform; the
                    # result is then keras-identical layout
                    lb.layer(LX.PermuteLayer(dims=(2, 1)))
                    idx += 1
                    transposed = False
            if cls in _TEMPORAL_LAYERS and cur is not None \
                    and len(cur) == 2 and not transposed:
                # a temporal consumer expects [B,F,T] but the tensor is in
                # keras-identical [B,T,F] layout (e.g. produced by Reshape)
                # — re-align before it, or the RNN silently reads features
                # as timesteps
                lb.layer(LX.PermuteLayer(dims=(2, 1)))
                idx += 1
                transposed = True
            shape_for_adapter = conv_src if (cls == "Dense" and conv_src) \
                else cur
            a = _adapt_layer(cls, cfg, shape_for_adapter)
            if cls == "Dense":
                conv_src = None
            if a is not None:
                lb.layer(a.layer)
                adapted.append((idx, a, shape_for_adapter))
                idx += 1
            if cls == "Lambda" and a is not None:
                # registered custom layers know their own output shape;
                # the keras-side table cannot
                try:
                    cur = a.layer.output_type(cur)
                except Exception:
                    cur = None
            else:
                cur = _keras_out_shape(cls, cfg, cur)
            if cur is not None:
                if len(cur) != 2:
                    transposed = False
                elif cls in ("Reshape", "Permute"):
                    transposed = False
                elif cls in _TEMPORAL_LAYERS:
                    transposed = True

        conf = lb.build()
        net = MultiLayerNetwork(conf)
        net.init()
        # overwrite initialized params with the imported weights
        for i, a, in_shape in adapted:
            if a.set_weights is None:
                continue
            name = a.layer.name
            if name not in weights:
                raise ImportException(f"no weights for layer {name!r} in h5")
            net._params[i] = a.set_weights(weights[name], in_shape)
        net._updater_state = conf.updater.init(net._trainable(net._params))
        return net

    @staticmethod
    def import_keras_model_and_weights(path,
                                       input_shape: Optional[Tuple] = None
                                       ) -> ComputationGraph:
        config, weights = _read_h5(path)
        cls_name = config["class_name"]
        if cls_name == "Sequential":
            raise ImportException("Sequential model; use "
                                  "import_keras_sequential_model_and_weights")
        entries = _layer_entries(config)
        gcfg = config.get("config", {})

        def _ref_names(spec):
            """input/output_layers spec -> layer names (keras 2 and 3).

            Single-ref specs may be flat ['name', 0, 0]; multi-ref are
            [['a',0,0], ['b',0,0]] (or plain name lists)."""
            if not spec:
                return []
            if isinstance(spec, (list, tuple)) and len(spec) == 3 and \
                    isinstance(spec[0], str) and \
                    all(isinstance(s, int) for s in spec[1:]):
                return [spec[0]]
            out = []
            for item in spec:
                out.append(item[0] if isinstance(item, (list, tuple))
                           else item)
            return out

        builder = NeuralNetConfiguration.builder().graph_builder()
        keras_shapes: Dict[str, Tuple] = {}
        adapted: Dict[str, Tuple[_Adapted, Tuple]] = {}
        alias: Dict[str, str] = {}  # keras layer name -> vertex name used
        unflattened: Dict[str, Tuple] = {}  # Flatten name -> conv shape
        # keras names whose runtime tensor is [B,F,T] against keras' [B,T,F]
        # (temporal producers); Reshape/Permute outputs are keras-identical
        transposed: Dict[str, bool] = {}

        input_names = _ref_names(gcfg.get("input_layers", []))
        builder.add_inputs(*input_names)

        for e in entries:
            cls, cfg = e["class_name"], e.get("config", {})
            name = cfg.get("name") or e.get("name")
            inbound = _parse_inbound(e.get("inbound_nodes", []))
            if cls == "InputLayer":
                shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
                keras_shapes[name] = tuple(shape[1:]) if shape else None
                # RNN-style inputs are fed [B,F,T] by our conventions
                transposed[name] = (keras_shapes[name] is not None
                                    and len(keras_shapes[name]) == 2)
                continue
            in_names = [alias.get(n, n) for n in inbound]
            in_shape = keras_shapes.get(inbound[0]) if inbound else None

            def _mark_layout(out_shape):
                if out_shape is not None and len(out_shape) == 2:
                    if cls in _TEMPORAL_LAYERS:
                        transposed[name] = True
                    elif cls in ("Reshape", "Permute"):
                        transposed[name] = False
                    else:  # layout-preserving (dropout/activation/merge...)
                        transposed[name] = bool(
                            transposed.get(inbound[0])) if inbound else False
                else:
                    transposed[name] = False
            if cls == "Flatten":
                if in_shape is not None and len(in_shape) == 2:
                    if any(s is None for s in in_shape):
                        raise ImportException(
                            "Flatten on a variable-length sequence is "
                            "unsupported; fix the timestep dimension")
                    # when the producer is temporal our tensor is [B,F,T]
                    # vs keras [B,T,F]: line the axes up before flattening
                    # (same treatment the Sequential importer applies)
                    total = int(np.prod(in_shape))
                    src = in_names[0]
                    if transposed.get(inbound[0]):
                        builder.add_layer(f"{name}_permute",
                                          LX.PermuteLayer(dims=(2, 1)),
                                          src)
                        src = f"{name}_permute"
                    builder.add_layer(name,
                                      LX.ReshapeLayer(target_shape=(total,)),
                                      src)
                    keras_shapes[name] = (total,)
                    transposed[name] = False
                    continue
                alias[name] = in_names[0]  # vanishes; preprocessor handles
                if in_shape is not None and len(in_shape) == 3:
                    unflattened[name] = in_shape
                keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)
                _mark_layout(keras_shapes[name])
                continue
            if cls in ("Reshape", "Permute"):
                if in_shape is None:
                    raise ImportException(
                        f"{cls} with unknown input shape is unsupported")
                if len(in_shape) >= 3:
                    raise ImportException(
                        f"{cls} on a conv tensor is unsupported (runtime "
                        "layout differs from keras); insert Flatten or "
                        "GlobalPooling first")
                if len(in_shape) == 2 and transposed.get(inbound[0]):
                    # align [B,F,T] -> keras [B,T,F] before the transform
                    builder.add_layer(f"{name}_align",
                                      LX.PermuteLayer(dims=(2, 1)),
                                      in_names[0])
                    in_names = [f"{name}_align"]
            elif cls in _TEMPORAL_LAYERS and in_shape is not None \
                    and len(in_shape) == 2 and inbound \
                    and not transposed.get(inbound[0], False):
                # temporal consumer on a keras-layout tensor: re-align to
                # [B,F,T] first (mirror of the Sequential treatment)
                builder.add_layer(f"{name}_align",
                                  LX.PermuteLayer(dims=(2, 1)), in_names[0])
                in_names = [f"{name}_align"]
            if cls == "Dense" and inbound and inbound[0] in unflattened:
                in_shape = unflattened[inbound[0]]
            if cls in ("Add", "Subtract", "Multiply", "Average", "Maximum",
                       "Minimum"):
                op = {"Add": "add", "Subtract": "subtract",
                      "Multiply": "product", "Average": "average",
                      "Maximum": "max", "Minimum": "min"}[cls]
                builder.add_vertex(name, ElementWiseVertex(op=op), *in_names)
                keras_shapes[name] = in_shape
                _mark_layout(in_shape)
                continue
            if cls == "Concatenate":
                builder.add_vertex(name, MergeVertex(), *in_names)
                shapes = [keras_shapes.get(n) for n in inbound]
                if in_shape is not None and all(s is not None
                                                for s in shapes):
                    merged = list(in_shape)
                    merged[-1] = sum(s[-1] for s in shapes)
                    keras_shapes[name] = tuple(merged)
                _mark_layout(keras_shapes.get(name))
                continue
            if cls == "Masking":
                raise ImportException(
                    "Masking in functional (ComputationGraph) models is "
                    "unsupported: mask threading is implemented for the "
                    "Sequential/MultiLayerNetwork path only; re-export as "
                    "Sequential")
            a = _adapt_layer(cls, cfg, in_shape)
            if a is None:
                alias[name] = in_names[0] if in_names else name
                keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)
                _mark_layout(keras_shapes[name])
                continue
            builder.add_layer(name, a.layer, *in_names)
            adapted[name] = (a, in_shape)
            if cls == "Lambda":
                try:
                    keras_shapes[name] = a.layer.output_type(in_shape)
                except Exception:
                    keras_shapes[name] = None
            else:
                keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)
            _mark_layout(keras_shapes.get(name))

        out_names = [alias.get(n, n)
                     for n in _ref_names(gcfg.get("output_layers", []))]
        builder.set_outputs(*out_names)
        in_types = [_keras_shape_to_input_type(keras_shapes.get(n) or
                                               (input_shape if input_shape
                                                else None))
                    for n in input_names]
        if all(t is not None for t in in_types):
            builder.set_input_types(*in_types)
        conf = builder.build()
        net = ComputationGraph(conf)
        net.init()
        for name, (a, in_shape) in adapted.items():
            if a.set_weights is None:
                continue
            if name not in weights:
                raise ImportException(f"no weights for layer {name!r} in h5")
            net._params[name] = a.set_weights(weights[name], in_shape)
        net._updater_state = conf.updater.init(net._trainable(net._params))
        return net


def _parse_inbound(inbound_nodes) -> List[str]:
    """Inbound layer names across Keras 2/3 serialization formats."""
    names: List[str] = []
    if not inbound_nodes:
        return names
    node = inbound_nodes[0]
    if isinstance(node, dict):  # keras 3: {"args": [...], "kwargs": {}}
        def find_hist(obj):
            if isinstance(obj, dict):
                if "keras_history" in obj.get("config", {}):
                    names.append(obj["config"]["keras_history"][0])
                else:
                    for v in obj.values():
                        find_hist(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    find_hist(v)
        find_hist(node.get("args", []))
    else:  # keras 2: [["layer", node_idx, tensor_idx, {}], ...]
        for item in node:
            names.append(item[0])
    return names


def import_keras_sequential_model_and_weights(path, input_shape=None):
    return KerasModelImport.import_keras_sequential_model_and_weights(
        path, input_shape)


def import_keras_model_and_weights(path, input_shape=None):
    return KerasModelImport.import_keras_model_and_weights(path, input_shape)
