"""Keras HDF5 model import -> MultiLayerNetwork / ComputationGraph.

Reference: `deeplearning4j/deeplearning4j-modelimport/src/main/java/org/
deeplearning4j/nn/modelimport/keras/KerasModelImport.java:45-151` (entry
points), `KerasModel.java:639` (getComputationGraph),
`KerasSequentialModel.java` (-> MultiLayerNetwork), and the 62 layer
adapters under `keras/layers/**`.

Handles both Keras 2 and Keras 3 legacy-h5 flavors (model_config JSON +
model_weights groups). Data-format note: Keras is channels-last (NHWC);
this framework's conv stack is NCHW like the reference DL4J — the importer
converts kernels (HWIO is shared) and reorders Flatten->Dense kernels from
(h,w,c) to (c,h,w) row order, the same fixup the reference applies via
KerasFlatten's preprocessor.
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...nn.conf import layers as L
from ...nn.conf.config import (InputType, MultiLayerConfiguration,
                               NeuralNetConfiguration)
from ...nn.graph.computation_graph import ComputationGraph
from ...nn.graph.vertices import ElementWiseVertex, MergeVertex
from ...nn.multilayer import MultiLayerNetwork
from ..ir import ImportException

_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "softmax": "softmax", "sigmoid": "sigmoid", "tanh": "tanh",
    "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid",
    "swish": "swish", "silu": "swish", "gelu": "gelu", "mish": "mish",
    "exponential": "exp", "leaky_relu": "leakyrelu",
}


def _act(name) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):  # serialized Activation object
        name = name.get("config", {}).get("name", "linear")
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ImportException(f"unsupported Keras activation {name!r}")


def _pair(v):
    return tuple(int(x) for x in v) if isinstance(v, (list, tuple)) \
        else (int(v), int(v))


def _keras_shape_to_input_type(shape) -> Optional[Tuple[int, ...]]:
    """Keras shape (no batch) -> InputType tuple. NHWC -> (C,H,W);
    [T, F] -> (F, T); [F] -> (F,)."""
    if shape is None:
        return None
    dims = [d for d in shape]
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(f, t if t is not None else -1)
    if len(dims) == 1:
        return InputType.feed_forward(dims[0]) if dims[0] else None
    return None


class _Adapted:
    """One imported layer: our config + a weight-mapping function."""

    def __init__(self, layer: Optional[L.Layer],
                 set_weights: Optional[Callable] = None):
        self.layer = layer
        self.set_weights = set_weights  # (weights, in_type) -> params dict


def _dense_adapter(cfg, keras_in_shape):
    units = int(cfg["units"])
    use_bias = bool(cfg.get("use_bias", True))
    layer = L.DenseLayer(n_out=units, activation=_act(cfg.get("activation")),
                         has_bias=use_bias, name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel = np.asarray(weights[0])
        # Flatten-after-conv fixup: Keras flattens (h,w,c), ours (c,h,w)
        if keras_in_shape is not None and len(keras_in_shape) == 3 and \
                kernel.shape[0] == int(np.prod(keras_in_shape)):
            h, w, c = keras_in_shape
            kernel = kernel.reshape(h, w, c, units).transpose(2, 0, 1, 3) \
                .reshape(c * h * w, units)
        p = {"W": jnp.asarray(kernel)}
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _conv2d_adapter(cfg, depthwise=False):
    strides = _pair(cfg.get("strides", (1, 1)))
    dilation = _pair(cfg.get("dilation_rate", (1, 1)))
    padding = "SAME" if cfg.get("padding", "valid") == "same" else "VALID"
    use_bias = bool(cfg.get("use_bias", True))
    act = _act(cfg.get("activation"))
    if depthwise:
        mult = int(cfg.get("depth_multiplier", 1))
        layer = L.DepthwiseConvolution2D(
            n_out=0, depth_multiplier=mult,
            kernel_size=_pair(cfg["kernel_size"]), stride=strides,
            padding=padding, dilation=dilation, activation=act,
            has_bias=use_bias, name=cfg.get("name"))
    else:
        layer = L.ConvolutionLayer(
            n_out=int(cfg["filters"]), kernel_size=_pair(cfg["kernel_size"]),
            stride=strides, padding=padding, dilation=dilation,
            activation=act, has_bias=use_bias, name=cfg.get("name"))

    def set_weights(weights, in_type):
        p = {"W": jnp.asarray(np.asarray(weights[0]))}  # HWIO both sides
        if use_bias:
            p["b"] = jnp.asarray(np.asarray(weights[1]))
        return p

    return _Adapted(layer, set_weights)


def _pool2d_adapter(cfg, pool_type):
    pool = _pair(cfg.get("pool_size", (2, 2)))
    strides = _pair(cfg.get("strides") or cfg.get("pool_size", (2, 2)))
    padding = "SAME" if cfg.get("padding", "valid") == "same" else "VALID"
    return _Adapted(L.SubsamplingLayer(
        pooling_type=pool_type, kernel_size=pool, stride=strides,
        padding=padding, name=cfg.get("name")))


def _bn_adapter(cfg):
    scale = bool(cfg.get("scale", True))
    center = bool(cfg.get("center", True))
    layer = L.BatchNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                                 decay=float(cfg.get("momentum", 0.99)),
                                 use_gamma_beta=True, name=cfg.get("name"))

    def set_weights(weights, in_type):
        w = [np.asarray(a) for a in weights]
        i = 0
        gamma = w[i] if scale else None
        i += 1 if scale else 0
        beta = w[i] if center else None
        i += 1 if center else 0
        mean, var = w[i], w[i + 1]
        c = mean.shape[0]
        return {"gamma": jnp.asarray(gamma if gamma is not None
                                     else np.ones(c, np.float32)),
                "beta": jnp.asarray(beta if beta is not None
                                    else np.zeros(c, np.float32)),
                "state_mean": jnp.asarray(mean),
                "state_var": jnp.asarray(var)}

    return _Adapted(layer, set_weights)


def _embedding_adapter(cfg):
    layer = L.EmbeddingSequenceLayer(n_in=int(cfg["input_dim"]),
                                     n_out=int(cfg["output_dim"]),
                                     name=cfg.get("name"))

    def set_weights(weights, in_type):
        return {"W": jnp.asarray(np.asarray(weights[0]))}

    return _Adapted(layer, set_weights)


def _lstm_adapter(cfg):
    units = int(cfg["units"])
    layer = L.LSTM(n_out=units, activation=_act(cfg.get("activation", "tanh")),
                   return_sequence=bool(cfg.get("return_sequences", False)),
                   name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel, rec, bias = [np.asarray(a) for a in weights[:3]]
        # Keras gate order [i, f, c, o] == ours — direct copy
        return {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec),
                "b": jnp.asarray(bias)}

    return _Adapted(layer, set_weights)


def _simple_rnn_adapter(cfg):
    units = int(cfg["units"])
    layer = L.SimpleRnn(n_out=units,
                        activation=_act(cfg.get("activation", "tanh")),
                        name=cfg.get("name"))

    def set_weights(weights, in_type):
        kernel, rec, bias = [np.asarray(a) for a in weights[:3]]
        return {"Wx": jnp.asarray(kernel), "Wh": jnp.asarray(rec),
                "b": jnp.asarray(bias)}

    return _Adapted(layer, set_weights)


def _adapt_layer(class_name: str, cfg: Dict[str, Any],
                 keras_in_shape) -> Optional[_Adapted]:
    """One Keras layer -> framework layer + weight mapper.

    Returns None for layers that vanish (InputLayer, Flatten — handled by
    automatic preprocessors like the reference's KerasFlatten)."""
    if class_name in ("InputLayer", "Flatten"):
        return None
    if class_name == "Dense":
        return _dense_adapter(cfg, keras_in_shape)
    if class_name == "Conv2D":
        return _conv2d_adapter(cfg)
    if class_name == "DepthwiseConv2D":
        return _conv2d_adapter(cfg, depthwise=True)
    if class_name == "MaxPooling2D":
        return _pool2d_adapter(cfg, "max")
    if class_name == "AveragePooling2D":
        return _pool2d_adapter(cfg, "avg")
    if class_name == "GlobalAveragePooling2D":
        return _Adapted(L.GlobalPoolingLayer(pooling_type="avg",
                                             name=cfg.get("name")))
    if class_name == "GlobalMaxPooling2D":
        return _Adapted(L.GlobalPoolingLayer(pooling_type="max",
                                             name=cfg.get("name")))
    if class_name == "BatchNormalization":
        return _bn_adapter(cfg)
    if class_name == "Dropout":
        return _Adapted(L.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                                       name=cfg.get("name")))
    if class_name == "Activation":
        return _Adapted(L.ActivationLayer(
            activation=_act(cfg.get("activation")), name=cfg.get("name")))
    if class_name == "LeakyReLU":
        return _Adapted(L.ActivationLayer(activation="leakyrelu",
                                          name=cfg.get("name")))
    if class_name == "ReLU":
        return _Adapted(L.ActivationLayer(activation="relu",
                                          name=cfg.get("name")))
    if class_name == "ELU":
        return _Adapted(L.ActivationLayer(activation="elu",
                                          name=cfg.get("name")))
    if class_name == "Embedding":
        return _embedding_adapter(cfg)
    if class_name == "LSTM":
        return _lstm_adapter(cfg)
    if class_name == "SimpleRNN":
        return _simple_rnn_adapter(cfg)
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and \
                isinstance(pad[0], (list, tuple)):
            padding = (int(pad[0][0]), int(pad[0][1]),
                       int(pad[1][0]), int(pad[1][1]))
        else:
            ph, pw = _pair(pad)
            padding = (ph, ph, pw, pw)
        return _Adapted(L.ZeroPaddingLayer(padding=padding,
                                           name=cfg.get("name")))
    raise ImportException(f"unsupported Keras layer type {class_name!r}")


# ---------------------------------------------------------------- h5 I/O
def _read_h5(path):
    import h5py
    with h5py.File(path, "r") as f:
        raw = f.attrs.get("model_config")
        if raw is None:
            raise ImportException(
                "h5 file has no model_config attr (weights-only file?); "
                "use import with a separate config JSON")
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        config = json.loads(raw)
        weights: Dict[str, List[np.ndarray]] = {}
        mw = f["model_weights"] if "model_weights" in f else f
        layer_names = [n.decode() if isinstance(n, bytes) else n
                       for n in mw.attrs.get("layer_names", list(mw.keys()))]
        for lname in layer_names:
            if lname not in mw:
                continue
            grp = mw[lname]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in grp.attrs.get("weight_names", [])]
            ws = []
            if wnames:
                for wn in wnames:
                    ws.append(np.array(grp[wn]))
            else:
                def visit(name, obj):
                    import h5py as _h
                    if isinstance(obj, _h.Dataset):
                        ws.append(np.array(obj))
                grp.visititems(visit)
            if ws:
                weights[lname] = ws
    return config, weights


def _layer_entries(model_cfg: Dict) -> List[Dict]:
    cfg = model_cfg.get("config", model_cfg)
    return cfg["layers"]


def _keras_out_shape(class_name, cfg, in_shape):
    """Track Keras-side (channels-last, batchless) shapes for weight fixups."""
    if in_shape is None:
        return None
    if class_name == "Dense":
        return (int(cfg["units"]),)
    if class_name == "Conv2D":
        h, w, c = in_shape
        sh, sw = _pair(cfg.get("strides", (1, 1)))
        kh, kw = _pair(cfg["kernel_size"])
        if cfg.get("padding", "valid") == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - kh) // sh + 1, (w - kw) // sw + 1
        return (oh, ow, int(cfg["filters"]))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        h, w, c = in_shape
        ph, pw = _pair(cfg.get("pool_size", (2, 2)))
        st = cfg.get("strides") or (ph, pw)
        sh, sw = _pair(st)
        if cfg.get("padding", "valid") == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh, ow = (h - ph) // sh + 1, (w - pw) // sw + 1
        return (oh, ow, c)
    if class_name in ("GlobalAveragePooling2D", "GlobalMaxPooling2D"):
        return (in_shape[-1],)
    if class_name == "Flatten":
        return (int(np.prod(in_shape)),)
    if class_name == "Embedding":
        return tuple(in_shape) + (int(cfg["output_dim"]),)
    if class_name == "LSTM":
        units = int(cfg["units"])
        return (in_shape[0], units) if cfg.get("return_sequences") \
            else (units,)
    if class_name == "ZeroPadding2D":
        h, w, c = in_shape
        pad = cfg.get("padding", (1, 1))
        if isinstance(pad, (list, tuple)) and pad and \
                isinstance(pad[0], (list, tuple)):
            return (h + pad[0][0] + pad[0][1], w + pad[1][0] + pad[1][1], c)
        ph, pw = _pair(pad)
        return (h + 2 * ph, w + 2 * pw, c)
    return in_shape  # shape-preserving (BN, Dropout, Activation...)


def _input_shape_of(entries) -> Optional[Tuple]:
    for e in entries:
        cfg = e.get("config", {})
        if e["class_name"] == "InputLayer":
            shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
            if shape:
                return tuple(shape[1:])
        bis = cfg.get("batch_input_shape") or cfg.get("batch_shape")
        if bis:
            return tuple(bis[1:])
    return None


class KerasModelImport:
    """Entry points mirroring the reference KerasModelImport API."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path, input_shape: Optional[Tuple] = None) -> MultiLayerNetwork:
        config, weights = _read_h5(path)
        if config["class_name"] not in ("Sequential",):
            raise ImportException(
                f"not a Sequential model ({config['class_name']}); use "
                f"import_keras_model_and_weights")
        entries = _layer_entries(config)
        keras_shape = input_shape or _input_shape_of(entries)
        if keras_shape is None:  # keras 3 Sequential: build_input_shape
            bis = config.get("config", {}).get("build_input_shape")
            if bis:
                keras_shape = tuple(bis[1:])
        if keras_shape is None:
            raise ImportException("could not determine input shape; pass "
                                  "input_shape=")

        lb = NeuralNetConfiguration.builder().list()
        in_type = _keras_shape_to_input_type(keras_shape)
        lb.set_input_type(in_type)
        adapted: List[Tuple[int, _Adapted, Tuple]] = []
        cur = tuple(keras_shape)
        conv_src = None  # pre-Flatten conv shape for Dense-kernel reordering
        idx = 0
        for e in entries:
            cls, cfg = e["class_name"], e.get("config", {})
            if cls == "Flatten" and cur is not None and len(cur) == 3:
                conv_src = cur
            shape_for_adapter = conv_src if (cls == "Dense" and conv_src) \
                else cur
            a = _adapt_layer(cls, cfg, shape_for_adapter)
            if cls == "Dense":
                conv_src = None
            if a is not None:
                lb.layer(a.layer)
                adapted.append((idx, a, shape_for_adapter))
                idx += 1
            cur = _keras_out_shape(cls, cfg, cur)

        conf = lb.build()
        net = MultiLayerNetwork(conf)
        net.init()
        # overwrite initialized params with the imported weights
        for i, a, in_shape in adapted:
            if a.set_weights is None:
                continue
            name = a.layer.name
            if name not in weights:
                raise ImportException(f"no weights for layer {name!r} in h5")
            net._params[i] = a.set_weights(weights[name], in_shape)
        net._updater_state = conf.updater.init(net._trainable(net._params))
        return net

    @staticmethod
    def import_keras_model_and_weights(path,
                                       input_shape: Optional[Tuple] = None
                                       ) -> ComputationGraph:
        config, weights = _read_h5(path)
        cls_name = config["class_name"]
        if cls_name == "Sequential":
            raise ImportException("Sequential model; use "
                                  "import_keras_sequential_model_and_weights")
        entries = _layer_entries(config)
        gcfg = config.get("config", {})

        def _ref_names(spec):
            """input/output_layers spec -> layer names (keras 2 and 3).

            Single-ref specs may be flat ['name', 0, 0]; multi-ref are
            [['a',0,0], ['b',0,0]] (or plain name lists)."""
            if not spec:
                return []
            if isinstance(spec, (list, tuple)) and len(spec) == 3 and \
                    isinstance(spec[0], str) and \
                    all(isinstance(s, int) for s in spec[1:]):
                return [spec[0]]
            out = []
            for item in spec:
                out.append(item[0] if isinstance(item, (list, tuple))
                           else item)
            return out

        builder = NeuralNetConfiguration.builder().graph_builder()
        keras_shapes: Dict[str, Tuple] = {}
        adapted: Dict[str, Tuple[_Adapted, Tuple]] = {}
        alias: Dict[str, str] = {}  # keras layer name -> vertex name used
        unflattened: Dict[str, Tuple] = {}  # Flatten name -> conv shape

        input_names = _ref_names(gcfg.get("input_layers", []))
        builder.add_inputs(*input_names)

        for e in entries:
            cls, cfg = e["class_name"], e.get("config", {})
            name = cfg.get("name") or e.get("name")
            inbound = _parse_inbound(e.get("inbound_nodes", []))
            if cls == "InputLayer":
                shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
                keras_shapes[name] = tuple(shape[1:]) if shape else None
                continue
            in_names = [alias.get(n, n) for n in inbound]
            in_shape = keras_shapes.get(inbound[0]) if inbound else None
            if cls == "Flatten":
                alias[name] = in_names[0]  # vanishes; preprocessor handles
                if in_shape is not None and len(in_shape) == 3:
                    unflattened[name] = in_shape
                keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)
                continue
            if cls == "Dense" and inbound and inbound[0] in unflattened:
                in_shape = unflattened[inbound[0]]
            if cls in ("Add", "Subtract", "Multiply", "Average", "Maximum",
                       "Minimum"):
                op = {"Add": "add", "Subtract": "sub", "Multiply": "mul",
                      "Average": "ave", "Maximum": "max",
                      "Minimum": "min"}[cls]
                builder.add_vertex(name, ElementWiseVertex(op=op), *in_names)
                keras_shapes[name] = in_shape
                continue
            if cls == "Concatenate":
                builder.add_vertex(name, MergeVertex(), *in_names)
                shapes = [keras_shapes.get(n) for n in inbound]
                if in_shape is not None and all(s is not None
                                                for s in shapes):
                    merged = list(in_shape)
                    merged[-1] = sum(s[-1] for s in shapes)
                    keras_shapes[name] = tuple(merged)
                continue
            a = _adapt_layer(cls, cfg, in_shape)
            if a is None:
                alias[name] = in_names[0] if in_names else name
                keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)
                continue
            builder.add_layer(name, a.layer, *in_names)
            adapted[name] = (a, in_shape)
            keras_shapes[name] = _keras_out_shape(cls, cfg, in_shape)

        out_names = [alias.get(n, n)
                     for n in _ref_names(gcfg.get("output_layers", []))]
        builder.set_outputs(*out_names)
        in_types = [_keras_shape_to_input_type(keras_shapes.get(n) or
                                               (input_shape if input_shape
                                                else None))
                    for n in input_names]
        if all(t is not None for t in in_types):
            builder.set_input_types(*in_types)
        conf = builder.build()
        net = ComputationGraph(conf)
        net.init()
        for name, (a, in_shape) in adapted.items():
            if a.set_weights is None:
                continue
            if name not in weights:
                raise ImportException(f"no weights for layer {name!r} in h5")
            net._params[name] = a.set_weights(weights[name], in_shape)
        net._updater_state = conf.updater.init(net._trainable(net._params))
        return net


def _parse_inbound(inbound_nodes) -> List[str]:
    """Inbound layer names across Keras 2/3 serialization formats."""
    names: List[str] = []
    if not inbound_nodes:
        return names
    node = inbound_nodes[0]
    if isinstance(node, dict):  # keras 3: {"args": [...], "kwargs": {}}
        def find_hist(obj):
            if isinstance(obj, dict):
                if "keras_history" in obj.get("config", {}):
                    names.append(obj["config"]["keras_history"][0])
                else:
                    for v in obj.values():
                        find_hist(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    find_hist(v)
        find_hist(node.get("args", []))
    else:  # keras 2: [["layer", node_idx, tensor_idx, {}], ...]
        for item in node:
            names.append(item[0])
    return names


def import_keras_sequential_model_and_weights(path, input_shape=None):
    return KerasModelImport.import_keras_sequential_model_and_weights(
        path, input_shape)


def import_keras_model_and_weights(path, input_shape=None):
    return KerasModelImport.import_keras_model_and_weights(path, input_shape)
