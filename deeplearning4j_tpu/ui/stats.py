"""StatsListener + StatsStorage (reference deeplearning4j-ui-model).

Reference: `StatsListener.java` (scores, param/update histograms and norms,
update:param ratios, memory, timing per iteration), `InMemoryStatsStorage`,
MapDB-backed `FileStatsStorage`, `RemoteUIStatsStorageRouter`.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class BaseStatsStorage:
    """StatsStorage API (reference org/deeplearning4j/api/storage)."""

    def put_static_info(self, session_id: str, info: Dict):
        raise NotImplementedError

    def put_update(self, session_id: str, record: Dict):
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def get_updates(self, session_id: str,
                    since_iteration: int = -1) -> List[Dict]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[Dict]:
        ups = self.get_updates(session_id)
        return ups[-1] if ups else None


class InMemoryStatsStorage(BaseStatsStorage):
    """Reference InMemoryStatsStorage."""

    def __init__(self):
        self._static: Dict[str, Dict] = {}
        self._updates: Dict[str, List[Dict]] = {}
        self._lock = threading.Lock()

    def put_static_info(self, session_id, info):
        with self._lock:
            self._static[session_id] = dict(info)
            self._updates.setdefault(session_id, [])

    def put_update(self, session_id, record):
        with self._lock:
            self._updates.setdefault(session_id, []).append(dict(record))

    def list_session_ids(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_updates(self, session_id, since_iteration=-1):
        with self._lock:
            ups = list(self._updates.get(session_id, []))
        return [u for u in ups if u.get("iteration", 0) > since_iteration]


class FileStatsStorage(BaseStatsStorage):
    """JSONL-file-backed storage (reference FileStatsStorage, minus MapDB):
    append-only updates file + static-info sidecar, reload-safe."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self._lock = threading.Lock()
        self._mem = InMemoryStatsStorage()
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec["kind"] == "static":
                        self._mem.put_static_info(rec["session"],
                                                  rec["data"])
                    else:
                        self._mem.put_update(rec["session"], rec["data"])

    def _append(self, kind, session_id, data):
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps({"kind": kind, "session": session_id,
                                    "data": data}) + "\n")

    def put_static_info(self, session_id, info):
        self._mem.put_static_info(session_id, info)
        self._append("static", session_id, info)

    def put_update(self, session_id, record):
        self._mem.put_update(session_id, record)
        self._append("update", session_id, record)

    def list_session_ids(self):
        return self._mem.list_session_ids()

    def get_static_info(self, session_id):
        return self._mem.get_static_info(session_id)

    def get_updates(self, session_id, since_iteration=-1):
        return self._mem.get_updates(session_id, since_iteration)


class RemoteUIStatsStorageRouter(BaseStatsStorage):
    """POST records to a remote UIServer (reference
    RemoteUIStatsStorageRouter)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def _post(self, endpoint: str, payload: Dict):
        import urllib.request
        req = urllib.request.Request(
            self.url + endpoint, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.read()

    def put_static_info(self, session_id, info):
        self._post("/remote/static", {"session": session_id, "data": info})

    def put_update(self, session_id, record):
        self._post("/remote/update", {"session": session_id, "data": record})

    def list_session_ids(self):
        return []

    def get_static_info(self, session_id):
        return None

    def get_updates(self, session_id, since_iteration=-1):
        return []


def _histogram(arr, bins=20):
    a = np.asarray(arr).ravel()
    if a.size == 0:
        return {"counts": [], "edges": []}
    counts, edges = np.histogram(a, bins=bins)
    return {"counts": counts.tolist(),
            "edges": [float(e) for e in edges]}


class StatsListener:
    """Per-iteration training stats collector (reference StatsListener).

    Attach to MultiLayerNetwork/ComputationGraph via `add_listener` /
    `_listeners`. Collects: score, per-layer param/gradient L2 norms and
    mean magnitudes, update:param ratios, histograms (every
    `histogram_frequency` iters), timing, device memory.
    """

    def __init__(self, storage: BaseStatsStorage, session_id: str = None,
                 update_frequency: int = 1, histogram_frequency: int = 10):
        self.storage = storage
        self.session_id = session_id or f"session_{int(time.time())}"
        self.update_frequency = update_frequency
        self.histogram_frequency = histogram_frequency
        self._static_sent = False
        self._last_time = None
        self._prev_flat: Optional[np.ndarray] = None

    def _send_static(self, model):
        info = {
            "model_class": type(model).__name__,
            "n_layers": len(getattr(model, "layers", [])) or
            len(getattr(model, "_order", [])),
            "n_params": int(model.num_params())
            if hasattr(model, "num_params") else 0,
            "start_time": time.time(),
        }
        try:
            import jax
            info["backend"] = jax.default_backend()
            info["device_count"] = jax.device_count()
        except Exception:
            pass
        self.storage.put_static_info(self.session_id, info)
        self._static_sent = True

    def _param_items(self, model):
        params = getattr(model, "_params", None)
        if isinstance(params, dict):
            for name, p in params.items():
                for k, v in p.items():
                    yield f"{name}/{k}", v
        elif isinstance(params, list):
            for i, p in enumerate(params):
                for k, v in p.items():
                    yield f"layer{i}/{k}", v

    def iteration_done(self, model, iteration, loss=None):
        if iteration % self.update_frequency != 0:
            return
        if not self._static_sent:
            self._send_static(model)
        now = time.time()
        dt = (now - self._last_time) if self._last_time else None
        self._last_time = now

        record: Dict[str, Any] = {
            "iteration": int(iteration),
            "time": now,
            "score": float(loss) if loss is not None else
            float(getattr(model, "score_value", float("nan"))),
            "iter_seconds": dt,
        }
        flats = []
        param_stats = {}
        with_hist = iteration % self.histogram_frequency == 0
        for name, v in self._param_items(model):
            if name.split("/")[-1].startswith("state_"):
                continue
            a = np.asarray(v)
            flats.append(a.ravel())
            s = {"l2": float(np.linalg.norm(a)),
                 "mean_mag": float(np.mean(np.abs(a)))}
            if with_hist:
                s["histogram"] = _histogram(a)
            param_stats[name] = s
        record["params"] = param_stats
        if flats:
            flat = np.concatenate(flats)
            if self._prev_flat is not None and \
                    self._prev_flat.shape == flat.shape:
                upd = flat - self._prev_flat
                p_norm = float(np.linalg.norm(self._prev_flat))
                record["update_param_ratio"] = \
                    float(np.linalg.norm(upd) / max(p_norm, 1e-12))
            self._prev_flat = flat
        try:
            import jax
            stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
            if stats:
                record["memory"] = {
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", 0)),
                }
        except Exception:
            pass
        self.storage.put_update(self.session_id, record)

    def on_epoch_end(self, epoch, model):
        pass
