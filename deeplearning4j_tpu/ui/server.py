"""Training dashboard HTTP server (reference VertxUIServer + TrainModule).

Reference: `deeplearning4j-vertx/.../VertxUIServer.java:78` serving the
train module (`module/train/TrainModule.java`) over HTTP, plus the remote
POST endpoints used by RemoteUIStatsStorageRouter.

stdlib http.server; endpoints:
  GET  /                      dashboard (score chart, param norms, ratios)
  GET  /train/sessions        session id list
  GET  /train/overview?sid=   static info + updates
  POST /remote/static|update  remote stats ingestion
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .stats import BaseStatsStorage, InMemoryStatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>DL4J-TPU Training UI</title>
<style>
 body { font-family: sans-serif; margin: 20px; background: #fafafa; }
 h1 { font-size: 20px; } h2 { font-size: 15px; color: #444; }
 .row { display: flex; gap: 24px; flex-wrap: wrap; }
 canvas { background: #fff; border: 1px solid #ccc; }
 #meta { color: #666; font-size: 13px; }
</style></head>
<body>
<h1>Training Dashboard</h1>
<div id="meta"></div>
<div class="row">
 <div><h2>Score vs Iteration</h2><canvas id="score" width="460" height="260"></canvas></div>
 <div><h2>Update : Param Ratio (log10)</h2><canvas id="ratio" width="460" height="260"></canvas></div>
</div>
<script>
function drawLine(canvas, xs, ys, color) {
  const c = canvas.getContext('2d');
  c.clearRect(0, 0, canvas.width, canvas.height);
  if (xs.length < 2) return;
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const px = x => 40 + (x - xmin) / (xmax - xmin || 1) * (canvas.width - 50);
  const py = y => canvas.height - 25 - (y - ymin) / (ymax - ymin || 1) * (canvas.height - 40);
  c.strokeStyle = '#999'; c.strokeRect(40, 15, canvas.width - 50, canvas.height - 40);
  c.fillStyle = '#333'; c.font = '11px sans-serif';
  c.fillText(ymax.toPrecision(4), 2, 20); c.fillText(ymin.toPrecision(4), 2, canvas.height - 25);
  c.strokeStyle = color; c.beginPath();
  xs.forEach((x, i) => i ? c.lineTo(px(x), py(ys[i])) : c.moveTo(px(x), py(ys[i])));
  c.stroke();
}
async function refresh() {
  const sessions = await (await fetch('train/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const data = await (await fetch('train/overview?sid=' + sid)).json();
  const ups = data.updates || [];
  const iters = ups.map(u => u.iteration);
  drawLine(document.getElementById('score'), iters, ups.map(u => u.score), '#c33');
  const rat = ups.filter(u => u.update_param_ratio != null);
  drawLine(document.getElementById('ratio'), rat.map(u => u.iteration),
           rat.map(u => Math.log10(u.update_param_ratio + 1e-12)), '#36c');
  const s = data.static || {};
  document.getElementById('meta').textContent =
    `session ${sid} | ${s.model_class || ''} | params: ${s.n_params || '?'} ` +
    `| backend: ${s.backend || '?'} x${s.device_count || 1} | updates: ${ups.length}`;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class UIServer:
    """Reference UIServer.getInstance().attach(statsStorage) pattern."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storage: BaseStatsStorage = InMemoryStatsStorage()
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: BaseStatsStorage):
        self.storage = storage
        return self

    # -- http -------------------------------------------------------------
    def _handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path in ("/", "/train", "/train/"):
                    body = _PAGE.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif url.path == "/train/sessions":
                    self._json(server.storage.list_session_ids())
                elif url.path == "/train/overview":
                    q = parse_qs(url.query)
                    sid = q.get("sid", [""])[0]
                    if not sid:
                        ids = server.storage.list_session_ids()
                        sid = ids[-1] if ids else ""
                    self._json({
                        "static": server.storage.get_static_info(sid),
                        "updates": server.storage.get_updates(sid),
                    })
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/remote/static":
                    server.storage.put_static_info(payload["session"],
                                                   payload["data"])
                    self._json({"ok": True})
                elif self.path == "/remote/update":
                    server.storage.put_update(payload["session"],
                                              payload["data"])
                    self._json({"ok": True})
                else:
                    self._json({"error": "not found"}, 404)

        return Handler

    def start(self) -> int:
        """Start serving (daemon thread); returns the bound port."""
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
