"""Training dashboard HTTP server (reference VertxUIServer + TrainModule).

Reference: `deeplearning4j-vertx/.../VertxUIServer.java:78` serving the
train module (`module/train/TrainModule.java`) over HTTP, plus the remote
POST endpoints used by RemoteUIStatsStorageRouter.

stdlib http.server via the shared handler base in `common/httpserver.py`
(Content-Length on every response, client disconnects without stack
traces — same hygiene as the serving front end); endpoints:
  GET  /                      dashboard (score chart, param norms, ratios)
  GET  /train/sessions        session id list
  GET  /train/overview?sid=   static info + updates
  GET  /metrics               runtime telemetry, Prometheus text exposition
  GET  /metrics.json          same registry as a JSON snapshot (+quantiles)
  GET  /debug/trace/<id>      one trace's buffered span events + tree
  GET  /debug/compile_cache   executable inventory with XLA cost analysis
  GET  /debug/memory          per-device memory stats
  POST /debug/profile?seconds=  on-demand jax.profiler capture
  POST /remote/static|update  remote stats ingestion

(The ``/debug/*`` family is the shared one from ``common/httpserver.py``
— the training dashboard answers the same debugging questions as the
serving front end, minus the serving-only recent-requests ring.)
"""
from __future__ import annotations

import json
import threading
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..common.environment import environment
from ..common.httpserver import (JsonRequestHandler,
                                 QuietThreadingHTTPServer, handle_debug_get,
                                 handle_debug_post, metrics_payload)
from .stats import BaseStatsStorage, InMemoryStatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>DL4J-TPU Training UI</title>
<style>
 body { font-family: sans-serif; margin: 20px; background: #fafafa; }
 h1 { font-size: 20px; } h2 { font-size: 14px; color: #444; margin: 4px 0; }
 .row { display: flex; gap: 22px; flex-wrap: wrap; }
 canvas { background: #fff; border: 1px solid #ccc; }
 #meta { color: #666; font-size: 13px; margin-bottom: 10px; }
 select { font-size: 13px; margin: 0 8px 8px 0; }
 table { border-collapse: collapse; font-size: 12px; background: #fff; }
 th, td { border: 1px solid #ddd; padding: 3px 8px; text-align: left; }
 th { background: #eee; }
 .legend { font-size: 11px; }
 .legend span { margin-right: 10px; }
</style></head>
<body>
<h1>Training Dashboard</h1>
<div>
 session <select id="sess"></select>
 layer <select id="layersel"></select>
 param <select id="paramsel"></select>
</div>
<div id="meta"></div>
<div class="row">
 <div><h2>Score vs Iteration</h2><canvas id="score" width="440" height="240"></canvas></div>
 <div><h2>Update : Param Ratio (log10)</h2><canvas id="ratio" width="440" height="240"></canvas></div>
 <div><h2>Iteration Time (s)</h2><canvas id="itertime" width="440" height="240"></canvas></div>
 <div><h2>Device Memory (MB)</h2><canvas id="mem" width="440" height="240"></canvas></div>
</div>
<div class="row">
 <div><h2>Per-layer Mean |W| (log10)</h2>
  <canvas id="layers" width="440" height="240"></canvas>
  <div id="layerlegend" class="legend"></div></div>
 <div><h2>Parameter Histogram (latest)</h2>
  <canvas id="hist" width="440" height="240"></canvas></div>
 <div><h2>Layers</h2><table id="layertable"></table></div>
</div>
<script>
const PALETTE = ['#c33','#36c','#2a2','#b70','#829','#067','#a14','#551'];
function axes(c, canvas, xmin, xmax, ymin, ymax) {
  c.clearRect(0, 0, canvas.width, canvas.height);
  c.strokeStyle = '#999'; c.strokeRect(40, 15, canvas.width - 50, canvas.height - 40);
  c.fillStyle = '#333'; c.font = '11px sans-serif';
  c.fillText(ymax.toPrecision(4), 2, 20);
  c.fillText(ymin.toPrecision(4), 2, canvas.height - 25);
  c.fillText(String(xmin), 40, canvas.height - 8);
  c.fillText(String(xmax), canvas.width - 40, canvas.height - 8);
}
function drawSeries(canvas, xs, seriesList) {
  // seriesList: [{ys, color}] sharing the xs domain
  const c = canvas.getContext('2d');
  const all = seriesList.flatMap(s => s.ys).filter(y => isFinite(y));
  if (xs.length < 2 || !all.length) {
    c.clearRect(0, 0, canvas.width, canvas.height); return;
  }
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...all), ymax = Math.max(...all);
  axes(c, canvas, xmin, xmax, ymin, ymax);
  const px = x => 40 + (x - xmin) / (xmax - xmin || 1) * (canvas.width - 50);
  const py = y => canvas.height - 25 - (y - ymin) / (ymax - ymin || 1) * (canvas.height - 40);
  for (const s of seriesList) {
    c.strokeStyle = s.color; c.beginPath();
    let started = false;
    xs.forEach((x, i) => {
      const y = s.ys[i];
      if (!isFinite(y)) return;
      if (started) c.lineTo(px(x), py(y)); else { c.moveTo(px(x), py(y)); started = true; }
    });
    c.stroke();
  }
}
function esc(t) {
  return String(t).replace(/[&<>"']/g,
      ch => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[ch]));
}
function drawLine(canvas, xs, ys, color) { drawSeries(canvas, xs, [{ys, color}]); }
function drawHist(canvas, hist) {
  const c = canvas.getContext('2d');
  c.clearRect(0, 0, canvas.width, canvas.height);
  if (!hist || !hist.counts || !hist.counts.length) return;
  const n = hist.counts.length, cmax = Math.max(...hist.counts);
  const e = hist.edges || [];
  axes(c, canvas, e.length ? e[0] : 0, e.length ? e[e.length - 1] : 1,
       0, cmax);
  const w = (canvas.width - 50) / n;
  c.fillStyle = '#36c';
  hist.counts.forEach((v, i) => {
    const h = v / (cmax || 1) * (canvas.height - 40);
    c.fillRect(40 + i * w, canvas.height - 25 - h, Math.max(w - 1, 1), h);
  });
}
function fillSelect(el, options) {
  // rebuild only when the option list changed (a rebuild collapses an
  // open dropdown); keep the user's selection, default to the LAST
  // option (newest session) on first fill
  const cur = el.value;
  const existing = [...el.options].map(o => o.value);
  if (existing.length !== options.length ||
      existing.some((v, i) => v !== options[i])) {
    el.innerHTML = '';
    for (const o of options) {
      const opt = document.createElement('option');
      opt.value = o; opt.textContent = o; el.appendChild(opt);
    }
    el.value = options.includes(cur) ? cur : options[options.length - 1];
  }
}
async function refresh() {
  const sessions = await (await fetch('train/sessions')).json();
  if (!sessions.length) return;
  fillSelect(document.getElementById('sess'), sessions);
  const sid = document.getElementById('sess').value;
  const data = await (await fetch('train/overview?sid=' + sid)).json();
  const ups = data.updates || [];
  const iters = ups.map(u => u.iteration);
  drawLine(document.getElementById('score'), iters, ups.map(u => u.score), '#c33');
  const rat = ups.filter(u => u.update_param_ratio != null);
  drawSeries(document.getElementById('ratio'), rat.map(u => u.iteration),
      [{ys: rat.map(u => Math.log10(u.update_param_ratio + 1e-12)), color: '#36c'}]);
  const tm = ups.filter(u => u.iter_seconds != null);
  drawSeries(document.getElementById('itertime'), tm.map(u => u.iteration),
      [{ys: tm.map(u => u.iter_seconds), color: '#2a2'}]);
  const mm = ups.filter(u => u.memory);
  drawSeries(document.getElementById('mem'), mm.map(u => u.iteration),
      [{ys: mm.map(u => u.memory.bytes_in_use / 1048576), color: '#b70'},
       {ys: mm.map(u => (u.memory.peak_bytes_in_use || 0) / 1048576), color: '#829'}]);

  // per-layer series: prefer the weight-like param (W/kernel) of each
  // layer over biases; note truncation when layers exceed the palette
  const last = ups[ups.length - 1] || {};
  const names = Object.keys(last.params || {});
  const layers = [...new Set(names.map(n => n.split('/')[0]))];
  const series = [], legend = [];
  layers.slice(0, PALETTE.length).forEach((ln, i) => {
    const mine = names.filter(n => n.startsWith(ln + '/'));
    const key = mine.find(n => /[/](W|w|kernel|Wx)$/.test(n)) || mine[0];
    if (!key) return;
    series.push({ys: ups.map(u => {
      const p = (u.params || {})[key];
      return p ? Math.log10(p.mean_mag + 1e-12) : NaN;
    }), color: PALETTE[i]});
    legend.push(`<span style="color:${PALETTE[i]}">■ ${esc(key)}</span>`);
  });
  if (layers.length > PALETTE.length) {
    legend.push(`<span>(+${layers.length - PALETTE.length} more layers)</span>`);
  }
  drawSeries(document.getElementById('layers'), iters, series);
  document.getElementById('layerlegend').innerHTML = legend.join('');

  // histogram of the selected param (latest update that carries one);
  // cleared when none exists so a stale chart never lingers
  fillSelect(document.getElementById('layersel'), layers);
  const lsel = document.getElementById('layersel').value;
  const pnames = names.filter(n => n.startsWith((lsel || '') + '/'));
  fillSelect(document.getElementById('paramsel'), pnames);
  const psel = document.getElementById('paramsel').value;
  let hist = null;
  for (let i = ups.length - 1; i >= 0; i--) {
    const p = (ups[i].params || {})[psel];
    if (p && p.histogram) { hist = p.histogram; break; }
  }
  drawHist(document.getElementById('hist'), hist);

  // layer table: latest l2 / mean|W| per param (names escaped — the
  // remote ingestion endpoint is open, so treat them as untrusted)
  const rows = ['<tr><th>param</th><th>L2</th><th>mean |W|</th></tr>'];
  for (const n of names) {
    const p = last.params[n];
    rows.push(`<tr><td>${esc(n)}</td><td>${p.l2.toPrecision(5)}</td>` +
              `<td>${p.mean_mag.toPrecision(5)}</td></tr>`);
  }
  document.getElementById('layertable').innerHTML = rows.join('');

  const s = data.static || {};
  document.getElementById('meta').textContent =
    `session ${sid} | ${s.model_class || ''} | layers: ${s.n_layers || '?'} ` +
    `| params: ${s.n_params || '?'} | backend: ${s.backend || '?'} ` +
    `x${s.device_count || 1} | updates: ${ups.length}`;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class UIServer:
    """Reference UIServer.getInstance().attach(statsStorage) pattern."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self.storage: BaseStatsStorage = InMemoryStatsStorage()
        self._httpd = None
        self._thread = None

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: BaseStatsStorage):
        self.storage = storage
        return self

    # -- http -------------------------------------------------------------
    def _handler(self):
        server = self

        class Handler(JsonRequestHandler):
            def do_GET(self):
                url = urlparse(self.path)
                if url.path in ("/", "/train", "/train/"):
                    self.send_payload(_PAGE.encode(), "text/html")
                elif url.path == "/metrics":
                    # Prometheus text exposition of the process registry
                    # (training + serving instrumentation alike)
                    self.send_payload(*metrics_payload())
                elif url.path == "/metrics.json":
                    self.send_payload(*metrics_payload("json"))
                elif url.path == "/train/sessions":
                    self.send_json(server.storage.list_session_ids())
                elif url.path == "/train/overview":
                    q = parse_qs(url.query)
                    sid = q.get("sid", [""])[0]
                    if not sid:
                        ids = server.storage.list_session_ids()
                        sid = ids[-1] if ids else ""
                    self.send_json({
                        "static": server.storage.get_static_info(sid),
                        "updates": server.storage.get_updates(sid),
                    })
                elif url.path.startswith("/debug/"):
                    if not (environment().debug_endpoints_enabled()
                            and handle_debug_get(self, url.path)):
                        self.send_json({"error": "not found"}, 404)
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):
                url = urlparse(self.path)
                if url.path.startswith("/debug/"):
                    if not (environment().debug_endpoints_enabled()
                            and handle_debug_post(self, url.path,
                                                  parse_qs(url.query))):
                        self.send_json({"error": "not found"}, 404)
                    return
                payload = json.loads(self.read_body() or b"{}")
                if self.path == "/remote/static":
                    server.storage.put_static_info(payload["session"],
                                                   payload["data"])
                    self.send_json({"ok": True})
                elif self.path == "/remote/update":
                    server.storage.put_update(payload["session"],
                                              payload["data"])
                    self.send_json({"ok": True})
                else:
                    self.send_json({"error": "not found"}, 404)

        return Handler

    def start(self) -> int:
        """Start serving (daemon thread); returns the bound port."""
        self._httpd = QuietThreadingHTTPServer(("127.0.0.1", self.port),
                                               self._handler())
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
