"""Training UI / observability.

Reference: `deeplearning4j-ui-parent/` (28.5k LoC) — StatsListener
(ui-model) collecting per-iteration stats into StatsStorage (in-memory or
file-backed), served by VertxUIServer's train module, with
RemoteUIStatsStorageRouter posting across JVMs.

TPU-native shape: same three roles, stdlib-only — `StatsListener` ->
`StatsStorage` (in-memory / JSONL file) -> `UIServer` (http.server
dashboard polling JSON endpoints). Remote posting via
`RemoteUIStatsStorageRouter` (urllib POST to a peer UIServer).
"""
from .stats import (InMemoryStatsStorage, FileStatsStorage, StatsListener,
                    RemoteUIStatsStorageRouter)
from .server import UIServer

__all__ = ["InMemoryStatsStorage", "FileStatsStorage", "StatsListener",
           "RemoteUIStatsStorageRouter", "UIServer"]
