"""Shared flat-Adam update for the model-level train steps.

The model modules (bert / bert-pipeline / bert-QA / seq2seq) all use the
same (u, m)-lists optimizer state layout; this is the single
tree_flatten -> adam_updater -> tree_unflatten pass they share.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import updater_ops


def adam_apply(params, grads, opt_state, learning_rate, iteration,
               cast_f32: bool = True):
    """One Adam step over a pytree. opt_state = (u_list, m_list) aligned
    with tree_leaves(params). With cast_f32, the update math runs in f32
    and the result is cast back to each param's dtype (bf16 masters)."""
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_p = jax.tree_util.tree_flatten(params)[0]
    u, m = opt_state
    new_p, new_u, new_m = [], [], []
    for p, g, ui, mi in zip(flat_p, flat_g, u, m):
        g_ = g.astype(jnp.float32) if cast_f32 else g
        upd, u2, m2 = updater_ops.adam_updater(g_, ui, mi,
                                               lr=learning_rate,
                                               iteration=iteration)
        if cast_f32:
            new_p.append((p.astype(jnp.float32) - upd).astype(p.dtype))
        else:
            new_p.append(p - upd)
        new_u.append(u2)
        new_m.append(m2)
    return jax.tree_util.tree_unflatten(treedef, new_p), (new_u, new_m)


def adam_init(params):
    flat = jax.tree_util.tree_leaves(params)
    return ([jnp.zeros(p.shape, jnp.float32) for p in flat],
            [jnp.zeros(p.shape, jnp.float32) for p in flat])
