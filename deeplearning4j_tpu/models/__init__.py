"""Model zoo (deeplearning4j-zoo analog)."""
