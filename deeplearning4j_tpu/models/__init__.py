"""Native flagship models: `bert` (encoder, TP/SP/PP training),
`causal_lm` (decoder-only LM with cache-aware attention — the generative
serving workload), `seq2seq` (LSTM encoder-decoder with cached greedy
decode)."""
