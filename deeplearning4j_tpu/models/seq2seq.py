"""Seq2Seq LSTM: encoder-decoder with teacher forcing + greedy decode.

Reference context: BASELINE config 4 ("Word2Vec / Seq2Seq LSTM") — the
reference builds seq2seq as a ComputationGraph of LSTM + RnnOutputLayer
with manual decode loops in user code (dl4j-examples
AdditionRNN/Seq2SeqExample pattern). TPU-native: one params pytree, the
training step is a single jitted fwd+bwd+Adam program, and autoregressive
decode is a `lax.scan` carrying the decode cache (the recurrent state —
the LSTM analog of a transformer KV cache) — compiled once, one
``decode_step`` per token, no per-token Python and no prefix recompute
(``greedy_decode_recompute`` keeps the naive O(T²) loop as the
regression-test reference).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import recurrent
from . import _optim


@dataclasses.dataclass
class Seq2SeqConfig:
    vocab_size: int = 64          # shared src/tgt vocab
    embed_dim: int = 64
    hidden: int = 128
    bos_token: int = 1
    pad_token: int = 0

    @staticmethod
    def tiny() -> "Seq2SeqConfig":
        return Seq2SeqConfig(vocab_size=16, embed_dim=16, hidden=32)


def init_params(key, c: Seq2SeqConfig) -> Dict:
    k = iter(jax.random.split(key, 8))
    std = 0.1

    def w(shape):
        return std * jax.random.normal(next(k), shape, jnp.float32)

    return {
        "embed": w((c.vocab_size, c.embed_dim)),
        "enc": {"Wx": w((c.embed_dim, 4 * c.hidden)),
                "Wh": w((c.hidden, 4 * c.hidden)),
                "b": jnp.zeros((4 * c.hidden,))},
        "dec": {"Wx": w((c.embed_dim, 4 * c.hidden)),
                "Wh": w((c.hidden, 4 * c.hidden)),
                "b": jnp.zeros((4 * c.hidden,))},
        "out": {"W": w((c.hidden, c.vocab_size)),
                "b": jnp.zeros((c.vocab_size,))},
    }


def _encode(params, src_ids):
    """src_ids [B, S] -> (h_T, c_T)."""
    emb = jnp.take(params["embed"], src_ids, axis=0)       # [B, S, E]
    _, h, cell = recurrent.lstm_layer(emb, params["enc"]["Wx"],
                                      params["enc"]["Wh"],
                                      params["enc"]["b"])
    return h, cell


def teacher_forcing_logits(params, src_ids, tgt_in_ids):
    """Training forward: decoder consumes gold tokens (teacher forcing)."""
    h0, c0 = _encode(params, src_ids)
    emb = jnp.take(params["embed"], tgt_in_ids, axis=0)
    h_seq, _, _ = recurrent.lstm_layer(emb, params["dec"]["Wx"],
                                       params["dec"]["Wh"],
                                       params["dec"]["b"], h0=h0, c0=c0)
    return jnp.einsum("bth,hv->btv", h_seq, params["out"]["W"]) \
        + params["out"]["b"]


def loss_fn(params, batch, c: Seq2SeqConfig):
    """batch: src [B,S], tgt_in [B,T] (BOS-shifted), tgt_out [B,T]."""
    logits = teacher_forcing_logits(params, batch["src"], batch["tgt_in"])
    labels = batch["tgt_out"]
    valid = labels != c.pad_token
    lsm = jax.nn.log_softmax(logits, axis=-1)
    per_tok = -jnp.take_along_axis(lsm, labels[..., None], axis=-1)[..., 0]
    per_tok = jnp.where(valid, per_tok, 0.0)
    return jnp.sum(per_tok) / jnp.maximum(jnp.sum(valid), 1)


def make_train_step(c: Seq2SeqConfig, learning_rate: float = 1e-2):
    def step(params, opt_state, batch, iteration):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, c)
        new_params, opt_state = _optim.adam_apply(
            params, grads, opt_state, learning_rate, iteration)
        return new_params, opt_state, loss

    # counted_jit (DL101): compile events + AOT-store routing
    from ..runtime.inference import counted_jit
    return counted_jit(step, tag=f"seq2seq_train:{id(step)}",
                       donate_argnums=(0, 1))


def init_opt_state(params):
    return _optim.adam_init(params)


def decode_step(params, cache, tok):
    """ONE cached decode step: the LSTM analog of a KV-cached transformer
    step. ``cache`` is the carried recurrent state ``(h, cell)`` — the
    entire summary of the prefix, so each token costs one ``lstm_cell``
    instead of re-running the decoder over the whole prefix. Returns
    ``(new_cache, logits [B, V])``."""
    h, cell = cache
    emb = jnp.take(params["embed"], tok, axis=0)           # [B, E]
    h, cell = recurrent.lstm_cell(emb, h, cell, params["dec"]["Wx"],
                                  params["dec"]["Wh"],
                                  params["dec"]["b"])
    logits = h @ params["out"]["W"] + params["out"]["b"]
    return (h, cell), logits


def greedy_decode(params, src_ids, max_len: int, c: Seq2SeqConfig):
    """Autoregressive argmax decode as one lax.scan with the decode cache
    (the recurrent state) carried through the scan — O(T) total work,
    the whole loop compiled. Token-identical to the naive
    ``greedy_decode_recompute`` reference (regression-tested)."""
    B = src_ids.shape[0]
    cache = _encode(params, src_ids)
    bos = jnp.full((B,), c.bos_token, jnp.int32)

    def step(carry, _):
        cache, tok = carry
        cache, logits = decode_step(params, cache, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    _, toks = lax.scan(step, (cache, bos), None, length=max_len)
    return jnp.swapaxes(toks, 0, 1)                        # [B, max_len]


def greedy_decode_recompute(params, src_ids, max_len: int, c: Seq2SeqConfig):
    """The naive O(T²) reference: every token re-runs the decoder LSTM
    over the ENTIRE generated prefix from the encoder state (the manual
    decode-loop pattern of the reference's Seq2SeqExample user code, and
    the transformer equivalent of recomputing attention over the whole
    prefix each step). Exists so the regression test can assert
    ``greedy_decode`` is token-identical while carrying the cache."""
    import numpy as np

    B = src_ids.shape[0]
    h0, c0 = _encode(params, src_ids)
    toks = np.full((B, 1), c.bos_token, np.int32)          # BOS + prefix
    out = []
    for _ in range(max_len):
        emb = jnp.take(params["embed"], jnp.asarray(toks), axis=0)
        h_seq, _, _ = recurrent.lstm_layer(emb, params["dec"]["Wx"],
                                           params["dec"]["Wh"],
                                           params["dec"]["b"], h0=h0, c0=c0)
        logits = h_seq[:, -1] @ params["out"]["W"] + params["out"]["b"]
        nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        out.append(nxt)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.asarray(np.stack(out, axis=1))              # [B, max_len]


def fit_copy_task(c: Seq2SeqConfig = None, steps: int = 300, B: int = 32,
                  S: int = 8, seed: int = 0, task: str = "reverse"):
    """Train on a synthetic sequence task; returns (params, losses)."""
    import numpy as np

    c = c or Seq2SeqConfig.tiny()
    rs = np.random.RandomState(seed)
    params = init_params(jax.random.key(seed), c)
    opt = init_opt_state(params)
    step = make_train_step(c)
    losses = []
    for i in range(steps):
        src = rs.randint(2, c.vocab_size, (B, S)).astype(np.int32)
        tgt = src[:, ::-1] if task == "reverse" else src
        tgt_in = np.concatenate(
            [np.full((B, 1), c.bos_token, np.int32), tgt[:, :-1]], axis=1)
        batch = {"src": jnp.asarray(src), "tgt_in": jnp.asarray(tgt_in),
                 "tgt_out": jnp.asarray(tgt)}
        params, opt, loss = step(params, opt, batch, i)
        losses.append(float(loss))
    return params, losses
