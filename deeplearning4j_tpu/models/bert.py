"""BERT: the flagship transformer, TPU-first.

Reference context: the reference runs BERT only via TF-frozen-graph import
(`samediff-import`, BASELINE.md config 3). Here BERT is a native model with
first-class sharding — the component the reference never had (SURVEY.md §2.4:
TP/SP/PP absent) and the north-star benchmark target (≥35% MFU).

Design:
- Pure-functional params pytree; bfloat16 activations/weights, f32 layernorm
  and softmax accumulation (MXU-native mixed precision).
- Megatron-style tensor parallelism via sharding annotations: attention
  heads and MLP hidden sharded over `tensor`; XLA/GSPMD inserts the
  all-reduces. No hand-written collectives in the model body.
- Sequence parallelism: attention dispatches to ring attention (shard_map
  over `seq`) when the mesh has a seq axis > 1.
- One jitted train step: fwd + masked-LM loss + bwd + Adam, params donated.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA, FSDP, PIPE, SEQ, TENSOR
from ..quant.transforms import (dequant_matmul, dequantize, take_rows,
                                tied_logits)
from . import _optim
from ..parallel.ring_attention import blockwise_attention, ring_attention


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def large() -> "BertConfig":
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096)

    @staticmethod
    def tiny() -> "BertConfig":
        """For tests/dryruns."""
        return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128,
                          max_position_embeddings=128)


# -- parameter init -----------------------------------------------------

def init_params(key, config: BertConfig) -> Dict:
    c = config
    dt = c.dtype
    std = 0.02

    def dense(key, shape):
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dt)

    keys = iter(jax.random.split(key, 8 + 8 * c.num_layers))
    params = {
        "embeddings": {
            "word": dense(next(keys), (c.vocab_size, c.hidden_size)),
            "position": dense(next(keys), (c.max_position_embeddings,
                                           c.hidden_size)),
            "token_type": dense(next(keys), (c.type_vocab_size, c.hidden_size)),
            "ln_g": jnp.ones((c.hidden_size,), jnp.float32),
            "ln_b": jnp.zeros((c.hidden_size,), jnp.float32),
        },
        "layers": [],
        "mlm": {
            "dense": dense(next(keys), (c.hidden_size, c.hidden_size)),
            "dense_b": jnp.zeros((c.hidden_size,), dt),
            "ln_g": jnp.ones((c.hidden_size,), jnp.float32),
            "ln_b": jnp.zeros((c.hidden_size,), jnp.float32),
            "bias": jnp.zeros((c.vocab_size,), jnp.float32),
        },
        "pooler": {
            "w": dense(next(keys), (c.hidden_size, c.hidden_size)),
            "b": jnp.zeros((c.hidden_size,), dt),
        },
    }
    H, Dh, E, F = c.num_heads, c.head_dim, c.hidden_size, c.intermediate_size
    for _ in range(c.num_layers):
        params["layers"].append({
            "attn": {
                "wq": dense(next(keys), (E, H, Dh)),
                "wk": dense(next(keys), (E, H, Dh)),
                "wv": dense(next(keys), (E, H, Dh)),
                "wo": dense(next(keys), (H, Dh, E)),
                "bq": jnp.zeros((H, Dh), dt), "bk": jnp.zeros((H, Dh), dt),
                "bv": jnp.zeros((H, Dh), dt), "bo": jnp.zeros((E,), dt),
            },
            "mlp": {
                "w1": dense(next(keys), (E, F)), "b1": jnp.zeros((F,), dt),
                "w2": dense(next(keys), (F, E)), "b2": jnp.zeros((E,), dt),
            },
            "ln1_g": jnp.ones((E,), jnp.float32),
            "ln1_b": jnp.zeros((E,), jnp.float32),
            "ln2_g": jnp.ones((E,), jnp.float32),
            "ln2_b": jnp.zeros((E,), jnp.float32),
        })
    return params


# -- sharding rules (Megatron TP + optional FSDP) ------------------------

def param_specs(config: BertConfig) -> Dict:
    """PartitionSpec tree matching init_params' structure."""
    layer = {
        "attn": {
            "wq": P(FSDP, TENSOR, None), "wk": P(FSDP, TENSOR, None),
            "wv": P(FSDP, TENSOR, None), "wo": P(TENSOR, None, FSDP),
            "bq": P(TENSOR, None), "bk": P(TENSOR, None),
            "bv": P(TENSOR, None), "bo": P(),
        },
        "mlp": {
            "w1": P(FSDP, TENSOR), "b1": P(TENSOR),
            "w2": P(TENSOR, FSDP), "b2": P(),
        },
        "ln1_g": P(), "ln1_b": P(), "ln2_g": P(), "ln2_b": P(),
    }
    return {
        "embeddings": {"word": P(FSDP, None), "position": P(),
                       "token_type": P(), "ln_g": P(), "ln_b": P()},
        "layers": [layer] * config.num_layers,
        "mlm": {"dense": P(FSDP, None), "dense_b": P(), "ln_g": P(),
                "ln_b": P(), "bias": P()},
        "pooler": {"w": P(FSDP, None), "b": P()},
    }


def _ln(x, g, b, eps):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mean) * lax.rsqrt(var + eps) * g + b).astype(x.dtype)


# -- forward ------------------------------------------------------------

def _flash_effective(seq_len: int) -> bool:
    """Whether a flash=True config actually runs the Pallas kernel at this
    sequence length (below DL4J_TPU_FLASH_MIN_SEQ the XLA path is faster —
    BENCH_r05 measured 14.6x at seq_len=128)."""
    from ..kernels import attention_dispatch
    return attention_dispatch(seq_len) == "flash"


def _attention(layer_params, h, attention_mask, config: BertConfig,
               mesh: Optional[Mesh], seq_parallel: bool,
               use_flash: bool = False, tp_axis: Optional[str] = None):
    """Multi-head attention. tp_axis: when running INSIDE a shard_map with
    head-sharded weights (the pipeline's Megatron-TP stages), names the
    mesh axis for the explicit f/g collectives (tp_copy before QKV,
    tp_reduce after the output projection); None means replicated weights
    or GSPMD-annotated sharding (XLA inserts the collectives)."""
    a = layer_params["attn"]
    if tp_axis is not None:
        from ..parallel.pipeline import tp_copy
        h_in = tp_copy(h, tp_axis)
    else:
        h_in = h
    q = jnp.einsum("bte,ehd->bthd", h_in,
                   dequantize(a["wq"], h_in.dtype)) + a["bq"]
    k = jnp.einsum("bte,ehd->bthd", h_in,
                   dequantize(a["wk"], h_in.dtype)) + a["bk"]
    v = jnp.einsum("bte,ehd->bthd", h_in,
                   dequantize(a["wv"], h_in.dtype)) + a["bv"]
    if seq_parallel and mesh is not None:
        # use_flash composes with SP: the Pallas kernel computes each
        # K/V block inside the ring (VERDICT r4 #4 / SURVEY §5)
        ctx = ring_attention(q, k, v, mesh, mask=attention_mask,
                             causal=False, use_flash=use_flash)
    elif use_flash and _flash_effective(q.shape[1]):
        from ..kernels import flash_attention
        ctx = flash_attention(q, k, v, mask=attention_mask)
    else:
        scale = config.head_dim ** -0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        if attention_mask is not None:
            big_neg = jnp.finfo(jnp.float32).min
            logits = jnp.where(attention_mask[:, None, None, :].astype(bool),
                               logits, big_neg)
        probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    out = jnp.einsum("bqhd,hde->bqe", ctx, dequantize(a["wo"], ctx.dtype))
    if tp_axis is not None:
        from ..parallel.pipeline import tp_reduce
        out = tp_reduce(out, tp_axis)
    return out + a["bo"]


def encode(params, input_ids, token_type_ids=None, attention_mask=None, *,
           config: BertConfig, mesh: Optional[Mesh] = None,
           seq_parallel: bool = False, use_flash: bool = False):
    """Token ids [B, T] → contextual encodings [B, T, E]."""
    c = config
    e = params["embeddings"]
    B, T = input_ids.shape
    h = take_rows(e["word"], input_ids, dtype=c.dtype)
    h = h + e["position"][None, :T]
    if token_type_ids is not None:
        h = h + jnp.take(e["token_type"], token_type_ids, axis=0)
    else:
        h = h + e["token_type"][0]
    h = _ln(h, e["ln_g"], e["ln_b"], c.layer_norm_eps)
    if mesh is not None:
        h = lax.with_sharding_constraint(
            h, NamedSharding(mesh, P((DATA, FSDP), SEQ if seq_parallel else None,
                                     None)))

    for layer in params["layers"]:
        attn_out = _attention(layer, h, attention_mask, c, mesh, seq_parallel,
                              use_flash)
        h = _ln(h + attn_out, layer["ln1_g"], layer["ln1_b"], c.layer_norm_eps)
        mlp = layer["mlp"]
        inter = jax.nn.gelu(dequant_matmul(h, mlp["w1"]) + mlp["b1"])
        if mesh is not None:
            inter = lax.with_sharding_constraint(
                inter, NamedSharding(
                    mesh, P((DATA, FSDP), SEQ if seq_parallel else None,
                            TENSOR)))
        mlp_out = dequant_matmul(inter, mlp["w2"]) + mlp["b2"]
        h = _ln(h + mlp_out, layer["ln2_g"], layer["ln2_b"], c.layer_norm_eps)
        if mesh is not None:
            h = lax.with_sharding_constraint(
                h, NamedSharding(mesh, P((DATA, FSDP),
                                         SEQ if seq_parallel else None, None)))
    return h


def mlm_logits(params, encodings, config: BertConfig):
    """Masked-LM head with tied decoder weights."""
    m = params["mlm"]
    h = jax.nn.gelu(dequant_matmul(encodings, m["dense"]) + m["dense_b"])
    h = _ln(h, m["ln_g"], m["ln_b"], config.layer_norm_eps)
    # tied decoder: per-row scales of a quantized word table fold into the
    # f32 logits
    return tied_logits(h, params["embeddings"]["word"]) + m["bias"]


def pooled(params, encodings):
    return jnp.tanh(dequant_matmul(encodings[:, 0], params["pooler"]["w"])
                    + params["pooler"]["b"])


def mlm_loss(params, batch, config: BertConfig, mesh=None,
             seq_parallel=False, use_flash=False):
    """Masked-LM cross entropy. batch: input_ids, labels (-100 = unmasked),
    attention_mask.

    The vocab softmax-xent stays on XLA's fusion deliberately: a Pallas
    vocab-tiled kernel was measured 0.93x/0.61x (fwd/train) against it at
    the headline shape and deleted (kernels/__init__.py has the numbers)."""
    enc = encode(params, batch["input_ids"],
                 batch.get("token_type_ids"), batch.get("attention_mask"),
                 config=config, mesh=mesh, seq_parallel=seq_parallel,
                 use_flash=use_flash)
    logits = mlm_logits(params, enc, config)
    labels = batch["labels"]
    valid = labels >= 0
    safe_labels = jnp.where(valid, labels, 0)
    lsm = jax.nn.log_softmax(logits, axis=-1)
    per_tok = -jnp.take_along_axis(lsm, safe_labels[..., None],
                                   axis=-1)[..., 0]
    per_tok = jnp.where(valid, per_tok, 0.0)
    return jnp.sum(per_tok) / jnp.maximum(jnp.sum(valid), 1)


# -- training step ------------------------------------------------------

def _make_loss_fn(config, mesh, seq_parallel, remat, use_flash):
    loss_fn = functools.partial(mlm_loss, config=config, mesh=mesh,
                                seq_parallel=seq_parallel,
                                use_flash=use_flash)
    if remat:
        # rematerialize the encoder to trade FLOPs for HBM (checkpointing)
        loss_fn = jax.checkpoint(loss_fn)
    return loss_fn


def _jit_step(fn, config, mesh, seq_parallel):
    """jit a ``(params, opt_state, batch, scalar) -> (params, opt_state,
    aux)`` step with donated params/state and, when a mesh is given, the
    TP/FSDP/SP shardings from param_specs. Routed through ``counted_jit``
    (DL101) so BERT training shares the recompile counters and — for the
    unsharded step — the persistent executable store."""
    from ..runtime.inference import counted_jit

    donate = (0, 1)
    if mesh is None:
        return counted_jit(fn, tag=f"bert_train:{id(fn)}",
                           donate_argnums=donate)
    specs = param_specs(config)
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    flat_specs = [NamedSharding(mesh, s) for s in
                  jax.tree_util.tree_leaves(
                      specs, is_leaf=lambda x: isinstance(x, P))]
    opt_sh = (flat_specs, flat_specs)
    batch_sh = NamedSharding(mesh, P((DATA, FSDP),
                                     SEQ if seq_parallel else None))
    # batch_sh is a pytree *prefix*: it applies to every entry of the batch
    # dict, whatever keys the caller provides (token_type_ids included)
    return counted_jit(
        fn, tag=f"bert_train:{id(fn)}", donate_argnums=donate,
        in_shardings=(param_sh, opt_sh, batch_sh, None),
        out_shardings=(param_sh, opt_sh, None))


def make_train_step(config: BertConfig, mesh: Optional[Mesh] = None,
                    learning_rate: float = 1e-4, seq_parallel: bool = False,
                    remat: bool = True, use_flash: bool = False):
    """Single jitted train step: fwd+bwd+Adam, donated params/state.

    With a mesh: params placed per param_specs (TP/FSDP), batch sharded over
    (data, fsdp), sequence over seq when seq_parallel — XLA emits all ICI
    collectives (the entire reference PS stack, §2.5).
    use_flash selects the Pallas flash-attention kernel.
    """
    loss_fn = _make_loss_fn(config, mesh, seq_parallel, remat, use_flash)

    def step(params, opt_state, batch, iteration):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, opt_state = _optim.adam_apply(
            params, grads, opt_state, learning_rate, iteration)
        return new_params, opt_state, loss

    return _jit_step(step, config, mesh, seq_parallel)


def make_scanned_train_step(config: BertConfig, n_steps: int,
                            mesh: Optional[Mesh] = None,
                            learning_rate: float = 1e-4,
                            seq_parallel: bool = False, remat: bool = True,
                            use_flash: bool = False):
    """``n_steps`` chained train steps in ONE dispatch (jitted lax.scan).

    Benchmarks MUST time this, never N separate calls of make_train_step's
    output: per-call wall timing through the axon tunnel is unreliable —
    repeated identical executes are replayed from cache, which produced the
    physically impossible BENCH_r04 headline (2,989% implied MFU). One scan
    is one execute whose wall time necessarily covers all ``n_steps`` of
    device work; the returned loss trajectory lets the caller verify that
    training actually stepped (losses must change step to step).

    Signature: ``(params, opt_state, batch, start_iteration) ->
    (params, opt_state, losses[n_steps])`` with params/opt donated.
    """
    loss_fn = _make_loss_fn(config, mesh, seq_parallel, remat, use_flash)

    def scanned(params, opt_state, batch, start_iteration):
        def body(carry, it):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            params, opt_state = _optim.adam_apply(
                params, grads, opt_state, learning_rate, it)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state),
            start_iteration + jnp.arange(n_steps, dtype=jnp.int32))
        return params, opt_state, losses

    return _jit_step(scanned, config, mesh, seq_parallel)


# -- SQuAD-style QA fine-tune head (BASELINE config 3) -------------------

def init_qa_params(key, config: BertConfig) -> Dict:
    """Span-extraction head: start/end logits per token (BERT-for-QA)."""
    w = 0.02 * jax.random.normal(key, (config.hidden_size, 2), jnp.float32)
    return {"w": w.astype(config.dtype),
            "b": jnp.zeros((2,), jnp.float32)}


def qa_logits(params, qa_params, batch, config: BertConfig, mesh=None):
    enc = encode(params, batch["input_ids"], batch.get("token_type_ids"),
                 batch.get("attention_mask"), config=config, mesh=mesh)
    logits = jnp.einsum("bte,ek->btk", enc, qa_params["w"]) \
        .astype(jnp.float32) + qa_params["b"]
    return logits[..., 0], logits[..., 1]      # start, end [B, T]


def qa_loss(params, qa_params, batch, config: BertConfig, mesh=None):
    """Cross entropy over start/end positions (SQuAD objective)."""
    start_logits, end_logits = qa_logits(params, qa_params, batch, config,
                                         mesh)
    mask = batch.get("attention_mask")
    if mask is not None:
        big_neg = jnp.finfo(jnp.float32).min
        start_logits = jnp.where(mask.astype(bool), start_logits, big_neg)
        end_logits = jnp.where(mask.astype(bool), end_logits, big_neg)

    def ce(logits, positions):
        lsm = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(lsm, positions[:, None],
                                             axis=-1)[:, 0])

    return 0.5 * (ce(start_logits, batch["start_positions"]) +
                  ce(end_logits, batch["end_positions"]))


def make_qa_train_step(config: BertConfig, mesh: Optional[Mesh] = None,
                       learning_rate: float = 3e-5):
    """Fine-tune step: encoder + QA head trained jointly (the BASELINE
    config-3 workload: BERT-base SQuAD fine-tune)."""


    def loss_fn(all_params, batch):
        return qa_loss(all_params["bert"], all_params["qa"], batch, config,
                       mesh)

    def step(all_params, opt_state, batch, iteration):
        loss, grads = jax.value_and_grad(loss_fn)(all_params, batch)
        new_params, opt_state = _optim.adam_apply(
            all_params, grads, opt_state, learning_rate, iteration)
        return new_params, opt_state, loss

    from ..runtime.inference import counted_jit
    return counted_jit(step, tag=f"bert_qa:{id(step)}",
                       donate_argnums=(0, 1))


# -- pipeline parallelism (dp x pp) --------------------------------------

def to_pipeline_params(params, n_stages: int):
    """Restructure flat params for the pipeline: encoder layers grouped
    into stages and stacked (leading stage dim); embed/head unchanged."""
    from ..parallel.pipeline import split_stages, stack_stage_params
    groups = split_stages(params["layers"], n_stages)
    return {
        "embeddings": params["embeddings"],
        "stages": stack_stage_params(groups),
        "mlm": params["mlm"],
        "pooler": params["pooler"],
    }


def from_pipeline_params(pp_params):
    """Inverse of to_pipeline_params: unstack stages back to a flat layer
    list (for checkpoint interchange with the non-pipelined layout)."""
    stages = pp_params["stages"]
    n_stages = jax.tree_util.tree_leaves(stages)[0].shape[0]
    layers = []
    for s in range(n_stages):
        layers.extend(jax.tree_util.tree_map(lambda p: p[s], stages))
    return {
        "embeddings": pp_params["embeddings"],
        "layers": layers,
        "mlm": pp_params["mlm"],
        "pooler": pp_params["pooler"],
    }


def pipeline_stage_specs(stages, tensor_parallel: bool = False):
    """Per-leaf PartitionSpecs for stage-stacked params: every leaf sharded
    over `pipe` on the stage dim; with tensor_parallel, attention heads and
    MLP intermediate additionally sharded over `tensor` (Megatron layout,
    the dp x tp x pp 3-axis composition)."""
    if not tensor_parallel:
        return jax.tree_util.tree_map(lambda _: P(PIPE), stages)
    attn = {"wq": P(PIPE, None, TENSOR, None),
            "wk": P(PIPE, None, TENSOR, None),
            "wv": P(PIPE, None, TENSOR, None),
            "bq": P(PIPE, TENSOR, None),
            "bk": P(PIPE, TENSOR, None),
            "bv": P(PIPE, TENSOR, None),
            "wo": P(PIPE, TENSOR, None, None),
            "bo": P(PIPE)}
    mlp = {"w1": P(PIPE, None, TENSOR), "b1": P(PIPE, TENSOR),
           "w2": P(PIPE, TENSOR, None), "b2": P(PIPE)}
    layer = {"attn": attn, "mlp": mlp, "ln1_g": P(PIPE), "ln1_b": P(PIPE),
             "ln2_g": P(PIPE), "ln2_b": P(PIPE)}
    return [layer for _ in stages]


def make_pipeline_train_step(config: BertConfig, mesh: Mesh,
                             n_microbatches: int,
                             learning_rate: float = 1e-4,
                             remat: bool = True,
                             schedule: str = "1f1b",
                             tensor_parallel: bool = False):
    """BERT training with pipeline parallelism over the `pipe` mesh axis,
    composed with data parallelism over (data, fsdp) and, with
    tensor_parallel=True, Megatron TP over `tensor` inside each stage
    (heads/intermediate sharded; psum after the row-parallel matmuls,
    tp_copy marking the activation fan-out) — the full dp x tp x pp
    3-axis composition.

    The reference has no PP at all (SURVEY §2.4) — this is the TPU-first
    differentiator: embed/head are the heterogeneous ends outside the loop,
    the repeated encoder block is the uniform pipelined stage, loss is
    scored on the last stage (scalar psum — no activation broadcast), and
    per-microbatch remat gives the 1F1B memory profile under jax.grad.

    schedule: "1f1b" (default — hand-scheduled interleaved backward,
    activation memory bounded by n_stages) or "gpipe" (autodiff through the
    scan; memory grows with n_microbatches).

    Use with `to_pipeline_params(init_params(...), n_stages)`.
    """

    from ..parallel.pipeline import (make_pipeline_loss,
                                     make_pipeline_loss_1f1b, tp_copy,
                                     tp_reduce)
    c = config
    tp = mesh.shape.get(TENSOR, 1) if tensor_parallel else 1

    tp_axis = TENSOR if tp > 1 else None

    def stage_fn(stage_layers, h):
        # stage_layers: list of layer dicts (this stage's slice); with
        # tp > 1 the attn/mlp leaves are the local TENSOR shard and the
        # math is Megatron column->row parallel per block (explicit f/g
        # collectives via tp_copy/tp_reduce)
        for layer in stage_layers:
            attn_out = _attention(layer, h, None, c, None, False,
                                  tp_axis=tp_axis)
            h = _ln(h + attn_out, layer["ln1_g"], layer["ln1_b"],
                    c.layer_norm_eps)
            mlp = layer["mlp"]
            hin = tp_copy(h, TENSOR) if tp > 1 else h
            inter = jax.nn.gelu(jnp.einsum("bte,ef->btf", hin, mlp["w1"])
                                + mlp["b1"])
            part = jnp.einsum("btf,fe->bte", inter, mlp["w2"])
            if tp > 1:
                part = tp_reduce(part, TENSOR)
            mlp_out = part + mlp["b2"]
            h = _ln(h + mlp_out, layer["ln2_g"], layer["ln2_b"],
                    c.layer_norm_eps)
        return h

    def head_fn(head_params, y, aux):
        m = head_params["mlm"]
        h = jax.nn.gelu(jnp.einsum("bte,ef->btf", y, m["dense"])
                        + m["dense_b"])
        h = _ln(h, m["ln_g"], m["ln_b"], c.layer_norm_eps)
        logits = jnp.einsum("bte,ve->btv", h, head_params["word"])
        logits = logits.astype(jnp.float32) + m["bias"]
        labels = aux["labels"]
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        lsm = jax.nn.log_softmax(logits, axis=-1)
        per_tok = -jnp.take_along_axis(lsm, safe[..., None], axis=-1)[..., 0]
        per_tok = jnp.where(valid, per_tok, 0.0)
        return jnp.sum(per_tok), jnp.sum(valid).astype(jnp.float32)

    # per-leaf specs only needed for tp; the default P(pipe) blanket
    # otherwise (spec trees act as pytree prefixes of the stage params)
    n_stages = max(mesh.shape.get(PIPE, 1), 1)
    per_stage = max(c.num_layers // n_stages, 1)
    specs = (pipeline_stage_specs(range(per_stage), tensor_parallel=True)
             if tp > 1 else None)

    if schedule == "1f1b":
        pipe_loss = make_pipeline_loss_1f1b(stage_fn, head_fn, mesh,
                                            n_microbatches,
                                            param_specs=specs)
    elif schedule == "gpipe":
        pipe_loss = make_pipeline_loss(stage_fn, head_fn, mesh,
                                       n_microbatches, remat=remat,
                                       param_specs=specs)
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(expected '1f1b' or 'gpipe')")

    def loss_fn(params, batch):
        e = params["embeddings"]
        ids = batch["input_ids"]
        B, T = ids.shape
        h = jnp.take(e["word"], ids, axis=0) + e["position"][None, :T]
        tt = batch.get("token_type_ids")
        h = h + (jnp.take(e["token_type"], tt, axis=0) if tt is not None
                 else e["token_type"][0])
        h = _ln(h, e["ln_g"], e["ln_b"], c.layer_norm_eps)
        head_params = {"mlm": params["mlm"], "word": e["word"]}
        aux = {"labels": batch["labels"]}
        loss_sum, wsum = pipe_loss(params["stages"], head_params, h, aux)
        return loss_sum / jnp.maximum(wsum, 1.0)

    def step(params, opt_state, batch, iteration):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, opt_state = _optim.adam_apply(
            params, grads, opt_state, learning_rate, iteration)
        return new_params, opt_state, loss

    from ..runtime.inference import counted_jit
    step = counted_jit(step, tag=f"bert_pipeline:{id(loss_fn)}",
                       donate_argnums=(0, 1))
    step.loss_fn = loss_fn  # exposed for grad-level parity tests
    return step


def place_pipeline_params(pipe_params, mesh: Mesh,
                          tensor_parallel: bool = False):
    """Stage-stacked leaves sharded over pipe (and tensor when
    tensor_parallel); embed/head replicated."""
    def repl(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)

    stage_specs = pipeline_stage_specs(pipe_params["stages"],
                                       tensor_parallel)
    stages = jax.tree_util.tree_map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        pipe_params["stages"], stage_specs,
        is_leaf=lambda x: isinstance(x, P) or isinstance(x, jax.Array))

    return {
        "embeddings": repl(pipe_params["embeddings"]),
        "stages": stages,
        "mlm": repl(pipe_params["mlm"]),
        "pooler": repl(pipe_params["pooler"]),
    }


def init_opt_state(params):
    flat = jax.tree_util.tree_leaves(params)
    zeros = [jnp.zeros(p.shape, jnp.float32) for p in flat]
    return (zeros, [jnp.zeros(p.shape, jnp.float32) for p in flat])


def place_opt_state(opt_state, config: BertConfig, mesh: Mesh):
    """Shard an Adam state (u_list, m_list) onto the mesh with the same
    per-param specs the train step pins (needed when restoring committed
    arrays, e.g. an orbax checkpoint, into the jitted step)."""
    specs = param_specs(config)
    flat_specs = [NamedSharding(mesh, s) for s in
                  jax.tree_util.tree_leaves(
                      specs, is_leaf=lambda x: isinstance(x, P))]
    u, m = opt_state
    return ([jax.device_put(a, s) for a, s in zip(u, flat_specs)],
            [jax.device_put(a, s) for a, s in zip(m, flat_specs)])


def place_params(params, config: BertConfig, mesh: Mesh):
    """Shard an (host/replicated) param tree onto the mesh per param_specs."""
    specs = param_specs(config)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P) or isinstance(x, jax.Array))


def flops_per_token(config: BertConfig) -> float:
    """Training FLOPs/token ≈ 6 * params_active + attention terms (for MFU)."""
    c = config
    E, F, L = c.hidden_size, c.intermediate_size, c.num_layers
    per_layer = 4 * E * E + 2 * E * F  # qkv+o projections + mlp matmuls
    embed_head = c.vocab_size * E      # tied mlm decoder matmul
    matmul_params = L * per_layer + embed_head + E * E
    return 6.0 * matmul_params


def count_params(params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))
