"""Decoder-only causal language model: the generative serving workload.

Reference context: the serving stack built through PR 6 only does one-shot
``predict`` — the reference ecosystem has no autoregressive serving path at
all. This model is the minimal decoder-only transformer that exercises the
generative fast path (``runtime.generation.DecodeEngine``): it reuses the
BERT block layout (post-LN residual blocks, f32 layernorm/softmax
accumulation, tied word-embedding head) with a causal mask and a
*cache-aware* attention so the same parameters serve three call shapes:

- ``forward``   — full-sequence causal forward ``[B, T] -> [B, T, V]``
  (training/eval, and the honest "recompute the whole prefix every token"
  reference the ``generative_decode`` bench measures against);
- ``prefill``   — fill one slot of a preallocated KV cache from a padded
  prompt in one fixed-shape dispatch and return the next-token logits;
- ``decode``    — one token per active slot against the cache (the O(1)
  per-token step; ``kernels.attention_dispatch`` routes this seq-len-1
  shape to the XLA attention path unconditionally).

KV cache layouts. The *paged* layout (PagedAttention, Kwon et al. 2023)
is what ``DecodeEngine`` serves from::

    {"k": [num_blocks, layers, block_size, heads, head_dim],
     "v": [num_blocks, layers, block_size, heads, head_dim]}

plus a per-slot **block table** ``[slots, max_blocks]`` of pool indices:
a sequence at length L only holds ``ceil(L/block_size)`` blocks, so long
and short requests share one memory budget instead of each reserving
``max_ctx`` rows. Block 0 is a scratch block: table entries past a
slot's allocated count point at it, so fixed-shape writes of padding
rows land somewhere harmless (every read of scratch content is masked
by the per-slot length). The legacy slab layout
``{"k"/"v": [slots, layers, max_ctx, heads, head_dim]}`` is kept as the
single-slot reference path — and is exactly the paged layout with
``block_size == max_ctx`` and one block per slot.

Rows at positions ``> lengths[slot]`` are masked out of every attention —
stale rows left by a previous occupant of the slot (or a freshly
re-allocated block) can never leak into a new request (the poison-value
test in tests/test_generation.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..quant.transforms import (dequant_matmul, dequantize, take_rows,
                                tied_logits)
from .bert import _ln


@dataclasses.dataclass
class CausalLMConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def tiny() -> "CausalLMConfig":
        """For tests/dryruns: f32 so the cached decode path is numerically
        interchangeable with the full-recompute forward (token-identical
        greedy continuations)."""
        return CausalLMConfig(vocab_size=97, hidden_size=64, num_layers=2,
                              num_heads=4, intermediate_size=128,
                              max_position_embeddings=256,
                              dtype=jnp.float32)


# -- parameters ----------------------------------------------------------

def init_params(key, config: CausalLMConfig) -> Dict:
    c = config
    dt = c.dtype
    std = 0.02

    def dense(key, shape):
        return (std * jax.random.normal(key, shape, jnp.float32)).astype(dt)

    keys = iter(jax.random.split(key, 2 + 8 * c.num_layers))
    params = {
        "embeddings": {
            "word": dense(next(keys), (c.vocab_size, c.hidden_size)),
            "position": dense(next(keys), (c.max_position_embeddings,
                                           c.hidden_size)),
            "ln_g": jnp.ones((c.hidden_size,), jnp.float32),
            "ln_b": jnp.zeros((c.hidden_size,), jnp.float32),
        },
        "layers": [],
    }
    H, Dh, E, F = c.num_heads, c.head_dim, c.hidden_size, c.intermediate_size
    for _ in range(c.num_layers):
        params["layers"].append({
            "attn": {
                "wq": dense(next(keys), (E, H, Dh)),
                "wk": dense(next(keys), (E, H, Dh)),
                "wv": dense(next(keys), (E, H, Dh)),
                "wo": dense(next(keys), (H, Dh, E)),
                "bq": jnp.zeros((H, Dh), dt), "bk": jnp.zeros((H, Dh), dt),
                "bv": jnp.zeros((H, Dh), dt), "bo": jnp.zeros((E,), dt),
            },
            "mlp": {
                "w1": dense(next(keys), (E, F)), "b1": jnp.zeros((F,), dt),
                "w2": dense(next(keys), (F, E)), "b2": jnp.zeros((E,), dt),
            },
            "ln1_g": jnp.ones((E,), jnp.float32),
            "ln1_b": jnp.zeros((E,), jnp.float32),
            "ln2_g": jnp.ones((E,), jnp.float32),
            "ln2_b": jnp.zeros((E,), jnp.float32),
        })
    return params


def init_kv_cache(config: CausalLMConfig, slots: int, max_ctx: int) -> Dict:
    """Preallocated per-slot KV cache (see module docstring for layout)."""
    c = config
    if max_ctx > c.max_position_embeddings:
        raise ValueError(
            f"max_ctx {max_ctx} exceeds max_position_embeddings "
            f"{c.max_position_embeddings}")
    shape = (int(slots), c.num_layers, int(max_ctx), c.num_heads, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


# -- shared block pieces -------------------------------------------------

def _mlp_ln(layer, h, attn_out, c: CausalLMConfig):
    """The post-attention half of a block: residual+LN, MLP, residual+LN."""
    h = _ln(h + attn_out, layer["ln1_g"], layer["ln1_b"], c.layer_norm_eps)
    mlp = layer["mlp"]
    # dequant_matmul == einsum("...e,ef->...f") for plain weights, and the
    # int8/fp8-at-rest contraction for a quantized twin
    inter = jax.nn.gelu(dequant_matmul(h, mlp["w1"]) + mlp["b1"])
    mlp_out = dequant_matmul(inter, mlp["w2"]) + mlp["b2"]
    return _ln(h + mlp_out, layer["ln2_g"], layer["ln2_b"], c.layer_norm_eps)


def _embed(params, input_ids, positions, c: CausalLMConfig):
    e = params["embeddings"]
    h = take_rows(e["word"], input_ids, dtype=c.dtype)
    h = h + jnp.take(e["position"], positions, axis=0)
    return _ln(h, e["ln_g"], e["ln_b"], c.layer_norm_eps)


def _lm_logits(params, h):
    """Tied word-embedding head, f32 logits (per-row scales of a
    quantized word table multiply the logits)."""
    return tied_logits(h, params["embeddings"]["word"])


_BIG_NEG = jnp.finfo(jnp.float32).min


def _causal_block(layer, h, c: CausalLMConfig, use_flash: bool = False):
    """Full-sequence causal attention block. Returns (h, (k, v)) with
    k/v [B, T, H, Dh] so prefill can bulk-write them into the cache."""
    from ..kernels import attention_dispatch

    a = layer["attn"]
    B, T = h.shape[0], h.shape[1]
    q = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wq"], h.dtype)) + a["bq"]
    k = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wk"], h.dtype)) + a["bk"]
    v = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wv"], h.dtype)) + a["bv"]
    if use_flash and attention_dispatch(T) == "flash":
        from ..kernels import flash_attention
        ctx = flash_attention(q, k, v, causal=True)
    else:
        scale = (q.shape[-1]) ** -0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
        causal = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(causal[None, None], logits, _BIG_NEG)
        probs = jax.nn.softmax(logits, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    out = jnp.einsum("bqhd,hde->bqe", ctx,
                     dequantize(a["wo"], h.dtype)) + a["bo"]
    return _mlp_ln(layer, h, out, c), (k, v)


# -- the three call shapes -----------------------------------------------

def forward(params, input_ids, config: CausalLMConfig,
            use_flash: bool = False):
    """Full causal forward: token ids [B, T] -> next-token logits
    [B, T, V] (f32). This is the recompute path — O(T²) work per generated
    token when used for decoding, which is exactly what the KV-cached
    ``prefill``/``decode`` pair exists to avoid."""
    B, T = input_ids.shape
    h = _embed(params, input_ids, jnp.arange(T)[None, :], config)
    for layer in params["layers"]:
        h, _ = _causal_block(layer, h, config, use_flash)
    return _lm_logits(params, h)


def prefill(params, cache, input_ids, slot, length, config: CausalLMConfig):
    """Fill ``slot`` of the KV cache from a padded prompt in ONE dispatch.

    ``input_ids`` [1, T] is the prompt zero-padded to its bucket; ``length``
    (traced scalar) is the real prompt length. All T rows of the slot are
    written — rows >= length hold padding garbage that the decode masks
    out (and overwrites as generation proceeds). Returns
    ``(cache, logits[V])`` with the logits taken at position length-1,
    i.e. the distribution of the first generated token.
    """
    c = config
    h = _embed(params, input_ids, jnp.arange(input_ids.shape[1])[None, :], c)
    ks, vs = [], []
    for layer in params["layers"]:
        h, (k, v) = _causal_block(layer, h, c)
        ks.append(k[0])            # [T, H, Dh]
        vs.append(v[0])
    upd_k = jnp.stack(ks)[None].astype(cache["k"].dtype)  # [1, L, T, H, Dh]
    upd_v = jnp.stack(vs)[None].astype(cache["v"].dtype)
    start = (slot, 0, 0, 0, 0)
    cache = {"k": lax.dynamic_update_slice(cache["k"], upd_k, start),
             "v": lax.dynamic_update_slice(cache["v"], upd_v, start)}
    last = lax.dynamic_index_in_dim(h[0], length - 1, axis=0,
                                    keepdims=False)
    return cache, _lm_logits(params, last)


def decode(params, cache, tokens, lengths, config: CausalLMConfig):
    """One KV-cached decode step over every slot.

    ``tokens`` [S] is each slot's current token (position ``lengths[s]``),
    ``lengths`` [S] how many tokens the slot's cache already holds. The
    step writes each token's K/V at its position and attends over
    positions ``0..lengths[s]`` — O(max_ctx) work per token instead of a
    full-prefix recompute. Returns ``(cache, logits[S, V])``.

    The query is seq-len-1, so ``kernels.attention_dispatch`` pins this
    step to the XLA attention path regardless of DL4J_TPU_FLASH_MIN_SEQ
    (a 1-row query can never amortize the Pallas kernel's blocking).
    """
    from ..kernels import attention_dispatch

    c = config
    S = tokens.shape[0]
    C = cache["k"].shape[2]
    positions = jnp.clip(lengths, 0, c.max_position_embeddings - 1)
    h = _embed(params, tokens, positions, c)            # [S, E]
    assert attention_dispatch(1) == "xla"
    key_mask = jnp.arange(C)[None, :] <= lengths[:, None]   # [S, C]
    scale = c.head_dim ** -0.5
    rows = jnp.arange(S)
    cache_k, cache_v = cache["k"], cache["v"]
    for i, layer in enumerate(params["layers"]):
        a = layer["attn"]
        q = jnp.einsum("se,ehd->shd", h, dequantize(a["wq"], h.dtype)) \
            + a["bq"]
        k = jnp.einsum("se,ehd->shd", h, dequantize(a["wk"], h.dtype)) \
            + a["bk"]
        v = jnp.einsum("se,ehd->shd", h, dequantize(a["wv"], h.dtype)) \
            + a["bv"]
        cache_k = cache_k.at[rows, i, lengths].set(
            k.astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[rows, i, lengths].set(
            v.astype(cache_v.dtype), mode="drop")
        att = jnp.einsum("shd,schd->shc", q, cache_k[:, i],
                         preferred_element_type=jnp.float32) * scale
        att = jnp.where(key_mask[:, None, :], att, _BIG_NEG)
        probs = jax.nn.softmax(att, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("shc,schd->shd", probs, cache_v[:, i])
        out = jnp.einsum("shd,hde->se", ctx,
                         dequantize(a["wo"], h.dtype)) + a["bo"]
        h = _mlp_ln(layer, h, out, c)
    return {"k": cache_k, "v": cache_v}, _lm_logits(params, h)


# -- paged (block-granular) KV cache -------------------------------------

def init_paged_kv_cache(config: CausalLMConfig, num_blocks: int,
                        block_size: int) -> Dict:
    """Block pool ``[num_blocks, layers, block_size, heads, head_dim]``
    (see module docstring). Block 0 is the scratch block the engine's
    allocator never hands out."""
    c = config
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (block 0 is scratch), got "
            f"{num_blocks}")
    shape = (int(num_blocks), c.num_layers, int(block_size), c.num_heads,
             c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _block_coords(tables, positions, block_size):
    """(block ids, in-block offsets) for token ``positions`` under the
    per-row block ``tables`` — both [R, T] for tables [R, MB]. Positions
    whose block-table column exceeds MB clip to the last column; the
    engine never lets a live position get there (max_ctx <= MB*Bs)."""
    mb = tables.shape[1]
    col = jnp.clip(positions // block_size, 0, mb - 1)
    blk = jnp.take_along_axis(tables, col, axis=1)
    return blk, positions % block_size


def paged_prefill(params, cache, input_ids, tables, lengths,
                  config: CausalLMConfig, start_pos=None):
    """Batched (optionally partial) prefill into the paged cache: fill
    each row's uncached tail in ONE dispatch.

    ``input_ids`` [B, T] are the *tail* tokens zero-padded to the bucket
    (for a cold prefill the tail is the whole prompt), ``tables`` [B, MB]
    each row's block table (unallocated columns -> scratch 0), ``lengths``
    [B] the real total prompt lengths, and ``start_pos`` [B] how many
    leading rows are already committed in the row's blocks (0 = cold; a
    prefix-cache hit attaches those blocks and prefills only positions
    ``start_pos[b]..lengths[b]-1``). Tail row ``j`` sits at absolute
    position ``start_pos[b]+j``; rows past the real tail
    (``j >= lengths[b]-start_pos[b]``) are redirected to the scratch
    block so fixed-shape padding writes can never clobber a committed —
    possibly *shared* — block. Attention runs over the gathered block
    view (cached prefix rows + the tail written this dispatch), masked
    causally at each tail row's absolute position, so a warm tail is
    numerically the same computation the cold prefill performs at those
    positions. Returns ``(cache, logits[B, V])`` with each row's logits
    taken at tail index ``lengths[b]-start_pos[b]-1``: the distribution
    of the row's first generated token."""
    from ..kernels import attention_dispatch

    c = config
    B, T = input_ids.shape
    MB = tables.shape[1]
    Bs = cache["k"].shape[2]
    C = MB * Bs
    if start_pos is None:
        start_pos = jnp.zeros((B,), jnp.int32)
    pos = start_pos[:, None] + jnp.arange(T)[None, :]           # [B, T]
    h = _embed(params, input_ids,
               jnp.clip(pos, 0, c.max_position_embeddings - 1), c)
    assert attention_dispatch(T, paged=True) == "paged"
    valid = jnp.arange(T)[None, :] < (lengths - start_pos)[:, None]
    blk, off = _block_coords(tables, pos, Bs)
    blk = jnp.where(valid, blk, 0)          # padding rows -> scratch block
    key_mask = jnp.arange(C)[None, None, :] <= pos[:, :, None]  # [B, T, C]
    scale = c.head_dim ** -0.5
    cache_k, cache_v = cache["k"], cache["v"]
    for i, layer in enumerate(params["layers"]):
        a = layer["attn"]
        q = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wq"], h.dtype)) \
            + a["bq"]
        k = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wk"], h.dtype)) \
            + a["bk"]
        v = jnp.einsum("bte,ehd->bthd", h, dequantize(a["wv"], h.dtype)) \
            + a["bv"]
        cache_k = cache_k.at[blk, i, off].set(
            k.astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[blk, i, off].set(
            v.astype(cache_v.dtype), mode="drop")
        # gather each row's blocks into its contiguous [C] key view: the
        # cached prefix rows plus the tail rows written just above
        ks = jnp.take(cache_k[:, i], tables, axis=0).reshape(
            B, C, c.num_heads, c.head_dim)
        vs = jnp.take(cache_v[:, i], tables, axis=0).reshape(
            B, C, c.num_heads, c.head_dim)
        att = jnp.einsum("bqhd,bchd->bhqc", q, ks,
                         preferred_element_type=jnp.float32) * scale
        att = jnp.where(key_mask[:, None], att, _BIG_NEG)
        probs = jax.nn.softmax(att, axis=-1).astype(h.dtype)
        ctx = jnp.einsum("bhqc,bchd->bqhd", probs, vs)
        out = jnp.einsum("bqhd,hde->bqe", ctx,
                         dequantize(a["wo"], h.dtype)) + a["bo"]
        h = _mlp_ln(layer, h, out, c)
    last = jnp.take_along_axis(
        h, jnp.clip(lengths - start_pos - 1, 0, T - 1)[:, None, None],
        axis=1)[:, 0]
    return {"k": cache_k, "v": cache_v}, _lm_logits(params, last)


def paged_decode(params, cache, tables, tokens, lengths,
                 config: CausalLMConfig):
    """Cache-aware step over every slot against the paged pool: ``Q=1``
    is the classic single-token decode, ``Q=k+1`` is the speculative
    verify pass (score a drafted continuation in one dispatch).

    ``tokens`` [S, Q] are each slot's next Q tokens (position
    ``lengths[s]+q``), ``lengths`` [S] how many committed rows each
    slot's blocks hold. Writes each token's K/V through the block table,
    then attends over the block pool — either through the Pallas
    paged-flash kernel (``kernels.paged_flash_decode``: the block table
    rides into the kernel as a scalar-prefetch operand and KV blocks
    stream HBM→VMEM with online-softmax accumulation) or the XLA
    block-table gather fallback; both live inside the jitted step, and
    the path is decided at trace time, so the executable set stays fixed
    (zero steady-state recompiles). Returns ``(cache, logits[S, Q, V])``.

    ``kernels.attention_dispatch(Q, paged=True, head_dim=, block_size=)``
    picks the path (``DL4J_TPU_PAGED_KERNEL``: auto routes to the kernel
    on accelerator backends when the pool layout tiles, on/off force);
    the decision ignores ``Q`` by contract so the decode step and the
    ``Q=k+1`` speculative verify always share a path. Both compute the
    same masked softmax over the same rows — greedy decode is
    token-identical across them (regression-gated)."""
    from ..kernels import attention_dispatch, paged_flash_decode

    c = config
    S, Q = tokens.shape
    MB = tables.shape[1]
    Bs = cache["k"].shape[2]
    C = MB * Bs
    pos = lengths[:, None] + jnp.arange(Q)[None, :]            # [S, Q]
    h = _embed(params, tokens,
               jnp.clip(pos, 0, c.max_position_embeddings - 1), c)
    path = attention_dispatch(Q, paged=True, head_dim=c.head_dim,
                              block_size=Bs)
    assert path in ("paged", "paged_flash")
    blk, off = _block_coords(tables, pos, Bs)
    key_mask = jnp.arange(C)[None, None, :] <= pos[:, :, None]  # [S, Q, C]
    scale = c.head_dim ** -0.5
    cache_k, cache_v = cache["k"], cache["v"]
    for i, layer in enumerate(params["layers"]):
        a = layer["attn"]
        q = jnp.einsum("sqe,ehd->sqhd", h, dequantize(a["wq"], h.dtype)) \
            + a["bq"]
        k = jnp.einsum("sqe,ehd->sqhd", h, dequantize(a["wk"], h.dtype)) \
            + a["bk"]
        v = jnp.einsum("sqe,ehd->sqhd", h, dequantize(a["wv"], h.dtype)) \
            + a["bv"]
        cache_k = cache_k.at[blk, i, off].set(
            k.astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[blk, i, off].set(
            v.astype(cache_v.dtype), mode="drop")
        if path == "paged_flash":
            # walk the block table in-kernel: each pool block is DMA'd
            # once, straight from its pool position — no gathered copy
            ctx = paged_flash_decode(q, cache_k[:, i], cache_v[:, i],
                                     tables, lengths, scale=scale)
        else:
            # gather each slot's blocks into its contiguous [C] key view
            ks = jnp.take(cache_k[:, i], tables, axis=0).reshape(
                S, C, c.num_heads, c.head_dim)
            vs = jnp.take(cache_v[:, i], tables, axis=0).reshape(
                S, C, c.num_heads, c.head_dim)
            att = jnp.einsum("sqhd,schd->shqc", q, ks,
                             preferred_element_type=jnp.float32) * scale
            att = jnp.where(key_mask[:, None], att, _BIG_NEG)
            probs = jax.nn.softmax(att, axis=-1).astype(h.dtype)
            ctx = jnp.einsum("shqc,schd->sqhd", probs, vs)
        out = jnp.einsum("sqhd,hde->sqe", ctx,
                         dequantize(a["wo"], h.dtype)) + a["bo"]
        h = _mlp_ln(layer, h, out, c)
    return {"k": cache_k, "v": cache_v}, _lm_logits(params, h)


def count_params(params) -> int:
    return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))


class CausalLM:
    """Config + params bundled behind the generative-model protocol the
    serving registry and ``DecodeEngine`` duck-type on: ``init_kv_cache``,
    ``prefill``, ``decode`` (and ``forward`` for the recompute path)."""

    def __init__(self, config: Optional[CausalLMConfig] = None,
                 params: Optional[Dict] = None, seed: int = 0):
        self.config = config or CausalLMConfig.tiny()
        self.params = (params if params is not None
                       else init_params(jax.random.key(seed), self.config))

    def init_kv_cache(self, slots: int, max_ctx: int) -> Dict:
        return init_kv_cache(self.config, slots, max_ctx)

    def prefill(self, params, cache, input_ids, slot, length):
        return prefill(params, cache, input_ids, slot, length, self.config)

    def decode(self, params, cache, tokens, lengths):
        return decode(params, cache, tokens, lengths, self.config)

    # paged protocol (what DecodeEngine actually serves from)
    def init_paged_kv_cache(self, num_blocks: int, block_size: int) -> Dict:
        return init_paged_kv_cache(self.config, num_blocks, block_size)

    def paged_prefill(self, params, cache, input_ids, tables, lengths,
                      start_pos=None):
        return paged_prefill(params, cache, input_ids, tables, lengths,
                             self.config, start_pos)

    def paged_decode(self, params, cache, tables, tokens, lengths):
        return paged_decode(params, cache, tables, tokens, lengths,
                            self.config)

    def forward(self, input_ids):
        return forward(self.params, input_ids, self.config)
