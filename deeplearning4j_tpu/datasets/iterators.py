"""DataSetIterator implementations.

Reference: `org/nd4j/linalg/dataset/api/iterator/` — DataSetIterator API with
ListDataSetIterator, ExistingDataSetIterator, AsyncDataSetIterator (prefetch),
plus DL4J's BenchmarkDataSetIterator.

TPU: AsyncDataSetIterator's double-buffered host→device prefetch is the key
performance piece — it overlaps host ETL with device compute so the MXU never
waits on input (`jax.device_put` on the prefetch thread).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import jax
import numpy as np

from ..ndarray.ndarray import NDArray
from .dataset import DataSet


class DataSetIterator:
    """Base iterator protocol (reference DataSetIterator interface)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def reset(self):
        pass

    def batch(self) -> int:
        return -1

    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(DataSetIterator):
    def __init__(self, datasets: Sequence[DataSet], batch_size: int = None):
        self._list = list(datasets)
        self._i = 0
        self._batch = batch_size or (self._list[0].num_examples()
                                     if self._list else 0)

    def has_next(self):
        return self._i < len(self._list)

    def next(self):
        ds = self._list[self._i]
        self._i += 1
        return ds

    def reset(self):
        self._i = 0

    def batch(self):
        return self._batch


class ArrayDataSetIterator(DataSetIterator):
    """Batches a single (features, labels) pair (TestDataSetIterator analog)."""

    def __init__(self, features, labels, batch_size: int, shuffle: bool = False,
                 seed: int = 123):
        self.features = features.jax() if isinstance(features, NDArray) else features
        self.labels = labels.jax() if isinstance(labels, NDArray) else labels
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self._epoch = 0
        self._order = None
        self._i = 0
        self.reset()

    def reset(self):
        n = self.features.shape[0]
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self._epoch)
            self._order = rng.permutation(n)
            self._epoch += 1
        else:
            self._order = np.arange(n)
        self._i = 0

    def has_next(self):
        return self._i < len(self._order)

    def next(self):
        # final batch may be partial (reference iterator behavior); the one
        # extra XLA compile for the ragged shape is accepted
        idx = self._order[self._i:self._i + self.batch_size]
        self._i += len(idx)
        return DataSet(NDArray(self.features[idx]), NDArray(self.labels[idx]))

    def batch(self):
        return self.batch_size


class AsyncDataSetIterator(DataSetIterator):
    """Background prefetch wrapper (reference AsyncDataSetIterator).

    A worker thread pulls from the underlying iterator and device_puts into a
    bounded queue; consumer overlaps compute with host-side prep + H2D DMA.

    `device` may be a Device OR a Sharding (e.g. ParallelWrapper's
    batch NamedSharding): batches then land already in the sharded layout on
    the prefetch thread, so the consumer's staging check is a pure no-op and
    the H2D transfer to every chip overlaps the previous step. A batch the
    sharding cannot take (e.g. a trailing partial batch not divisible by the
    mesh) falls back to the default device; the consumer re-places it.
    """

    def __init__(self, underlying: DataSetIterator, queue_size: int = 2,
                 device=None):
        self.underlying = underlying
        self.queue_size = queue_size
        self.device = device or jax.devices()[0]
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._done = object()
        self._start()

    def _place(self, x):
        try:
            return jax.device_put(x, self.device)
        except Exception:
            return jax.device_put(x, jax.devices()[0])

    def _start(self):
        def worker():
            try:
                self.underlying.reset()
                while self.underlying.has_next():
                    ds = self.underlying.next()
                    feats = self._place(ds.features.jax())
                    labs = (self._place(ds.labels.jax())
                            if ds.labels is not None else None)
                    self._queue.put(DataSet(NDArray(feats),
                                            None if labs is None else NDArray(labs)))
            finally:
                self._queue.put(self._done)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        self._head = None
        self._exhausted = False
        self._consumed = False
        self._advance()

    def _advance(self):
        item = self._queue.get()
        if item is self._done:
            self._head = None
            self._exhausted = True
        else:
            self._head = item

    def has_next(self):
        return not self._exhausted

    def next(self):
        ds = self._head
        self._consumed = True
        self._advance()
        return ds

    def reset(self):
        if not self._consumed and not self._exhausted:
            return  # fresh prefetch pass, nothing consumed — keep it
        if self._thread is not None and self._thread.is_alive():
            # drain remaining items so the worker can exit
            while not self._exhausted:
                self._advance()
            self._thread.join()
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._start()

    def batch(self):
        return self.underlying.batch()


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed batch, zero host overhead (reference
    `BenchmarkDataSetIterator.java` — isolates model throughput from ETL)."""

    def __init__(self, feature_shape, num_classes: int, num_batches: int,
                 dtype="float32", seed: int = 42):
        from ..ndarray import factory as nd
        nd.set_seed(seed)
        self._features = nd.randn(*feature_shape, dtype=dtype)
        labels_idx = np.random.RandomState(seed).randint(
            0, num_classes, feature_shape[0])
        self._labels = nd.one_hot(labels_idx, num_classes)
        self.num_batches = num_batches
        self._i = 0

    def has_next(self):
        return self._i < self.num_batches

    def next(self):
        self._i += 1
        return DataSet(self._features, self._labels)

    def reset(self):
        self._i = 0

    def batch(self):
        return self._features.shape[0]


class NativeBatchDataSetIterator(DataSetIterator):
    """Minibatch iterator backed by the C++ batch-assembler ring
    (`deeplearning4j_tpu.native.NativeBatchIterator`): shuffling and
    gather-copies happen on a native thread outside the GIL while the
    previous step runs on device — the AsyncDataSetIterator role with
    native workers (reference AsyncDataSetIterator + DataVec local
    executor threads)."""

    def __init__(self, features, labels, batch_size: int,
                 shuffle: bool = True, seed: int = 0, n_slots: int = 4,
                 drop_last=None):
        import numpy as _np
        self._x = _np.asarray(features.numpy() if hasattr(features, "numpy")
                              else features, _np.float32)
        self._y = _np.asarray(labels.numpy() if hasattr(labels, "numpy")
                              else labels, _np.float32)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.n_slots = n_slots
        #: True (default): every batch has exactly ``batch_size`` rows —
        #: required by code jitted on a fixed batch dimension (the fit fast
        #: path's whole-epoch scan needs uniform shapes). Pass False to opt
        #: into the reference DataSetIterator contract, which emits a
        #: trailing partial batch (expect a one-off recompile on the ragged
        #: shape). Default flipped False->True in r4 — see MIGRATING.md.
        defaulted = drop_last is None
        self.drop_last = True if defaulted else drop_last
        if (defaulted and self.drop_last
                and self._x.shape[0] >= self.batch_size
                and self._x.shape[0] % self.batch_size != 0):
            import warnings
            warnings.warn(
                f"NativeBatchIterator: {self._x.shape[0] % self.batch_size} "
                f"trailing rows (of {self._x.shape[0]}) are dropped per "
                f"epoch under the drop_last=True default (differs from the "
                f"reference DataSetIterator contract); pass drop_last=False "
                f"to keep the partial batch, or drop_last=True to silence",
                stacklevel=2)
        if self.drop_last and self._x.shape[0] < self.batch_size:
            raise ValueError(
                f"dataset has {self._x.shape[0]} rows < batch_size="
                f"{self.batch_size}: with drop_last=True (the default) the "
                f"iterator would yield zero batches; lower batch_size or "
                f"pass drop_last=False")
        self._epoch = 0
        self._it = None
        self.reset()

    def reset(self):
        from .. import native
        if self._it is not None:
            self._it.close()
        self._it = native.NativeBatchIterator(
            self._x, self._y, self.batch_size, shuffle=self.shuffle,
            seed=self.seed + self._epoch, num_epochs=1,
            n_slots=self.n_slots, drop_last=self.drop_last)
        self._epoch += 1

    def __next__(self) -> DataSet:
        x, y = next(self._it)
        return DataSet(x, y)

    def close(self):
        if self._it is not None:
            self._it.close()
            self._it = None
