"""DataSet / MultiDataSet containers.

Reference: `org/nd4j/linalg/dataset/DataSet.java`, `MultiDataSet.java` —
features+labels (+masks) bundles with split/shuffle/normalize helpers.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..ndarray.ndarray import NDArray
from ..ndarray import factory as nd


def _wrap(x):
    if x is None or isinstance(x, NDArray):
        return x
    return NDArray(x)


def one_hot_labels(idx: np.ndarray, n: int) -> np.ndarray:
    """Integer class ids → one-hot float32 matrix."""
    idx = np.asarray(idx).astype(np.int64).reshape(-1)
    out = np.zeros((len(idx), n), dtype=np.float32)
    out[np.arange(len(idx)), idx] = 1.0
    return out


class DataSet:
    """features + labels (+ optional masks)."""

    def __init__(self, features=None, labels=None, features_mask=None,
                 labels_mask=None):
        self.features = _wrap(features)
        self.labels = _wrap(labels)
        self.features_mask = _wrap(features_mask)
        self.labels_mask = _wrap(labels_mask)

    def num_examples(self) -> int:
        return self.features.shape[0] if self.features is not None else 0

    def get_features(self):
        return self.features

    def get_labels(self):
        return self.labels

    def shuffle(self, seed: Optional[int] = None):
        if seed is not None:
            nd.set_seed(seed)
        perm = np.random.RandomState(seed).permutation(self.num_examples())
        self.features = NDArray(self.features.jax()[perm])
        if self.labels is not None:
            self.labels = NDArray(self.labels.jax()[perm])
        return self

    def split_test_and_train(self, num_train: int):
        train = DataSet(self.features[:num_train].dup(),
                        self.labels[:num_train].dup() if self.labels is not None else None)
        test = DataSet(self.features[num_train:].dup(),
                       self.labels[num_train:].dup() if self.labels is not None else None)
        return train, test

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        return [DataSet(self.features[i:i + batch_size].dup(),
                        self.labels[i:i + batch_size].dup()
                        if self.labels is not None else None)
                for i in range(0, n, batch_size)]

    def sample(self, num: int, seed: Optional[int] = None) -> "DataSet":
        idx = np.random.RandomState(seed).choice(self.num_examples(), num,
                                                 replace=False)
        return DataSet(NDArray(self.features.jax()[idx]),
                       NDArray(self.labels.jax()[idx])
                       if self.labels is not None else None)

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        feats = nd.concat([d.features for d in datasets], axis=0)
        labs = nd.concat([d.labels for d in datasets], axis=0) \
            if datasets[0].labels is not None else None
        return DataSet(feats, labs)

    def __repr__(self):
        return (f"DataSet(features={None if self.features is None else self.features.shape}, "
                f"labels={None if self.labels is None else self.labels.shape})")


class MultiDataSet:
    """Multiple feature/label arrays (reference MultiDataSet)."""

    def __init__(self, features: Sequence = (), labels: Sequence = (),
                 features_masks: Sequence = None, labels_masks: Sequence = None):
        self.features = [_wrap(f) for f in features]
        self.labels = [_wrap(l) for l in labels]
        self.features_masks = ([_wrap(m) for m in features_masks]
                               if features_masks else None)
        self.labels_masks = ([_wrap(m) for m in labels_masks]
                             if labels_masks else None)

    def num_examples(self) -> int:
        return self.features[0].shape[0] if self.features else 0
