"""Data normalizers / preprocessors.

Reference: `nd4j/nd4j-backends/nd4j-api-parent/nd4j-api/src/main/java/org/nd4j/linalg/dataset/api/preprocessor/`
— `NormalizerStandardize.java` (z-score, streaming fit over an iterator),
`NormalizerMinMaxScaler.java`, `ImagePreProcessingScaler.java` (pixel /255
into [a,b]), `MultiNormalizer.java`, serializer
(`serializer/NormalizerSerializer.java`).

TPU note: statistics are computed on host in float64 (streaming, one pass,
Chan et al. parallel-merge form); transform happens as a cheap fused
elementwise op that XLA folds into the input pipeline.
"""
from __future__ import annotations

import json
import os
import zipfile
from typing import List, Optional

import numpy as np

from ..ndarray.ndarray import NDArray
from .dataset import DataSet


def _as_np(x) -> np.ndarray:
    return np.asarray(x.jax() if isinstance(x, NDArray) else x)


class DataNormalization:
    """fit / transform / revert protocol (reference DataNormalization)."""

    def fit(self, data):
        """data: DataSet or DataSetIterator."""
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = NDArray(self.transform_array(_as_np(ds.features)))
        if self.fit_labels_enabled() and ds.labels is not None:
            ds.labels = NDArray(self.transform_labels(_as_np(ds.labels)))
        return ds

    def pre_process(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = NDArray(self.revert_array(_as_np(ds.features)))
        if self.fit_labels_enabled() and ds.labels is not None:
            ds.labels = NDArray(self.revert_labels(_as_np(ds.labels)))
        return ds

    def revert_labels(self, y: np.ndarray) -> np.ndarray:
        return y

    def transform_array(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert_array(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform_labels(self, y: np.ndarray) -> np.ndarray:
        return y

    def fit_labels_enabled(self) -> bool:
        return False

    # serde
    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict):
        raise NotImplementedError


def _iter_datasets(data):
    if isinstance(data, DataSet):
        yield data
    else:
        data.reset()
        while data.has_next():
            yield data.next()
        data.reset()


def _feature_axes(x: np.ndarray):
    """Statistics are per-feature-column: reduce over batch (+time for
    [b, f, t] sequence data)."""
    if x.ndim == 3:
        return (0, 2)
    return tuple(i for i in range(x.ndim) if i != 1) if x.ndim > 1 else (0,)


def _broadcastable(stat: np.ndarray, x: np.ndarray) -> np.ndarray:
    if x.ndim <= 1:
        return stat
    shape = [1] * x.ndim
    shape[1] = -1
    return stat.reshape(shape)


class NormalizerStandardize(DataNormalization):
    """Z-score per feature column (reference NormalizerStandardize.java).

    Streaming one-pass fit: merges per-batch (count, mean, M2) with the
    parallel Welford/Chan update so iterator fit never materializes the
    whole dataset.
    """

    def __init__(self, fit_label: bool = False):
        self._fit_label = fit_label
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.label_mean: Optional[np.ndarray] = None
        self.label_std: Optional[np.ndarray] = None

    def fit_labels_enabled(self):
        return self._fit_label

    @staticmethod
    def _streaming_stats(arrays):
        n = 0
        mean = m2 = None
        for x in arrays:
            x = np.asarray(x, np.float64)
            ax = _feature_axes(x)
            cnt = int(np.prod([x.shape[a] for a in ax])) if x.ndim > 1 \
                else x.shape[0]
            bm = x.mean(axis=ax)
            bv = x.var(axis=ax)
            if mean is None:
                n, mean, m2 = cnt, bm, bv * cnt
            else:
                delta = bm - mean
                tot = n + cnt
                mean = mean + delta * (cnt / tot)
                m2 = m2 + bv * cnt + delta ** 2 * (n * cnt / tot)
                n = tot
        std = np.sqrt(m2 / n)
        std[std == 0] = 1.0
        return mean.astype(np.float32), std.astype(np.float32)

    def fit(self, data):
        feats, labs = [], []
        for ds in _iter_datasets(data):
            feats.append(_as_np(ds.features))
            if self._fit_label and ds.labels is not None:
                labs.append(_as_np(ds.labels))
        self.mean, self.std = self._streaming_stats(feats)
        if labs:
            self.label_mean, self.label_std = self._streaming_stats(labs)
        return self

    def transform_array(self, x):
        return ((x - _broadcastable(self.mean, x))
                / _broadcastable(self.std, x)).astype(np.float32)

    def revert_array(self, x):
        return (x * _broadcastable(self.std, x)
                + _broadcastable(self.mean, x)).astype(np.float32)

    def transform_labels(self, y):
        if self.label_mean is None:
            return y
        return ((y - _broadcastable(self.label_mean, y))
                / _broadcastable(self.label_std, y)).astype(np.float32)

    def revert_labels(self, y):
        if self.label_mean is None:
            return y
        return (y * _broadcastable(self.label_std, y)
                + _broadcastable(self.label_mean, y)).astype(np.float32)

    def state_dict(self):
        return {"type": "NormalizerStandardize",
                "fit_label": self._fit_label,
                "mean": self.mean, "std": self.std,
                "label_mean": self.label_mean, "label_std": self.label_std}

    def load_state_dict(self, d):
        self._fit_label = d["fit_label"]
        self.mean, self.std = d["mean"], d["std"]
        self.label_mean, self.label_std = d["label_mean"], d["label_std"]


class NormalizerMinMaxScaler(DataNormalization):
    """Scale each feature column into [min_range, max_range]
    (reference NormalizerMinMaxScaler.java)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range, self.max_range = float(min_range), float(max_range)
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def fit(self, data):
        lo = hi = None
        for ds in _iter_datasets(data):
            x = _as_np(ds.features)
            ax = _feature_axes(x)
            bl, bh = x.min(axis=ax), x.max(axis=ax)
            lo = bl if lo is None else np.minimum(lo, bl)
            hi = bh if hi is None else np.maximum(hi, bh)
        self.data_min, self.data_max = lo, hi
        return self

    def _scale(self):
        rng = self.data_max - self.data_min
        rng[rng == 0] = 1.0
        return rng

    def transform_array(self, x):
        z = (x - _broadcastable(self.data_min, x)) \
            / _broadcastable(self._scale(), x)
        return (z * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def revert_array(self, x):
        z = (x - self.min_range) / (self.max_range - self.min_range)
        return (z * _broadcastable(self._scale(), x)
                + _broadcastable(self.data_min, x)).astype(np.float32)

    def state_dict(self):
        return {"type": "NormalizerMinMaxScaler",
                "min_range": self.min_range, "max_range": self.max_range,
                "data_min": self.data_min, "data_max": self.data_max}

    def load_state_dict(self, d):
        self.min_range, self.max_range = d["min_range"], d["max_range"]
        self.data_min, self.data_max = d["data_min"], d["data_max"]


class ImagePreProcessingScaler(DataNormalization):
    """Pixel [0, 2^bits-1] → [a, b] (reference ImagePreProcessingScaler.java).
    Stateless — fit is a no-op."""

    def __init__(self, a: float = 0.0, b: float = 1.0, max_bits: int = 8):
        self.a, self.b = float(a), float(b)
        self.max_pixel = float(2 ** max_bits - 1)

    def fit(self, data):
        return self

    def transform_array(self, x):
        return (x / self.max_pixel * (self.b - self.a)
                + self.a).astype(np.float32)

    def revert_array(self, x):
        return ((x - self.a) / (self.b - self.a)
                * self.max_pixel).astype(np.float32)

    def state_dict(self):
        return {"type": "ImagePreProcessingScaler", "a": self.a, "b": self.b,
                "max_pixel": self.max_pixel}

    def load_state_dict(self, d):
        self.a, self.b, self.max_pixel = d["a"], d["b"], d["max_pixel"]


class MultiNormalizer:
    """Per-input/per-output normalizers for MultiDataSet
    (reference MultiNormalizer / MultiDataNormalization)."""

    def __init__(self, feature_normalizers: List[DataNormalization]):
        self.feature_normalizers = feature_normalizers

    def fit(self, mds_iter):
        from .dataset import MultiDataSet
        buf = [[] for _ in self.feature_normalizers]
        items = [mds_iter] if isinstance(mds_iter, MultiDataSet) else mds_iter
        for mds in items:
            for i, f in enumerate(mds.features):
                buf[i].append(DataSet(f, None))
        for i, norm in enumerate(self.feature_normalizers):
            from .iterators import ListDataSetIterator
            norm.fit(ListDataSetIterator(buf[i]))
        return self

    def transform(self, mds):
        for i, norm in enumerate(self.feature_normalizers):
            mds.features[i] = NDArray(
                norm.transform_array(_as_np(mds.features[i])))
        return mds


_NORMALIZER_TYPES = {
    "NormalizerStandardize": NormalizerStandardize,
    "NormalizerMinMaxScaler": NormalizerMinMaxScaler,
    "ImagePreProcessingScaler": ImagePreProcessingScaler,
}


class NormalizerSerializer:
    """Save/restore normalizer state (reference
    `preprocessor/serializer/NormalizerSerializer.java`) — zip of meta JSON
    + npz arrays."""

    @staticmethod
    def write(normalizer: DataNormalization, path: str):
        state = normalizer.state_dict()
        arrays = {k: v for k, v in state.items()
                  if isinstance(v, np.ndarray)}
        meta = {k: v for k, v in state.items()
                if not isinstance(v, np.ndarray)}
        meta["__array_keys__"] = sorted(arrays)
        with zipfile.ZipFile(path, "w") as z:
            z.writestr("meta.json", json.dumps(meta))
            import io
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            z.writestr("arrays.npz", buf.getvalue())

    @staticmethod
    def restore(path: str) -> DataNormalization:
        import io
        with zipfile.ZipFile(path) as z:
            meta = json.loads(z.read("meta.json"))
            npz = np.load(io.BytesIO(z.read("arrays.npz")))
            state = {k: v for k, v in meta.items()
                     if k != "__array_keys__"}
            for k in meta["__array_keys__"]:
                state[k] = npz[k]
            for k in ("mean", "std", "label_mean", "label_std",
                      "data_min", "data_max"):
                state.setdefault(k, None)
        cls = _NORMALIZER_TYPES[meta["type"]]
        obj = cls.__new__(cls)
        ref = cls()  # defaults for fields not in state
        obj.__dict__.update(ref.__dict__)
        state.pop("type")
        obj.load_state_dict({**{k: None for k in ()}, **state})
        return obj
