from .dataset import DataSet, MultiDataSet  # noqa: F401
from .iterators import (ArrayDataSetIterator, AsyncDataSetIterator,  # noqa: F401
                        BenchmarkDataSetIterator, DataSetIterator,
                        ListDataSetIterator)
