from .dataset import DataSet, MultiDataSet  # noqa: F401
from .iterators import (ArrayDataSetIterator, AsyncDataSetIterator,  # noqa: F401
                        BenchmarkDataSetIterator, DataSetIterator,
                        ListDataSetIterator)
from .record_iterator import (RecordReaderDataSetIterator,  # noqa: F401
                              SequenceRecordReaderDataSetIterator)
from .normalizers import (DataNormalization, NormalizerStandardize,  # noqa: F401
                          NormalizerMinMaxScaler, ImagePreProcessingScaler,
                          MultiNormalizer, NormalizerSerializer)
from .fetchers import (MnistDataFetcher, EmnistDataFetcher,  # noqa: F401
                       Cifar10Fetcher, MnistDataSetIterator,
                       EmnistDataSetIterator, Cifar10DataSetIterator,
                       IrisDataSetIterator, DigitsDataSetIterator, parse_idx)
