"""Dataset fetchers + ready-made iterators (MNIST / EMNIST / CIFAR-10 / Iris
/ Digits).

Reference: `deeplearning4j/deeplearning4j-data/deeplearning4j-datasets/src/main/java/org/deeplearning4j/datasets/fetchers/MnistDataFetcher.java`
(idx-ubyte parsing + checksum-verified download cache),
`EmnistDataFetcher.java`, `Cifar10Fetcher.java`, and the iterator wrappers
`.../datasets/iterator/impl/MnistDataSetIterator.java`,
`IrisDataSetIterator.java`.

This environment has zero network egress, so fetchers READ a local cache
(``$DL4J_TPU_DATA`` or ``~/.deeplearning4j_tpu/<name>/``) and raise a clear
error when artifacts are absent. Two datasets ship offline regardless:
Iris and the 8x8 Digits set (via scikit-learn's bundled copies), which the
end-to-end tests train on.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Optional, Tuple

import numpy as np

from ..ndarray.ndarray import NDArray
from .dataset import DataSet
from .iterators import ArrayDataSetIterator, DataSetIterator


def _data_root() -> str:
    return os.environ.get(
        "DL4J_TPU_DATA",
        os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _open_maybe_gz(path: str):
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    return open(path, "rb")


def _find(name: str, *candidates: str) -> str:
    base = os.path.join(_data_root(), name)
    for c in candidates:
        p = os.path.join(base, c)
        if os.path.exists(p) or os.path.exists(p + ".gz"):
            return p
    raise FileNotFoundError(
        f"{name} artifacts not found under {base} (looked for "
        f"{candidates}); this environment has no network egress — place the "
        f"files there manually, or use DigitsDataSetIterator / "
        f"IrisDataSetIterator which ship offline")


def parse_idx(path: str) -> np.ndarray:
    """Parse an IDX-format file (the MNIST container format).

    Plain u8 files route through the native C++ decoder when built
    (`deeplearning4j_tpu.native`); gz/typed files use the numpy path."""
    if not path.endswith(".gz"):
        try:
            from .. import native
            if native.available():
                with open(path, "rb") as f:
                    if f.read(3)[2:] == b"\x08":  # u8 payload
                        return native.read_idx(path)
        except Exception:
            pass
    with _open_maybe_gz(path) as f:
        magic = struct.unpack(">I", f.read(4))[0]
        dtype_code = (magic >> 8) & 0xFF
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtypes = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                  0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}
        data = np.frombuffer(f.read(), dtype=np.dtype(
            dtypes[dtype_code]).newbyteorder(">"))
        return data.reshape(dims)


class MnistDataFetcher:
    """Reads idx files from the local cache (reference MnistDataFetcher)."""

    NUM_EXAMPLES = 60000
    NUM_EXAMPLES_TEST = 10000

    def __init__(self, train: bool = True, dataset: str = "mnist",
                 prefix: Optional[str] = None):
        self.dataset = dataset
        pre = prefix or ("train" if train else "t10k")
        self.images_path = _find(dataset, f"{pre}-images-idx3-ubyte",
                                 f"{pre}-images.idx3-ubyte")
        self.labels_path = _find(dataset, f"{pre}-labels-idx1-ubyte",
                                 f"{pre}-labels.idx1-ubyte")

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        images = parse_idx(self.images_path).astype(np.float32)
        labels = parse_idx(self.labels_path).astype(np.int64)
        return images.reshape(len(images), -1), labels


class EmnistDataFetcher(MnistDataFetcher):
    """EMNIST subsets (reference EmnistDataFetcher): files named
    emnist-<subset>-train-images-idx3-ubyte etc."""

    def __init__(self, subset: str = "balanced", train: bool = True):
        split = "train" if train else "test"
        super().__init__(train=train, dataset="emnist",
                         prefix=f"emnist-{subset}-{split}")


class Cifar10Fetcher:
    """CIFAR-10 python-pickle batches (reference Cifar10Fetcher)."""

    def __init__(self, train: bool = True):
        base = os.path.join(_data_root(), "cifar10", "cifar-10-batches-py")
        names = [f"data_batch_{i}" for i in range(1, 6)] if train \
            else ["test_batch"]
        self.paths = [os.path.join(base, n) for n in names]
        for p in self.paths:
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"CIFAR-10 batch missing: {p} (no network egress; place "
                    f"cifar-10-batches-py there manually)")

    def fetch(self) -> Tuple[np.ndarray, np.ndarray]:
        xs, ys = [], []
        for p in self.paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.float32))
            ys.append(np.asarray(d[b"labels"], np.int64))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32)
        return x, np.concatenate(ys)


from .dataset import one_hot_labels as _one_hot  # noqa: E402


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference `iterator/impl/MnistDataSetIterator.java`: flattened 784-dim
    features in [0,1] + one-hot labels."""

    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123,
                 binarize: bool = False):
        x, y = MnistDataFetcher(train=train).fetch()
        x = x / 255.0
        if binarize:
            x = (x > 0.5).astype(np.float32)
        super().__init__(x.astype(np.float32), _one_hot(y, 10), batch_size,
                         shuffle=shuffle, seed=seed)


class EmnistDataSetIterator(ArrayDataSetIterator):
    _NUM_LABELS = {"balanced": 47, "byclass": 62, "bymerge": 47,
                   "digits": 10, "letters": 26, "mnist": 10}

    def __init__(self, subset: str, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123):
        x, y = EmnistDataFetcher(subset=subset, train=train).fetch()
        n = self._NUM_LABELS[subset]
        if subset == "letters":  # 1-indexed labels
            y = y - y.min()
        super().__init__((x / 255.0).astype(np.float32), _one_hot(y, n),
                         batch_size, shuffle=shuffle, seed=seed)


class Cifar10DataSetIterator(ArrayDataSetIterator):
    def __init__(self, batch_size: int, train: bool = True,
                 shuffle: bool = True, seed: int = 123):
        x, y = Cifar10Fetcher(train=train).fetch()
        super().__init__((x / 255.0).astype(np.float32), _one_hot(y, 10),
                         batch_size, shuffle=shuffle, seed=seed)


class IrisDataSetIterator(ArrayDataSetIterator):
    """Reference `iterator/impl/IrisDataSetIterator.java` — the classic 150
    x 4 dataset, bundled offline (scikit-learn ships the CSV)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150,
                 shuffle: bool = False, seed: int = 123):
        from sklearn.datasets import load_iris
        d = load_iris()
        x = np.asarray(d.data[:num_examples], np.float32)
        y = _one_hot(np.asarray(d.target[:num_examples]), 3)
        super().__init__(x, y, batch_size, shuffle=shuffle, seed=seed)


class DigitsDataSetIterator(ArrayDataSetIterator):
    """8x8 handwritten digits (1797 samples, bundled offline via
    scikit-learn) — the real-data stand-in for MNIST end-to-end tests in
    the no-egress environment. Features scaled to [0,1], optionally shaped
    [b, 1, 8, 8] for CNN input."""

    def __init__(self, batch_size: int, train: bool = True,
                 as_image: bool = False, shuffle: bool = True,
                 seed: int = 123, train_fraction: float = 0.8):
        from sklearn.datasets import load_digits
        d = load_digits()
        x = np.asarray(d.data, np.float32) / 16.0
        y = np.asarray(d.target)
        n_train = int(len(x) * train_fraction)
        rng = np.random.RandomState(42)
        perm = rng.permutation(len(x))
        idx = perm[:n_train] if train else perm[n_train:]
        x, y = x[idx], y[idx]
        if as_image:
            x = x.reshape(-1, 1, 8, 8)
        super().__init__(x, _one_hot(y, 10), batch_size,
                         shuffle=shuffle, seed=seed)
