"""RecordReader → DataSet bridge iterators.

Reference: `deeplearning4j/deeplearning4j-data/deeplearning4j-datavec-iterators/src/main/java/org/deeplearning4j/datasets/datavec/RecordReaderDataSetIterator.java`
(label column + numClasses → one-hot, regression mode, optional
TransformProcess pre-pass) and `SequenceRecordReaderDataSetIterator.java`.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..etl.records import RecordReader, SequenceRecordReader
from ..etl.transform_process import TransformProcess
from ..etl.executor import LocalTransformExecutor
from ..etl.writable import to_double
from ..ndarray.ndarray import NDArray
from .dataset import DataSet, one_hot_labels as _one_hot
from .iterators import DataSetIterator


class RecordReaderDataSetIterator(DataSetIterator):
    """Tabular or image records → batched DataSets.

    - classification: ``label_index`` + ``num_classes`` → one-hot labels
    - regression: ``regression=True`` with ``label_index``(+``label_index_to``)
    - unsupervised: ``label_index=None``
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = -1,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None,
                 transform_process: Optional[TransformProcess] = None):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to
        self.tp = transform_process
        self._features: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None
        self._i = 0
        self._materialize()

    def _materialize(self):
        records = list(self.reader)
        if self.tp is not None:
            records = LocalTransformExecutor.execute(records, self.tp)
        if not records:
            raise ValueError("record reader produced no records")
        feats, labels = [], []
        for rec in records:
            if (len(rec) and isinstance(rec[0], np.ndarray)
                    and rec[0].ndim > 1):
                # image-style record: [array, label?]
                feats.append(np.asarray(rec[0], np.float32))
                if self.label_index is not None and len(rec) > 1:
                    labels.append(rec[1])
                continue
            row = list(rec)
            li = self.label_index
            if li is not None:
                if li < 0:
                    li = len(row) + li
                hi = self.label_index_to if self.label_index_to is not None \
                    else li
                lab = [to_double(v) for v in row[li:hi + 1]]
                labels.append(lab[0] if len(lab) == 1 else lab)
                del row[li:hi + 1]
            feats.append([to_double(v) for v in row])
        self._features = np.asarray(feats, dtype=np.float32)
        if self.label_index is not None and labels:
            lab = np.asarray(labels)
            if self.regression or self.num_classes is None:
                if lab.ndim == 1:
                    lab = lab[:, None]
                self._labels = lab.astype(np.float32)
            else:
                self._labels = _one_hot(np.asarray(lab).reshape(-1),
                                        self.num_classes)
        else:
            self._labels = None
        self._i = 0

    # -- iterator protocol ----------------------------------------------
    def has_next(self):
        return self._i < len(self._features)

    def next(self):
        sl = slice(self._i, self._i + self.batch_size)
        self._i += self.batch_size
        return DataSet(NDArray(self._features[sl]),
                       None if self._labels is None
                       else NDArray(self._labels[sl]))

    def reset(self):
        self._i = 0

    def batch(self):
        return self.batch_size

    def total_examples(self):
        return len(self._features)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence records → [batch, features, time] DataSets with padding masks
    (reference SequenceRecordReaderDataSetIterator AlignmentMode):
    ALIGN_START (default) pads at the end; ALIGN_END right-aligns each
    sequence so its last timestep sits at index max_t-1 (for many-to-one
    setups reading the final step)."""

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 label_index: int = -1,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 align: str = "ALIGN_START"):
        if align not in ("ALIGN_START", "ALIGN_END"):
            raise ValueError(f"align must be ALIGN_START or ALIGN_END, "
                             f"got {align!r}")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.align = align
        self._seqs: List = list(reader)
        self._i = 0

    def has_next(self):
        return self._i < len(self._seqs)

    def next(self):
        batch = self._seqs[self._i:self._i + self.batch_size]
        self._i += self.batch_size
        max_t = max(len(s) for s in batch)
        nf = len(batch[0][0]) - 1
        feats = np.zeros((len(batch), nf, max_t), np.float32)
        mask = np.zeros((len(batch), max_t), np.float32)
        li = self.label_index if self.label_index >= 0 \
            else len(batch[0][0]) + self.label_index
        if self.regression or self.num_classes is None:
            labs = np.zeros((len(batch), 1, max_t), np.float32)
        else:
            labs = np.zeros((len(batch), self.num_classes, max_t), np.float32)
        for b, seq in enumerate(batch):
            off = max_t - len(seq) if self.align == "ALIGN_END" else 0
            for t0, row in enumerate(seq):
                t = t0 + off
                vals = [to_double(v) for j, v in enumerate(row) if j != li]
                feats[b, :, t] = vals
                mask[b, t] = 1.0
                lv = to_double(row[li])
                if self.regression or self.num_classes is None:
                    labs[b, 0, t] = lv
                else:
                    labs[b, int(lv), t] = 1.0
        return DataSet(NDArray(feats), NDArray(labs),
                       features_mask=NDArray(mask),
                       labels_mask=NDArray(mask.copy()))

    def reset(self):
        self._i = 0

    def batch(self):
        return self.batch_size
