"""BERT QA fine-tune head (BASELINE config 3: SQuAD-style span extraction)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import bert


class TestBertQA:
    def test_finetune_reduces_loss(self):
        c = bert.BertConfig.tiny()
        c.dtype = jnp.float32
        rs = np.random.RandomState(0)
        B, T = 8, 32
        params = bert.init_params(jax.random.key(0), c)
        qa = bert.init_qa_params(jax.random.key(1), c)
        all_params = {"bert": params, "qa": qa}
        flat = jax.tree_util.tree_leaves(all_params)
        opt = ([jnp.zeros(p.shape, jnp.float32) for p in flat],
               [jnp.zeros(p.shape, jnp.float32) for p in flat])
        step = bert.make_qa_train_step(c, learning_rate=1e-3)

        batch = {
            "input_ids": jnp.asarray(
                rs.randint(0, c.vocab_size, (B, T)), jnp.int32),
            "attention_mask": jnp.ones((B, T), jnp.int32),
            "start_positions": jnp.asarray(rs.randint(0, T, B), jnp.int32),
            "end_positions": jnp.asarray(rs.randint(0, T, B), jnp.int32),
        }
        losses = []
        for i in range(12):
            all_params, opt, loss = step(all_params, opt, batch, i)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()

    def test_qa_logits_shapes_and_mask(self):
        c = bert.BertConfig.tiny()
        c.dtype = jnp.float32
        rs = np.random.RandomState(1)
        B, T = 2, 16
        params = bert.init_params(jax.random.key(0), c)
        qa = bert.init_qa_params(jax.random.key(1), c)
        mask = np.ones((B, T), np.int32)
        mask[:, 10:] = 0
        batch = {"input_ids": jnp.asarray(
                     rs.randint(0, c.vocab_size, (B, T)), jnp.int32),
                 "attention_mask": jnp.asarray(mask)}
        start, end = bert.qa_logits(params, qa, batch, c)
        assert start.shape == (B, T) and end.shape == (B, T)
