"""SameDiff control flow: cond / while_loop / scan / TensorArray +
serializable strided-slice.

Reference behavior: If/While/TensorArray execution in
`nd4j/.../internal/InferenceSession.java:828` and `ADRs/0020 - New Control
flow.md`; here they lower to lax.cond/while_loop/scan (SURVEY §7 table).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.ndarray import factory as nd


class TestCond:
    def test_forward_both_branches(self):
        for pred, expected in [(True, 6.0), (False, -3.0)]:
            sd = SameDiff.create()
            x = sd.placeholder("x", (3,))
            p = sd.constant(np.asarray(pred))
            out = sd.cond(p,
                          lambda a: a * 2.0,
                          lambda a: a - 2.0,
                          x)
            res = out.eval({"x": np.ones(3, np.float32)})
            assert res.numpy().sum() == pytest.approx(expected)

    def test_multi_output_and_grad(self):
        sd = SameDiff.create()
        w = sd.var("w", np.asarray([2.0, 3.0], np.float32))
        p = sd.constant(np.asarray(True))
        a, b = sd.cond(p,
                       lambda v: (v * v, v + 1.0),
                       lambda v: (v, v),
                       w)
        loss = (a + b).sum()
        sd.set_loss_variables(loss)
        g = sd.calculate_gradients({}, ["w"])["w"].numpy()
        # d/dw (w^2 + w + 1) = 2w + 1
        np.testing.assert_allclose(g, [5.0, 7.0])

    def test_serialization_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2,))
        p = sd.constant(np.asarray(False))
        out = sd.cond(p, lambda a: a * 10.0, lambda a: a * -1.0, x)
        out.rename("out")
        path = str(tmp_path / "cond.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        xs = np.asarray([1.0, 2.0], np.float32)
        r1 = sd.output({"x": xs}, ["out"])["out"].numpy()
        r2 = sd2.output({"x": xs}, ["out"])["out"].numpy()
        np.testing.assert_allclose(r1, r2)
        np.testing.assert_allclose(r2, [-1.0, -2.0])


class TestWhileLoop:
    def test_counter(self):
        sd = SameDiff.create()
        i0 = sd.constant(np.asarray(0.0, np.float32))
        acc0 = sd.constant(np.asarray(1.0, np.float32))
        i_f, acc_f = sd.while_loop(
            lambda i, acc: i < 5.0,
            lambda i, acc: (i + 1.0, acc * 2.0),
            i0, acc0)
        assert acc_f.eval({}).numpy() == pytest.approx(32.0)
        assert i_f.eval({}).numpy() == pytest.approx(5.0)

    def test_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        n = sd.placeholder("n", ())
        i0 = sd.constant(np.asarray(0.0, np.float32))
        s0 = sd.constant(np.asarray(0.0, np.float32))
        _, total = sd.while_loop(
            lambda i, s: i < n,
            lambda i, s: (i + 1.0, s + i),
            i0, s0)
        total.rename("total")
        path = str(tmp_path / "while.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        r = sd2.output({"n": np.asarray(4.0, np.float32)},
                       ["total"])["total"].numpy()
        assert r == pytest.approx(0 + 1 + 2 + 3)


class TestScan:
    def test_cumsum_scan(self):
        sd = SameDiff.create()
        xs = sd.placeholder("xs", (4,))
        c0 = sd.constant(np.asarray(0.0, np.float32))
        final, ys = sd.scan(lambda c, x: (c + x, c + x), c0, xs)
        r = ys.eval({"xs": np.asarray([1, 2, 3, 4], np.float32)})
        np.testing.assert_allclose(r.numpy(), [1, 3, 6, 10])

    def test_rnn_decode_trains_and_roundtrips(self, tmp_path):
        """VERDICT item 6 'done' criterion: an RNN-decode-style looped graph
        builds, trains (gradient through the loop), and save/loads. The
        body closes over the weight var (auto-captured as loop invariant)."""
        B, T, F = 2, 5, 3
        rs = np.random.RandomState(0)
        sd = SameDiff.create()
        x = sd.placeholder("x", (T, B, F))
        w = sd.var("w", rs.randn(F, F).astype(np.float32) * 0.5)
        h0 = sd.constant(np.zeros((B, F), np.float32))

        def body(h, x_t):
            nh = x_t.mmul(w) + h   # closes over parent var w
            return nh, nh

        final_h, h_seq = sd.scan(body, init=[h0], xs=[x])
        loss = final_h.sum()
        loss.rename("loss")
        sd.set_loss_variables("loss")
        xs_val = rs.randn(T, B, F).astype(np.float32)
        g = sd.calculate_gradients({"x": xs_val}, ["w"])["w"].numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

        h_seq.rename("h_seq")
        path = str(tmp_path / "scan.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        r1 = sd.output({"x": xs_val}, ["h_seq"])["h_seq"].numpy()
        r2 = sd2.output({"x": xs_val}, ["h_seq"])["h_seq"].numpy()
        np.testing.assert_allclose(r1, r2, atol=1e-6)
        # the loop really ran: h_seq[t] = cumulative sum of x[:t+1] @ w
        expected = np.cumsum(xs_val @ (w.get_arr().numpy()), axis=0)
        np.testing.assert_allclose(r1, expected, atol=1e-4)


class TestTensorArray:
    def test_write_read_stack(self):
        sd = SameDiff.create()
        ta = sd.tensor_array(3, (2,))
        a = sd.constant(np.asarray([1.0, 2.0], np.float32))
        b = sd.constant(np.asarray([3.0, 4.0], np.float32))
        ta.write(0, a).write(2, b)
        stacked = ta.stack()
        r = stacked.eval({}).numpy()
        np.testing.assert_allclose(r, [[1, 2], [0, 0], [3, 4]])
        np.testing.assert_allclose(ta.read(2).eval({}).numpy(), [3, 4])


class TestSerializableSlicing:
    def test_getitem_graph_roundtrips(self, tmp_path):
        """VERDICT round-1 weak #2: sliced graphs must be saveable."""
        sd = SameDiff.create()
        x = sd.placeholder("x", (4, 6))
        y = x[1:3, ::2] * 2.0
        z = x[0] + x[-1]
        out = y.sum() + z.sum()
        out.rename("out")
        path = str(tmp_path / "sliced.sdz")
        sd.save(path)
        sd2 = SameDiff.load(path)
        xs = np.arange(24, dtype=np.float32).reshape(4, 6)
        r1 = sd.output({"x": xs}, ["out"])["out"].numpy()
        r2 = sd2.output({"x": xs}, ["out"])["out"].numpy()
        np.testing.assert_allclose(r1, r2)
        expected = (xs[1:3, ::2] * 2.0).sum() + (xs[0] + xs[-1]).sum()
        np.testing.assert_allclose(r1, expected)

    def test_newaxis_and_ellipsis(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 3, 4))
        y = x[..., 0]
        z = x[:, None, 1, :]
        assert y.eval({"x": np.ones((2, 3, 4), np.float32)}).shape == (2, 3)
        assert z.eval({"x": np.ones((2, 3, 4), np.float32)}).shape == (2, 1, 4)
