"""MultiLayerNetwork tests: config DSL, init, fit, eval, serde — the layer-API
slice of the reference's dl4jcore tests."""
import numpy as np
import pytest

from deeplearning4j_tpu import nd
from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn import (InputType, MultiLayerConfiguration,
                                   MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               DropoutLayer,
                                               GlobalPoolingLayer, LSTM,
                                               OutputLayer, RnnOutputLayer,
                                               SubsamplingLayer)


def _xor_data():
    X = np.array([[0, 0], [0, 1], [1, 0], [1, 1]], np.float32)
    Y = np.array([[1, 0], [0, 1], [0, 1], [1, 0]], np.float32)
    return nd.create(X), nd.create(Y)


class TestConfigDSL:
    def test_builder(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(42)
                .updater(Adam(learning_rate=0.01))
                .l2(1e-4)
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=3))
                .build())
        assert len(conf.layers) == 2
        assert conf.seed == 42
        assert conf.l2 == 1e-4

    def test_json_roundtrip(self):
        conf = (NeuralNetConfiguration.builder()
                .updater(Adam(learning_rate=0.01))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .set_input_type(InputType.feed_forward(4))
                .build())
        j = conf.to_json()
        conf2 = MultiLayerConfiguration.from_json(j)
        assert len(conf2.layers) == 2
        assert conf2.layers[0].activation == "tanh"
        assert isinstance(conf2.updater, Adam)
        assert conf2.updater.learning_rate == 0.01

    def test_shape_inference_cnn(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5)))
                .layer(SubsamplingLayer(kernel_size=(2, 2)))
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.convolutional(28, 28, 1))
                .build())
        types = conf.layer_input_types()
        assert types[0] == (1, 28, 28)
        assert types[1] == (6, 24, 24)   # 28-5+1
        assert types[2] == (6 * 12 * 12,)  # flattened by auto preprocessor
        net = MultiLayerNetwork(conf).init()
        assert net._params[2]["W"].shape == (864, 32)


class TestTraining:
    def test_xor(self):
        X, Y = _xor_data()
        conf = (NeuralNetConfiguration.builder()
                .seed(7)
                .updater(Adam(learning_rate=0.1))
                .list()
                .layer(DenseLayer(n_in=2, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(X, Y)
        for _ in range(300):
            net.fit(ds)
        preds = net.predict(X).to_list()
        assert preds == [0, 1, 1, 0]
        assert net.score(ds) < 0.1

    def test_fit_iterator_and_evaluate(self):
        rng = np.random.RandomState(0)
        X = rng.randn(200, 4).astype(np.float32)
        Y_idx = (X.sum(axis=1) > 0).astype(np.int64)
        Y = np.eye(2, dtype=np.float32)[Y_idx]
        it = ArrayDataSetIterator(nd.create(X), nd.create(Y), batch_size=50)
        conf = (NeuralNetConfiguration.builder()
                .seed(1)
                .updater(Adam(learning_rate=0.05))
                .list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, num_epochs=20)
        e = net.evaluate(it)
        assert e.accuracy() > 0.95
        assert 0 <= e.f1() <= 1

    def test_batchnorm_training(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32) * 10 + 5
        Y = np.eye(2, dtype=np.float32)[(X[:, 0] > 5).astype(np.int64)]
        conf = (NeuralNetConfiguration.builder()
                .seed(3)
                .updater(Adam(learning_rate=0.05))
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="identity"))
                .layer(BatchNormalization())
                .layer(ActivationLayer(activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(nd.create(X), nd.create(Y))
        for _ in range(30):
            net.fit(ds)
        # running stats should have moved off init values
        assert float(np.abs(net._params[1]["state_mean"]).sum()) > 0.1
        assert net.score(ds) < 0.5

    def test_dropout_layer_runs(self):
        X, Y = _xor_data()
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(learning_rate=0.1)).list()
                .layer(DenseLayer(n_in=2, n_out=16, activation="relu"))
                .layer(DropoutLayer(rate=0.5))
                .layer(OutputLayer(n_in=16, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(DataSet(X, Y), num_epochs=3)
        out = net.output(X)
        assert out.shape == (4, 2)

    def test_cnn_forward_and_fit(self):
        rng = np.random.RandomState(0)
        X = rng.randn(8, 1, 8, 8).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        conf = (NeuralNetConfiguration.builder().seed(9)
                .updater(Adam(learning_rate=0.01)).list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2)))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(nd.create(X))
        assert out.shape == (8, 2)
        np.testing.assert_allclose(out.numpy().sum(axis=1), np.ones(8),
                                   rtol=1e-5)
        net.fit(DataSet(nd.create(X), nd.create(Y)), num_epochs=2)

    def test_lstm_classification(self):
        # simple sequence classification: mean of sequence sign
        rng = np.random.RandomState(0)
        X = rng.randn(16, 3, 5).astype(np.float32)  # [B, F, T]
        Y = np.eye(2, dtype=np.float32)[(X.mean(axis=(1, 2)) > 0).astype(int)]
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Adam(learning_rate=0.02)).list()
                .layer(LSTM(n_in=3, n_out=8))
                .layer(GlobalPoolingLayer(pooling_type="avg"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(nd.create(X))
        assert out.shape == (16, 2)
        net.fit(DataSet(nd.create(X), nd.create(Y)), num_epochs=3)

    def test_rnn_output_layer(self):
        rng = np.random.RandomState(0)
        X = rng.randn(4, 3, 6).astype(np.float32)
        Y = np.zeros((4, 2, 6), np.float32)
        Y[:, 0, :] = 1.0
        conf = (NeuralNetConfiguration.builder().seed(13)
                .updater(Adam(learning_rate=0.05)).list()
                .layer(LSTM(n_in=3, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(nd.create(X))
        assert out.shape == (4, 2, 6)
        net.fit(DataSet(nd.create(X), nd.create(Y)), num_epochs=5)
        assert net.score_value < 1.0


class TestParams:
    def test_flattened_params_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().seed(2).list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        flat = net.params()
        assert flat.length() == net.num_params() == (3 * 4 + 4) + (4 * 2 + 2)
        doubled = flat * 2.0
        net.set_params(doubled)
        np.testing.assert_allclose(net.params().numpy(), doubled.numpy())

    def test_clone_independent(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_in=2, n_out=2))
                .layer(OutputLayer(n_in=2, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        c = net.clone()
        c.set_params(net.params() * 0.0)
        assert float(net.params().norm2_number()) > 0


class TestSerde:
    def test_save_restore(self, tmp_path):
        X, Y = _xor_data()
        conf = (NeuralNetConfiguration.builder().seed(4)
                .updater(Adam(learning_rate=0.1)).list()
                .layer(DenseLayer(n_in=2, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(DataSet(X, Y), num_epochs=20)
        path = str(tmp_path / "model.zip")
        net.save(path, save_updater=True)
        net2 = MultiLayerNetwork.load(path, load_updater=True)
        np.testing.assert_allclose(net2.output(X).numpy(),
                                   net.output(X).numpy(), rtol=1e-6)
        # training continues from restored updater state without blowing up
        net2.fit(DataSet(X, Y), num_epochs=1)


class TestReviewRegressions:
    def test_partial_final_batch_used(self):
        rng = np.random.RandomState(0)
        X = rng.randn(10, 2).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 10)]
        it = ArrayDataSetIterator(nd.create(X), nd.create(Y), batch_size=4)
        seen = sum(ds.num_examples() for ds in it)
        assert seen == 10  # partial final batch of 2 is yielded

    def test_single_sigmoid_evaluation_thresholds(self):
        from deeplearning4j_tpu.nn.evaluation import Evaluation
        e = Evaluation(num_classes=2)
        labels = nd.create([[1.0], [0.0], [1.0]])
        preds = nd.create([[0.9], [0.2], [0.7]])
        e.eval(labels, preds)
        assert e.accuracy() == 1.0

    def test_listener_can_touch_model_mid_fit(self):
        # donation regression: listener calls output() during training
        X, Y = _xor_data()
        conf = (NeuralNetConfiguration.builder().seed(21)
                .updater(Adam(learning_rate=0.1)).list()
                .layer(DenseLayer(n_in=2, n_out=4, activation="tanh"))
                .layer(OutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()

        outputs = []

        class Touch:
            def iteration_done(self, model, iteration, loss=None):
                outputs.append(model.output(X).numpy())

        net.set_listeners(Touch())
        net.fit(DataSet(X, Y), num_epochs=3)
        assert len(outputs) == 3
        assert np.isfinite(outputs[-1]).all()
