"""Regression tests for review findings (post-hoc fixes).

Covers: stateful-vertex input collected post-preprocessor in CG fit;
Subsampling3D shape inference with numeric padding; CenterLossOutputLayer
purity (no tracer leaks); CG JSON round-trip of doubly-wrapped layers.
"""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf.config import (CnnToFeedForwardPreProcessor,
                                               InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               LSTM, OutputLayer)
from deeplearning4j_tpu.nn.conf.layers_extra import (CenterLossOutputLayer,
                                                     LastTimeStep,
                                                     MaskZeroLayer,
                                                     Subsampling3DLayer)
from deeplearning4j_tpu.nn.graph.computation_graph import (
    ComputationGraph, ComputationGraphConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_cg_fit_with_bn_behind_preprocessor():
    """Stateful vertex (BN) behind a preprocessor: new_state must see the
    post-preprocessor (flattened) input, not the raw NCHW tensor."""
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.convolutional(4, 4, 2))
            .add_layer("conv", ConvolutionLayer(n_out=2, kernel_size=(1, 1)),
                       "in")
            .add_layer("bn", BatchNormalization(n_out=32), "conv",
                       preprocessor=CnnToFeedForwardPreProcessor())
            .add_layer("out", OutputLayer(n_in=32, n_out=3), "bn")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.RandomState(0).randn(6, 2, 4, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.arange(6) % 3]
    net.fit(DataSet(x, y), num_epochs=2)  # raised TypeError before the fix
    assert net.output(x)[0].shape == (6, 3)


def test_subsampling3d_output_type_with_padding():
    layer = Subsampling3DLayer(kernel_size=(2, 2, 2), padding=(1, 1, 1))
    inferred = layer.output_type((3, 4, 4, 4))
    x = np.zeros((1, 3, 4, 4, 4), np.float32)
    real = layer.forward({}, x).shape[1:]
    assert inferred == tuple(real) == (3, 3, 3, 3)


def test_center_loss_pure_and_trains():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(CenterLossOutputLayer(n_in=8, n_out=3))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(12, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)]
    out = net.output(x)                      # jitted forward first
    # compute_loss after a jitted output() must not leak tracers
    loss = net.layers[-1].compute_loss(y, out.jax())
    assert np.isfinite(float(loss))
    before = net.score(DataSet(x, y))
    net.fit(DataSet(x, y), num_epochs=20)
    after = net.score(DataSet(x, y))
    assert after < before
    # centers were actually updated from their zero init
    centers = np.asarray(net._params[-1]["state_centers"])
    assert np.abs(centers).sum() > 0


def test_cg_json_roundtrip_nested_wrappers():
    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.recurrent(4, 7))
            .add_layer("l", LastTimeStep(
                underlying=MaskZeroLayer(underlying=LSTM(n_in=4, n_out=6))),
                "in")
            .add_layer("out", OutputLayer(n_in=6, n_out=2), "l")
            .set_outputs("out")
            .build())
    conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
    inner = conf2.vertices["l"].layer.underlying.underlying
    assert isinstance(inner, LSTM)
    assert inner.n_out == 6
    net = ComputationGraph(conf2).init()
    out = net.output(np.zeros((2, 4, 7), np.float32))
    assert out[0].shape == (2, 2)
