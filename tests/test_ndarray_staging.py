"""Element-write staging (VERDICT round-1 weak #5): runs of putScalar /
__setitem__ writes cost O(parent + N), flushing to device once on read."""
import time

import numpy as np

from deeplearning4j_tpu.ndarray import factory as nd
from deeplearning4j_tpu.ndarray.ndarray import NDArray


class TestStagedWrites:
    def test_put_scalar_run_semantics(self):
        a = nd.zeros(4, 5)
        for i in range(4):
            for j in range(5):
                a.put_scalar((i, j), i * 10 + j)
        expected = np.arange(4)[:, None] * 10 + np.arange(5)[None, :]
        np.testing.assert_allclose(a.numpy(), expected)

    def test_view_write_through_staged(self):
        a = nd.zeros(6, 6)
        row = a.get_row(2)          # view
        for j in range(6):
            row.put_scalar(j, j + 1.0)
        np.testing.assert_allclose(a.numpy()[2], np.arange(1, 7))
        # interleaved device ops still see the writes
        b = a.add(1.0)
        np.testing.assert_allclose(b.numpy()[2], np.arange(2, 8))

    def test_nested_view_staging(self):
        a = nd.zeros(4, 4, 4)
        v = a[1]                    # [4,4] view
        vv = v[2]                   # [4] view of view
        vv.put_scalar(3, 42.0)
        assert float(a.numpy()[1, 2, 3]) == 42.0

    def test_mixed_bulk_and_scalar(self):
        a = nd.zeros(3, 3)
        a.put_scalar((0, 0), 1.0)   # staged
        a.assign(5.0)               # bulk write invalidates staging
        np.testing.assert_allclose(a.numpy(), np.full((3, 3), 5.0))
        a.put_scalar((1, 1), 7.0)
        assert float(a.numpy()[1, 1]) == 7.0
        assert float(a.numpy()[0, 0]) == 5.0

    def test_write_run_is_fast(self):
        """1k element writes into a 1M-element parent must not rebuild the
        parent per write (was O(N x parent))."""
        a = nd.zeros(1024, 1024)
        a.numpy()  # materialize
        t0 = time.perf_counter()
        for i in range(1000):
            a.put_scalar((i % 1024, (i * 7) % 1024), float(i))
        dt_writes = time.perf_counter() - t0
        assert dt_writes < 1.0  # staged: microseconds/write, not ms
        assert float(a.numpy()[7, 49]) == 7.0

    def test_setitem_slice_staged(self):
        a = nd.zeros(5, 5)
        a[1:3, 2:4] = 9.0
        a[0] = np.arange(5)
        np.testing.assert_allclose(a.numpy()[1:3, 2:4], np.full((2, 2), 9.0))
        np.testing.assert_allclose(a.numpy()[0], np.arange(5))
