"""Generative serving fast path (models/causal_lm + runtime/generation +
serving /generate).

Covers the acceptance contract of the generative PR: KV-cached
prefill/decode is token-identical to the full-recompute forward;
continuous batching admits/leaves per token (no head-of-line blocking,
deterministic under concurrency, no stale-KV leakage across slot reuse);
steady-state decode performs zero recompiles after warmup (one prefill
executable per prompt bucket + one decode executable); seq-len-1 decode
shapes always dispatch to the XLA attention path; donated-cache steps
record cache=bypass instead of silently missing from compile telemetry;
and POST /v1/models/<name>/generate works end-to-end through admission +
trace context with reconstructable prefill/decode spans.
"""
import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.environment import environment
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.models import causal_lm
from deeplearning4j_tpu.runtime import compile_cache
from deeplearning4j_tpu.runtime.generation import (DecodeEngine,
                                                   is_generative_model,
                                                   sample_tokens)
from deeplearning4j_tpu.runtime.inference import EngineClosedError

CFG = causal_lm.CausalLMConfig.tiny()


@pytest.fixture(scope="module")
def model():
    return causal_lm.CausalLM(CFG, seed=0)


@pytest.fixture(scope="module")
def shared_engine(model):
    """One warmed engine shared by the read-only decode tests (engine
    construction compiles executables; lifecycle/poison tests build their
    own)."""
    eng = DecodeEngine(model, slots=3, max_ctx=64, prompt_buckets=[32])
    yield eng
    eng.close(10)


def _wait_until(fn, timeout=5.0):
    """Poll for an eventually-true read (ring records are written after
    the response bytes reach the client)."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        v = fn()
        if v:
            return v
        time.sleep(0.02)
    return fn()


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(
        0, CFG.vocab_size, n).astype(np.int32)


_REF_JIT = {}


def _ref_greedy(model, prompt, n):
    """Greedy continuation via the full-recompute forward (the O(T²)
    reference the cached path must match token for token). One fixed
    [1, 64] executable per model so the whole module pays one compile."""
    fwd = _REF_JIT.get(id(model))
    if fwd is None:
        fwd = jax.jit(lambda ids: causal_lm.forward(model.params, ids,
                                                    model.config))
        _REF_JIT[id(model)] = fwd
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        ids = np.zeros((1, 64), np.int32)
        ids[0, :len(toks)] = toks
        logits = fwd(jnp.asarray(ids))
        tok = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(tok)
        toks.append(tok)
    return out


def _engine(model, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_ctx", 64)
    kw.setdefault("prompt_buckets", [32])
    return DecodeEngine(model, **kw)


# ---------------------------------------------------------------------------
# model: causal forward + cache-aware attention
# ---------------------------------------------------------------------------

class TestCausalLM:
    def test_forward_shapes_and_dtype(self, model):
        logits = model.forward(jnp.zeros((2, 5), jnp.int32))
        assert logits.shape == (2, 5, CFG.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, model):
        """Changing a later token must not change earlier positions'
        logits — the causal-mask contract autoregression rests on."""
        ids = _prompt(10, seed=1)
        a = model.forward(jnp.asarray(ids[None]))
        ids2 = ids.copy()
        ids2[7] = (ids2[7] + 1) % CFG.vocab_size
        b = model.forward(jnp.asarray(ids2[None]))
        np.testing.assert_allclose(np.asarray(a[0, :7]),
                                   np.asarray(b[0, :7]), atol=1e-5)
        assert not np.allclose(np.asarray(a[0, 7:]), np.asarray(b[0, 7:]))

    def test_prefill_then_decode_matches_forward(self, model):
        """prefill(padded prompt) + N cached decode steps == the full
        forward's greedy continuation, token for token."""
        prompt = _prompt(6, seed=2)
        ref = _ref_greedy(model, prompt, 6)
        cache = model.init_kv_cache(slots=2, max_ctx=32)
        ids = np.zeros((1, 16), np.int32)
        ids[0, :6] = prompt
        cache, logits = model.prefill(
            model.params, cache, jnp.asarray(ids),
            jnp.asarray(1, jnp.int32), jnp.asarray(6, jnp.int32))
        got = [int(jnp.argmax(logits))]
        decode = jax.jit(model.decode)  # one executable for the loop
        tokens = np.zeros(2, np.int32)
        lengths = np.zeros(2, np.int32)
        for i in range(5):
            tokens[1], lengths[1] = got[-1], 6 + i
            cache, logits = decode(model.params, cache,
                                   jnp.asarray(tokens),
                                   jnp.asarray(lengths))
            got.append(int(jnp.argmax(logits[1])))
        assert got == ref

    def test_kv_cache_shape_and_ctx_cap(self, model):
        cache = model.init_kv_cache(slots=3, max_ctx=16)
        assert cache["k"].shape == (3, CFG.num_layers, 16, CFG.num_heads,
                                    CFG.head_dim)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            model.init_kv_cache(slots=1,
                                max_ctx=CFG.max_position_embeddings + 1)

    def test_protocol_detection(self, model):
        assert is_generative_model(model)
        assert not is_generative_model(object())


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

class TestSampling:
    def test_greedy_at_zero_temperature(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(3, 17),
                             jnp.float32)
        toks = sample_tokens(logits, jnp.zeros(3), jnp.zeros(3, jnp.int32),
                             jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.argmax(np.asarray(logits), -1))

    def test_top_k_one_is_greedy(self):
        logits = jnp.asarray(np.random.RandomState(1).randn(4, 11),
                             jnp.float32)
        toks = sample_tokens(logits, jnp.ones(4),
                             jnp.ones(4, jnp.int32),
                             jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(toks),
                                      np.argmax(np.asarray(logits), -1))

    def test_top_k_restricts_support(self):
        logits = jnp.asarray(np.random.RandomState(2).randn(1, 50),
                             jnp.float32)
        top3 = set(np.argsort(np.asarray(logits[0]))[-3:])
        for seed in range(20):
            t = sample_tokens(logits, jnp.ones(1) * 2.0,
                              jnp.full(1, 3, jnp.int32),
                              jax.random.PRNGKey(seed))
            assert int(t[0]) in top3

    def test_per_slot_mixed_configs(self):
        # slot 0 greedy, slot 1 sampled — one call, fixed shapes
        logits = jnp.asarray(np.random.RandomState(3).randn(2, 29),
                             jnp.float32)
        toks = sample_tokens(logits, jnp.asarray([0.0, 1.5]),
                             jnp.asarray([0, 0], jnp.int32),
                             jax.random.PRNGKey(11))
        assert int(toks[0]) == int(np.argmax(np.asarray(logits[0])))
        assert 0 <= int(toks[1]) < 29


# ---------------------------------------------------------------------------
# DecodeEngine: correctness, continuous batching, lifecycle
# ---------------------------------------------------------------------------

class TestDecodeEngine:
    def test_greedy_matches_recompute_reference(self, model,
                                                shared_engine):
        prompt = _prompt(7, seed=3)
        ref = _ref_greedy(model, prompt, 8)
        res = shared_engine.generate(prompt, max_tokens=8).result(
            timeout=60)
        assert res["tokens"] == ref
        assert res["finish_reason"] == "length"
        assert res["prompt_tokens"] == 7
        assert res["completion_tokens"] == 8
        assert res["ttft_s"] > 0

    def test_eos_stop(self, model, shared_engine):
        prompt = _prompt(5, seed=4)
        ref = _ref_greedy(model, prompt, 1)
        res = shared_engine.generate(prompt, max_tokens=16,
                                     eos_token=ref[0]).result(timeout=60)
        assert res["tokens"] == ref[:1]
        assert res["finish_reason"] == "eos"

    def test_concurrent_equals_sequential(self, model, shared_engine):
        """Continuous batching must not change outputs: N requests
        submitted together decode to exactly what each decodes alone."""
        prompts = [_prompt(n, seed=10 + n) for n in (4, 9, 14)]
        refs = [_ref_greedy(model, p, 5) for p in prompts]
        futs = [shared_engine.generate(p, max_tokens=5) for p in prompts]
        for fut, ref in zip(futs, refs):
            assert fut.result(timeout=60)["tokens"] == ref

    def test_no_head_of_line_blocking(self, model, shared_engine):
        """A short request admitted after a long one must finish first —
        the whole point of per-token join/leave."""
        done = []
        long_fut = shared_engine.generate(_prompt(4, seed=20),
                                          max_tokens=30)
        long_fut.add_done_callback(lambda f: done.append("long"))
        short_fut = shared_engine.generate(_prompt(4, seed=21),
                                           max_tokens=3)
        short_fut.add_done_callback(lambda f: done.append("short"))
        short_fut.result(timeout=60)
        long_fut.result(timeout=60)
        assert done[0] == "short", done

    def test_slot_recycling_no_stale_kv_leakage(self, model):
        """Poison-value check: after a slot is recycled, rows a previous
        occupant wrote (and rows poisoned outright) must never reach a
        new request's attention — lengths-masking is the containment."""
        prompt = _prompt(6, seed=30)
        ref = _ref_greedy(model, prompt, 6)
        eng = _engine(model, slots=1)
        try:
            # occupy and release the only slot
            eng.generate(_prompt(10, seed=31), max_tokens=8).result(60)
            # poison EVERY cache row outright: only masking (not luck)
            # can keep the next request clean; prefill overwrites rows
            # [0, bucket) and decode masks everything past `lengths`
            with eng._dispatch_lock:
                eng._cache = {k: jnp.full_like(v, 1e9)
                              for k, v in eng._cache.items()}
            res = eng.generate(prompt, max_tokens=6).result(timeout=60)
            assert res["tokens"] == ref
        finally:
            eng.close(10)

    def test_streaming_callback(self, model, shared_engine):
        seen = []
        res = shared_engine.generate(_prompt(5, seed=40), max_tokens=5,
                                     on_token=seen.append).result(
            timeout=60)
        assert seen == res["tokens"]

    def test_prompt_validation(self, model, shared_engine):
        with pytest.raises(ValueError, match="at least one"):
            shared_engine.generate([])
        with pytest.raises(ValueError, match="no room"):
            shared_engine.generate(list(range(64)))  # == max_ctx

    def test_max_tokens_capped_by_context(self, model):
        eng = _engine(model, max_ctx=16, prompt_buckets=[8])
        try:
            res = eng.generate(_prompt(8, seed=41),
                               max_tokens=500).result(timeout=60)
            # cap = max_ctx - prompt_len
            assert res["completion_tokens"] == 8
            assert res["finish_reason"] == "length"
        finally:
            eng.close(10)

    def test_drain_rejects_and_start_reopens(self, model):
        eng = _engine(model)
        eng.generate(_prompt(4, seed=42), max_tokens=2).result(60)
        assert eng.drain(timeout_s=30)
        with pytest.raises(EngineClosedError):
            eng.generate(_prompt(4, seed=42))
        eng.start()
        assert eng.generate(_prompt(4, seed=42),
                            max_tokens=2).result(60)["tokens"]
        assert eng.close(30)
        with pytest.raises(EngineClosedError):
            eng.start()

    def test_admission_timeout_expires_queued_request(self, model):
        """A request whose deadline passes before a slot frees must fail
        with TimeoutError without any model work."""
        eng = _engine(model, slots=1, max_ctx=128, prompt_buckets=[8])
        try:
            blocker = eng.generate(_prompt(4, seed=43), max_tokens=80)
            late = eng.generate(_prompt(4, seed=44), max_tokens=2,
                                timeout_s=0.0)
            with pytest.raises(TimeoutError):
                late.result(timeout=60)
            blocker.result(timeout=60)
        finally:
            eng.close(10)

    def test_stats_surface(self, model, shared_engine):
        before = shared_engine.stats()
        shared_engine.generate(_prompt(4, seed=45), max_tokens=3).result(60)
        s = shared_engine.stats()
        assert s["requests"] == before["requests"] + 1
        assert s["tokens"] == before["tokens"] + 3
        assert s["prefills"] == before["prefills"] + 1
        assert s["slots"] == 3
        # explicit buckets, plus the always-present max_ctx top rung
        # (preempted riders' prefixes must stay admittable)
        assert s["prompt_buckets"] == [32, 64]


class TestCompileCounting:
    def test_one_executable_per_bucket_plus_one_decode(self, model):
        """Warmup compiles exactly len(ladder) * len(batch ladder)
        prefill executables + 1 decode executable; steady-state traffic
        then compiles NOTHING — the zero-recompile acceptance
        invariant."""
        env = environment()
        eng = DecodeEngine(model, slots=2, max_ctx=64,
                           prompt_buckets=[8, 32], prefill_batch=2)
        expected = len(eng.ladder) * len(eng.batch_ladder) + 1
        try:
            env.reset_compile_count()
            eng.warmup()
            # ladder (8, 32, + max_ctx rung) x batch ladder (1, 2)
            # prefill executables, + 1 decode
            assert env.compile_count() == expected
            eng.warmup()  # idempotent
            assert env.compile_count() == expected
            env.reset_compile_count()
            futs = [eng.generate(_prompt(n, seed=50 + n), max_tokens=4)
                    for n in (3, 8, 20, 5)]
            for f in futs:
                f.result(timeout=60)
            assert env.compile_count() == 0
        finally:
            eng.close(10)
            env.reset_compile_count()


# ---------------------------------------------------------------------------
# satellite: decode shapes always dispatch to the XLA attention path
# ---------------------------------------------------------------------------

class TestDecodeAttentionDispatch:
    def test_seq_len_one_always_xla(self):
        from deeplearning4j_tpu.kernels import attention_dispatch
        env = environment()
        prev = env.flash_min_seq()
        try:
            # even a threshold that would send EVERYTHING to flash must
            # not move the decode shape off the XLA path
            env.set_flash_min_seq(1)
            assert attention_dispatch(1) == "xla"
            assert attention_dispatch(0) == "xla"
            assert attention_dispatch(2) == "flash"
        finally:
            env.set_flash_min_seq(prev)

    def test_decode_shape_ticks_dispatch_counter(self, model):
        """Tracing the decode step records dl4j_attn_dispatch_total with
        path=xla (once per compiled executable)."""
        from deeplearning4j_tpu.kernels import attention_dispatch

        fam = registry().counter(
            "dl4j_attn_dispatch_total",
            "Attention path decisions for flash=True configs",
            labels=("path",))
        before = fam.labels(path="xla").value()
        env = environment()
        prev = env.flash_min_seq()
        try:
            env.set_flash_min_seq(1)  # adversarial: flash for everything
            assert attention_dispatch(1) == "xla"
        finally:
            env.set_flash_min_seq(prev)
        assert fam.labels(path="xla").value() == before + 1

    def test_paged_path_ticks_paged_label(self):
        """The block-table gather attention of paged_decode records its
        own path=paged label — paged and slab decode executables stay
        distinguishable in telemetry — and never takes the flash kernel,
        whatever the query length or DL4J_TPU_FLASH_MIN_SEQ."""
        from deeplearning4j_tpu.kernels import attention_dispatch

        fam = registry().counter(
            "dl4j_attn_dispatch_total",
            "Attention path decisions for flash=True configs",
            labels=("path",))
        before = fam.labels(path="paged").value()
        env = environment()
        prev = env.flash_min_seq()
        try:
            env.set_flash_min_seq(1)
            assert attention_dispatch(1, paged=True) == "paged"
            assert attention_dispatch(512, paged=True) == "paged"
        finally:
            env.set_flash_min_seq(prev)
        assert fam.labels(path="paged").value() == before + 2


# ---------------------------------------------------------------------------
# satellite: donated-cache steps are store-ineligible, never silent
# ---------------------------------------------------------------------------

class TestDonatedDecodeCompileCache:
    def test_decode_steps_bypass_store_with_histogram_evidence(self, model):
        """Donated-KV-cache prefill/decode entries must (a) never land in
        the raw executable store and (b) still record the *reasoned*
        cache=bypass:donation on the dl4j_compile_seconds histogram —
        observable, not silently missing, and attributable."""
        fam = registry().histogram(
            "dl4j_compile_seconds",
            "Wall time to materialize + first-run an executable, by cache "
            "outcome", labels=("kind", "cache"))

        def bypass_count(kind):
            return sum(child.count() for key, child in fam.children()
                       if key == (kind, "bypass:donation"))

        pre_prefill = bypass_count("prefill")
        pre_decode = bypass_count("decode")
        eng = DecodeEngine(model, slots=2, max_ctx=64,
                           prompt_buckets=[16], prefill_batch=1)
        try:
            eng.warmup()
        finally:
            eng.close(10)
        # one prefill executable per ladder rung ([16] + max_ctx top
        # rung), one decode executable — every one a store bypass
        assert bypass_count("prefill") == pre_prefill + len(eng.ladder)
        assert bypass_count("decode") == pre_decode + 1
        inv = compile_cache.inventory()
        assert inv["enabled"]  # conftest pins a live per-run cache dir
        kinds = {e.get("tag_kind") for e in inv["entries"]}
        assert "prefill" not in kinds and "decode" not in kinds


# ---------------------------------------------------------------------------
# serving: registry + HTTP /generate end to end
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _post(url, doc, timeout=30, headers=()):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json", **dict(headers)})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


@pytest.fixture(scope="module")
def served_lm(model):
    """One served registry shared by the endpoint tests (each deploy
    compiles executables; the hot-swap test runs last and restores v1)."""
    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer

    reg = ModelRegistry(manifest_dir=None, retain=1)
    reg.deploy("lm", "v1", model, decode_slots=2, decode_max_ctx=64,
               decode_prompt_buckets=[32])
    srv = ModelServer(reg)
    port = srv.start()
    yield reg, srv, f"http://127.0.0.1:{port}"
    srv.stop()
    reg.drain_all(save_manifests=False)


class TestRegistryGenerate:
    def test_deploy_detects_generative_and_describes(self, model):
        from deeplearning4j_tpu.serving import ModelRegistry

        reg = ModelRegistry(manifest_dir=None, retain=0)
        try:
            mv = reg.deploy("lm", "v1", model, decode_slots=2,
                            decode_max_ctx=64,
                            decode_prompt_buckets=[8])
            assert isinstance(mv.engine, DecodeEngine)
            assert mv.describe()["generative"] is True
            assert reg.ready()
            prompt = _prompt(5, seed=60)
            ref = _ref_greedy(model, prompt, 4)
            res = reg.generate("lm", prompt, max_tokens=4)
            assert res["tokens"] == ref
            with pytest.raises(TypeError, match="generative"):
                reg.predict("lm", np.zeros((1, 4), np.float32))
        finally:
            reg.drain_all(save_manifests=False)

    def test_generate_on_non_generative_raises(self):
        from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.serving import ModelRegistry

        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        reg = ModelRegistry(manifest_dir=None, retain=0)
        try:
            reg.deploy("mlp", "v1", net,
                       example=np.zeros((2, 4), np.float32))
            with pytest.raises(TypeError, match="not generative"):
                reg.generate("mlp", [1, 2, 3])
        finally:
            reg.drain_all(save_manifests=False)


class TestGenerateEndpoint:
    def test_end_to_end_with_trace_and_debug_spans(self, served_lm, model):
        """The acceptance path: POST /generate through admission + trace
        context; the response echoes X-Trace-Id and the request's
        prefill/decode spans are reconstructable via /debug/requests."""
        reg, srv, base = served_lm
        prompt = _prompt(5, seed=70)
        ref = _ref_greedy(model, prompt, 6)
        status, headers, body = _post(
            base + "/v1/models/lm/generate",
            {"prompt": [int(t) for t in prompt], "max_tokens": 6})
        assert status == 200
        trace_id = headers.get("X-Trace-Id")
        assert trace_id
        doc = json.loads(body)
        assert doc["tokens"] == ref
        assert doc["model"] == "lm" and doc["version"] == "v1"
        assert doc["finish_reason"] == "length"
        assert doc["ttft_s"] > 0

        # the ring record lands after the response bytes reach the
        # client: poll, same as the PR-6 tracing tests
        doc = _wait_until(lambda: (lambda d: d["count"] == 1 and d)(
            json.loads(_get(
                base + f"/debug/requests?trace_id={trace_id}")[2])))
        assert doc and doc["count"] == 1
        rec = doc["requests"][0]
        assert rec["kind"] == "generate"
        names = []

        def walk(spans):
            for s in spans:
                names.append(s["name"])
                walk(s.get("children", []))

        walk(rec["spans"])
        assert "serving/request" in names
        assert "serving/admission" in names
        assert "generation/prefill" in names
        assert "generation/decode" in names

    def test_traceparent_joined(self, served_lm, model):
        reg, srv, base = served_lm
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        status, headers, _ = _post(
            base + "/v1/models/lm/generate",
            {"prompt": [1, 2, 3], "max_tokens": 2},
            headers={"traceparent": tp})
        assert status == 200
        assert headers.get("X-Trace-Id") == "ab" * 16

    def test_streaming_chunks(self, served_lm, model):
        reg, srv, base = served_lm
        prompt = _prompt(4, seed=71)
        ref = _ref_greedy(model, prompt, 5)
        req = urllib.request.Request(
            base + "/v1/models/lm/generate",
            data=json.dumps({"prompt": [int(t) for t in prompt],
                             "max_tokens": 5, "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        r = urllib.request.urlopen(req, timeout=30)
        assert r.status == 200
        assert r.headers.get("X-Trace-Id")
        assert "ndjson" in r.headers.get("Content-Type", "")
        lines = [json.loads(l) for l in r.read().splitlines() if l.strip()]
        streamed = [l["token"] for l in lines if "token" in l]
        assert streamed == ref
        tail = lines[-1]
        assert tail["done"] is True and tail["tokens"] == ref

    def test_error_mapping(self, served_lm):
        reg, srv, base = served_lm
        status, _, _ = _post(base + "/v1/models/nope/generate",
                             {"prompt": [1]})
        assert status == 404
        status, _, body = _post(base + "/v1/models/lm/generate", {})
        assert status == 400 and b"prompt" in body
        status, _, _ = _post(base + "/v1/models/lm/generate",
                             {"prompt": "not ids"})
        assert status == 400
        # predict on a generative model is a client error, not a 500
        status, _, body = _post(base + "/v1/models/lm/predict",
                                {"inputs": [[1.0]]})
        assert status == 400 and b"generative" in body

    def test_sampled_generation_within_vocab(self, served_lm):
        reg, srv, base = served_lm
        status, _, body = _post(
            base + "/v1/models/lm/generate",
            {"prompt": [3, 7], "max_tokens": 6, "temperature": 0.8,
             "top_k": 10})
        assert status == 200
        toks = json.loads(body)["tokens"]
        assert len(toks) == 6
        assert all(0 <= t < CFG.vocab_size for t in toks)

    def test_generate_feeds_slo_with_ttft(self, served_lm):
        reg, srv, base = served_lm
        _post(base + "/v1/models/lm/generate",
              {"prompt": [1, 2], "max_tokens": 2})
        assert _wait_until(lambda: any(
            w["total"] >= 1
            for w in srv.slo_for("lm").snapshot()["windows"]))

    def test_debug_decode_endpoint(self, served_lm):
        """GET /debug/decode joins every current generative engine's
        slot map + block pool + speculative state into the debug
        surface (and, via decode_snapshots(), the flight recorder)."""
        reg, srv, base = served_lm
        _post(base + "/v1/models/lm/generate",
              {"prompt": [5, 6, 7], "max_tokens": 2})
        status, _, body = _get(base + "/debug/decode")
        assert status == 200
        snaps = json.loads(body)["decode"]
        snap = next(s for s in snaps if s["model"] == "lm")
        assert snap["version"] == "v1"
        assert snap["pool"]["scratch_block"] == 0
        assert snap["pool"]["free_blocks"] <= snap["pool"]["total_blocks"]
        assert len(snap["slots"]) == 2
        assert snap["prefill"]["batch"] >= 1
        assert snap["speculative"]["enabled"] is False
        assert snap["queue_depth"] >= 0

    def test_hot_swap_generative_version(self, served_lm, model):
        """Warm-before-cutover + rollback work for DecodeEngine versions
        exactly as for predict engines."""
        reg, srv, base = served_lm
        model2 = causal_lm.CausalLM(CFG, seed=9)
        reg.deploy("lm", "v2", model2, decode_slots=2, decode_max_ctx=64,
                   decode_prompt_buckets=[32])
        status, _, body = _post(base + "/v1/models/lm/generate",
                                {"prompt": [4, 4, 4], "max_tokens": 3})
        assert status == 200
        assert json.loads(body)["version"] == "v2"
        reg.rollback("lm")
        status, _, body = _post(base + "/v1/models/lm/generate",
                                {"prompt": [4, 4, 4], "max_tokens": 3})
        assert status == 200
        assert json.loads(body)["version"] == "v1"


class TestDecodeEnvKnobs:
    def test_defaults_and_overrides(self):
        env = environment()
        assert env.decode_slots() == 8
        assert env.decode_max_ctx() == 256
        assert env.decode_max_tokens() == 128
        try:
            env.set_decode_slots(3)
            env.set_decode_max_ctx(64)
            env.set_decode_max_tokens(16)
            assert env.decode_slots() == 3
            assert env.decode_max_ctx() == 64
            assert env.decode_max_tokens() == 16
        finally:
            from deeplearning4j_tpu.common.environment import \
                SystemProperties
            env.clear_property(SystemProperties.DECODE_SLOTS)
            env.clear_property(SystemProperties.DECODE_MAX_CTX)
            env.clear_property(SystemProperties.DECODE_MAX_TOKENS)

    def test_engine_reads_env_defaults(self, model):
        env = environment()
        try:
            env.set_decode_slots(3)
            env.set_decode_max_ctx(48)
            eng = DecodeEngine(model)
            assert eng.slots == 3
            assert eng.max_ctx == 48
            eng.close(5)
        finally:
            from deeplearning4j_tpu.common.environment import \
                SystemProperties
            env.clear_property(SystemProperties.DECODE_SLOTS)
            env.clear_property(SystemProperties.DECODE_MAX_CTX)


# ---------------------------------------------------------------------------
# tentpole: paged KV block pool
# ---------------------------------------------------------------------------

class TestPagedKVBlocks:
    def test_blocks_track_sequence_length(self, model):
        """The reservation the paging PR exists for: a sequence holds
        ceil((rows written + 1) / block_size) blocks at every step —
        never the slab layout's full max_ctx worth."""
        # prefix cache off: this test pins the raw paging accounting,
        # where completion returns every block to the pool
        eng = _engine(model, slots=2, prompt_buckets=[16], kv_block_size=8,
                      prefix_cache=False)
        samples = []

        def cb(_tok):
            samples.append((int(eng._nblocks.sum()),
                            int(eng._lengths.sum())))

        try:
            total = eng.stats()["kv_blocks_free"]
            assert total == eng.kv_blocks == 2 * eng.max_blocks
            res = eng.generate(_prompt(12, seed=80), max_tokens=20,
                               on_token=cb).result(timeout=60)
            assert len(res["tokens"]) == 20
            for nblocks, length in samples:
                # within one block of committed rows (+1 for the write
                # horizon the scheduler pre-allocates)
                assert 0 <= nblocks * eng.block_size - length \
                    <= eng.block_size
            peak = max(nb for nb, _ in samples)
            # final length 32 rows -> 4 blocks; slab would pin all 8
            assert peak < eng.max_blocks
            # every block returned on completion
            assert eng.stats()["kv_blocks_free"] == total
        finally:
            eng.close(10)

    def test_blocks_free_gauge_tracks_pool(self, model):
        fam = registry().gauge(
            "dl4j_kv_blocks_free",
            "Free KV-cache blocks in the paged decode pool",
            labels=("model",))
        eng = _engine(model, kv_block_size=8, model_name="kvgauge",
                      prefix_cache=False)
        child = fam.labels(model="kvgauge")
        dips = []
        try:
            assert child.value() == eng.kv_blocks
            eng.generate(_prompt(10, seed=81), max_tokens=8,
                         on_token=lambda t: dips.append(child.value())
                         ).result(timeout=60)
            assert min(dips) < eng.kv_blocks  # held while decoding
            assert child.value() == eng.kv_blocks  # returned on finish
        finally:
            eng.close(10)

    def test_over_pool_request_rejected_at_submit(self, model):
        """A request whose worst case cannot fit the pool must fail at
        generate(), not deadlock the scheduler mid-decode."""
        eng = _engine(model, kv_block_size=8, kv_blocks=4)  # 32 rows
        try:
            with pytest.raises(ValueError, match="KV blocks"):
                # prompt 8 + capped max_tokens 56 -> 8 blocks > 4
                eng.generate(_prompt(8, seed=82), max_tokens=56)
            res = eng.generate(_prompt(8, seed=82),
                               max_tokens=8).result(timeout=60)
            assert len(res["tokens"]) == 8  # 16 rows = 2 blocks: fits
        finally:
            eng.close(10)

    def test_slab_layout_is_block_size_max_ctx(self, model):
        """kv_block_size >= max_ctx reproduces the legacy slab: one
        block per slot, admission == slot availability."""
        eng = _engine(model, kv_block_size=4096)
        try:
            assert eng.block_size == eng.max_ctx
            assert eng.max_blocks == 1
            assert eng.kv_blocks == eng.slots
        finally:
            eng.close(10)

    def test_debug_snapshot_surface(self, model):
        eng = _engine(model, kv_block_size=8, model_name="snap")
        gate, release = threading.Event(), threading.Event()

        def cb(_tok):
            gate.set()
            release.wait(30)

        try:
            fut = eng.generate(_prompt(6, seed=83), max_tokens=4,
                               on_token=cb)
            assert gate.wait(30)
            snap = eng.debug_snapshot()
            assert snap["model"] == "snap"
            assert snap["pool"]["scratch_block"] == 0
            assert snap["pool"]["block_size"] == 8
            assert snap["pool"]["free_blocks"] < snap["pool"]["total_blocks"]
            occupied = [s for s in snap["slots"] if s["active"]]
            assert len(occupied) == 1
            assert occupied[0]["prompt_tokens"] == 6
            assert occupied[0]["blocks"]  # non-scratch ids
            assert all(b > 0 for b in occupied[0]["blocks"])
            assert snap["speculative"]["enabled"] is False
            release.set()
            fut.result(timeout=60)
        finally:
            release.set()
            eng.close(10)


class TestPreemption:
    def test_pool_exhaustion_preempts_lifo_and_recomputes(self, model):
        """Two riders whose combined growth exceeds the pool: the later-
        admitted one is preempted (blocks reclaimed, requeued at the
        queue head), then recomputed from prompt + committed tokens —
        greedy output stays token-identical for BOTH."""
        fam = registry().counter(
            "dl4j_decode_preempted_total",
            "Sequences preempted (blocks reclaimed, requeued for "
            "recompute) because the KV block pool ran dry mid-decode")
        before = fam.value()
        # pool of 5 blocks = 40 rows; each request's worst case is 4
        # blocks (32 rows), so both fit alone but not together
        eng = _engine(model, slots=2, prompt_buckets=[16],
                      kv_block_size=8, kv_blocks=5)
        pa, pb = _prompt(8, seed=84), _prompt(8, seed=85)
        ra, rb = _ref_greedy(model, pa, 24), _ref_greedy(model, pb, 24)
        try:
            fa = eng.generate(pa, max_tokens=24)
            fb = eng.generate(pb, max_tokens=24)
            assert fa.result(timeout=120)["tokens"] == ra
            assert fb.result(timeout=120)["tokens"] == rb
            s = eng.stats()
            assert s["preempted"] >= 1
            assert fam.value() >= before + 1
            # nothing leaked: completed prefixes legitimately stay in
            # the radix cache; free + cached must cover the whole pool
            assert (s["kv_blocks_free"]
                    + s["prefix_cached_blocks"]) == 5
        finally:
            eng.close(10)


# ---------------------------------------------------------------------------
# tentpole: batched prefill
# ---------------------------------------------------------------------------

class TestBatchedPrefill:
    def _gated_long(self, eng, seed):
        """Start a request whose first on_token blocks the decode loop:
        everything submitted while it is blocked is queued together, so
        the next admission's grouping is deterministic."""
        entered, release = threading.Event(), threading.Event()

        def gate(_tok):
            entered.set()
            release.wait(30)

        fut = eng.generate(_prompt(5, seed=seed), max_tokens=8,
                           on_token=gate)
        assert entered.wait(30)
        return fut, release

    def test_same_bucket_prompts_share_one_dispatch(self, model):
        eng = _engine(model, slots=4, prompt_buckets=[16],
                      prefill_batch=4)
        prompts = [_prompt(6, seed=90 + i) for i in range(3)]
        refs = [_ref_greedy(model, p, 4) for p in prompts]
        long_ref = _ref_greedy(model, _prompt(5, seed=89), 8)
        try:
            before = eng.stats()
            long_fut, release = self._gated_long(eng, 89)
            futs = [eng.generate(p, max_tokens=4) for p in prompts]
            release.set()
            for f, ref in zip(futs, refs):
                assert f.result(timeout=60)["tokens"] == ref
            assert long_fut.result(timeout=60)["tokens"] == long_ref
            s = eng.stats()
            assert s["prefills"] - before["prefills"] == 4
            # one dispatch for the long prompt + ONE for the group of 3
            assert (s["prefill_dispatches"]
                    - before["prefill_dispatches"]) == 2
        finally:
            eng.close(10)

    def test_mixed_buckets_do_not_share_a_dispatch(self, model):
        """Coalescing is per bucket: padding a 20-token prompt into a
        16-bucket dispatch would corrupt it, so it gets its own."""
        eng = _engine(model, slots=4, prompt_buckets=[16, 32],
                      prefill_batch=4)
        p16a, p32, p16b = (_prompt(6, seed=94), _prompt(20, seed=95),
                           _prompt(7, seed=96))
        refs = [_ref_greedy(model, p, 3) for p in (p16a, p32, p16b)]
        try:
            before = eng.stats()
            long_fut, release = self._gated_long(eng, 93)
            futs = [eng.generate(p, max_tokens=3)
                    for p in (p16a, p32, p16b)]
            release.set()
            for f, ref in zip(futs, refs):
                assert f.result(timeout=60)["tokens"] == ref
            long_fut.result(timeout=60)
            # long alone + {p16a, p16b} grouped + p32 alone
            assert (eng.stats()["prefill_dispatches"]
                    - before["prefill_dispatches"]) == 3
        finally:
            eng.close(10)


# ---------------------------------------------------------------------------
# tentpole: greedy speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculativeDecode:
    def test_same_model_draft_token_identical(self, model):
        eng = _engine(model, draft_model=model, spec_k=3)
        prompts = [_prompt(n, seed=100 + n) for n in (5, 9)]
        refs = [_ref_greedy(model, p, 10) for p in prompts]
        try:
            futs = [eng.generate(p, max_tokens=10) for p in prompts]
            for f, ref in zip(futs, refs):
                assert f.result(timeout=60)["tokens"] == ref
            s = eng.stats()
            assert s["spec_steps"] > 0
            assert s["spec_proposed"] > 0
            # an identical draft should verify nearly everything
            assert s.get("spec_acceptance", 0) >= 0.9
            snap = eng.debug_snapshot()
            assert snap["speculative"]["enabled"]
            assert snap["speculative"]["k"] == 3
            assert snap["speculative"]["acceptance_rate"] is not None
        finally:
            eng.close(10)

    def test_truncated_draft_token_identical(self, model):
        """The production shape: a cheaper draft sharing the target's
        first layer + embeddings. Whatever it proposes, verification
        must keep the greedy output byte-for-byte the target's own."""
        dcfg = dataclasses.replace(CFG, num_layers=1)
        draft = causal_lm.CausalLM(dcfg, params={
            "embeddings": model.params["embeddings"],
            "layers": model.params["layers"][:1]})
        eng = _engine(model, draft_model=draft, spec_k=2)
        prompt = _prompt(6, seed=110)
        ref = _ref_greedy(model, prompt, 12)
        try:
            res = eng.generate(prompt, max_tokens=12).result(timeout=60)
            assert res["tokens"] == ref
            s = eng.stats()
            assert s["spec_steps"] > 0
            assert s.get("spec_acceptance") is not None
        finally:
            eng.close(10)

    def test_sampled_rider_falls_back_to_plain_decode(self, model):
        """Speculation is greedy-only: any sampled rider in the batch
        sends the whole step down the plain path."""
        eng = _engine(model, draft_model=model, spec_k=3)
        try:
            res = eng.generate(_prompt(5, seed=111), max_tokens=8,
                               temperature=0.8, top_k=10
                               ).result(timeout=60)
            assert len(res["tokens"]) == 8
            assert all(0 <= t < CFG.vocab_size for t in res["tokens"])
            assert eng.stats()["spec_steps"] == 0
        finally:
            eng.close(10)

    def test_non_generative_draft_rejected(self, model):
        with pytest.raises(TypeError, match="draft_model"):
            _engine(model, draft_model=object(), spec_k=2)


class TestPagedEnvKnobs:
    def test_defaults_and_overrides(self):
        from deeplearning4j_tpu.common.environment import SystemProperties
        env = environment()
        assert env.kv_block_size() == 16
        assert env.spec_draft_k() == 0
        try:
            env.set_kv_block_size(4)
            env.set_spec_draft_k(2)
            assert env.kv_block_size() == 4
            assert env.spec_draft_k() == 2
        finally:
            env.clear_property(SystemProperties.KV_BLOCK_SIZE)
            env.clear_property(SystemProperties.SPEC_DRAFT_K)

    def test_engine_reads_env_knobs(self, model):
        from deeplearning4j_tpu.common.environment import SystemProperties
        env = environment()
        try:
            env.set_kv_block_size(4)
            env.set_spec_draft_k(2)
            eng = _engine(model, draft_model=model)
            assert eng.block_size == 4
            assert eng.max_blocks == 16
            assert eng.spec_k == 2 and eng._spec_enabled
            eng.close(5)
            # spec_k=0 disables even with a draft wired
            eng = _engine(model, draft_model=model, spec_k=0)
            assert not eng._spec_enabled
            eng.close(5)
        finally:
            env.clear_property(SystemProperties.KV_BLOCK_SIZE)
            env.clear_property(SystemProperties.SPEC_DRAFT_K)


# ---------------------------------------------------------------------------
# tentpole: prefix-aware KV reuse (radix cache over the paged pool)
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_warm_repeat_reuses_and_stays_token_identical(self, model):
        """The headline: a repeated prompt attaches its block-aligned
        cached prefix (all but the final block run — one tail token must
        still prefill to produce logits) and decodes the exact tokens of
        the cold run."""
        eng = _engine(model, kv_block_size=8, kv_blocks=16)
        prompt = _prompt(23, seed=120)
        ref = _ref_greedy(model, prompt, 6)
        try:
            cold = eng.generate(prompt, max_tokens=6).result(timeout=60)
            s0 = eng.stats()
            assert cold["tokens"] == ref
            assert s0["prefix_hits"] == 0 and s0["prefix_misses"] == 1
            assert s0["prefix_cached_blocks"] > 0
            warm = eng.generate(prompt, max_tokens=6).result(timeout=60)
            s1 = eng.stats()
            assert warm["tokens"] == ref
            assert s1["prefix_hits"] == 1
            # 23-token prompt, block 8: blocks [0:8) and [8:16) cached;
            # the 22-row cap never binds here (16 <= 22)
            assert s1["prefix_reused_rows"] == 16
            # warm prefill computed only the 7-row tail
            assert s1["prefill_rows"] - s0["prefill_rows"] == 7
        finally:
            eng.close(10)

    def test_multi_turn_history_reattaches(self, model):
        """Turn 2 re-sends turn 1's prompt + generated reply + new user
        tokens: the cached run covers the whole committed history
        (prompt AND generated tokens), so only the new tail prefills."""
        eng = _engine(model, kv_block_size=8, kv_blocks=16,
                      prompt_buckets=[32, 64], max_ctx=64)
        p1 = _prompt(12, seed=121)
        try:
            t1 = eng.generate(p1, max_tokens=8).result(timeout=60)
            turn2 = np.concatenate(
                [p1, np.asarray(t1["tokens"], np.int32),
                 _prompt(6, seed=122)])
            ref = _ref_greedy(model, turn2, 5)
            s0 = eng.stats()
            t2 = eng.generate(turn2, max_tokens=5).result(timeout=60)
            s1 = eng.stats()
            assert t2["tokens"] == ref
            assert s1["prefix_hits"] - s0["prefix_hits"] == 1
            # committed history = 12 + 8 = 20 rows -> 2 full blocks
            assert s1["prefix_reused_rows"] - s0["prefix_reused_rows"] == 16
        finally:
            eng.close(10)

    def test_divergent_suffix_forks_not_corrupts(self, model):
        """Two prompts sharing 16 tokens then diverging: the second
        attaches the shared run and prefills its own suffix into fresh
        blocks — the first request's cached blocks must stay intact
        (verified by decoding both against the recompute reference)."""
        eng = _engine(model, kv_block_size=8, kv_blocks=16)
        common = _prompt(16, seed=123)
        a = np.concatenate([common, _prompt(7, seed=124)])
        b = np.concatenate([common, _prompt(7, seed=125)])
        ra, rb = _ref_greedy(model, a, 6), _ref_greedy(model, b, 6)
        try:
            assert eng.generate(a, max_tokens=6).result(60)["tokens"] == ra
            s0 = eng.stats()
            assert eng.generate(b, max_tokens=6).result(60)["tokens"] == rb
            s1 = eng.stats()
            assert s1["prefix_reused_rows"] - s0["prefix_reused_rows"] == 16
            # replaying A after B's fork must still see A's blocks
            assert eng.generate(a, max_tokens=6).result(60)["tokens"] == ra
        finally:
            eng.close(10)

    def test_lru_eviction_reclaims_unattached_leaves(self, model):
        """A pool sized for ~2 cached prompts: filling it with distinct
        prompts forces leaf eviction (counted on the engine and the
        dl4j_kv_prefix_evictions_total counter) and decode stays
        correct throughout."""
        fam = registry().counter(
            "dl4j_kv_prefix_evictions_total",
            "KV prefix-cache blocks reclaimed by LRU leaf eviction")
        before = fam.value()
        eng = _engine(model, kv_block_size=8, kv_blocks=8)
        prompts = [_prompt(14, seed=130 + i) for i in range(4)]
        refs = [_ref_greedy(model, p, 4) for p in prompts]
        try:
            for p, ref in zip(prompts, refs):
                assert eng.generate(p, max_tokens=4
                                    ).result(60)["tokens"] == ref
            s = eng.stats()
            assert s["prefix_evictions"] > 0
            assert fam.value() - before == s["prefix_evictions"]
            # the pool never leaked: all blocks free or cached
            assert (s["kv_blocks_free"] + s["prefix_cached_blocks"]
                    == eng.kv_blocks)
        finally:
            eng.close(10)

    def test_disabled_engine_never_caches(self, model):
        eng = _engine(model, kv_block_size=8, prefix_cache=False)
        prompt = _prompt(23, seed=126)
        ref = _ref_greedy(model, prompt, 6)
        try:
            for _ in range(2):
                assert eng.generate(prompt, max_tokens=6
                                    ).result(60)["tokens"] == ref
            s = eng.stats()
            assert s["prefix_cache"] is False
            assert s["prefix_hits"] == 0 and s["prefix_misses"] == 0
            assert s["prefix_cached_blocks"] == 0
            assert eng.debug_snapshot()["prefix_cache"]["enabled"] is False
        finally:
            eng.close(10)

    def test_debug_snapshot_exposes_radix(self, model):
        eng = _engine(model, kv_block_size=8, model_name="radix-snap")
        try:
            eng.generate(_prompt(20, seed=127), max_tokens=4).result(60)
            snap = eng.debug_snapshot()["prefix_cache"]
            assert snap["enabled"] is True
            assert snap["cached_blocks"] == len(snap["nodes"]) > 0
            for nd in snap["nodes"]:
                assert nd["block"] > 0          # never the scratch block
                assert len(nd["digest"]) == 12  # chained sha1, truncated
                assert nd["refs"] == 0          # nothing attached now
        finally:
            eng.close(10)

    def test_prefix_blocks_gauge_tracks_cache(self, model):
        fam = registry().gauge(
            "dl4j_kv_prefix_blocks",
            "KV blocks currently held by the prefix cache's radix tree",
            labels=("model",))
        eng = _engine(model, kv_block_size=8, model_name="pfxgauge")
        child = fam.labels(model="pfxgauge")
        try:
            eng.generate(_prompt(17, seed=128), max_tokens=3).result(60)
            assert child.value() == eng.stats()["prefix_cached_blocks"] > 0
        finally:
            eng.close(10)


class TestPrefixCacheEnvKnobs:
    def test_default_and_override(self):
        from deeplearning4j_tpu.common.environment import SystemProperties
        env = environment()
        assert env.prefix_cache_enabled() is True
        try:
            env.set_prefix_cache(False)
            assert env.prefix_cache_enabled() is False
        finally:
            env.clear_property(SystemProperties.PREFIX_CACHE)

    def test_engine_reads_env_knob(self, model):
        from deeplearning4j_tpu.common.environment import SystemProperties
        env = environment()
        try:
            env.set_prefix_cache(False)
            eng = _engine(model)
            assert eng.stats()["prefix_cache"] is False
            eng.close(5)
            # the constructor kwarg wins over the env default
            eng = _engine(model, prefix_cache=True)
            assert eng.stats()["prefix_cache"] is True
            eng.close(5)
        finally:
            env.clear_property(SystemProperties.PREFIX_CACHE)


class TestPrefixCachePreemption:
    def test_preempted_request_reattaches_cached_prefix(self, model):
        """Satellite regression (preemption/fork interplay): a LIFO-
        preempted request publishes its regrown prefix (prompt +
        committed tokens) into the radix cache before releasing its
        blocks, so the re-admit attaches that run and prefills ONLY the
        uncached tail instead of recomputing from scratch."""
        # pool of 6 blocks = 48 rows; both requests' worst case is 4
        # blocks, so the later one is preempted mid-decode (empirically
        # stable: the re-admit re-attaches 2 full cached blocks)
        eng = _engine(model, slots=2, prompt_buckets=[16, 32],
                      kv_block_size=8, kv_blocks=6)
        pa, pb = _prompt(8, seed=84), _prompt(8, seed=85)
        ra, rb = _ref_greedy(model, pa, 24), _ref_greedy(model, pb, 24)
        try:
            fa = eng.generate(pa, max_tokens=24)
            fb = eng.generate(pb, max_tokens=24)
            assert fa.result(timeout=120)["tokens"] == ra
            assert fb.result(timeout=120)["tokens"] == rb
            s = eng.stats()
            assert s["preempted"] >= 1
            # the re-admit was a cache hit on its own regrown prefix:
            # at least its full prompt block came back from the tree
            assert s["prefix_hits"] >= 1
            assert s["prefix_reused_rows"] >= 8
            # and the re-prefill computed fewer rows than a cold
            # recompute of both requests' full prefixes would have
            cold_rows = 2 * 8 + 8 + s["prefix_reused_rows"]
            assert s["prefill_rows"] < cold_rows
            # nothing leaked: every block is free or cached
            assert (s["kv_blocks_free"] + s["prefix_cached_blocks"]
                    == eng.kv_blocks)
        finally:
            eng.close(10)


class TestPrefixCacheSpeculative:
    def test_spec_with_prefix_sharing_token_identical(self, model):
        """Satellite regression (spec compat): draft+target decode with
        prefix sharing enabled — including a warm request attached to
        cached blocks the draft cache knows nothing about — must stay
        token-identical to the plain greedy reference. The target's
        verify pass is authoritative, so stale draft KV for reused rows
        can cost acceptance but never change tokens."""
        dcfg = dataclasses.replace(CFG, num_layers=1)
        draft = causal_lm.CausalLM(dcfg, params={
            "embeddings": model.params["embeddings"],
            "layers": model.params["layers"][:1]})
        eng = _engine(model, kv_block_size=8, kv_blocks=16,
                      draft_model=draft, spec_k=3)
        prompt = _prompt(19, seed=140)
        ref = _ref_greedy(model, prompt, 10)
        try:
            cold = eng.generate(prompt, max_tokens=10).result(timeout=60)
            warm = eng.generate(prompt, max_tokens=10).result(timeout=60)
            assert cold["tokens"] == ref
            assert warm["tokens"] == ref
            s = eng.stats()
            assert s["prefix_hits"] == 1      # the warm run reused blocks
            assert s["spec_steps"] > 0        # and speculation really ran
        finally:
            eng.close(10)
