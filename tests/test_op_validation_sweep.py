"""Broad per-op validation sweep through the OpValidation harness:
forward-vs-numpy, gradcheck (where differentiable), and serialization
round-trip for a representative op of every major family — the reference's
OpValidation CI pattern (`OpValidation.java` + per-op TestCases)."""
import numpy as np
import pytest


from deeplearning4j_tpu.autodiff.validation import OpValidation, TestCase


def _r(*shape, seed=0, scale=1.0, positive=False):
    rs = np.random.RandomState(seed)
    a = rs.randn(*shape).astype(np.float32) * scale
    return np.abs(a) + 0.1 if positive else a


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


CASES = [
    # transforms / activations
    TestCase("exp", [_r(3, 4)]).expect_fn(np.exp).grad_check(),
    TestCase("log", [_r(3, 4, positive=True)]).expect_fn(np.log)
        .grad_check(),
    TestCase("sqrt", [_r(8, positive=True)]).expect_fn(np.sqrt).grad_check(),
    TestCase("sigmoid", [_r(4, 4)])
        .expect_fn(lambda x: 1 / (1 + np.exp(-x))).grad_check(),
    TestCase("softplus", [_r(6)])
        .expect_fn(lambda x: np.log1p(np.exp(x))).grad_check(),
    TestCase("relu", [_r(5, 5)]).expect_fn(lambda x: np.maximum(x, 0)),
    TestCase("abs", [_r(7)]).expect_fn(np.abs),
    TestCase("floor", [_r(6, scale=3)]).expect_fn(np.floor),
    TestCase("sign", [_r(6)]).expect_fn(np.sign),
    # pairwise / broadcastable
    TestCase("add", [_r(3, 4), _r(4, seed=1)])
        .expect_fn(lambda a, b: a + b).grad_check(),
    TestCase("multiply", [_r(3, 4), _r(3, 4, seed=2)])
        .expect_fn(lambda a, b: a * b).grad_check(),
    TestCase("maximum", [_r(5), _r(5, seed=3)]).expect_fn(np.maximum),
    TestCase("squaredsubtract", [_r(4), _r(4, seed=4)])
        .expect_fn(lambda a, b: (a - b) ** 2).grad_check(),
    TestCase("floordiv", [_r(5, scale=4), _r(5, seed=5, positive=True)])
        .expect_fn(lambda a, b: np.floor_divide(a, b)),
    # reductions
    TestCase("reduce_sum", [_r(3, 5)], {"dims": (1,)})
        .expect_fn(lambda x: x.sum(axis=1)).grad_check(),
    TestCase("reduce_mean", [_r(3, 5)], {"dims": (0,), "keep_dims": True})
        .expect_fn(lambda x: x.mean(axis=0, keepdims=True)).grad_check(),
    TestCase("reduce_max", [_r(4, 4)], {"dims": (1,)})
        .expect_fn(lambda x: x.max(axis=1)),
    TestCase("reduce_norm2", [_r(6)])
        .expect_fn(lambda x: np.linalg.norm(x)).grad_check(),
    TestCase("reduce_logsumexp", [_r(3, 4)], {"dims": (1,)})
        .expect_fn(lambda x: np.log(np.exp(x).sum(axis=1))).grad_check(),
    TestCase("argmax", [_r(4, 6)], {"dims": 1})
        .expect_fn(lambda x: np.argmax(x, axis=1)),
    TestCase("cumsum", [_r(8)], {"axis": 0})
        .expect_fn(lambda x: np.cumsum(x)).grad_check(),
    # shape
    TestCase("reshape", [_r(3, 4)], {"shape": (4, 3)})
        .expect_fn(lambda x: x.reshape(4, 3)),
    TestCase("transpose", [_r(2, 3, 4)], {"axes": (2, 0, 1)})
        .expect_fn(lambda x: x.transpose(2, 0, 1)).grad_check(),
    TestCase("concat", [_r(2, 3), _r(2, 3, seed=6)], {"axis": 1})
        .expect_fn(lambda a, b: np.concatenate([a, b], axis=1)),
    TestCase("tile", [_r(2, 2)], {"reps": (2, 3)})
        .expect_fn(lambda x: np.tile(x, (2, 3))),
    TestCase("pad", [_r(2, 2)], {"paddings": [(1, 1), (0, 2)]})
        .expect_fn(lambda x: np.pad(x, [(1, 1), (0, 2)])),
    TestCase("squeeze", [_r(2, 1, 3)], {"axis": 1})
        .expect_fn(lambda x: x.squeeze(1)),
    TestCase("gather", [_r(5, 3), np.asarray([0, 2, 4])], {"axis": 0})
        .expect_fn(lambda x, i: x[i]),
    TestCase("reverse", [_r(4, 3)], {"dims": (0,)})
        .expect_fn(lambda x: x[::-1]),
    TestCase("tf_strided_slice", [_r(4, 6)],
             {"spec": [("slice", 1, 3, 1), ("slice", None, None, 2)]})
        .expect_fn(lambda x: x[1:3, ::2]),
    # blas / linalg
    TestCase("matmul", [_r(3, 4), _r(4, 5, seed=7)])
        .expect_fn(lambda a, b: a @ b).grad_check(),
    TestCase("tensormmul", [_r(2, 3, 4), _r(4, 5, seed=8)],
             {"axes_a": (2,), "axes_b": (0,)})
        .expect_fn(lambda a, b: np.tensordot(a, b, axes=((2,), (0,)))),
    TestCase("einsum", [_r(3, 4), _r(3, 4, seed=9)],
             {"equation": "ij,ij->i"})
        .expect_fn(lambda a, b: (a * b).sum(axis=1)).grad_check(),
    # nn
    TestCase("softmax", [_r(4, 5)]).expect_fn(_softmax).grad_check(),
    TestCase("log_softmax", [_r(3, 6)])
        .expect_fn(lambda x: np.log(_softmax(x))).grad_check(),
    TestCase("layer_norm", [_r(4, 8), np.ones(8, np.float32),
                            np.zeros(8, np.float32)])
        .expect_fn(lambda x, g, b:
                   (x - x.mean(-1, keepdims=True)) /
                   np.sqrt(x.var(-1, keepdims=True) + 1e-5)).tol(1e-4)
        .grad_check(),
    TestCase("biasadd", [_r(3, 4), _r(4, seed=10)])
        .expect_fn(lambda x, b: x + b).grad_check(),
    TestCase("l2_loss", [_r(6)])
        .expect_fn(lambda x: (x ** 2).sum() / 2).grad_check(),
    # comparisons / select
    TestCase("greater", [_r(5), _r(5, seed=11)]).expect_fn(np.greater),
    TestCase("select", [np.asarray([True, False, True]),
                        np.asarray([1., 2., 3.], np.float32),
                        np.asarray([9., 8., 7.], np.float32)])
        .expect(np.asarray([1., 8., 3.], np.float32)),
    # segment / scatter
    TestCase("segment_sum", [_r(6), np.asarray([0, 0, 1, 1, 2, 2])],
             {"num_segments": 3})  # static under jit (XLA shape rule)
        .expect_fn(lambda x, s: np.asarray(
            [x[:2].sum(), x[2:4].sum(), x[4:].sum()])),
    TestCase("scatter_upd",
             [np.zeros((4, 2), np.float32), np.asarray([1, 3]),
              np.ones((2, 2), np.float32)])
        .expect(np.asarray([[0, 0], [1, 1], [0, 0], [1, 1]], np.float32)),
    # images
    TestCase("adjust_contrast", [np.asarray(
        [[[[1.0], [3.0]], [[5.0], [7.0]]]], np.float32)], {"factor": 2.0})
        .expect(np.asarray([[[[-2.0], [2.0]], [[6.0], [10.0]]]],
                           np.float32)),
    # compression round-trip is covered elsewhere; updaters aren't
    # differentiable ops — excluded by design.
]


@pytest.mark.parametrize("tc", CASES, ids=lambda tc: tc.op_name)
def test_op_validation_sweep(tc):
    err = OpValidation.validate(tc)
    assert err is None, err


def test_sweep_records_coverage():
    # self-contained: run the sweep here so ordering/xdist can't break it
    for tc in CASES:
        OpValidation.validate(tc)
    rep = OpValidation.coverage_report()
    assert rep["validated"] >= 30
