"""Whole-model import conformance on STOCK architectures (VERDICT r4 #2).

The reference proves import fidelity on complete real networks
(`platform-tests/run-keras-tests.sh`, `TFGraphTestAllSameDiff`), not just
per-op sweeps. These tests build `keras.applications` models with
weights=None (randomly initialized), import the saved h5, and golden-check
the full forward pass — composition bugs (layout chains, fused-BN
patterns, SE blocks, merge ops) that op-level conformance cannot see.
Plus one frozen TF1-style .pb of a non-BERT conv net through the TF path.
"""
import os
import tempfile

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.modelimport import (  # noqa: E402
    import_keras_model_and_weights, import_tf_graph)


def _roundtrip(m, x, name):
    golden = m.predict(x, verbose=0)
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, f"{name}.h5")
        m.save(p)
        net = import_keras_model_and_weights(p)
        res = np.asarray(net.output(x.transpose(0, 3, 1, 2))[0].numpy())
    return res, golden


def _check(res, golden, atol=1e-4):
    np.testing.assert_allclose(res, golden, atol=atol, rtol=1e-4)
    # argmax only means something when the golden's top-2 margin clears
    # the numeric tolerance (random-weight softmax over 1000 classes is
    # near-uniform; sub-tolerance noise can flip the argmax legitimately)
    top2 = np.sort(golden.ravel())[-2:]
    if top2[1] - top2[0] > 2 * atol:
        assert res.argmax() == golden.argmax()


class TestStockArchitectures:
    """One test per architecture so a failure names its network."""

    def test_mobilenet_v2(self):
        m = keras.applications.MobileNetV2(weights=None)
        x = np.random.RandomState(0).rand(1, 224, 224, 3).astype(
            np.float32) * 2 - 1
        _check(*_roundtrip(m, x, "mobilenetv2"))

    def test_resnet50_v2(self):
        m = keras.applications.ResNet50V2(weights=None)
        x = np.random.RandomState(1).rand(1, 224, 224, 3).astype(np.float32)
        _check(*_roundtrip(m, x, "resnet50v2"))

    def test_densenet121(self):
        m = keras.applications.DenseNet121(weights=None)
        x = np.random.RandomState(2).rand(1, 224, 224, 3).astype(np.float32)
        _check(*_roundtrip(m, x, "densenet121"))

    def test_efficientnet_b0(self):
        # exercises Rescaling/Normalization preprocessing + SE blocks
        # (GlobalPool -> Reshape(1,1,C) -> 1x1 convs -> Multiply)
        m = keras.applications.EfficientNetB0(weights=None)
        x = np.random.RandomState(3).rand(1, 224, 224, 3).astype(
            np.float32) * 255
        _check(*_roundtrip(m, x, "efficientnetb0"))

    def test_inception_v3(self):
        m = keras.applications.InceptionV3(weights=None)
        x = np.random.RandomState(4).rand(1, 299, 299, 3).astype(
            np.float32) * 2 - 1
        _check(*_roundtrip(m, x, "inceptionv3"), atol=5e-4)


class TestMergeOpsGolden:
    def test_all_merge_layers_match_keras(self):
        """Subtract/Multiply/Average/Maximum/Minimum merge vertices vs
        keras (the Multiply mapping was broken until EfficientNet's SE
        blocks exercised it)."""
        from keras import layers
        inp = keras.Input((6,))
        a = layers.Dense(5, activation="tanh", name="da")(inp)
        b = layers.Dense(5, activation="sigmoid", name="db")(inp)
        outs = [layers.Subtract(name="sub")([a, b]),
                layers.Multiply(name="mul")([a, b]),
                layers.Average(name="ave")([a, b]),
                layers.Maximum(name="mx")([a, b]),
                layers.Minimum(name="mn")([a, b])]
        m = keras.Model(inp, outs)
        x = np.random.RandomState(5).randn(3, 6).astype(np.float32)
        goldens = m.predict(x, verbose=0)
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "merges.h5")
            m.save(p)
            net = import_keras_model_and_weights(p)
            res = net.output(x)
        for r, g in zip(res, goldens):
            np.testing.assert_allclose(np.asarray(r.numpy()), g, atol=1e-5)


class TestFrozenTF1Graph:
    def test_frozen_conv_net_pb(self):
        """A non-BERT conv net as a frozen TF1-style GraphDef through the
        TF import path (the TFGraphTestAllSameDiff whole-model pattern)."""
        tf = pytest.importorskip("tensorflow")
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2)

        m = keras.Sequential([
            keras.Input((32, 32, 3)),
            keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
            keras.layers.MaxPooling2D(2),
            keras.layers.BatchNormalization(),
            keras.layers.Conv2D(16, 3, padding="valid", activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(10, activation="softmax"),
        ])
        x = np.random.RandomState(6).rand(2, 32, 32, 3).astype(np.float32)
        golden = m.predict(x, verbose=0)

        fn = tf.function(lambda t: m(t, training=False))
        conc = fn.get_concrete_function(
            tf.TensorSpec((2, 32, 32, 3), tf.float32, name="input"))
        frozen = convert_variables_to_constants_v2(conc)
        gd = frozen.graph.as_graph_def()
        out_name = frozen.outputs[0].name.split(":")[0]
        in_name = frozen.inputs[0].name.split(":")[0]

        imp = import_tf_graph(gd.SerializeToString(),
                              input_shapes={in_name: (2, 32, 32, 3)},
                              outputs=[out_name])
        res = imp.output({in_name: x}, [out_name])[out_name].numpy()
        np.testing.assert_allclose(np.asarray(res), golden, atol=1e-4,
                                   rtol=1e-4)
