"""Pallas kernels vs XLA reference implementations (interpret mode on CPU).

VERDICT round-1 item 9: kernels/ was an empty placeholder. These tests run
the exact kernel bodies through the Pallas interpreter.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.kernels import flash_attention


def _ref_attention(q, k, v, mask=None, causal=False):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] != 0, s, -1e30)
    if causal:
        S = q.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class TestFlashAttention:
    def _qkv(self, rs, B=2, S=128, H=2, D=16):
        mk = lambda: jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
        return mk(), mk(), mk()

    def test_matches_reference(self):
        rs = np.random.RandomState(0)
        q, k, v = self._qkv(rs)
        out = flash_attention(q, k, v, tile_q=64, tile_k=64)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_masked(self):
        rs = np.random.RandomState(1)
        q, k, v = self._qkv(rs)
        mask = np.ones((2, 128), np.int32)
        mask[:, 100:] = 0
        out = flash_attention(q, k, v, mask=jnp.asarray(mask),
                              tile_q=64, tile_k=64)
        ref = _ref_attention(q, k, v, mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_causal(self):
        rs = np.random.RandomState(2)
        q, k, v = self._qkv(rs, S=64)
        out = flash_attention(q, k, v, causal=True, tile_q=32, tile_k=32)
        ref = _ref_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_gradients_flow(self):
        rs = np.random.RandomState(3)
        q, k, v = self._qkv(rs, S=64)

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, tile_q=32,
                                           tile_k=32) ** 2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        def ref_loss(q, k, v):
            return jnp.sum(_ref_attention(q, k, v) ** 2)

        rq, rk, rv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=1e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=1e-4)

    def test_masked_gradients_match_reference(self):
        """The Pallas backward (dq/dkv kernels) under a key mask."""
        rs = np.random.RandomState(4)
        q, k, v = self._qkv(rs, S=64)
        mask = np.ones((2, 64), np.int32)
        mask[:, 50:] = 0
        mask = jnp.asarray(mask)

        def loss(fn):
            def f(q, k, v):
                return jnp.sum(fn(q, k, v) ** 2)
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        got = loss(lambda q, k, v: flash_attention(q, k, v, mask=mask,
                                                   tile_q=32, tile_k=32))
        want = loss(lambda q, k, v: _ref_attention(q, k, v, mask=mask))
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-4)

    def test_causal_gradients_match_reference(self):
        rs = np.random.RandomState(5)
        q, k, v = self._qkv(rs, S=64)

        def loss(fn):
            def f(q, k, v):
                return jnp.sum(fn(q, k, v) * jnp.cos(fn(q, k, v)))
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        got = loss(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                   tile_q=32, tile_k=32))
        want = loss(lambda q, k, v: _ref_attention(q, k, v, causal=True))
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-4)

    def test_mismatched_tiles_grad(self):
        """tile_q != tile_k exercises the lcm padding in the backward too."""
        rs = np.random.RandomState(6)
        q, k, v = self._qkv(rs, S=96)

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, tile_q=64,
                                           tile_k=32) ** 2)

        def rf(q, k, v):
            return jnp.sum(_ref_attention(q, k, v) ** 2)

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(rf, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=1e-4)


class TestNonDivisibleShapes:
    """Regression: non-tile-multiple shapes must pad, not silently corrupt."""

    def test_flash_attention_odd_seq_len(self):
        rs = np.random.RandomState(7)
        B, S, H, D = 2, 200, 2, 16   # 200 % 128 != 0
        mk = lambda: jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        out = flash_attention(q, k, v)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_flash_attention_odd_seq_with_mask_and_grad(self):
        rs = np.random.RandomState(8)
        B, S, H, D = 1, 150, 2, 8
        mk = lambda: jnp.asarray(rs.randn(B, S, H, D).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        mask = np.ones((B, S), np.int32)
        mask[:, 120:] = 0
        out = flash_attention(q, k, v, mask=jnp.asarray(mask))
        ref = _ref_attention(q, k, v, mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        g = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, mask=jnp.asarray(mask)) ** 2))(q)
        gr = jax.grad(lambda q: jnp.sum(
            _ref_attention(q, k, v, mask=jnp.asarray(mask)) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), atol=1e-4)

