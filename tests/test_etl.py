"""ETL (DataVec-equivalent) tests: schema, transforms, conditions, filters,
reducers, sequences, readers, serde, analysis — mirrors the reference's
datavec-api test coverage (TransformProcessTest, CSVRecordReaderTest, ...)."""
import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.etl import (
    analyze_local, analyze_quality_local, BooleanNot, BooleanOr,
    CollectionInputSplit, CollectionRecordReader, ColumnCondition, ColumnType,
    ConditionOp, CSVRecordReader, CSVRecordWriter, CSVSequenceRecordReader,
    FileSplit, infer_schema, JacksonLineRecordReader, LineRecordReader,
    LocalTransformExecutor, NullWritableColumnCondition, Reducer, Schema,
    SequenceSchema, StringRegexColumnCondition, StringSplit,
    SVMLightRecordReader, TransformProcess)


def _schema():
    return (Schema.Builder()
            .add_column_string("name")
            .add_column_categorical("city", ["SF", "NYC", "LA"])
            .add_column_integer("age")
            .add_column_double("score")
            .build())


ROWS = [
    ["alice", "SF", 30, 1.5],
    ["bob", "NYC", 40, 2.5],
    ["carol", "LA", 25, 3.5],
    ["dave", "SF", 35, 4.5],
]


class TestSchema:
    def test_builder_and_lookup(self):
        s = _schema()
        assert s.num_columns() == 4
        assert s.column_names() == ["name", "city", "age", "score"]
        assert s.column_type("age") == ColumnType.Integer
        assert s.index_of("score") == 3
        with pytest.raises(KeyError):
            s.index_of("nope")

    def test_json_roundtrip(self):
        s = _schema()
        s2 = Schema.from_json(s.to_json())
        assert s == s2
        assert s2.meta("city").state_names == ["SF", "NYC", "LA"]

    def test_sequence_schema_roundtrip(self):
        s = SequenceSchema.Builder().add_column_double("x").build()
        s2 = Schema.from_json(s.to_json())
        assert isinstance(s2, SequenceSchema)

    def test_infer(self):
        s = infer_schema(ROWS, ["name", "city", "age", "score"])
        assert s.column_type("age") == ColumnType.Integer
        assert s.column_type("score") == ColumnType.Double
        assert s.column_type("name") == ColumnType.String


class TestTransforms:
    def test_remove_and_rename(self):
        tp = (TransformProcess.Builder(_schema())
              .remove_columns("name")
              .rename_column("score", "points")
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        fs = tp.final_schema()
        assert fs.column_names() == ["city", "age", "points"]
        assert out[0] == ["SF", 30, 1.5]

    def test_categorical_to_integer_and_onehot(self):
        tp = (TransformProcess.Builder(_schema())
              .remove_columns("name")
              .categorical_to_integer("city")
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert [r[0] for r in out] == [0, 1, 2, 0]

        tp2 = (TransformProcess.Builder(_schema())
               .remove_columns("name")
               .categorical_to_one_hot("city")
               .build())
        out2 = LocalTransformExecutor.execute(ROWS, tp2)
        assert tp2.final_schema().column_names() == [
            "city[SF]", "city[NYC]", "city[LA]", "age", "score"]
        assert out2[1][:3] == [0, 1, 0]

    def test_math_ops(self):
        tp = (TransformProcess.Builder(_schema())
              .double_math_op("score", "Multiply", 2.0)
              .integer_math_op("age", "Add", 1)
              .double_columns_math_op("sum", "Add", "age", "score")
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert out[0][3] == 3.0            # score*2
        assert out[0][2] == 31             # age+1
        assert isinstance(out[0][2], int)  # integer column stays integral
        assert out[0][4] == 34.0           # (age+1) + score*2

    def test_math_function(self):
        tp = (TransformProcess.Builder(_schema())
              .double_math_function("score", "LOG")
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert out[0][3] == pytest.approx(np.log(1.5))

    def test_conditional_replace(self):
        cond = ColumnCondition("age", ConditionOp.GreaterOrEqual, 35)
        tp = (TransformProcess.Builder(_schema())
              .conditional_replace_value_transform("age", 0, cond)
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert [r[2] for r in out] == [30, 0, 25, 0]

    def test_filter(self):
        tp = (TransformProcess.Builder(_schema())
              .filter(ColumnCondition("city", ConditionOp.Equal, "SF"))
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert [r[0] for r in out] == ["bob", "carol"]

    def test_filter_in_set_and_combinators(self):
        cond = (ColumnCondition("city", ConditionOp.InSet,
                                value_set=["SF", "LA"])
                | ColumnCondition("age", ConditionOp.GreaterThan, 38))
        tp = TransformProcess.Builder(_schema()).filter(cond).build()
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert len(out) == 0  # every row matches one of the two

        cond2 = BooleanNot(ColumnCondition("city", ConditionOp.Equal, "SF"))
        tp2 = TransformProcess.Builder(_schema()).filter(cond2).build()
        out2 = LocalTransformExecutor.execute(ROWS, tp2)
        assert [r[0] for r in out2] == ["alice", "dave"]

    def test_string_ops(self):
        tp = (TransformProcess.Builder(_schema())
              .append_string_column_transform("name", "_x")
              .change_case("name", "UPPER")
              .concatenate_string_columns("full", "-", "name", "city")
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        assert out[0][0] == "ALICE_X"
        assert out[0][4] == "ALICE_X-SF"

    def test_replace_empty_and_quality(self):
        rows = [["a", "SF", None, 1.0], ["b", "NYC", 20, None]]
        schema = _schema()
        q = analyze_quality_local(schema, rows)
        assert q.quality_for("age").missing == 1
        tp = (TransformProcess.Builder(schema)
              .replace_empty_with_value("age", -1)
              .build())
        out = LocalTransformExecutor.execute(rows, tp)
        assert out[0][2] == -1

    def test_time_ops(self):
        schema = Schema.Builder().add_column_string("ts").build()
        tp = (TransformProcess.Builder(schema)
              .string_to_time("ts", "%Y-%m-%d %H:%M:%S")
              .derive_columns_from_time("ts", ["YEAR", "HOUR"])
              .build())
        out = LocalTransformExecutor.execute(
            [["2024-06-15 13:45:00"]], tp)
        assert out[0][1] == 2024
        assert out[0][2] == 13
        assert tp.final_schema().column_names() == ["ts", "ts_year",
                                                    "ts_hour"]

    def test_reducer(self):
        r = Reducer(key_columns=["city"],
                    ops={"age": "Mean", "score": "Sum"})
        tp = (TransformProcess.Builder(_schema())
              .remove_columns("name")
              .reduce(r)
              .build())
        out = LocalTransformExecutor.execute(ROWS, tp)
        fs = tp.final_schema()
        assert fs.column_names() == ["city", "mean(age)", "sum(score)"]
        sf = next(r for r in out if r[0] == "SF")
        assert sf[1] == pytest.approx(32.5)
        assert sf[2] == pytest.approx(6.0)

    def test_convert_to_sequence_and_offset(self):
        schema = (Schema.Builder().add_column_string("key")
                  .add_column_integer("t").add_column_double("v").build())
        rows = [["a", 2, 2.0], ["a", 1, 1.0], ["b", 1, 5.0], ["a", 3, 3.0],
                ["b", 2, 6.0]]
        from deeplearning4j_tpu.etl.transforms import (
            SequenceDifferenceTransform)
        tp = (TransformProcess.Builder(schema)
              .convert_to_sequence("key", order_column="t")
              .transform(SequenceDifferenceTransform("v"))
              .build())
        out = LocalTransformExecutor.execute(rows, tp)
        assert len(out) == 2   # two sequences
        a = out[0]
        assert [r[2] for r in a] == [0, 1.0, 1.0]  # diffs after sort by t

    def test_tp_json_roundtrip(self):
        cond = ColumnCondition("age", ConditionOp.LessThan, 30)
        tp = (TransformProcess.Builder(_schema())
              .remove_columns("name")
              .categorical_to_integer("city")
              .double_math_op("score", "Add", 10.0)
              .filter(cond)
              .build())
        tp2 = TransformProcess.from_json(tp.to_json())
        out1 = LocalTransformExecutor.execute(ROWS, tp2)
        out2 = LocalTransformExecutor.execute(ROWS, tp)
        assert out1 == out2
        assert tp2.final_schema() == tp.final_schema()


class TestReaders:
    def test_csv_reader_string_split(self):
        rr = CSVRecordReader().initialize(
            StringSplit("1,2.5,foo\n4,5.5,bar\n"))
        recs = list(rr)
        assert recs == [["1", "2.5", "foo"], ["4", "5.5", "bar"]]

    def test_csv_reader_file(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("h1,h2\n1,2\n3,4\n")
        rr = CSVRecordReader(skip_num_lines=1).initialize(
            FileSplit(str(p)))
        assert list(rr) == [["1", "2"], ["3", "4"]]
        rr.reset()
        rec, meta = rr.next_with_meta()
        assert rec == ["1", "2"]
        assert meta.uri.endswith("data.csv")

    def test_line_reader(self):
        rr = LineRecordReader().initialize(StringSplit("a\nb\nc"))
        assert list(rr) == [["a"], ["b"], ["c"]]

    def test_collection_reader(self):
        rr = CollectionRecordReader(ROWS).initialize()
        assert len(list(rr)) == 4

    def test_jackson_line_reader(self):
        data = '{"a": 1, "b": "x"}\n{"a": 2, "b": "y"}\n'
        rr = JacksonLineRecordReader(["b", "a"]).initialize(
            StringSplit(data))
        assert list(rr) == [["x", 1], ["y", 2]]

    def test_svmlight_reader(self):
        rr = SVMLightRecordReader(num_features=4).initialize(
            StringSplit("1 1:0.5 3:2.0\n0 2:1.5\n"))
        recs = list(rr)
        assert recs[0] == [0.5, 0.0, 2.0, 0.0, 1.0]
        assert recs[1] == [0.0, 1.5, 0.0, 0.0, 0.0]

    def test_csv_sequence_reader(self, tmp_path):
        for i, content in enumerate(["1,10\n2,20\n", "3,30\n"]):
            (tmp_path / f"seq_{i}.csv").write_text(content)
        rr = CSVSequenceRecordReader().initialize(
            FileSplit(str(tmp_path), allowed_extensions=["csv"]))
        seqs = list(rr)
        assert len(seqs) == 2
        assert seqs[0] == [["1", "10"], ["2", "20"]]

    def test_csv_writer_roundtrip(self, tmp_path):
        p = str(tmp_path / "out.csv")
        with CSVRecordWriter(p) as w:
            w.write_all([["a", 1], ["b", 2]])
        rr = CSVRecordReader().initialize(FileSplit(p))
        assert list(rr) == [["a", "1"], ["b", "2"]]

    def test_file_split_filters_and_shuffles(self, tmp_path):
        for n in ["x.csv", "y.csv", "z.txt"]:
            (tmp_path / n).write_text("1\n")
        fs = FileSplit(str(tmp_path), allowed_extensions=["csv"])
        assert len(fs.locations()) == 2
        fs2 = FileSplit(str(tmp_path), allowed_extensions=["csv"],
                        rng_seed=1)
        assert sorted(fs2.locations()) == sorted(fs.locations())


class TestAnalysis:
    def test_analyze_local(self):
        a = analyze_local(_schema(), ROWS)
        age = a.analysis_for("age")
        assert age.min == 25 and age.max == 40
        assert age.mean == pytest.approx(32.5)
        city = a.analysis_for("city")
        assert city.state_counts == {"SF": 2, "NYC": 1, "LA": 1}

    def test_schema_typed_pipeline_from_csv(self):
        """Full pipeline: CSV strings → typed → filtered → vectorized."""
        csv = "name,city,age,score\nalice,SF,30,1.5\nbob,NYC,40,2.5\n"
        rr = CSVRecordReader(skip_num_lines=1).initialize(StringSplit(csv))
        tp = (TransformProcess.Builder(_schema())
              .convert_to_integer("age")
              .convert_to_double("score")
              .remove_columns("name")
              .categorical_to_integer("city")
              .build())
        out = LocalTransformExecutor.execute(list(rr), tp)
        assert out == [[0, 30, 1.5], [1, 40, 2.5]]


class TestJoin:
    """Reference transform/join/Join.java behavior."""

    def _schemas(self):
        from deeplearning4j_tpu.etl.schema import Schema
        left = (Schema.Builder().add_column_integer("id")
                .add_column_string("name").build())
        right = (Schema.Builder().add_column_integer("id")
                 .add_column_double("score").build())
        return left, right

    def test_inner_join(self):
        from deeplearning4j_tpu.etl.join import Join, JoinType
        left_s, right_s = self._schemas()
        join = (Join.builder(JoinType.INNER)
                .set_join_columns("id")
                .set_schemas(left_s, right_s).build())
        out = join.execute([[1, "a"], [2, "b"], [3, "c"]],
                           [[2, 0.5], [3, 0.7], [4, 0.9]])
        assert out == [[2, "b", 0.5], [3, "c", 0.7]]
        assert join.output_schema().column_names() == ["id", "name", "score"]

    def test_left_and_full_outer(self):
        from deeplearning4j_tpu.etl.join import Join, JoinType
        left_s, right_s = self._schemas()
        left_rows = [[1, "a"], [2, "b"]]
        right_rows = [[2, 0.5], [9, 0.9]]
        lo = (Join.builder(JoinType.LEFT_OUTER).set_join_columns("id")
              .set_schemas(left_s, right_s).build()).execute(left_rows,
                                                             right_rows)
        assert lo == [[1, "a", None], [2, "b", 0.5]]
        fo = (Join.builder(JoinType.FULL_OUTER).set_join_columns("id")
              .set_schemas(left_s, right_s).build()).execute(left_rows,
                                                             right_rows)
        assert [1, "a", None] in fo and [2, "b", 0.5] in fo \
            and [9, None, 0.9] in fo

    def test_name_collision_prefixed(self):
        from deeplearning4j_tpu.etl.join import Join, JoinType
        from deeplearning4j_tpu.etl.schema import Schema
        left_s = (Schema.Builder().add_column_integer("id")
                  .add_column_double("v").build())
        right_s = (Schema.Builder().add_column_integer("id")
                   .add_column_double("v").build())
        join = (Join.builder(JoinType.INNER).set_join_columns("id")
                .set_schemas(left_s, right_s).build())
        assert join.output_schema().column_names() == ["id", "v", "right_v"]
