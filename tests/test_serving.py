"""Production serving subsystem (serving/ + the engine lifecycle hooks).

Covers the acceptance contract of the serving PR: deploy -> serve over
HTTP -> hot-swap with zero failed in-flight requests -> rollback;
admission shedding under synthetic overload (429 + retry-after, bounded
queue); deadline expiry before dispatch; /readyz flipping only after
warmup; SIGTERM graceful drain saving warmup manifests; and the
InferenceEngine drain()/close()/deadline satellites.
"""
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.common.environment import environment
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.runtime import compile_cache
from deeplearning4j_tpu.runtime.inference import (EngineClosedError,
                                                  InferenceEngine)
from deeplearning4j_tpu.serving import (AdmissionController,
                                        DeadlineExceededError,
                                        GracefulLifecycle, ModelRegistry,
                                        ModelServer, ShedError)

N_IN, N_OUT = 6, 3


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=4, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _get(url, timeout=10):
    """(status, headers, parsed-or-raw body) without raising on 4xx/5xx."""
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        body = r.read()
        return r.status, r.headers, body
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _post(url, data, content_type="application/json", timeout=30,
          headers=()):
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": content_type,
                                          **dict(headers)})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


# ---------------------------------------------------------------------------
# InferenceEngine drain/close/deadline satellites
# ---------------------------------------------------------------------------

class TestEngineDrainClose:
    def test_drain_flushes_queued_requests(self):
        eng = InferenceEngine(_mlp(), max_batch=8, max_delay_ms=50.0)
        futs = [eng.submit(_x(2, seed=i)) for i in range(3)]
        assert eng.drain(timeout_s=30)
        for f in futs:
            out = f.result(timeout=5)  # resolved, not dropped
            assert np.asarray(out.jax()).shape == (2, N_OUT)

    def test_submit_after_drain_raises(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.drain()
        with pytest.raises(EngineClosedError, match="draining"):
            eng.submit(_x())

    def test_submit_after_close_raises(self):
        # the regression the satellite asks for: a late submit must fail
        # with a clear error, not hang on a dead batcher thread
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.submit(_x()).result(timeout=10)
        eng.close()
        with pytest.raises(EngineClosedError, match="closed"):
            eng.submit(_x())

    def test_infer_after_close_raises(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.close()
        with pytest.raises(EngineClosedError):
            eng.infer(_x())

    def test_drain_and_close_are_idempotent(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        assert eng.drain()
        assert eng.drain()
        assert eng.close()
        assert eng.close()
        assert eng.closed

    def test_start_reverses_drain_but_not_close(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.drain()
        assert eng.draining
        eng.start()  # a rollback re-admits a parked engine
        out = eng.submit(_x()).result(timeout=10)
        assert np.asarray(out.jax()).shape == (4, N_OUT)
        eng.close()
        with pytest.raises(EngineClosedError, match="cannot be restarted"):
            eng.start()

    def test_context_manager_still_works(self):
        with InferenceEngine(_mlp(), max_batch=8) as eng:
            assert eng.submit(_x()).result(timeout=10) is not None
        # stop() (not close): the engine stays usable
        assert eng.submit(_x()).result(timeout=10) is not None


class TestEngineDeadline:
    def test_expired_request_resolves_with_timeout_error(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        fut = eng.submit(_x(), timeout_s=0.0)  # already expired at pop
        with pytest.raises(TimeoutError):
            fut.result(timeout=10)

    def test_unexpired_request_serves_normally(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        out = eng.submit(_x(), timeout_s=30.0).result(timeout=10)
        assert np.asarray(out.jax()).shape == (4, N_OUT)

    def test_expired_does_not_poison_live_requests(self):
        eng = InferenceEngine(_mlp(), max_batch=8, max_delay_ms=20.0)
        dead = eng.submit(_x(2, seed=1), timeout_s=0.0)
        live = eng.submit(_x(2, seed=2), timeout_s=30.0)
        out = live.result(timeout=10)
        assert np.asarray(out.jax()).shape == (2, N_OUT)
        with pytest.raises(TimeoutError):
            dead.result(timeout=10)

    def test_expiry_counted_in_metrics(self):
        reg = environment().metrics()
        fam = reg.counter("dl4j_inference_deadline_expired_total")
        before = fam.value()
        eng = InferenceEngine(_mlp(), max_batch=8)
        with pytest.raises(TimeoutError):
            eng.submit(_x(), timeout_s=0.0).result(timeout=10)
        assert fam.value() >= before + 1


class TestEngineManifestHandoff:
    def test_observed_entries_round_trip(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.infer(_x(3))
        entries = eng.observed_entries()
        assert entries and entries[0]["buckets"] == [4]  # 3 -> bucket 4
        eng2 = InferenceEngine(_mlp(1), max_batch=8)
        warmed = eng2.warmup(entries=entries)
        assert warmed == [4]
        assert len(eng2._warmed) == 1


# ---------------------------------------------------------------------------
# ModelRegistry: deploy / hot swap / rollback
# ---------------------------------------------------------------------------

class TestModelRegistry:
    def test_deploy_and_predict(self):
        reg = ModelRegistry(manifest_dir=None)
        mv = reg.deploy("m", "v1", _mlp(), example=_x())
        assert mv.state == "ready"
        out = reg.predict("m", _x())
        np.testing.assert_allclose(np.asarray(out.jax()),
                                   np.asarray(_mlp().output(_x()).jax()),
                                   rtol=1e-5)

    def test_deploy_warms_before_cutover(self):
        reg = ModelRegistry(manifest_dir=None)
        mv = reg.deploy("m", "v1", _mlp(), example=_x())
        # the ladder compiled before any traffic: warmup keys recorded
        assert len(mv.engine._warmed) == len(mv.engine.ladder)

    def test_duplicate_version_rejected(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(), example=_x())
        with pytest.raises(ValueError, match="already"):
            reg.deploy("m", "v1", _mlp(1))

    def test_unknown_model_and_version_raise_keyerror(self):
        reg = ModelRegistry(manifest_dir=None)
        with pytest.raises(KeyError):
            reg.get("nope")
        reg.deploy("m", "v1", _mlp(), example=_x())
        with pytest.raises(KeyError):
            reg.get("m", "v9")

    def test_hot_swap_repoints_and_drains_old(self):
        reg = ModelRegistry(manifest_dir=None)
        v1 = reg.deploy("m", "v1", _mlp(0), example=_x())
        v2 = reg.deploy("m", "v2", _mlp(1), example=_x())
        assert reg.get("m") is v2
        assert v1.state == "retired"
        assert v1.engine.draining and not v1.engine.closed  # parked warm
        out = reg.predict("m", _x())
        np.testing.assert_allclose(
            np.asarray(out.jax()),
            np.asarray(_mlp(1).output(_x()).jax()), rtol=1e-5)

    def test_swap_warms_incoming_from_outgoing_traffic(self):
        # no example given on the v2 deploy: its engine warms from the
        # shapes v1 actually served (the in-process manifest handoff)
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(0), example=None, warm=False)
        reg.warm("m")  # nothing to warm: flips ready with no sources
        reg.predict("m", _x(3))
        reg.predict("m", _x(7))
        v2 = reg.deploy("m", "v2", _mlp(1))
        warmed_buckets = {b for b, _ in v2.engine._warmed}
        assert warmed_buckets == {4, 8}  # 3 -> 4, 7 -> 8

    def test_rollback_repoints_to_previous(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(0), example=_x())
        reg.deploy("m", "v2", _mlp(1), example=_x())
        back = reg.rollback("m")
        assert back.version == "v1"
        assert reg.get("m").version == "v1"
        out = reg.predict("m", _x())  # v1 engine re-admitted instantly
        np.testing.assert_allclose(
            np.asarray(out.jax()),
            np.asarray(_mlp(0).output(_x()).jax()), rtol=1e-5)

    def test_rollback_without_previous_raises(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(), example=_x())
        with pytest.raises(RuntimeError, match="no retained version"):
            reg.rollback("m")

    def test_retention_cap_closes_oldest(self):
        reg = ModelRegistry(manifest_dir=None, retain=1)
        v1 = reg.deploy("m", "v1", _mlp(0), example=_x())
        reg.deploy("m", "v2", _mlp(1), example=_x())
        reg.deploy("m", "v3", _mlp(2), example=_x())
        assert v1.engine.closed  # evicted beyond retain=1
        with pytest.raises(KeyError):
            reg.get("m", "v1")
        assert reg.get("m", "v2") is not None  # retained for rollback

    def test_pinned_version_predict(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(0), example=_x())
        reg.deploy("m", "v2", _mlp(1), example=_x())
        reg.rollback("m")  # v2 parked again, v1 current
        out = reg.predict("m", _x())
        np.testing.assert_allclose(
            np.asarray(out.jax()),
            np.asarray(_mlp(0).output(_x()).jax()), rtol=1e-5)
        # pinning a retired version surfaces the closed error
        with pytest.raises(EngineClosedError):
            reg.predict("m", _x(), version="v2")

    def test_hot_swap_zero_failed_inflight(self):
        """The acceptance bar: deploy + rollback under concurrent traffic
        with not one failed request."""
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(0), example=_x())
        errors, done = [], threading.Event()

        def client(seed):
            x = _x(2, seed=seed)
            while not done.is_set():
                try:
                    out = reg.predict("m", x)
                    assert np.asarray(out.jax()).shape == (2, N_OUT)
                except Exception as e:  # noqa: BLE001 - the test IS this
                    errors.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        reg.deploy("m", "v2", _mlp(1))
        time.sleep(0.1)
        reg.rollback("m")
        time.sleep(0.1)
        done.set()
        for t in threads:
            t.join()
        assert errors == []

    def test_models_listing(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("a", "v1", _mlp(), example=_x())
        reg.deploy("a", "v2", _mlp(1), example=_x())
        listing = reg.models()
        assert listing["a"]["current"] == "v2"
        assert [v["version"] for v in listing["a"]["versions"]] == \
            ["v1", "v2"]
        assert listing["a"]["versions"][1]["state"] == "ready"

    def test_manifest_saved_and_replayed_across_registries(self, tmp_path):
        d = str(tmp_path)
        reg = ModelRegistry(manifest_dir=d)
        reg.deploy("m", "v1", _mlp(), warm=False)
        reg.warm("m")
        reg.predict("m", _x(5))  # observed: bucket 8
        reg.drain_all()
        assert os.path.exists(os.path.join(d, "m.warmup.json"))
        # the "next replica": same manifest dir, fresh registry/model
        reg2 = ModelRegistry(manifest_dir=d)
        mv = reg2.deploy("m", "v1", _mlp(1))  # no example, no outgoing
        assert {b for b, _ in mv.engine._warmed} == {8}


# ---------------------------------------------------------------------------
# AdmissionController
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_admits_within_capacity(self):
        ctrl = AdmissionController("m", max_concurrent=2, queue_depth=4,
                                   high_water=3)
        assert ctrl.run(lambda: 42) == 42

    def test_sheds_past_high_water(self):
        ctrl = AdmissionController("m", max_concurrent=1, queue_depth=4,
                                   high_water=1, default_timeout_s=None)
        release = threading.Event()
        started = threading.Event()

        def hog():
            with ctrl.admit():
                started.set()
                release.wait(10)

        def waiter():
            with ctrl.admit():
                pass

        t1 = threading.Thread(target=hog)
        t1.start()
        started.wait(5)
        t2 = threading.Thread(target=waiter)
        t2.start()
        for _ in range(100):  # until t2 is queued
            if ctrl.depth() >= 1:
                break
            time.sleep(0.01)
        with pytest.raises(ShedError) as ei:
            ctrl.admit()
        assert ei.value.retry_after_s > 0
        release.set()
        t1.join()
        t2.join()

    def test_shed_happens_before_dispatch(self):
        ctrl = AdmissionController("m", max_concurrent=1, queue_depth=1,
                                   high_water=1, default_timeout_s=None)
        calls = []
        hold = threading.Event()
        go = threading.Event()

        def hog():
            ctrl.run(lambda: (go.set(), hold.wait(10)))

        t = threading.Thread(target=hog)
        t.start()
        go.wait(5)
        waiter = threading.Thread(
            target=lambda: ctrl.run(lambda: calls.append("late")))
        waiter.start()
        for _ in range(100):
            if ctrl.depth() >= 1:
                break
            time.sleep(0.01)
        with pytest.raises(ShedError):
            ctrl.run(lambda: calls.append("shed"))  # fn must NOT run
        assert "shed" not in calls
        hold.set()
        t.join()
        waiter.join()
        assert calls == ["late"]

    def test_deadline_expires_while_waiting(self):
        ctrl = AdmissionController("m", max_concurrent=1, queue_depth=8,
                                   high_water=8)
        hold = threading.Event()
        go = threading.Event()
        t = threading.Thread(
            target=lambda: ctrl.run(lambda: (go.set(), hold.wait(10))))
        t.start()
        go.wait(5)
        calls = []
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceededError):
            ctrl.run(lambda: calls.append("ran"), timeout_s=0.05)
        assert time.monotonic() - t0 < 5  # expired on budget, not later
        assert calls == []  # shed before dispatch, never after
        hold.set()
        t.join()

    def test_fifo_fairness_no_barging(self):
        """A releaser immediately re-arriving must queue behind the
        waiter, not starve it (the tail the serving_overload p99 gate
        measures)."""
        ctrl = AdmissionController("m", max_concurrent=1, queue_depth=8,
                                   high_water=8, default_timeout_s=None)
        order = []
        lock = threading.Lock()

        def client(name, n):
            for i in range(n):
                with ctrl.admit():
                    with lock:
                        order.append(name)
                    time.sleep(0.002)

        threads = [threading.Thread(target=client, args=(i, 10))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # fair interleaving: no client runs many turns back-to-back while
        # others wait (with barging, runs of 10 were routine)
        longest_run, run = 1, 1
        for a, b in zip(order, order[1:]):
            run = run + 1 if a == b else 1
            longest_run = max(longest_run, run)
        assert longest_run <= 3, order

    def test_close_sheds_waiters_and_new_arrivals(self):
        ctrl = AdmissionController("m", max_concurrent=1, queue_depth=8,
                                   high_water=8, default_timeout_s=None)
        hold = threading.Event()
        go = threading.Event()
        results = []

        def hog():
            with ctrl.admit():
                go.set()
                hold.wait(10)

        def waiter():
            try:
                with ctrl.admit():
                    results.append("ran")
            except ShedError:
                results.append("shed")

        t1 = threading.Thread(target=hog)
        t1.start()
        go.wait(5)
        t2 = threading.Thread(target=waiter)
        t2.start()
        for _ in range(100):
            if ctrl.depth() >= 1:
                break
            time.sleep(0.01)
        ctrl.close()
        t2.join(5)
        assert results == ["shed"]
        with pytest.raises(ShedError, match="draining"):
            ctrl.admit()
        hold.set()
        t1.join()

    def test_metrics_labeled_per_model_and_version(self):
        reg = environment().metrics()
        ctrl = AdmissionController("labeled-model", max_concurrent=2,
                                   queue_depth=4, high_water=3)
        ctrl.run(lambda: None, version="v7")
        fam = reg.get("dl4j_serving_requests_total")
        series = {key for key, _ in fam.children()}
        assert ("labeled-model", "v7", "ok") in series
        lat = reg.get("dl4j_serving_queue_seconds")
        assert ("labeled-model", "v7") in {k for k, _ in lat.children()}


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------

@pytest.fixture
def served():
    reg = ModelRegistry(manifest_dir=None)
    reg.deploy("mlp", "v1", _mlp(0), example=_x())
    server = ModelServer(reg)
    port = server.start()
    yield reg, server, f"http://127.0.0.1:{port}"
    server.stop()
    reg.drain_all(save_manifests=False)


class TestModelServer:
    def test_predict_json(self, served):
        reg, server, base = served
        code, headers, body = _post(
            base + "/v1/models/mlp/predict",
            json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 200
        assert headers["Content-Length"] == str(len(body))
        doc = json.loads(body)
        assert doc["model"] == "mlp" and doc["version"] == "v1"
        np.testing.assert_allclose(
            np.asarray(doc["outputs"], np.float32),
            np.asarray(_mlp(0).output(_x()).jax()), rtol=1e-4)

    def test_predict_pinned_version(self, served):
        reg, server, base = served
        reg.deploy("mlp", "v2", _mlp(1), example=_x())
        code, _, body = _post(
            base + "/v1/models/mlp:v2/predict",
            json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 200
        assert json.loads(body)["version"] == "v2"

    def test_predict_pinned_retired_version_409(self, served):
        # a parked (drained-for-rollback) version refuses pinned traffic
        # with 409, not a 500 + stack trace
        reg, server, base = served
        reg.deploy("mlp", "v2", _mlp(1), example=_x())
        code, _, body = _post(
            base + "/v1/models/mlp:v1/predict",
            json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 409
        assert "error" in json.loads(body)

    def test_predict_npy_roundtrip(self, served):
        import io
        reg, server, base = served
        buf = io.BytesIO()
        np.save(buf, _x())
        code, headers, body = _post(base + "/v1/models/mlp/predict",
                                    buf.getvalue(), "application/x-npy")
        assert code == 200
        assert headers["Content-Type"] == "application/x-npy"
        assert headers["X-Model-Version"] == "v1"
        out = np.load(io.BytesIO(body))
        assert out.shape == (4, N_OUT)

    def test_unknown_model_404(self, served):
        _, _, base = served
        code, _, body = _post(base + "/v1/models/nope/predict",
                              json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 404
        assert "error" in json.loads(body)
        code, _, _ = _post(base + "/v1/models/mlp:v9/predict",
                           json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 404

    def test_bad_payload_400(self, served):
        _, _, base = served
        code, _, _ = _post(base + "/v1/models/mlp/predict",
                           json.dumps({"wrong": 1}).encode())
        assert code == 400

    def test_models_listing(self, served):
        _, _, base = served
        code, _, body = _get(base + "/v1/models")
        assert code == 200
        doc = json.loads(body)
        assert doc["models"]["mlp"]["current"] == "v1"

    def test_healthz_always_ok(self, served):
        _, _, base = served
        code, _, body = _get(base + "/healthz")
        assert code == 200 and body == b"ok"

    def test_readyz_flips_only_after_warmup(self):
        reg = ModelRegistry(manifest_dir=None)
        server = ModelServer(reg)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            reg.deploy("cold", "v1", _mlp(), warm=False)
            code, _, body = _get(base + "/readyz")
            assert code == 503
            assert json.loads(body)["ready"] is False
            reg.warm("cold", example=_x())
            code, _, body = _get(base + "/readyz")
            assert code == 200
            assert json.loads(body)["ready"] is True
        finally:
            server.stop()
            reg.drain_all(save_manifests=False)

    def test_metrics_endpoints_shared_with_ui(self, served):
        _, _, base = served
        code, headers, body = _get(base + "/metrics")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert b"dl4j_serving_requests_total" in body
        code, _, body = _get(base + "/metrics.json")
        assert code == 200
        assert "dl4j_inference_requests_total" in json.loads(body)

    def test_overload_returns_429_with_retry_after(self, served):
        reg, server, base = served
        ctrl = AdmissionController("mlp", max_concurrent=1, queue_depth=1,
                                   high_water=0, default_timeout_s=None)
        server.set_admission("mlp", ctrl)
        permit = ctrl.admit()  # hold the only slot; high_water=0 -> shed
        try:
            code, headers, body = _post(
                base + "/v1/models/mlp/predict",
                json.dumps({"inputs": _x().tolist()}).encode())
            assert code == 429
            assert int(headers["Retry-After"]) >= 1
            assert json.loads(body)["retry_after_s"] > 0
        finally:
            permit.__exit__(None, None, None)

    def test_deadline_expiry_returns_504(self, served):
        reg, server, base = served
        ctrl = AdmissionController("mlp", max_concurrent=1, queue_depth=8,
                                   high_water=8, default_timeout_s=None)
        server.set_admission("mlp", ctrl)
        permit = ctrl.admit()  # saturate so the request waits
        try:
            code, _, body = _post(
                base + "/v1/models/mlp/predict",
                json.dumps({"inputs": _x().tolist(),
                            "timeout_s": 0.05}).encode())
            assert code == 504
            assert "deadline" in json.loads(body)["error"]
        finally:
            permit.__exit__(None, None, None)

    def test_unknown_path_404(self, served):
        _, _, base = served
        code, _, _ = _get(base + "/v1/nope")
        assert code == 404


class TestClientDisconnects:
    def test_broken_pipe_suppressed_without_traceback(self, served,
                                                      capsys):
        reg, server, base = served
        httpd = server._httpd
        before = httpd.client_disconnects
        try:
            raise BrokenPipeError("peer went away")
        except BrokenPipeError:
            httpd.handle_error(None, ("127.0.0.1", 12345))
        assert httpd.client_disconnects == before + 1
        assert capsys.readouterr().err == ""  # no stack trace in logs

    def test_real_errors_still_reported(self, served, capsys):
        _, server, _ = served
        try:
            raise ValueError("an actual bug")
        except ValueError:
            server._httpd.handle_error(None, ("127.0.0.1", 12345))
        assert "ValueError" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Graceful lifecycle (SIGTERM drain)
# ---------------------------------------------------------------------------

class TestGracefulLifecycle:
    def test_sigterm_drains_and_saves_manifest(self, tmp_path):
        d = str(tmp_path)
        reg = ModelRegistry(manifest_dir=d)
        reg.deploy("m", "v1", _mlp(), example=_x())
        reg.predict("m", _x(5))
        server = ModelServer(reg)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        lc = GracefulLifecycle(reg, server, drain_timeout_s=10)
        lc.install()
        try:
            signal.raise_signal(signal.SIGTERM)
            assert lc.wait_drained(30)
            # manifest for the next replica
            path = os.path.join(d, "m.warmup.json")
            assert os.path.exists(path)
            doc = json.load(open(path))
            assert doc["entries"]  # the observed shapes were persisted
            # engines drained: late work fails fast
            with pytest.raises(EngineClosedError):
                reg.predict("m", _x())
            assert not reg.ready()
            # http socket closed last
            with pytest.raises(urllib.error.URLError):
                urllib.request.urlopen(base + "/healthz", timeout=2)
        finally:
            lc.uninstall()

    def test_drain_is_idempotent(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(), example=_x())
        lc = GracefulLifecycle(reg, server=None, drain_timeout_s=10)
        assert lc.drain()
        assert lc.drain()  # second call waits on the first, no explosion
        assert lc.drained

    def test_uninstall_restores_previous_handler(self):
        prev = signal.getsignal(signal.SIGTERM)
        reg = ModelRegistry(manifest_dir=None)
        lc = GracefulLifecycle(reg).install()
        assert signal.getsignal(signal.SIGTERM) != prev
        lc.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_begin_drain_sheds_http_traffic(self):
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("m", "v1", _mlp(), example=_x())
        server = ModelServer(reg)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            server.begin_drain()
            code, headers, _ = _post(
                base + "/v1/models/m/predict",
                json.dumps({"inputs": _x().tolist()}).encode())
            assert code == 503
            assert "Retry-After" in headers
            code, _, _ = _get(base + "/readyz")
            assert code == 503
        finally:
            server.stop()
            reg.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# Manifest-dir handoff (runtime/compile_cache.py)
# ---------------------------------------------------------------------------

class TestServingManifestDir:
    def test_env_override_wins(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_SERVING_MANIFEST_DIR", str(tmp_path))
        assert compile_cache.serving_manifest_dir() == str(tmp_path)

    def test_defaults_under_cache_dir(self):
        d = compile_cache.serving_manifest_dir()
        cache_dir = environment().cache_dir()
        assert d == os.path.join(cache_dir, "manifests")
        assert os.path.isdir(d)

    def test_disabled_when_cache_disabled(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_CACHE_DIR", "")
        assert compile_cache.serving_manifest_dir() is None
