"""Op descriptors (org/nd4j/ir analog) + ProfileAnalyzer trace comparison."""
import json

import numpy as np

from deeplearning4j_tpu.common.profile_analyzer import (aggregate, compare,
                                                        load_trace)
from deeplearning4j_tpu.ops.descriptors import (all_descriptors, describe,
                                                to_json)


class TestOpDescriptors:
    def test_describe_matmul(self):
        d = describe("matmul")
        assert d.name == "matmul" and d.category == "blas"
        names = [a.name for a in d.args]
        assert names[:2] == ["a", "b"]
        ta = next(a for a in d.args if a.name == "transpose_a")
        assert ta.arg_type == "BOOL" and not ta.required

    def test_all_descriptors_cover_registry(self):
        descs = all_descriptors()
        assert len(descs) > 500
        assert "conv2d" in descs and "scan" in descs

    def test_json_export(self, tmp_path):
        path = str(tmp_path / "ops.json")
        to_json(path)
        data = json.loads(open(path).read())
        assert data["add"]["category"] == "broadcastable" or \
            "category" in data["add"]


def _trace(path, durs):
    events = [{"name": n, "ph": "X", "pid": 0, "tid": 0,
               "ts": i * 1000.0, "dur": d}
              for i, (n, d) in enumerate(durs)]
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


class TestProfileAnalyzer:
    def test_aggregate(self, tmp_path):
        p = str(tmp_path / "a.json")
        _trace(p, [("matmul", 100.0), ("matmul", 300.0), ("softmax", 50.0)])
        agg = aggregate(load_trace(p))
        assert agg["matmul"]["total_us"] == 400.0
        assert agg["matmul"]["count"] == 2
        assert agg["softmax"]["avg_us"] == 50.0

    def test_compare(self, tmp_path):
        pa = str(tmp_path / "a.json")
        pb = str(tmp_path / "b.json")
        _trace(pa, [("matmul", 100.0), ("softmax", 50.0)])
        _trace(pb, [("matmul", 400.0), ("softmax", 55.0), ("new_op", 10.0)])
        rows = compare(pa, pb)
        assert rows[0]["name"] == "matmul"       # largest delta first
        assert rows[0]["ratio"] == 4.0
        names = {r["name"] for r in rows}
        assert "new_op" in names                  # present only in B

    def test_begin_end_events(self, tmp_path):
        p = str(tmp_path / "be.json")
        events = [
            {"name": "step", "ph": "B", "pid": 0, "tid": 1, "ts": 100.0},
            {"name": "step", "ph": "E", "pid": 0, "tid": 1, "ts": 350.0},
        ]
        with open(p, "w") as f:
            json.dump(events, f)   # bare-list flavor
        agg = aggregate(load_trace(p))
        assert agg["step"]["total_us"] == 250.0
