"""TfliteRunner: execute real converter-produced .tflite files, golden-
checked against TF's own tflite Interpreter (the independent runtime).

Reference role: nd4j-tvm / foreign-runtime interop (VERDICT r2 partial #29).
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from deeplearning4j_tpu.interop import TfliteRunner  # noqa: E402


def _convert(model):
    conv = tf.lite.TFLiteConverter.from_keras_model(model)
    return conv.convert()


def _interp_golden(flat, inputs):
    it = tf.lite.Interpreter(model_content=flat)
    in_det = it.get_input_details()
    for d, x in zip(in_det, inputs):
        it.resize_tensor_input(d["index"], x.shape)
    it.allocate_tensors()
    in_det = it.get_input_details()
    for d, x in zip(in_det, inputs):
        it.set_tensor(d["index"], x)
    it.invoke()
    return [it.get_tensor(d["index"]) for d in it.get_output_details()]


def _run_both(model, inputs, atol=1e-5):
    flat = _convert(model)
    golden = _interp_golden(flat, inputs)
    runner = TfliteRunner(flat)
    res = runner.run(list(inputs))
    got = [res[n].numpy() for n in runner.output_names]
    assert len(got) == len(golden)
    for g, w in zip(got, golden):
        np.testing.assert_allclose(g, w, atol=atol, rtol=1e-4)
    return runner


class TestTfliteRunner:
    def test_mlp(self):
        rs = np.random.RandomState(0)
        m = tf.keras.Sequential([
            tf.keras.Input((12,)),
            tf.keras.layers.Dense(16, activation="relu"),
            tf.keras.layers.Dense(4, activation="softmax"),
        ])
        x = rs.randn(3, 12).astype(np.float32)
        runner = _run_both(m, [x])
        assert len(runner.input_names) == 1

    def test_cnn(self):
        rs = np.random.RandomState(1)
        m = tf.keras.Sequential([
            tf.keras.Input((16, 16, 3)),
            tf.keras.layers.Conv2D(8, 3, padding="same",
                                   activation="relu"),
            tf.keras.layers.MaxPooling2D(2),
            tf.keras.layers.DepthwiseConv2D(3, padding="valid"),
            tf.keras.layers.AveragePooling2D(2),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(5),
        ])
        x = rs.randn(2, 16, 16, 3).astype(np.float32)
        _run_both(m, [x], atol=1e-4)

    def test_elementwise_and_concat(self):
        rs = np.random.RandomState(2)
        a = tf.keras.Input((8,))
        b = tf.keras.Input((8,))
        s = tf.keras.layers.Add()([a, b])
        d = tf.keras.layers.Subtract()([a, b])
        m1 = tf.keras.layers.Multiply()([s, d])
        cat = tf.keras.layers.Concatenate()([s, m1])
        out = tf.keras.layers.Activation("tanh")(cat)
        m = tf.keras.Model([a, b], out)
        xs = [rs.randn(2, 8).astype(np.float32) for _ in range(2)]
        _run_both(m, xs)

    def test_mean_and_reshape(self):
        rs = np.random.RandomState(3)
        inp = tf.keras.Input((6, 4))
        r = tf.keras.layers.Reshape((12, 2))(inp)
        g = tf.keras.layers.GlobalAveragePooling1D()(r)
        m = tf.keras.Model(inp, g)
        x = rs.randn(2, 6, 4).astype(np.float32)
        _run_both(m, [x])

    def test_named_dict_inputs_and_missing_raises(self):
        rs = np.random.RandomState(4)
        m = tf.keras.Sequential([
            tf.keras.Input((5,)),
            tf.keras.layers.Dense(2),
        ])
        flat = _convert(m)
        runner = TfliteRunner(flat)
        x = rs.randn(1, 5).astype(np.float32)
        out = runner.run({runner.input_names[0]: x})
        assert out[runner.output_names[0]].numpy().shape == (1, 2)
        with pytest.raises(KeyError, match="missing input"):
            runner.run({"nope": x})

    def test_quantized_rejected(self):
        m = tf.keras.Sequential([
            tf.keras.Input((4,)),
            tf.keras.layers.Dense(2),
        ])
        conv = tf.lite.TFLiteConverter.from_keras_model(m)
        conv.optimizations = [tf.lite.Optimize.DEFAULT]

        def rep():
            for _ in range(4):
                yield [np.random.rand(1, 4).astype(np.float32)]

        conv.representative_dataset = rep
        conv.target_spec.supported_ops = [
            tf.lite.OpsSet.TFLITE_BUILTINS_INT8]
        conv.inference_input_type = tf.uint8
        conv.inference_output_type = tf.uint8
        try:
            flat = conv.convert()
        except Exception:
            pytest.skip("full-int8 conversion unavailable in this TF build")
        with pytest.raises(ValueError, match="quantized"):
            TfliteRunner(flat)


class TestTfliteReviewFixes:
    def test_dense_on_sequence_rank3(self):
        """FULLY_CONNECTED on a rank-3 tensor keeps the leading dims
        (tflite collapses to [-1, in], not [batch, -1])."""
        rs = np.random.RandomState(5)
        m = tf.keras.Sequential([
            tf.keras.Input((4, 6)),
            tf.keras.layers.Dense(3, activation="relu"),
        ])
        x = rs.randn(2, 4, 6).astype(np.float32)
        runner = _run_both(m, [x])
        out = runner.run([x])
        assert out[runner.output_names[0]].numpy().shape == (2, 4, 3)

    def test_dynamic_range_quantized_rejected(self):
        """Weight-only int8 keeps float io; it must still be refused."""
        m = tf.keras.Sequential([
            tf.keras.Input((64,)),
            tf.keras.layers.Dense(64),
        ])
        conv = tf.lite.TFLiteConverter.from_keras_model(m)
        conv.optimizations = [tf.lite.Optimize.DEFAULT]
        flat = conv.convert()
        with pytest.raises(ValueError, match="quantiz"):
            TfliteRunner(flat)
