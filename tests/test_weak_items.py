"""Round-2 weak-item coverage: evaluation breadth, transfer learning,
solvers, workspace shims, environment config (VERDICT weak #8, missing #9,
plus SURVEY §7 workspace/env obligations)."""
import numpy as np
import pytest

from deeplearning4j_tpu.common.environment import (Environment,
                                                   SystemProperties,
                                                   environment)
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.evaluation import (Evaluation,
                                              EvaluationCalibration,
                                              ROCBinary, ROCMultiClass)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.solvers import (LBFGS, ConjugateGradient,
                                           LineGradientDescent)
from deeplearning4j_tpu.nn.transferlearning import (FineTuneConfiguration,
                                                    TransferLearning)
from deeplearning4j_tpu.runtime.workspace import (LayerWorkspaceMgr,
                                                  MemoryWorkspace,
                                                  Nd4jWorkspaceManager,
                                                  WorkspaceConfiguration,
                                                  workspace_manager)


def _net(n_out=4):
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .updater(Adam(learning_rate=1e-2))
            .list()
            .layer(L.DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(L.DenseLayer(n_out=12, activation="tanh"))
            .layer(L.OutputLayer(n_out=n_out, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(rs, b=16, f=8, c=4):
    x = rs.randn(b, f).astype(np.float32)
    y = np.zeros((b, c), np.float32)
    y[np.arange(b), rs.randint(0, c, b)] = 1.0
    return x, y


class TestEvaluationBreadth:
    def test_top_n_accuracy_bounds(self):
        rs = np.random.RandomState(0)
        e = Evaluation(top_n=3)
        y = np.eye(5)[rs.randint(0, 5, 200)]
        p = rs.rand(200, 5)
        p /= p.sum(-1, keepdims=True)
        e.eval(y, p)
        assert e.top_n_accuracy() >= e.accuracy()
        assert 0 <= e.top_n_accuracy() <= 1

    def test_top_n_perfect_when_n_equals_classes(self):
        rs = np.random.RandomState(1)
        e = Evaluation(top_n=5)
        y = np.eye(5)[rs.randint(0, 5, 50)]
        p = rs.rand(50, 5)
        e.eval(y, p)
        assert e.top_n_accuracy() == 1.0

    def test_roc_binary_perfect_classifier(self):
        rb = ROCBinary()
        y = np.asarray([[0, 1], [0, 0], [1, 1], [1, 0]], np.float64)
        p = np.asarray([[0.1, 0.9], [0.2, 0.1], [0.9, 0.8], [0.8, 0.3]])
        rb.eval(y, p)
        assert rb.calculate_auc(0) == pytest.approx(1.0)
        assert rb.num_outputs() == 2

    def test_roc_multiclass(self):
        rs = np.random.RandomState(2)
        rm = ROCMultiClass()
        cls = rs.randint(0, 3, 300)
        y = np.eye(3)[cls]
        # semi-informative scores
        p = np.eye(3)[cls] * 0.5 + rs.rand(300, 3) * 0.5
        rm.eval(y, p)
        assert rm.num_classes() == 3
        assert rm.calculate_average_auc() > 0.7

    def test_calibration_perfectly_calibrated(self):
        rs = np.random.RandomState(3)
        c = EvaluationCalibration(reliability_bins=5)
        p = rs.rand(5000, 1)
        y = (rs.rand(5000, 1) < p).astype(np.float64)
        c.eval(y, p)
        assert c.expected_calibration_error(0) < 0.05
        mean_pred, observed = c.reliability_curve(0)
        np.testing.assert_allclose(mean_pred, observed, atol=0.1)


class TestTransferLearning:
    def test_freeze_and_replace_head(self):
        rs = np.random.RandomState(0)
        src = _net(n_out=4)
        x, y = _xy(rs)
        src.fit(x, y)

        ftc = (FineTuneConfiguration.builder()
               .updater(Sgd(learning_rate=5e-2))
               .build())
        net = (TransferLearning.Builder(src)
               .fine_tune_configuration(ftc)
               .set_feature_extractor(1)     # freeze layers 0..1
               .n_out_replace(2, 7)          # new 7-class head
               .build())
        assert net.layers[2].n_out == 7
        frozen_before = [np.asarray(v) for v in net._params[0].values()]
        y7 = np.zeros((16, 7), np.float32)
        y7[np.arange(16), rs.randint(0, 7, 16)] = 1.0
        net.fit(x, y7)
        net.fit(x, y7)
        # frozen layer params unchanged, head trained
        for before, (k, after) in zip(frozen_before,
                                      net._params[0].items()):
            np.testing.assert_allclose(before, np.asarray(after))
        out = net.output(x).numpy()
        assert out.shape == (16, 7)

    def test_remove_and_append(self):
        src = _net()
        net = (TransferLearning.Builder(src)
               .remove_output_layer()
               .add_layer(L.DenseLayer(n_in=12, n_out=6, activation="relu"))
               .add_layer(L.OutputLayer(n_in=6, n_out=2,
                                        activation="softmax", loss="mcxent"))
               .build())
        rs = np.random.RandomState(1)
        x, _ = _xy(rs)
        assert net.output(x).shape == (16, 2)


class TestSolvers:
    @pytest.mark.parametrize("solver_cls", [LineGradientDescent,
                                            ConjugateGradient, LBFGS])
    def test_solver_decreases_loss(self, solver_cls):
        rs = np.random.RandomState(0)
        net = _net()
        x, y = _xy(rs, b=32)
        solver = solver_cls(max_iterations=25)
        final = solver.optimize(net, x, y)
        assert len(solver.scores) > 2
        assert final < solver.scores[0] * 0.9

    def test_lbfgs_faster_than_gd_on_quadratic_like(self):
        rs = np.random.RandomState(1)
        x, y = _xy(rs, b=64)
        lb = LBFGS(max_iterations=15)
        lb.optimize(_net(), x, y)
        gd = LineGradientDescent(max_iterations=15)
        gd.optimize(_net(), x, y)
        assert lb.scores[-1] <= gd.scores[-1] * 1.1


class TestWorkspaceShims:
    def test_scoping(self):
        ws = MemoryWorkspace(WorkspaceConfiguration.builder()
                             .initial_size(1 << 20).build(), "TEST_WS")
        assert not ws.is_scope_active()
        with ws:
            assert ws.is_scope_active()
            assert Nd4jWorkspaceManager.current_workspace() is ws
        assert not ws.is_scope_active()
        assert ws.generation == 1
        Nd4jWorkspaceManager.assert_no_workspaces_open()

    def test_manager_thread_scoped(self):
        ws1 = workspace_manager.get_workspace_for_current_thread(
            workspace_id="A")
        ws2 = workspace_manager.get_workspace_for_current_thread(
            workspace_id="A")
        assert ws1 is ws2

    def test_layer_workspace_mgr(self):
        mgr = LayerWorkspaceMgr.no_workspaces()
        arr = mgr.create("ACTIVATIONS", (2, 3))
        assert arr.shape == (2, 3)
        assert mgr.leverage_to("ACTIVATIONS", arr) is arr


class TestEnvironment:
    def test_layered_resolution(self, monkeypatch):
        env = Environment()
        assert env.default_float_dtype() == "float32"
        monkeypatch.setenv("DL4J_TPU_DEFAULT_DTYPE", "bfloat16")
        assert env.default_float_dtype() == "bfloat16"
        env.set_default_float_dtype("float16")   # override beats env var
        assert env.default_float_dtype() == "float16"

    def test_debug_flags(self):
        env = Environment()
        assert not env.is_debug()
        env.set_debug(True)
        assert env.is_debug()

    def test_singleton_and_introspection(self):
        env = environment()
        assert env is environment()
        assert env.num_devices() >= 1
        assert env.backend() in ("cpu", "tpu", "gpu", "axon")


class TestGraphTransferLearning:
    def test_freeze_and_replace_on_graph(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.graph.computation_graph import \
            ComputationGraph
        rs = np.random.RandomState(0)
        b = (NeuralNetConfiguration.builder()
             .seed(2).updater(Adam(learning_rate=1e-2)).graph_builder())
        b.add_inputs("in")
        b.set_input_types(InputType.feed_forward(8))
        b.add_layer("f1", L.DenseLayer(n_in=8, n_out=16,
                                       activation="relu"), "in")
        b.add_layer("out", L.OutputLayer(n_in=16, n_out=4,
                                         activation="softmax",
                                         loss="mcxent"), "f1")
        b.set_outputs("out")
        src = ComputationGraph(b.build()).init()

        x, y = _xy(rs)
        src.fit(x, y)
        net = (TransferLearning.GraphBuilder(src)
               .fine_tune_configuration(
                   FineTuneConfiguration.builder()
                   .updater(Sgd(learning_rate=5e-2)).build())
               .set_feature_extractor("f1")
               .n_out_replace("out", 6)
               .build())
        frozen_before = {k: np.asarray(v)
                         for k, v in net._params["f1"].items()}
        y6 = np.zeros((16, 6), np.float32)
        y6[np.arange(16), rs.randint(0, 6, 16)] = 1.0
        net.fit(x, y6)
        net.fit(x, y6)
        for k, before in frozen_before.items():
            np.testing.assert_allclose(before,
                                       np.asarray(net._params["f1"][k]))
        assert net.output(x)[0].shape == (16, 6)


class TestFeedForwardToRnnPreProcessor:
    def test_roundtrip(self):
        from deeplearning4j_tpu.nn.conf.config import (
            FeedForwardToRnnPreProcessor, RnnToFeedForwardPreProcessor)
        import jax.numpy as jnp
        rs = np.random.RandomState(0)
        x_rnn = jnp.asarray(rs.randn(4, 3, 5).astype(np.float32))  # [B,F,T]
        flat = RnnToFeedForwardPreProcessor()(x_rnn)               # [B*T,F]
        assert flat.shape == (20, 3)
        back = FeedForwardToRnnPreProcessor(timesteps=5)(flat)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x_rnn),
                                   atol=1e-6)
