"""ComputationGraph tests.

Models the reference's ComputationGraph tests
(platform-tests/.../dl4jcore/nn/graph/ComputationGraphTestRNN.java,
TestComputationGraphNetwork.java): construction, topo order, multi-input/
multi-output fit, vertices, serde round-trip.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               LossLayer, OutputLayer)
from deeplearning4j_tpu.nn.graph import (AttentionVertex, ComputationGraph,
                                         ComputationGraphConfiguration,
                                         ElementWiseVertex, L2NormalizeVertex,
                                         L2Vertex, MergeVertex, ReshapeVertex,
                                         ScaleVertex, ShiftVertex, StackVertex,
                                         SubsetVertex, UnstackVertex)


def simple_graph():
    return (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3), "d1")
            .set_outputs("out")
            .build())


class TestGraphConstruction:
    def test_topological_order(self):
        conf = simple_graph()
        order = conf.topological_order()
        assert order.index("in") < order.index("d1") < order.index("out")

    def test_cycle_detection(self):
        conf = simple_graph()
        conf.vertex_inputs["d1"] = ["out"]  # introduce a cycle
        with pytest.raises(ValueError):
            conf.topological_order()

    def test_output_types(self):
        conf = simple_graph()
        types = conf.vertex_output_types()
        assert types["d1"] == (8,)
        assert types["out"] == (3,)

    def test_num_params(self):
        net = ComputationGraph(simple_graph()).init()
        # d1: 4*8+8, out: 8*3+3
        assert net.num_params() == 4 * 8 + 8 + 8 * 3 + 3


class TestGraphFit:
    def test_fit_reduces_loss(self):
        net = ComputationGraph(simple_graph()).init()
        rng = np.random.RandomState(0)
        x = rng.randn(32, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
        ds = DataSet(x, y)
        before = net.score(ds)
        net.fit(ds, num_epochs=30)
        after = net.score(ds)
        assert after < before * 0.7

    def test_output_shape(self):
        net = ComputationGraph(simple_graph()).init()
        out = net.output(np.ones((5, 4), np.float32))
        assert out[0].shape == (5, 3)
        # softmax rows sum to 1
        np.testing.assert_allclose(np.asarray(out[0].jax()).sum(-1),
                                   np.ones(5), rtol=1e-5)

    def test_multi_input_merge(self):
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("a", "b")
                .add_vertex("merge", MergeVertex(), "a", "b")
                .add_layer("out", OutputLayer(n_in=6, n_out=2), "merge")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        rng = np.random.RandomState(1)
        mds = MultiDataSet(
            features=[rng.randn(8, 2).astype(np.float32),
                      rng.randn(8, 4).astype(np.float32)],
            labels=[np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]])
        before = net.score(mds)
        net.fit(mds, num_epochs=25)
        assert net.score(mds) < before

    def test_multi_output(self):
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("shared", DenseLayer(n_in=4, n_out=8,
                                                activation="tanh"), "in")
                .add_layer("out1", OutputLayer(n_in=8, n_out=2), "shared")
                .add_layer("out2", OutputLayer(n_in=8, n_out=3), "shared")
                .set_outputs("out1", "out2").build())
        net = ComputationGraph(conf).init()
        rng = np.random.RandomState(2)
        x = rng.randn(16, 4).astype(np.float32)
        mds = MultiDataSet(
            features=[x],
            labels=[np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)],
                    np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]])
        outs = net.output(x)
        assert outs[0].shape == (16, 2) and outs[1].shape == (16, 3)
        before = net.score(mds)
        net.fit(mds, num_epochs=20)
        assert net.score(mds) < before


class TestVertices:
    def _run(self, vertex, inputs, n_inputs=None):
        return vertex.forward({}, [jnp.asarray(x) for x in inputs])

    def test_elementwise(self):
        a = np.array([[1., 2.]])
        b = np.array([[3., 5.]])
        assert np.allclose(self._run(ElementWiseVertex("add"), [a, b]),
                           [[4., 7.]])
        assert np.allclose(self._run(ElementWiseVertex("subtract"), [a, b]),
                           [[-2., -3.]])
        assert np.allclose(self._run(ElementWiseVertex("product"), [a, b]),
                           [[3., 10.]])
        assert np.allclose(self._run(ElementWiseVertex("average"), [a, b]),
                           [[2., 3.5]])
        assert np.allclose(self._run(ElementWiseVertex("max"), [a, b]),
                           [[3., 5.]])

    def test_stack_unstack(self):
        a = np.ones((2, 3), np.float32)
        b = 2 * np.ones((2, 3), np.float32)
        stacked = self._run(StackVertex(), [a, b])
        assert stacked.shape == (4, 3)
        u1 = UnstackVertex(from_index=1, stack_size=2).forward(
            {}, [stacked])
        assert np.allclose(u1, b)

    def test_subset(self):
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        out = self._run(SubsetVertex(from_idx=1, to_idx=3), [x])
        assert out.shape == (2, 3)
        assert np.allclose(out[0], [1, 2, 3])

    def test_l2_normalize(self):
        x = np.array([[3., 4.]])
        out = self._run(L2NormalizeVertex(), [x])
        assert np.allclose(out, [[0.6, 0.8]])

    def test_l2_distance(self):
        a = np.array([[0., 0.]])
        b = np.array([[3., 4.]])
        out = self._run(L2Vertex(), [a, b])
        assert np.allclose(out, [[5.]], atol=1e-3)

    def test_scale_shift_reshape(self):
        x = np.ones((2, 4), np.float32)
        assert np.allclose(self._run(ScaleVertex(scale=3.0), [x]), 3.0)
        assert np.allclose(self._run(ShiftVertex(shift=1.5), [x]), 2.5)
        out = self._run(ReshapeVertex(shape=(2, 2)), [x])
        assert out.shape == (2, 2, 2)

    def test_attention_vertex(self):
        import jax
        v = AttentionVertex(n_in=8, n_out=8, n_heads=2, head_size=4)
        params = v.init_params(jax.random.key(0), [(8, 5)])
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(3, 8, 5).astype(np.float32))
        out = v.forward(params, [x, x, x])
        assert out.shape == (3, 8, 5)
        # masked positions get ~zero attention: compare masked vs unmasked
        mask = jnp.asarray(np.array([[1, 1, 1, 0, 0]] * 3, np.float32))
        out_m = v.forward(params, [x, x, x, mask])
        assert out_m.shape == (3, 8, 5)
        assert not np.allclose(out, out_m)


class TestGraphSerde:
    def test_json_round_trip(self):
        conf = simple_graph()
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert conf2.inputs == conf.inputs
        assert conf2.outputs == conf.outputs
        assert set(conf2.vertices) == set(conf.vertices)
        assert conf2.vertex_inputs == conf.vertex_inputs

    def test_save_load(self, tmp_path):
        net = ComputationGraph(simple_graph()).init()
        rng = np.random.RandomState(3)
        x = rng.randn(4, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
        net.fit(DataSet(x, y), num_epochs=2)
        out_before = np.asarray(net.output(x)[0].jax())
        p = tmp_path / "cg.zip"
        net.save(str(p), save_updater=True)
        net2 = ComputationGraph.load(str(p), load_updater=True)
        out_after = np.asarray(net2.output(x)[0].jax())
        np.testing.assert_allclose(out_before, out_after, rtol=1e-6)

    def test_clone_independent(self):
        net = ComputationGraph(simple_graph()).init()
        clone = net.clone()
        rng = np.random.RandomState(4)
        x = rng.randn(4, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
        net.fit(DataSet(x, y), num_epochs=3)
        # clone unchanged by original's training
        o1 = np.asarray(net.output(x)[0].jax())
        o2 = np.asarray(clone.output(x)[0].jax())
        assert not np.allclose(o1, o2)


class TestReviewRegressions:
    def test_cg_batchnorm_state_updates(self):
        """CG fit must refresh BatchNormalization running stats (review
        finding: states were frozen at init)."""
        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=6,
                                           activation="identity"), "in")
                .add_layer("bn", BatchNormalization(n_out=6), "d")
                .add_layer("out", OutputLayer(n_in=6, n_out=2), "bn")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        rng = np.random.RandomState(5)
        x = (rng.randn(32, 4) * 5 + 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)]
        net.fit(DataSet(x, y), num_epochs=10)
        mean = np.asarray(net._params["bn"]["state_mean"])
        var = np.asarray(net._params["bn"]["state_var"])
        assert not np.allclose(mean, 0.0)
        assert not np.allclose(var, 1.0)

    def test_preprocessor_serde_keeps_args(self):
        """Parameterized preprocessors round-trip with their fields (review
        finding: args were dropped)."""
        from deeplearning4j_tpu.nn.conf.config import \
            FeedForwardToCnnPreProcessor
        conf = (NeuralNetConfiguration.builder().graph_builder()
                .add_inputs("in")
                .add_layer("c", ConvolutionLayer(n_in=3, n_out=4,
                                                 kernel_size=(3, 3)),
                           "in",
                           preprocessor=FeedForwardToCnnPreProcessor(3, 4, 4))
                .add_layer("out", OutputLayer(n_in=4 * 2 * 2, n_out=2), "c",
                           preprocessor=None)
                .set_outputs("out").build())
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        pre = conf2.vertices["c"].preprocessor
        assert pre.channels == 3 and pre.height == 4 and pre.width == 4

    def test_preprocessor_vertex_serde(self):
        from deeplearning4j_tpu.nn.conf.config import \
            CnnToFeedForwardPreProcessor
        from deeplearning4j_tpu.nn.graph import PreprocessorVertex
        conf = (NeuralNetConfiguration.builder().graph_builder()
                .add_inputs("in")
                .add_vertex("flat", PreprocessorVertex(
                    preprocessor=CnnToFeedForwardPreProcessor()), "in")
                .add_layer("out", OutputLayer(n_in=12, n_out=2), "flat")
                .set_outputs("out").build())
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        v = conf2.vertices["flat"]
        x = jnp.ones((2, 3, 2, 2))
        assert v.forward({}, [x]).shape == (2, 12)

    def test_early_stopping_with_cg(self, tmp_path):
        """LocalFileModelSaver round-trips a ComputationGraph (review
        finding: loader was hardcoded to MultiLayerNetwork)."""
        from deeplearning4j_tpu.nn.earlystopping import (
            EarlyStoppingConfiguration, EarlyStoppingTrainer,
            LocalFileModelSaver, MaxEpochsTerminationCondition)
        net = ComputationGraph(simple_graph()).init()
        rng = np.random.RandomState(6)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
        ds = DataSet(x, y)
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            model_saver=LocalFileModelSaver(str(tmp_path)))
        result = EarlyStoppingTrainer(cfg, net).fit([ds])
        best = result.get_best_model()
        assert isinstance(best, ComputationGraph)
        assert best.output(x)[0].shape == (16, 3)
