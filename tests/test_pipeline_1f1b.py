"""1F1B pipeline schedule (VERDICT round-2 item 3).

'Done' criteria: 1F1B numerically equals the GPipe autodiff path (loss AND
grads, including the input cotangent that feeds the embed), and its compiled
peak temp memory at n_micro=8 is lower than GPipe's (activation memory
bounded by n_stages, not n_micro).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh
from deeplearning4j_tpu.parallel.pipeline import (make_pipeline_loss,
                                                  make_pipeline_loss_1f1b,
                                                  stack_stage_params)

needs8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _head_fn(hp, y, aux):
    d = (y @ hp["wo"] - aux["target"]) ** 2
    return jnp.sum(d), jnp.float32(d.size)


def _setup(rs, S=4, B=8, D=16):
    stage_params = [
        {"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
         "b": jnp.zeros((D,), jnp.float32)} for _ in range(S)]
    head = {"wo": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3)}
    x = jnp.asarray(rs.randn(B, D).astype(np.float32))
    target = jnp.asarray(rs.randn(B, D).astype(np.float32))
    return stack_stage_params(stage_params), head, x, {"target": target}


@needs8
class Test1F1B:
    def test_loss_matches_gpipe(self):
        rs = np.random.RandomState(0)
        stacked, head, x, aux = _setup(rs)
        mesh = make_mesh(MeshConfig(data=2, pipe=4))
        l_g = make_pipeline_loss(_stage_fn, _head_fn, mesh, n_microbatches=4)
        l_1 = make_pipeline_loss_1f1b(_stage_fn, _head_fn, mesh,
                                      n_microbatches=4)
        sg, wg = l_g(stacked, head, x, aux)
        s1, w1 = l_1(stacked, head, x, aux)
        np.testing.assert_allclose(float(s1), float(sg), rtol=1e-6)
        np.testing.assert_allclose(float(w1), float(wg), rtol=1e-6)

    def test_grads_match_gpipe(self):
        """Stage grads, head grads, AND the x cotangent (what the caller's
        embedding sees) must match the autodiff GPipe backward."""
        rs = np.random.RandomState(1)
        stacked, head, x, aux = _setup(rs)
        mesh = make_mesh(MeshConfig(data=2, pipe=4))

        def scalar(loss_fn):
            def f(sp, hp, xx):
                s, w = loss_fn(sp, hp, xx, aux)
                return s / w
            return f

        l_g = scalar(make_pipeline_loss(_stage_fn, _head_fn, mesh, 4))
        l_1 = scalar(make_pipeline_loss_1f1b(_stage_fn, _head_fn, mesh, 4))
        gg = jax.grad(l_g, argnums=(0, 1, 2))(stacked, head, x)
        g1 = jax.grad(l_1, argnums=(0, 1, 2))(stacked, head, x)
        for a, b in zip(jax.tree_util.tree_leaves(gg),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-4, atol=1e-6)

    def test_param_dependent_weight_cotangent(self):
        """wsum's cotangent must flow: with a head whose weight output
        depends on params and activations, grad(s/w) through 1F1B must
        still equal the GPipe autodiff backward (regression for the
        hard-coded (1,0) head pull)."""
        rs = np.random.RandomState(7)
        stacked, head, x, aux = _setup(rs)
        mesh = make_mesh(MeshConfig(data=2, pipe=4))

        def head_w(hp, y, aux):
            o = y @ hp["wo"]
            d = (o - aux["target"]) ** 2
            return jnp.sum(d), jnp.sum(jax.nn.sigmoid(o))

        def scalar(loss_fn):
            def f(sp, hp, xx):
                s, w = loss_fn(sp, hp, xx, aux)
                return s / w
            return f

        l_g = scalar(make_pipeline_loss(_stage_fn, head_w, mesh, 4))
        l_1 = scalar(make_pipeline_loss_1f1b(_stage_fn, head_w, mesh, 4))
        np.testing.assert_allclose(
            float(l_1(stacked, head, x)), float(l_g(stacked, head, x)),
            rtol=1e-6)
        gg = jax.grad(l_g, argnums=(0, 1, 2))(stacked, head, x)
        g1 = jax.grad(l_1, argnums=(0, 1, 2))(stacked, head, x)
        for a, b in zip(jax.tree_util.tree_leaves(gg),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-4, atol=1e-6)

    def test_uneven_bubble_microbatches(self):
        """n_micro > n_stages and n_micro == n_stages both stay exact."""
        rs = np.random.RandomState(2)
        stacked, head, x, aux = _setup(rs, S=2, B=16)
        mesh = make_mesh(MeshConfig(data=2, pipe=2),
                         devices=jax.devices()[:4])
        for n_micro in (2, 4, 8):
            l_g = make_pipeline_loss(_stage_fn, _head_fn, mesh, n_micro)
            l_1 = make_pipeline_loss_1f1b(_stage_fn, _head_fn, mesh, n_micro)
            sg, _ = l_g(stacked, head, x, aux)
            s1, _ = l_1(stacked, head, x, aux)
            np.testing.assert_allclose(float(s1), float(sg), rtol=1e-6,
                                       err_msg=f"n_micro={n_micro}")

    def test_peak_memory_below_gpipe(self):
        """Compiled temp-memory at n_micro=8: 1F1B (stash ∝ n_stages) must
        stay under autodiff-GPipe (residuals ∝ n_micro)."""
        rs = np.random.RandomState(3)
        # larger activations so residual stash dominates temp memory
        stacked, head, x, aux = _setup(rs, S=4, B=64, D=256)
        mesh = make_mesh(MeshConfig(data=1, pipe=4),
                         devices=jax.devices()[:4])

        def compiled_temp_bytes(loss_fn):
            def f(sp, hp, xx):
                s, w = loss_fn(sp, hp, xx, aux)
                return s / w

            g = jax.jit(jax.grad(f, argnums=(0, 1, 2)))
            lowered = g.lower(stacked, head, x)
            mem = lowered.compile().memory_analysis()
            if mem is None or not hasattr(mem, "temp_size_in_bytes"):
                pytest.skip("memory_analysis unsupported on this backend")
            return mem.temp_size_in_bytes

        gpipe = compiled_temp_bytes(
            make_pipeline_loss(_stage_fn, _head_fn, mesh, 8, remat=True))
        f1b1 = compiled_temp_bytes(
            make_pipeline_loss_1f1b(_stage_fn, _head_fn, mesh, 8))
        assert f1b1 < gpipe, (f1b1, gpipe)


@needs8
class TestBertLargeDepth1F1B:
    def test_bert_large_depth_dp2_pp4(self):
        """The r2 weak-#3 claim closed: a BERT-large-DEPTH model (24
        layers, tiny widths) trains one dp2 x pp4 1F1B step with n_micro=8
        on the CPU mesh — the configuration GPipe's O(n_micro) activation
        stash was flagged as not holding up."""
        from deeplearning4j_tpu.models import bert
        c = bert.BertConfig(vocab_size=97, hidden_size=16, num_layers=24,
                            num_heads=2, intermediate_size=32,
                            max_position_embeddings=64)
        mesh = make_mesh(MeshConfig(data=2, pipe=4))
        params = bert.place_pipeline_params(
            bert.to_pipeline_params(bert.init_params(jax.random.key(0), c),
                                    4), mesh)
        opt = bert.init_opt_state(params)
        step = bert.make_pipeline_train_step(c, mesh, n_microbatches=8,
                                             schedule="1f1b")
        rs = np.random.RandomState(0)
        B, T = 16, 16
        batch = {
            "input_ids": jnp.asarray(rs.randint(0, 97, (B, T)), jnp.int32),
            "labels": jnp.asarray(
                np.where(rs.rand(B, T) < 0.2,
                         rs.randint(0, 97, (B, T)), -100), jnp.int32),
        }
        # apples-to-apples: remat=True on the GPipe baseline too (matches
        # test_peak_memory_below_gpipe) so the comparison isolates the
        # schedule, not rematerialization
        gpipe_step = bert.make_pipeline_train_step(
            c, mesh, n_microbatches=8, remat=True, schedule="gpipe")
        mems = {}
        for name, fn in (("1f1b", step), ("gpipe", gpipe_step)):
            mem = fn.lower(params, opt, batch, 0).compile() \
                    .memory_analysis()
            if mem is None or not hasattr(mem, "temp_size_in_bytes"):
                pytest.skip("memory_analysis unsupported on this backend")
            mems[name] = mem.temp_size_in_bytes
        # the property this test exists for: activation memory bounded
        # by stage count, not microbatch count
        assert mems["1f1b"] < mems["gpipe"], mems
        params, opt, loss = step(params, opt, batch, 0)
        jax.block_until_ready(loss)
        assert np.isfinite(float(loss))
