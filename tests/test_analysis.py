"""dl4jlint: the framework-invariant static analysis pass + the DL105
runtime lock-order tracker.

The tier-1 contract (ISSUE 9): ``python -m deeplearning4j_tpu.analysis``
must exit 0 on the repo — every finding fixed or baselined with a
justification — and the pass must keep *ratcheting*: fixture tests pin
each rule's true positives AND its documented false-positive guards, so
a checker that goes blind (or trigger-happy) fails here before it lies
in CI.
"""
import json
import textwrap
import threading
import time

import pytest

from deeplearning4j_tpu.analysis import (analyze_source, load_baseline,
                                         run_analysis)
from deeplearning4j_tpu.common import locks


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


def _analyze(src, relpath="deeplearning4j_tpu/fixture.py"):
    return analyze_source(textwrap.dedent(src), relpath)


# ---------------------------------------------------------------------------
# DL101 — bare jax.jit
# ---------------------------------------------------------------------------

class TestDL101:
    def test_flags_bare_call(self):
        f = _rules(_analyze("""
            import jax
            def make(fn):
                return jax.jit(fn, donate_argnums=(0,))
        """), "DL101")
        assert len(f) == 1 and "make" in f[0].message

    def test_flags_decorator(self):
        f = _rules(_analyze("""
            import jax
            @jax.jit
            def step(p, x):
                return p
        """), "DL101")
        assert len(f) == 1 and "@jax.jit" in f[0].message

    def test_flags_functools_partial(self):
        f = _rules(_analyze("""
            import functools, jax
            jitted = functools.partial(jax.jit, static_argnums=(1,))
        """), "DL101")
        assert len(f) == 1 and "partial" in f[0].message

    def test_false_positive_guard_counted_jit_implementation(self):
        # the sanctioned site: counted_jit's own body wraps jax.jit
        f = _rules(_analyze("""
            import jax
            def counted_jit(fn, tag, **kw):
                jfn = jax.jit(fn, **kw)
                return jfn
        """), "DL101")
        assert f == []

    def test_counted_jit_usage_is_clean(self):
        f = _rules(_analyze("""
            from deeplearning4j_tpu.runtime.inference import counted_jit
            def make(fn):
                return counted_jit(fn, tag="t")
        """), "DL101")
        assert f == []


# ---------------------------------------------------------------------------
# DL102 — env reads bypassing Environment
# ---------------------------------------------------------------------------

class TestDL102:
    def test_flags_subscript_get_and_getenv(self):
        f = _rules(_analyze("""
            import os
            a = os.environ["DL4J_TPU_FOO"]
            b = os.environ.get("DL4J_TPU_BAR", "1")
            c = os.getenv("DL4J_TPU_BAZ")
        """), "DL102")
        assert len(f) == 3

    def test_flags_undeclared_knob(self):
        f = _rules(_analyze("""
            import os
            v = os.environ.get("DL4J_TPU_NO_SUCH_KNOB_EVER")
        """), "DL102")
        assert len(f) == 1 and "not even declared" in f[0].message

    def test_declared_knob_still_flagged_but_not_undeclared(self):
        f = _rules(_analyze("""
            import os
            v = os.environ.get("DL4J_TPU_METRICS")
        """), "DL102")
        assert len(f) == 1 and "not even declared" not in f[0].message

    def test_false_positive_guard_environment_impl_exempt(self):
        f = _rules(_analyze("""
            import os
            v = os.environ.get("DL4J_TPU_DEBUG")
        """, relpath="deeplearning4j_tpu/common/environment.py"), "DL102")
        assert f == []

    def test_non_dl4j_vars_ignored(self):
        f = _rules(_analyze("""
            import os
            v = os.environ.get("HOME")
            w = os.environ.get("XLA_FLAGS", "")
        """), "DL102")
        assert f == []

    def test_helper_wrapper_read_flagged(self):
        f = _rules(_analyze("""
            def _env_bool(name, default=False):
                return False
            v = _env_bool("DL4J_TPU_SOMETHING")
        """), "DL102")
        assert len(f) == 1


# ---------------------------------------------------------------------------
# DL103 — host syncs in traced code
# ---------------------------------------------------------------------------

class TestDL103:
    def test_item_inside_jitted_fn(self):
        f = _rules(_analyze("""
            import jax
            @jax.jit
            def step(p, x):
                return p * x.item()
        """), "DL103")
        assert len(f) == 1 and ".item()" in f[0].message

    def test_float_cast_and_np_asarray_in_scan_body(self):
        f = _rules(_analyze("""
            import jax
            import numpy as np
            def body(carry, inp):
                v = float(inp)
                w = np.asarray(carry)
                return carry, v
            def run(xs):
                return jax.lax.scan(body, 0.0, xs)
        """), "DL103")
        assert len(f) == 2

    def test_time_and_host_random_in_jitted(self):
        f = _rules(_analyze("""
            import jax, time, random
            @jax.jit
            def step(p):
                t = time.time()
                r = random.random()
                return p + t + r
        """), "DL103")
        assert len(f) == 2

    def test_false_positive_guard_item_outside_traced_code(self):
        f = _rules(_analyze("""
            def host_side(arr):
                return arr.item()
        """), "DL103")
        assert f == []

    def test_false_positive_guard_shape_arithmetic(self):
        # int()/float() over static shapes is trace-safe by design
        f = _rules(_analyze("""
            import jax
            @jax.jit
            def step(p, x):
                n = int(x.shape[0])
                return p * n
        """), "DL103")
        assert f == []

    def test_false_positive_guard_debug_callback(self):
        f = _rules(_analyze("""
            import jax
            @jax.jit
            def step(p):
                jax.debug.callback(lambda v: float(v), p)
                return p
        """), "DL103")
        assert f == []


# ---------------------------------------------------------------------------
# DL104 — metrics/tracing hygiene
# ---------------------------------------------------------------------------

class TestDL104:
    def test_flags_off_namespace_metric(self):
        f = _rules(_analyze("""
            def setup(reg):
                reg.counter("requests_total", "d")
        """), "DL104")
        assert len(f) == 1 and "dl4j_*" in f[0].message

    def test_flags_unregistered_label(self):
        f = _rules(_analyze("""
            def setup(reg):
                reg.histogram("dl4j_x_seconds", "d",
                              labels=("model", "user_id"))
        """), "DL104")
        assert len(f) == 1 and "user_id" in f[0].message

    def test_flags_bare_span_statement(self):
        f = _rules(_analyze("""
            from deeplearning4j_tpu.common.tracing import span
            def work():
                span("serving/thing")
                return 1
        """), "DL104")
        assert len(f) == 1 and "context manager" in f[0].message

    def test_false_positive_guard_with_span(self):
        f = _rules(_analyze("""
            from deeplearning4j_tpu.common.tracing import span
            def work():
                with span("serving/thing", model="m"):
                    return 1
        """), "DL104")
        assert f == []

    def test_flags_private_metrics_flag_reread(self):
        f = _rules(_analyze("""
            import os
            def enabled():
                return os.environ.get("DL4J_TPU_METRICS", "1") != "0"
        """), "DL104")
        assert len(f) == 1 and "DL4J_TPU_METRICS" in f[0].message

    def test_false_positive_guard_metrics_impl_exempt(self):
        f = _rules(_analyze("""
            import os
            def enabled():
                return os.environ.get("DL4J_TPU_METRICS", "1") != "0"
        """, relpath="deeplearning4j_tpu/common/metrics.py"), "DL104")
        assert f == []

    def test_registered_labels_clean(self):
        f = _rules(_analyze("""
            def setup(reg):
                reg.counter("dl4j_things_total", "d",
                            labels=("model", "version", "outcome"))
        """), "DL104")
        assert f == []


# ---------------------------------------------------------------------------
# DL105 — static lock-order analysis
# ---------------------------------------------------------------------------

_INVERTED = """
    import threading

    class Engine:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""


class TestDL105Static:
    def test_reports_cycle(self):
        f = _rules(_analyze(_INVERTED), "DL105")
        assert len(f) == 1
        assert "cycle" in f[0].message
        assert "Engine._a" in f[0].message and "Engine._b" in f[0].message

    def test_consistent_order_clean(self):
        f = _rules(_analyze("""
            import threading

            class Engine:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._a:
                        with self._b:
                            pass
        """), "DL105")
        assert f == []

    def test_cycle_through_method_call(self):
        f = _rules(_analyze("""
            import threading

            class Engine:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def _inner(self):
                    with self._b:
                        pass

                def forward(self):
                    with self._a:
                        self._inner()

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
        """), "DL105")
        assert len(f) == 1 and "cycle" in f[0].message

    def test_self_deadlock_on_plain_lock(self):
        f = _rules(_analyze("""
            import threading

            class Engine:
                def __init__(self):
                    self._a = threading.Lock()

                def work(self):
                    with self._a:
                        with self._a:
                            pass
        """), "DL105")
        assert len(f) == 1 and "self-deadlock" in f[0].message

    def test_false_positive_guard_reentrant_rlock(self):
        f = _rules(_analyze("""
            import threading

            class Engine:
                def __init__(self):
                    self._a = threading.RLock()

                def work(self):
                    with self._a:
                        with self._a:
                            pass
        """), "DL105")
        assert f == []

    def test_ordered_wrappers_are_recognized(self):
        f = _rules(_analyze("""
            from deeplearning4j_tpu.common.locks import (ordered_lock,
                                                         ordered_rlock)

            class Engine:
                def __init__(self):
                    self._a = ordered_lock("a")
                    self._b = ordered_rlock("b")

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
        """), "DL105")
        assert len(f) == 1 and "cycle" in f[0].message

    def test_thread_start_not_confused_with_engine_start(self):
        # the documented guard: self._thread is a threading.Thread, so
        # .start() under a lock must NOT expand to Engine.start()
        f = _rules(_analyze("""
            import threading

            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition()
                    self._thread = threading.Thread(target=self.run)

                def start(self):
                    with self._cv:
                        pass

                def run(self):
                    pass

                def spawn(self):
                    with self._lock:
                        self._thread.start()
        """), "DL105")
        assert f == []


# ---------------------------------------------------------------------------
# DL105 — runtime tracker (common.locks)
# ---------------------------------------------------------------------------

@pytest.fixture()
def tracker():
    prev = locks.set_lock_check(True)
    saved = locks.violations()
    locks.clear_violations()
    yield locks
    locks.set_lock_check(prev)
    locks.clear_violations()
    # conftest's module fixture asserts on violations for some suites;
    # don't leak ours into theirs (we cleared; nothing to restore beyond
    # the enabled flag)
    del saved


class TestRuntimeTracker:
    def test_cross_thread_inversion_detected(self, tracker):
        a = locks.ordered_lock("t.A")
        b = locks.ordered_lock("t.B")
        errs = []

        def ab():
            try:
                with a:
                    with b:
                        time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        def ba():
            try:
                with b:
                    with a:
                        time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        # run the two orders on two *sequential* threads: a real A->B /
        # B->A inversion without constructing the actual deadlock
        t1 = threading.Thread(target=ab, name="order-ab")
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba, name="order-ba")
        t2.start()
        t2.join()
        assert not errs
        v = tracker.violations()
        assert len(v) == 1
        assert v[0]["kind"] == "order_inversion"
        assert set(v[0]["locks"]) == {"t.A", "t.B"}
        # both witnesses name their thread and held stack
        assert {v[0]["first"]["thread"], v[0]["second"]["thread"]} == \
            {"order-ab", "order-ba"}

    def test_inversion_reported_once_per_pair(self, tracker):
        a = locks.ordered_lock("t.C")
        b = locks.ordered_lock("t.D")
        for _ in range(3):
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        assert len(tracker.violations()) == 1

    def test_consistent_order_is_clean(self, tracker):
        a = locks.ordered_lock("t.E")
        b = locks.ordered_lock("t.F")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert tracker.violations() == []

    def test_condition_wait_roundtrip_clean(self, tracker):
        cv = locks.ordered_condition("t.cv")
        outer = locks.ordered_lock("t.outer")
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(1.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.02)
        with outer:
            with cv:
                done.append(1)
                cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert tracker.violations() == []

    def test_reentrant_rlock_clean(self, tracker):
        r = locks.ordered_rlock("t.R")
        with r:
            with r:
                pass
        assert tracker.violations() == []

    def test_self_deadlock_recorded_before_blocking(self, tracker):
        s = locks.ordered_lock("t.S")
        assert s.acquire()
        try:
            assert s.acquire(timeout=0.05) is False
        finally:
            s.release()
        v = tracker.violations()
        assert len(v) == 1 and v[0]["kind"] == "self_deadlock"

    def test_disabled_tracker_records_nothing(self):
        prev = locks.set_lock_check(False)
        locks.clear_violations()
        try:
            a = locks.ordered_lock("t.off.A")
            b = locks.ordered_lock("t.off.B")
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
            assert locks.violations() == []
            assert locks.acquisition_edges() == {}
        finally:
            locks.set_lock_check(prev)

    def test_serving_stack_constructs_ordered_locks(self):
        # the conversion satellite: engine + registry locks are tracked
        from deeplearning4j_tpu.runtime.inference import InferenceEngine
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        reg = ModelRegistry(manifest_dir=None)
        assert isinstance(reg._lock, locks.OrderedLock)
        assert reg._lock.reentrant
        assert isinstance(
            InferenceEngine.__init__.__globals__["ordered_condition"],
            type(locks.ordered_condition))


# ---------------------------------------------------------------------------
# baseline mechanics + the tier-1 repo gate
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_result():
    """ONE full-package pass shared by the gate tests (the pass is ~2 s
    on CPU; tier-1 time is a budget — see the static_analysis bench)."""
    return run_analysis()


class TestBaseline:
    def test_every_entry_has_justification(self):
        for e in load_baseline():
            assert str(e.get("justification", "")).strip(), e

    def test_missing_justification_rejected(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(
            [{"rule": "DL101", "path": "x.py", "justification": "  "}]))
        with pytest.raises(ValueError, match="justification"):
            load_baseline(str(p))

    def test_baseline_never_silently_grows(self, repo_result):
        # the ratchet: the checked-in baseline must not contain stale
        # entries (every suppression still suppresses something real)
        assert repo_result.unused_baseline == [], (
            "stale baseline entries — a baselined finding was fixed; "
            f"delete its entry: {repo_result.unused_baseline}")


class TestRepoGate:
    def test_package_has_zero_unbaselined_findings(self, repo_result):
        """THE tier-1 gate: new violations of DL101-DL105 fail here —
        the in-process equivalent of `python -m deeplearning4j_tpu.
        analysis` exiting 0 on the repo (the CLI is the same
        run_analysis call; its glue is covered on small inputs below)."""
        assert repo_result.ok, "unbaselined findings:\n" + "\n".join(
            f.render() for f in repo_result.findings)
        assert repo_result.modules > 150  # the package was actually walked

    def test_cli_exits_zero_on_clean_path(self, tmp_path):
        from deeplearning4j_tpu.analysis.__main__ import main
        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x\n")
        assert main([str(good)]) == 0

    def test_cli_exits_nonzero_on_violation(self, tmp_path, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        bad = tmp_path / "bad.py"
        bad.write_text("import jax\nf = jax.jit(lambda x: x)\n")
        assert main([str(bad)]) == 1
        assert "DL101" in capsys.readouterr().out

    def test_cli_list_rules(self, capsys):
        from deeplearning4j_tpu.analysis.__main__ import main
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in ("DL101", "DL102", "DL103", "DL104", "DL105"):
            assert rule in out

    def test_environment_declares_lock_check_knob(self):
        from deeplearning4j_tpu.common.environment import (EnvironmentVars,
                                                           environment)
        assert EnvironmentVars.DL4J_TPU_LOCK_CHECK == "DL4J_TPU_LOCK_CHECK"
        assert environment().lock_check() in (True, False)
