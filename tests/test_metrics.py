"""Unified runtime telemetry tests (common/metrics.py, common/tracing.py).

Covers: registry semantics (labeled counters/gauges/histograms, quantile
estimation, thread-safety under concurrent increments), span nesting +
chrome-trace export round-trip through `profile_analyzer.load_trace`/
`aggregate`, the Prometheus text-format golden check, disabled-mode no-op
behavior, the compile-counter bridge, the instrumented InferenceEngine /
fit() hot paths, and the UI server's /metrics endpoints.
"""
import json
import logging
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.common import profile_analyzer
from deeplearning4j_tpu.common.environment import environment
from deeplearning4j_tpu.common.metrics import (MetricsRegistry,
                                               exponential_buckets,
                                               linear_buckets, registry)
from deeplearning4j_tpu.common.tracing import Tracer, span, tracer


@pytest.fixture(autouse=True)
def _telemetry_enabled():
    """Every test starts with the singleton registry enabled and leaves
    the global enabled-state as it found it."""
    reg = registry()
    prev = reg.enabled
    reg.set_enabled(True)
    yield reg
    reg.set_enabled(prev)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_inc(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("c_total", "help")
        c.inc()
        c.inc(4)
        assert c.value() == 5.0

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry(enabled=True)
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("c_total").inc(-1)

    def test_get_or_create_returns_same_family(self):
        reg = MetricsRegistry(enabled=True)
        assert reg.counter("x_total") is reg.counter("x_total")

    def test_kind_clash_raises(self):
        reg = MetricsRegistry(enabled=True)
        reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_label_set_clash_raises(self):
        reg = MetricsRegistry(enabled=True)
        reg.counter("x_total", labels=("a",))
        with pytest.raises(ValueError, match="labels"):
            reg.counter("x_total", labels=("b",))

    def test_labeled_children_independent_and_cached(self):
        reg = MetricsRegistry(enabled=True)
        fam = reg.counter("req_total", labels=("code",))
        a, b = fam.labels(code="200"), fam.labels(code="500")
        a.inc(3)
        b.inc()
        assert a.value() == 3.0 and b.value() == 1.0
        assert fam.labels(code="200") is a  # cached child identity
        with pytest.raises(ValueError, match="use .labels"):
            fam.inc()  # labeled family has no default child

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry(enabled=True)
        g = reg.gauge("depth")
        g.set(10)
        g.inc(2)
        g.dec(5)
        assert g.value() == 7.0

    def test_histogram_count_sum(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("lat", buckets=(1.0, 10.0))
        for v in (0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count() == 3
        assert reg.get("lat")._default.sum() == pytest.approx(55.5)

    def test_histogram_quantiles(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("q", buckets=linear_buckets(1.0, 1.0, 100))
        for v in range(1, 101):
            h.observe(float(v))
        assert h.quantile(0.50) == pytest.approx(50.0, abs=1.5)
        assert h.quantile(0.90) == pytest.approx(90.0, abs=1.5)
        assert h.quantile(0.99) == pytest.approx(99.0, abs=1.5)

    def test_quantile_clamps_to_top_bucket(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("q", buckets=(1.0, 2.0))
        h.observe(100.0)  # lands in +Inf overflow
        assert h.quantile(0.99) == 2.0

    def test_empty_histogram_quantile_nan(self):
        reg = MetricsRegistry(enabled=True)
        assert np.isnan(reg.histogram("q").quantile(0.5))

    def test_exponential_buckets(self):
        assert exponential_buckets(1.0, 2.0, 4) == (1.0, 2.0, 4.0, 8.0)
        with pytest.raises(ValueError):
            exponential_buckets(0.0, 2.0, 4)

    def test_thread_safety_concurrent_increments(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("tc_total")
        h = reg.histogram("th", buckets=(0.5,))
        n_threads, per_thread = 8, 5000

        def work():
            for _ in range(per_thread):
                c.inc()
                h.observe(1.0)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == n_threads * per_thread
        assert h.count() == n_threads * per_thread


# ---------------------------------------------------------------------------
# Prometheus text exposition (golden)
# ---------------------------------------------------------------------------

class TestPrometheusText:
    def test_golden_format(self):
        reg = MetricsRegistry(enabled=True)
        reg.counter("req_total", "Total requests",
                    labels=("code",)).labels(code="200").inc(3)
        reg.gauge("queue_depth", "Depth").set(5)
        h = reg.histogram("lat", "Latency", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        expected = (
            "# HELP lat Latency\n"
            "# TYPE lat histogram\n"
            'lat_bucket{le="0.1"} 1\n'
            'lat_bucket{le="1"} 2\n'
            'lat_bucket{le="+Inf"} 3\n'
            "lat_sum 5.55\n"
            "lat_count 3\n"
            "# HELP queue_depth Depth\n"
            "# TYPE queue_depth gauge\n"
            "queue_depth 5\n"
            "# HELP req_total Total requests\n"
            "# TYPE req_total counter\n"
            'req_total{code="200"} 3\n'
        )
        assert reg.prometheus_text() == expected

    def test_snapshot_is_strict_json(self):
        reg = MetricsRegistry(enabled=True)
        reg.histogram("empty_h")  # no observations: quantiles must be None
        reg.counter("c_total").inc()
        s = json.loads(json.dumps(reg.snapshot(), allow_nan=False))
        assert s["empty_h"]["series"][0]["p50"] is None
        assert s["c_total"]["series"][0]["value"] == 1.0


# ---------------------------------------------------------------------------
# tracing spans
# ---------------------------------------------------------------------------

class TestTracing:
    def test_span_records_x_event(self):
        t = Tracer(capacity=64)
        with t.span("work", phase="test"):
            time.sleep(0.002)
        (ev,) = t.events()
        assert ev["name"] == "work" and ev["ph"] == "X"
        assert ev["dur"] >= 1000  # >= 1ms in microseconds
        assert ev["args"] == {"phase": "test"}

    def test_span_nesting_containment(self):
        t = Tracer(capacity=64)
        with t.span("outer"):
            time.sleep(0.001)
            with t.span("inner"):
                time.sleep(0.001)
            time.sleep(0.001)
        inner, outer = t.events()  # inner exits (appends) first
        assert inner["name"] == "inner" and outer["name"] == "outer"
        assert outer["ts"] <= inner["ts"]
        assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
        assert outer["dur"] > inner["dur"]

    def test_ring_buffer_drops_oldest(self):
        t = Tracer(capacity=4)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        names = [e["name"] for e in t.events()]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_export_round_trip_through_profile_analyzer(self, tmp_path):
        t = Tracer(capacity=64)
        for _ in range(3):
            with t.span("step"):
                time.sleep(0.001)
        with t.span("eval"):
            time.sleep(0.001)
        path = str(tmp_path / "trace.json")
        assert t.export(path) == 4
        agg = profile_analyzer.aggregate(profile_analyzer.load_trace(path))
        assert agg["step"]["count"] == 3
        assert agg["step"]["total_us"] > 0
        assert agg["step"]["avg_us"] > 0
        assert agg["eval"]["count"] == 1
        assert agg.unmatched == 0

    def test_export_gzip(self, tmp_path):
        t = Tracer(capacity=8)
        with t.span("z"):
            pass
        path = str(tmp_path / "trace.json.gz")
        t.export(path)
        events = profile_analyzer.load_trace(path)
        assert events[0]["name"] == "z"


# ---------------------------------------------------------------------------
# disabled-mode no-op behavior
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_disabled_registry_writes_are_noops(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("c_total")
        g = reg.gauge("g")
        h = reg.histogram("h")
        c.inc(5)
        g.set(3)
        h.observe(1.0)
        assert c.value() == 0.0 and g.value() == 0.0 and h.count() == 0

    def test_disabled_span_records_nothing(self, _telemetry_enabled):
        _telemetry_enabled.set_enabled(False)
        before = len(tracer().events())
        s = span("never")
        with s:
            pass
        assert len(tracer().events()) == before
        # the no-op context manager is a shared singleton — no allocation
        assert span("never2") is s

    def test_env_var_resolution(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_METRICS", "0")
        assert MetricsRegistry().enabled is False
        monkeypatch.setenv("DL4J_TPU_METRICS", "1")
        assert MetricsRegistry().enabled is True

    def test_environment_toggle_reaches_registry(self, _telemetry_enabled):
        env = environment()
        env.set_metrics_enabled(False)
        assert registry().enabled is False
        env.set_metrics_enabled(True)
        assert registry().enabled is True


# ---------------------------------------------------------------------------
# profile_analyzer unmatched-E regression (satellite)
# ---------------------------------------------------------------------------

class TestAggregateUnmatched:
    def test_orphan_end_events_counted(self):
        events = [
            {"name": "a", "ph": "B", "ts": 0, "tid": 1},
            {"name": "a", "ph": "E", "ts": 10, "tid": 1},
            {"name": "b", "ph": "E", "ts": 5, "tid": 1},   # no B ever
            {"name": "a", "ph": "E", "ts": 20, "tid": 2},  # wrong tid
        ]
        agg = profile_analyzer.aggregate(events)
        assert agg.unmatched == 2
        assert agg["a"]["count"] == 1
        assert agg["a"]["total_us"] == pytest.approx(10.0)
        assert "b" not in agg

    def test_clean_trace_reports_zero(self):
        events = [{"name": "x", "ph": "X", "ts": 0, "dur": 5.0}]
        agg = profile_analyzer.aggregate(events)
        assert agg.unmatched == 0
        assert agg["x"]["count"] == 1


# ---------------------------------------------------------------------------
# environment bridge: compiles_total + debug listener logging (satellites)
# ---------------------------------------------------------------------------

class TestEnvironmentBridge:
    def test_record_compile_feeds_compiles_total(self):
        env = environment()
        child = registry().counter(
            "dl4j_compiles_total",
            "Executable materializations recorded by counted_jit",
            labels=("kind", "cache")).labels(kind="tmetrics",
                                             cache="bypass")
        v0 = child.value()
        assert env.record_compile(("tmetrics:1:sig", "a"))
        assert child.value() == v0 + 1
        # duplicate key: already materialized, no metric increment
        assert not env.record_compile(("tmetrics:1:sig", "a"))
        assert child.value() == v0 + 1

    def test_record_compile_cache_labels(self):
        env = environment()
        fam = registry().counter(
            "dl4j_compiles_total",
            "Executable materializations recorded by counted_jit",
            labels=("kind", "cache"))
        hit = fam.labels(kind="tlabels", cache="hit")
        v0 = hit.value()
        assert env.record_compile(("tlabels:1:sig", "h"), cache="hit")
        assert hit.value() == v0 + 1
        miss = fam.labels(kind="tlabels", cache="miss")
        v1 = miss.value()
        assert env.record_compile(("tlabels:2:sig", "m"), cache="miss")
        assert miss.value() == v1 + 1

    def test_debug_logs_listener_exception_once(self, caplog):
        env = environment()
        prev_debug = env.is_debug()
        env.set_debug(True)

        def bad(key):
            raise RuntimeError("boom")

        env.add_compile_listener(bad)
        try:
            with caplog.at_level(logging.ERROR,
                                 logger="deeplearning4j_tpu.common"
                                        ".environment"):
                env.record_compile(("tdbg:1",))
                env.record_compile(("tdbg:2",))
        finally:
            env.remove_compile_listener(bad)
            env.set_debug(prev_debug)
        logged = [r for r in caplog.records
                  if "compile listener" in r.getMessage()]
        assert len(logged) == 1  # once per listener, not per event
        assert logged[0].exc_info is not None

    def test_silent_without_debug(self, caplog):
        env = environment()
        prev_debug = env.is_debug()
        env.set_debug(False)

        def bad(key):
            raise RuntimeError("boom")

        env.add_compile_listener(bad)
        try:
            with caplog.at_level(logging.ERROR):
                env.record_compile(("tquiet:1",))
        finally:
            env.remove_compile_listener(bad)
            env.set_debug(prev_debug)
        assert not [r for r in caplog.records
                    if "compile listener" in r.getMessage()]

    def test_trace_buffer_knob(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_TRACE_BUFFER", "1234")
        assert environment().trace_buffer() == 1234


# ---------------------------------------------------------------------------
# instrumented hot paths
# ---------------------------------------------------------------------------

def _mlp(n_in=6, hidden=8, n_out=3, seed=0):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="tanh"))
            .layer(OutputLayer(n_in=hidden, n_out=n_out))
            .build())
    return MultiLayerNetwork(conf).init()


def _series(name, **labels):
    """Current value/count of one labeled series from the snapshot."""
    fam = registry().snapshot().get(name)
    if fam is None:
        return None
    for s in fam["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s
    return None


class TestInferenceEngineTelemetry:
    def test_submit_populates_queue_and_latency_metrics(self):
        from deeplearning4j_tpu.runtime.inference import InferenceEngine
        net = _mlp()
        eng = InferenceEngine(net, max_batch=8, max_delay_ms=5.0)
        lat0 = sum(s["count"] for s in registry().snapshot()
                   ["dl4j_inference_latency_seconds"]["series"])
        req = registry().get("dl4j_inference_requests_total")
        req0 = req.value() if req else 0.0
        rng = np.random.RandomState(0)
        with eng:
            futs = [eng.submit(jnp.asarray(
                rng.randn(2, 6).astype(np.float32))) for _ in range(6)]
            outs = [f.result(timeout=60) for f in futs]
        assert all(o.shape == (2, 3) for o in outs)
        snap = registry().snapshot()
        lat = sum(s["count"] for s in
                  snap["dl4j_inference_latency_seconds"]["series"])
        assert lat > lat0  # per-bucket latency observed
        assert registry().get(
            "dl4j_inference_requests_total").value() == req0 + 6
        assert "dl4j_inference_queue_depth" in snap
        co = snap["dl4j_inference_coalesce_size"]["series"][0]
        assert co["count"] >= 1
        # padding histogram saw the 2-row -> 2-bucket dispatches
        assert sum(s["count"] for s in
                   snap["dl4j_inference_padding_ratio"]["series"]) > 0

    def test_infer_counts_requests_and_spans(self):
        from deeplearning4j_tpu.runtime.inference import InferenceEngine
        net = _mlp()
        eng = InferenceEngine(net, max_batch=8)
        before = len(tracer().events())
        eng.infer(jnp.zeros((3, 6), jnp.float32))
        names = [e["name"] for e in tracer().events()[before:]]
        assert "inference/dispatch" in names

    def test_disabled_engine_records_nothing(self, _telemetry_enabled):
        from deeplearning4j_tpu.runtime.inference import InferenceEngine
        net = _mlp()
        _telemetry_enabled.set_enabled(False)
        eng = InferenceEngine(net, max_batch=8)
        lat_fam = registry().get("dl4j_inference_latency_seconds")
        before = sum(c.count() for _, c in lat_fam.children())
        ev_before = len(tracer().events())
        eng.infer(jnp.zeros((3, 6), jnp.float32))
        assert sum(c.count() for _, c in lat_fam.children()) == before
        assert len(tracer().events()) == ev_before


class TestTrainingTelemetry:
    def _dataset(self, n=2, b=8):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.RandomState(0)
        out = []
        for _ in range(n):
            x = rng.randn(b, 6).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, b)]
            out.append(DataSet(jnp.asarray(x), jnp.asarray(y)))
        return out

    def test_scanned_fit_counts_steps_and_samples(self):
        net = _mlp()
        s0 = _series("dl4j_train_steps_total", path="scan")
        n0 = s0["value"] if s0 else 0.0
        net.fit(self._dataset(n=3, b=8), num_epochs=2)
        s = _series("dl4j_train_steps_total", path="scan")
        assert s["value"] == n0 + 6  # 3 batches x 2 epochs
        samples = _series("dl4j_train_samples_total", path="scan")
        assert samples["value"] >= 6 * 8
        assert net._last_batch_size == 8

    def test_per_step_fit_emits_spans(self):
        from deeplearning4j_tpu.nn.listeners import CollectScoresListener
        net = _mlp()
        net.set_listeners(CollectScoresListener())  # forces per-step path
        before = len(tracer().events())
        net.fit(self._dataset(n=2, b=4), num_epochs=1)
        names = [e["name"] for e in tracer().events()[before:]]
        assert names.count("train/dispatch") == 2
        assert "train/data_wait" in names
        assert "train/device" in names

    def test_span_export_aggregates_with_durations(self, tmp_path):
        from deeplearning4j_tpu.nn.listeners import CollectScoresListener
        tracer().clear()
        net = _mlp()
        net.set_listeners(CollectScoresListener())
        net.fit(self._dataset(n=2, b=4), num_epochs=2)
        path = str(tmp_path / "train_trace.json")
        from deeplearning4j_tpu.common import tracing
        assert tracing.export(path) > 0
        agg = profile_analyzer.aggregate(profile_analyzer.load_trace(path))
        assert agg["train/dispatch"]["count"] == 4
        assert agg["train/dispatch"]["total_us"] > 0
        assert agg.unmatched == 0

    def test_metrics_listener_bridges_iterations(self):
        from deeplearning4j_tpu.nn.listeners import MetricsListener
        net = _mlp()
        lst = MetricsListener()
        net.set_listeners(lst)
        it0 = registry().get("dl4j_listener_iterations_total").value()
        net.fit(self._dataset(n=3, b=4), num_epochs=1)
        assert registry().get(
            "dl4j_listener_iterations_total").value() == it0 + 3
        assert registry().get("dl4j_iteration_seconds").count() >= 2
        score = registry().get("dl4j_train_score").value()
        assert np.isfinite(score)

    def test_performance_listener_samples_per_sec(self):
        from deeplearning4j_tpu.nn.listeners import PerformanceListener
        lines = []
        lst = PerformanceListener(frequency=1, log_fn=lines.append)

        class FakeModel:
            score_value = 1.0
            _last_batch_size = 32

        m = FakeModel()
        lst.iteration_done(m, 0)
        lst._last_time -= 2.0  # pretend 2s elapsed since iteration 0
        lst.iteration_done(m, 1)
        assert lst.batches_per_sec == pytest.approx(0.5, rel=0.2)
        assert lst.samples_per_sec == pytest.approx(16.0, rel=0.2)
        assert any("samples/sec" in l for l in lines)

    def test_performance_listener_live_fit(self):
        from deeplearning4j_tpu.nn.listeners import PerformanceListener
        lines = []
        net = _mlp()
        net.set_listeners(PerformanceListener(frequency=1,
                                              log_fn=lines.append))
        net.fit(self._dataset(n=3, b=8), num_epochs=1)
        assert net._last_batch_size == 8
        assert any("samples/sec" in l for l in lines)

    def test_samediff_fit_counts_steps(self):
        from deeplearning4j_tpu import nd
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        from deeplearning4j_tpu.learning import Adam

        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        y = sd.placeholder("y", (None, 1))
        w = sd.var("w", nd.zeros(3, 1))
        loss = sd.loss.mean_squared_error(x.mmul(w), None, y)
        sd.set_loss_variables(loss)
        sd.set_training_config(TrainingConfig(
            updater=Adam(learning_rate=0.1),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        rng = np.random.RandomState(1)
        ds = DataSet(nd.create(rng.randn(4, 3).astype(np.float32)),
                     nd.create(rng.randn(4, 1).astype(np.float32)))
        s0 = _series("dl4j_train_steps_total", path="samediff")
        n0 = s0["value"] if s0 else 0.0
        sd.fit(ListDataSetIterator([ds, ds]), num_epochs=1)
        s = _series("dl4j_train_steps_total", path="samediff")
        assert s["value"] == n0 + 2
        assert sd._last_batch_size == 4


class TestUIServerMetricsEndpoint:
    def test_metrics_routes(self):
        from deeplearning4j_tpu.ui.server import UIServer

        # populate the registry through the real hot paths first
        from deeplearning4j_tpu.runtime.inference import InferenceEngine
        net = _mlp()
        eng = InferenceEngine(net, max_batch=8, max_delay_ms=5.0)
        with eng:
            eng.submit(jnp.zeros((2, 6), jnp.float32)).result(timeout=60)
        net.fit(TestTrainingTelemetry()._dataset(n=2, b=4), num_epochs=1)

        server = UIServer(port=0)
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            for needle in ("dl4j_inference_latency_seconds_bucket",
                           "dl4j_inference_queue_depth",
                           "dl4j_compiles_total",
                           "dl4j_train_steps_total",
                           "dl4j_train_samples_total"):
                assert needle in text, f"{needle} missing from /metrics"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json",
                    timeout=10) as r:
                snap = json.loads(r.read())
            assert snap["dl4j_train_steps_total"]["type"] == "counter"
            lat = snap["dl4j_inference_latency_seconds"]
            assert lat["type"] == "histogram"
            assert sum(s["count"] for s in lat["series"]) >= 1
        finally:
            server.stop()
