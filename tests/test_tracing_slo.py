"""Request-scoped tracing, SLO burn-rate gating, /debug endpoints (PR 6).

Covers the acceptance contract: an HTTP request carrying a W3C
``traceparent`` shares its trace_id with the admission/dispatch spans and
gets it echoed as ``X-Trace-Id``; a coalesced micro-batch dispatch links
back to every rider; a deadline-expired request's full timeline is
reconstructable from ``/debug/requests`` by trace_id; a fast-burning SLO
flips ``/readyz``; ``/debug/profile`` produces a loadable jax profiler
capture. Plus the satellites: span error status + counter, atomic
``tracer().export``, admission EWMA/waiters gauges, uptime/build-info
gauges, and histogram exemplars.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.common import tracing
from deeplearning4j_tpu.common.environment import environment
from deeplearning4j_tpu.common.metrics import (MetricsRegistry, registry,
                                               touch_runtime_info)
from deeplearning4j_tpu.common.tracing import (TraceContext,
                                               context_from_traceparent,
                                               format_traceparent,
                                               new_trace_id,
                                               parse_traceparent, span,
                                               span_tree, tracer,
                                               use_context)
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.runtime.inference import InferenceEngine
from deeplearning4j_tpu.serving import (AdmissionController,
                                        GracefulLifecycle, ModelRegistry,
                                        ModelServer, SLOTracker)

N_IN, N_OUT = 6, 3


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=4, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _post(url, data=b"", content_type="application/json", timeout=30,
          headers=()):
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": content_type,
                                          **dict(headers)})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _wait_until(pred, timeout=10.0):
    """Poll until ``pred()`` is truthy and return it. The server finishes
    a request's bookkeeping (root span append, ring record, SLO record)
    *after* writing the response, so a client asserting on it must give
    the handler thread a beat."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    return pred()


@pytest.fixture
def served():
    reg = ModelRegistry(manifest_dir=None)
    reg.deploy("mlp", "v1", _mlp(0), example=_x())
    server = ModelServer(reg)
    port = server.start()
    yield reg, server, f"http://127.0.0.1:{port}"
    server.stop()
    reg.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# trace context + W3C traceparent
# ---------------------------------------------------------------------------

class TestTraceContext:
    def test_parse_format_roundtrip(self):
        ctx = TraceContext(new_trace_id(), tracing.new_span_id())
        parsed = parse_traceparent(format_traceparent(ctx))
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id

    @pytest.mark.parametrize("header", [
        None, "", "garbage", "00-abc-def-01",
        "00-" + "0" * 32 + "-" + "ab" * 8 + "-01",   # all-zero trace
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero parent
        "00-" + "zz" * 16 + "-" + "ab" * 8 + "-01",  # non-hex
        "ff-" + "ab" * 16 + "-" + "ab" * 8 + "-01",  # forbidden version
    ])
    def test_parse_rejects_malformed(self, header):
        assert parse_traceparent(header) is None

    def test_context_from_traceparent(self):
        hdr = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        ctx = context_from_traceparent(hdr)
        assert ctx.trace_id == "ab" * 16 and ctx.span_id == "cd" * 8
        fresh = context_from_traceparent(None)
        assert len(fresh.trace_id) == 32 and fresh.span_id == ""

    def test_nested_spans_form_tree(self):
        tid = new_trace_id()
        with use_context(TraceContext(tid)):
            with span("outer", k=1):
                with span("inner_a"):
                    pass
                with span("inner_b"):
                    pass
        events = tracer().events_for(tid)
        assert {e["name"] for e in events} == {"outer", "inner_a",
                                               "inner_b"}
        tree = span_tree(events)
        assert len(tree) == 1 and tree[0]["name"] == "outer"
        assert [c["name"] for c in tree[0]["children"]] == ["inner_a",
                                                            "inner_b"]
        assert tree[0]["args"] == {"k": 1}

    def test_span_without_context_stays_flat(self):
        with span("flat_span_xyz"):
            pass
        evs = [e for e in tracer().events()
               if e["name"] == "flat_span_xyz"]
        assert evs and "trace_id" not in evs[-1].get("args", {})

    def test_record_enters_tree_cross_thread(self):
        tid = new_trace_id()
        ctx = TraceContext(tid, tracing.new_span_id())
        t0 = time.perf_counter()
        tracer().record("batcher/work", t0, t0 + 0.001, context=ctx,
                        rows=3)
        events = tracer().events_for(tid)
        assert events[-1]["name"] == "batcher/work"
        assert events[-1]["args"]["parent_span_id"] == ctx.span_id
        assert events[-1]["args"]["rows"] == 3

    def test_span_tree_orphan_becomes_root(self):
        tid = new_trace_id()
        ctx = TraceContext(tid, "feedfacefeedface")  # parent not buffered
        tracer().record("orphan", 0.0, 0.001, context=ctx)
        tree = span_tree(tracer().events_for(tid))
        assert len(tree) == 1 and tree[0]["name"] == "orphan"

    def test_disabled_tracing_noop(self):
        reg = registry()
        prev = reg.enabled
        reg.set_enabled(False)
        try:
            tid = new_trace_id()
            with use_context(TraceContext(tid)):
                with span("should_not_record"):
                    pass
                assert tracer().record("nor_this", 0, 1) is None
            assert tracer().events_for(tid) == []
        finally:
            reg.set_enabled(prev)


# ---------------------------------------------------------------------------
# satellite: span error status + dl4j_span_errors_total
# ---------------------------------------------------------------------------

class TestSpanErrors:
    def test_failing_span_records_error_and_counter(self):
        fam = registry().counter(
            "dl4j_span_errors_total",
            "Spans that exited with an exception, by span name",
            labels=("name",))
        before = fam.labels(name="err_span_test").value()
        tid = new_trace_id()
        with pytest.raises(ValueError):
            with use_context(TraceContext(tid)):
                with span("err_span_test", job=7):
                    raise ValueError("boom")
        ev = tracer().events_for(tid)[-1]
        assert ev["args"]["error"] == "ValueError"
        assert ev["args"]["job"] == 7  # original attrs survive
        assert fam.labels(name="err_span_test").value() == before + 1

    def test_clean_span_has_no_error(self):
        tid = new_trace_id()
        with use_context(TraceContext(tid)):
            with span("clean_span_test"):
                pass
        assert "error" not in tracer().events_for(tid)[-1]["args"]

    def test_record_with_error_attr_counts(self):
        fam = registry().counter(
            "dl4j_span_errors_total",
            "Spans that exited with an exception, by span name",
            labels=("name",))
        before = fam.labels(name="rec_err_test").value()
        tracer().record("rec_err_test", 0.0, 0.001, error="TimeoutError")
        assert fam.labels(name="rec_err_test").value() == before + 1


# ---------------------------------------------------------------------------
# satellite: atomic export with parent-dir creation
# ---------------------------------------------------------------------------

class TestExportAtomic:
    def test_export_creates_parent_dirs(self, tmp_path):
        with span("export_parent_test"):
            pass
        path = tmp_path / "a" / "b" / "trace.json"
        n = tracer().export(str(path))
        assert path.exists() and n >= 1
        doc = json.loads(path.read_text())
        assert any(e["name"] == "export_parent_test"
                   for e in doc["traceEvents"])

    def test_export_leaves_no_tmp_file(self, tmp_path):
        path = tmp_path / "t.json"
        tracer().export(str(path))
        leftovers = [p for p in os.listdir(tmp_path) if ".tmp" in p]
        assert leftovers == []

    def test_export_gzip_still_works(self, tmp_path):
        import gzip
        path = tmp_path / "deep" / "t.json.gz"
        tracer().export(str(path))
        with gzip.open(path, "rt") as f:
            assert "traceEvents" in json.load(f)


# ---------------------------------------------------------------------------
# histogram exemplars
# ---------------------------------------------------------------------------

class TestExemplars:
    def test_exemplar_recorded_per_bucket(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("ex_h", "x", buckets=(0.1, 1.0))
        h.observe(0.05)                       # no exemplar
        h.observe(5.0, exemplar="tail-trace")  # +Inf bucket
        h.observe(0.5, exemplar="mid-trace")
        series = reg.snapshot()["ex_h"]["series"][0]
        ex = {e["le"]: e["trace_id"] for e in series["exemplars"]}
        assert ex == {"+Inf": "tail-trace", "1": "mid-trace"}
        json.dumps(reg.snapshot(), allow_nan=False)  # stays strict JSON

    def test_no_exemplars_key_when_none(self):
        reg = MetricsRegistry(enabled=True)
        reg.histogram("plain_h", "x", buckets=(1.0,)).observe(0.5)
        assert "exemplars" not in reg.snapshot()["plain_h"]["series"][0]

    def test_engine_latency_carries_trace_exemplar(self):
        eng = InferenceEngine(_mlp(3), max_batch=4)
        tid = new_trace_id()
        with use_context(TraceContext(tid)):
            eng.infer(_x(2))
        fam = registry().get("dl4j_inference_latency_seconds")
        found = [e for _, child in fam.children()
                 for e in child.exemplars() if e["trace_id"] == tid]
        assert found, "traced dispatch should leave a latency exemplar"


# ---------------------------------------------------------------------------
# satellite: admission internals exported
# ---------------------------------------------------------------------------

class TestAdmissionGauges:
    def test_ewma_and_waiters_gauges(self):
        ctrl = AdmissionController("gauged-model", max_concurrent=2,
                                   queue_depth=4, high_water=3,
                                   default_timeout_s=None)
        ctrl.run(lambda: time.sleep(0.005))
        reg = registry()
        ewma = reg.get("dl4j_serving_ewma_service_seconds")
        assert ewma is not None
        val = ewma.labels(model="gauged-model").value()
        assert val > 0  # seeded, then EWMA-updated by the completion
        waiters = reg.get("dl4j_serving_waiters")
        assert waiters.labels(model="gauged-model").value() == 0

    def test_waiters_counts_active_holder(self):
        ctrl = AdmissionController("gauged-model-2", max_concurrent=1,
                                   queue_depth=4, high_water=3,
                                   default_timeout_s=None)
        with ctrl.admit():
            assert registry().get("dl4j_serving_waiters").labels(
                model="gauged-model-2").value() == 1
        assert registry().get("dl4j_serving_waiters").labels(
            model="gauged-model-2").value() == 0


# ---------------------------------------------------------------------------
# satellite: uptime + build info
# ---------------------------------------------------------------------------

class TestRuntimeInfoGauges:
    def test_touch_runtime_info_sets_gauges(self):
        import jax
        touch_runtime_info()
        reg = registry()
        assert reg.get("dl4j_uptime_seconds").value() > 0
        fam = reg.get("dl4j_build_info")
        (labels, child), = fam.children()
        label_map = dict(zip(fam.label_names, labels))
        assert label_map["jax_version"] == jax.__version__
        assert label_map["platform"] == jax.default_backend()
        assert label_map["cache"] in ("enabled", "disabled")
        assert child.value() == 1

    def test_metrics_endpoints_carry_runtime_info(self, served):
        _, _, base = served
        code, _, body = _get(base + "/metrics")
        assert code == 200
        assert b"dl4j_uptime_seconds" in body
        assert b"dl4j_build_info" in body
        code, _, body = _get(base + "/metrics.json")
        doc = json.loads(body)
        assert doc["dl4j_uptime_seconds"]["series"][0]["value"] > 0


# ---------------------------------------------------------------------------
# SLO tracker
# ---------------------------------------------------------------------------

class TestSLOTracker:
    def test_burn_rate_math(self):
        t = SLOTracker("m", objective=0.9, latency_objective_s=None,
                       windows=((10.0, 2.0),), min_samples=1)
        for i in range(10):
            t.record(0.01, ok=i >= 5)  # 5 bad of 10
        # error rate 0.5 against a 0.1 budget -> burn rate 5
        assert t.burn_rate(10.0) == pytest.approx(5.0)
        assert t.hit_rate(10.0) == pytest.approx(0.5)
        assert not t.healthy()

    def test_idle_model_is_healthy(self):
        t = SLOTracker("m-idle", objective=0.999)
        assert t.burn_rate(300.0) == 0.0
        assert t.healthy() and t.snapshot()["healthy"]

    def test_min_samples_guard(self):
        t = SLOTracker("m-guard", objective=0.999,
                       latency_objective_s=None,
                       windows=((10.0, 1.0),), min_samples=5)
        for _ in range(3):
            t.record(0.01, ok=False)
        assert t.healthy()  # burning hard, but not enough evidence
        for _ in range(3):
            t.record(0.01, ok=False)
        assert not t.healthy()

    def test_all_windows_must_burn(self):
        clock = [1000.0]
        t = SLOTracker("m-windows", objective=0.9,
                       latency_objective_s=None,
                       windows=((5.0, 1.0), (1000.0, 1.0)),
                       min_samples=1, clock=lambda: clock[0])
        # long-ago successes keep the long window under threshold
        for _ in range(200):
            t.record(0.01, ok=True)
        clock[0] += 900.0
        for _ in range(10):
            t.record(0.01, ok=False)
        assert t.burn_rate(5.0) > 1.0       # short window fully burning
        assert t.burn_rate(1000.0) < 1.0    # long window still fine
        assert t.healthy()

    def test_latency_objective_counts_slow_ok_as_bad(self):
        t = SLOTracker("m-lat", objective=0.5, latency_objective_s=0.05,
                       windows=((10.0, 1.0),), min_samples=1)
        assert t.record(0.01, ok=True) is True
        assert t.record(0.2, ok=True) is False  # ok but too slow

    def test_gauges_exported(self):
        t = SLOTracker("m-gauges", objective=0.9,
                       latency_objective_s=None,
                       windows=((10.0, 1.0),), min_samples=1)
        t.record(0.01, ok=False)
        reg = registry()
        assert reg.get("dl4j_slo_burn_rate").labels(
            model="m-gauges", window=10).value() > 0
        assert reg.get("dl4j_slo_healthy").labels(
            model="m-gauges").value() == 0


# ---------------------------------------------------------------------------
# end-to-end trace propagation over HTTP
# ---------------------------------------------------------------------------

class TestEndToEndTracing:
    def test_traceparent_joined_and_echoed(self, served):
        _, _, base = served
        tid = "ab" * 16
        code, headers, body = _post(
            base + "/v1/models/mlp/predict",
            json.dumps({"inputs": _x().tolist()}).encode(),
            headers=[("traceparent", f"00-{tid}-{'cd' * 8}-01")])
        assert code == 200
        assert headers["X-Trace-Id"] == tid
        # admission + dispatch spans all share the request's trace_id
        # (the root span lands just after the response is written)
        want = {"serving/request", "serving/admission", "serving/predict",
                "inference/dispatch"}
        names = _wait_until(
            lambda: (lambda got: want <= got and got)(
                {e["name"] for e in tracer().events_for(tid)}))
        assert want <= set(names or ())

    def test_fresh_trace_minted_without_header(self, served):
        _, _, base = served
        code, headers, _ = _post(
            base + "/v1/models/mlp/predict",
            json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 200
        tid = headers["X-Trace-Id"]
        assert len(tid) == 32
        assert _wait_until(
            lambda: any(e["name"] == "serving/request"
                        for e in tracer().events_for(tid)))

    def test_error_response_still_echoes_trace_id(self, served):
        _, _, base = served
        tid = "5e" * 16
        code, headers, _ = _post(
            base + "/v1/models/nope/predict",
            json.dumps({"inputs": _x().tolist()}).encode(),
            headers=[("traceparent", f"00-{tid}-{'cd' * 8}-01")])
        assert code == 404
        assert headers["X-Trace-Id"] == tid

    def test_coalesced_dispatch_links_both_riders(self):
        eng = InferenceEngine(_mlp(1), max_batch=8, max_delay_ms=50)
        eng.warmup(_x(2))
        ctx_a = TraceContext(new_trace_id())
        ctx_b = TraceContext(new_trace_id())
        # queue both requests before the batcher thread starts, so they
        # deterministically coalesce into one dispatch
        orig = eng._ensure_thread
        eng._ensure_thread = lambda: None
        try:
            with use_context(ctx_a):
                fa = eng.submit(_x(2, seed=1))
            with use_context(ctx_b):
                fb = eng.submit(_x(3, seed=2))
        finally:
            eng._ensure_thread = orig
        eng._ensure_thread()
        fa.result(timeout=30)
        fb.result(timeout=30)
        dispatches = [e for e in tracer().events()
                      if e["name"] == "inference/dispatch"
                      and ctx_a.trace_id in e.get("args", {}).get(
                          "trace_ids", [])]
        assert dispatches, "dispatch span must name its riders"
        args = dispatches[-1]["args"]
        assert set(args["trace_ids"]) == {ctx_a.trace_id, ctx_b.trace_id}
        assert args["coalesced"] == 2
        # each rider's own trace carries its ride span (queue + dispatch;
        # recorded by the batcher just after resolving the futures)
        for ctx in (ctx_a, ctx_b):
            rides = _wait_until(
                lambda: [e for e in tracer().events_for(ctx.trace_id)
                         if e["name"] == "inference/ride"])
            assert rides and rides[-1]["args"]["coalesced"] == 2
            assert rides[-1]["args"]["queue_s"] >= 0
        eng.close(5)

    def test_expired_submit_leaves_error_span(self):
        eng = InferenceEngine(_mlp(2), max_batch=4)
        eng.warmup(_x(2))
        ctx = TraceContext(new_trace_id())
        orig = eng._ensure_thread
        eng._ensure_thread = lambda: None
        try:
            with use_context(ctx):
                fut = eng.submit(_x(2), timeout_s=0.0)
            time.sleep(0.01)
        finally:
            eng._ensure_thread = orig
        eng._ensure_thread()
        with pytest.raises(TimeoutError):
            fut.result(timeout=30)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            evs = [e for e in tracer().events_for(ctx.trace_id)
                   if e["name"] == "inference/queue_expired"]
            if evs:
                break
            time.sleep(0.01)
        assert evs and evs[-1]["args"]["error"] == "TimeoutError"
        eng.close(5)

    def test_deadline_expired_timeline_reconstructable(self, served):
        """Acceptance: a 504'd request's admission wait is readable from
        /debug/requests by its trace_id."""
        reg, server, base = served
        ctrl = AdmissionController("mlp", max_concurrent=1, queue_depth=8,
                                   high_water=8, default_timeout_s=None)
        server.set_admission("mlp", ctrl)
        tid = "dd" * 16
        permit = ctrl.admit()  # saturate: the request waits, then expires
        try:
            code, headers, _ = _post(
                base + "/v1/models/mlp/predict",
                json.dumps({"inputs": _x().tolist(),
                            "timeout_s": 0.05}).encode(),
                headers=[("traceparent", f"00-{tid}-{'cd' * 8}-01")])
            assert code == 504
            assert headers["X-Trace-Id"] == tid
        finally:
            permit.__exit__(None, None, None)
        doc = _wait_until(lambda: (lambda d: d["count"] == 1 and d)(
            json.loads(_get(base + f"/debug/requests?trace_id={tid}")[2])))
        assert doc and doc["count"] == 1
        rec = doc["requests"][0]
        assert rec["status"] == 504 and rec["outcome"] == "deadline"
        assert rec["timeout_s"] == pytest.approx(0.05)
        assert rec["duration_s"] >= 0.05  # the admission wait is in it
        # the span tree shows WHERE the time went: the admission wait
        # under serving/request, exited with error status
        tree = rec["spans"]
        assert tree and tree[0]["name"] == "serving/request"

        def _find(nodes, name):
            for n in nodes:
                if n["name"] == name:
                    return n
                hit = _find(n["children"], name)
                if hit is not None:
                    return hit
            return None

        adm = _find(tree, "serving/admission")
        assert adm is not None
        assert adm["args"]["error"] == "DeadlineExceededError"
        assert adm["dur"] >= 0.05 * 1e6  # waited the full budget (us)


# ---------------------------------------------------------------------------
# SLO burn-rate -> /readyz
# ---------------------------------------------------------------------------

class TestReadyzSLOGate:
    def test_fast_burn_flips_readyz(self, served, monkeypatch):
        _, server, base = served
        tracker = SLOTracker("mlp", objective=0.999,
                             latency_objective_s=None,
                             windows=((5.0, 1.0), (10.0, 1.0)),
                             min_samples=5)
        server.set_slo("mlp", tracker)
        code, _, body = _get(base + "/readyz")
        assert code == 200 and json.loads(body)["slo_healthy"]
        for _ in range(10):
            tracker.record(0.01, ok=False)
        code, _, body = _get(base + "/readyz")
        doc = json.loads(body)
        assert code == 503
        assert doc["ready"] is False and doc["slo_healthy"] is False
        assert doc["slo"]["mlp"]["windows"][0]["burn_rate"] > 1.0
        # the gate is an env knob: models stay warm, readyz recovers
        monkeypatch.setenv("DL4J_TPU_SLO_READYZ", "0")
        code, _, body = _get(base + "/readyz")
        assert code == 200
        assert json.loads(body)["slo_healthy"] is False  # still reported

    def test_slo_fed_by_http_outcomes(self, served):
        _, server, base = served
        tracker = SLOTracker("mlp", objective=0.9,
                             latency_objective_s=None,
                             windows=((60.0, 1.0),), min_samples=1)
        server.set_slo("mlp", tracker)
        code, _, _ = _post(base + "/v1/models/mlp/predict",
                           json.dumps({"inputs": _x().tolist()}).encode())
        assert code == 200
        _wait_until(lambda: tracker._counts(60.0)[1] == 1)
        assert tracker.hit_rate(60.0) == 1.0
        # a 404 (client mistake) must NOT count against the SLO
        _post(base + "/v1/models/nope/predict",
              json.dumps({"inputs": _x().tolist()}).encode())
        time.sleep(0.1)  # give its (absent) bookkeeping a chance to land
        assert tracker._counts(60.0)[1] == 1


# ---------------------------------------------------------------------------
# /debug endpoint family
# ---------------------------------------------------------------------------

class TestDebugEndpoints:
    def test_debug_requests_ring(self, served):
        _, server, base = served
        for i in range(3):
            _post(base + "/v1/models/mlp/predict",
                  json.dumps({"inputs": _x(2, seed=i).tolist()}).encode())
        _wait_until(lambda: len(server.request_ring) >= 3)
        doc = _wait_until(lambda: (lambda d: d["count"] == 2 and d)(
            json.loads(_get(base + "/debug/requests?n=2")[2])))
        assert doc and doc["count"] == 2
        rec = doc["requests"][0]
        assert rec["model"] == "mlp" and rec["outcome"] == "ok"
        assert rec["spans"][0]["name"] == "serving/request"

    def test_debug_trace_fetch(self, served):
        _, _, base = served
        tid = "fa" * 16
        _post(base + "/v1/models/mlp/predict",
              json.dumps({"inputs": _x().tolist()}).encode(),
              headers=[("traceparent", f"00-{tid}-{'cd' * 8}-01")])
        doc = _wait_until(lambda: (lambda d: d["count"] >= 3 and any(
            n["name"] == "serving/request" for n in d["tree"]) and d)(
                json.loads(_get(base + f"/debug/trace/{tid}")[2])))
        assert doc and doc["trace_id"] == tid
        assert doc["tree"][0]["name"] == "serving/request"

    def test_debug_slo_endpoint(self, served):
        _, server, base = served
        server.slo_for("mlp")
        code, _, body = _get(base + "/debug/slo")
        doc = json.loads(body)
        assert code == 200 and doc["healthy"] is True
        assert "mlp" in doc["models"]

    def test_debug_compile_cache_inventory(self, served):
        _, _, base = served
        code, _, body = _get(base + "/debug/compile_cache")
        doc = json.loads(body)
        assert code == 200 and doc["enabled"]
        # the deploy's warmup populated the store (conftest pins the dir)
        assert doc["entry_count"] >= 1 and doc["entries"]
        entry = doc["entries"][0]
        assert entry["payload_bytes"] > 0 and entry["key"]
        costed = [e for e in doc["entries"] if "cost" in e]
        assert costed, "warmup-compiled entries carry XLA cost analysis"
        assert costed[0]["cost"].get("flops", 0) > 0

    def test_debug_memory(self, served):
        _, _, base = served
        code, _, body = _get(base + "/debug/memory")
        doc = json.loads(body)
        assert code == 200
        assert len(doc["devices"]) >= 1
        assert doc["devices"][0]["platform"] == "cpu"

    def test_debug_profile_capture_loadable(self, served, tmp_path,
                                            monkeypatch):
        """Acceptance: POST /debug/profile produces a loadable jax
        profiler capture (an .xplane.pb on disk)."""
        monkeypatch.setenv("DL4J_TPU_PROFILE_DIR", str(tmp_path))
        _, _, base = served
        code, _, body = _post(base + "/debug/profile?seconds=0.2")
        doc = json.loads(body)
        assert code == 200, doc
        assert os.path.isdir(doc["path"])
        xplanes = [f for f in doc["files"]
                   if f["file"].endswith(".xplane.pb")]
        assert xplanes and xplanes[0]["bytes"] > 0
        on_disk = os.path.join(doc["path"], xplanes[0]["file"])
        assert os.path.getsize(on_disk) == xplanes[0]["bytes"]

    def test_debug_profile_rejects_bad_seconds(self, served):
        _, _, base = served
        code, _, body = _post(base + "/debug/profile?seconds=abc")
        assert code == 400

    def test_debug_disabled_by_env(self, served, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DEBUG_ENDPOINTS", "0")
        _, _, base = served
        for path in ("/debug/requests", "/debug/memory",
                     "/debug/compile_cache"):
            code, _, _ = _get(base + path)
            assert code == 404
        code, _, _ = _post(base + "/debug/profile?seconds=0.1")
        assert code == 404

    def test_ui_server_shares_debug_family(self):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer(port=0)
        port = ui.start()
        base = f"http://127.0.0.1:{port}"
        try:
            code, _, body = _get(base + "/debug/memory")
            assert code == 200 and json.loads(body)["devices"]
            code, _, body = _get(base + "/debug/compile_cache")
            assert code == 200 and json.loads(body)["enabled"]
            tid = new_trace_id()
            with use_context(TraceContext(tid)):
                with span("ui_debug_probe"):
                    pass
            code, _, body = _get(base + f"/debug/trace/{tid}")
            assert code == 200
            assert json.loads(body)["tree"][0]["name"] == "ui_debug_probe"
        finally:
            ui.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_dump_writes_ring_and_traces(self, served, tmp_path):
        reg, server, base = served
        tid = "bb" * 16
        _post(base + "/v1/models/mlp/predict",
              json.dumps({"inputs": _x().tolist()}).encode(),
              headers=[("traceparent", f"00-{tid}-{'cd' * 8}-01")])
        _wait_until(lambda: server.request_ring.find(tid) is not None)
        life = GracefulLifecycle(reg, server)
        path = str(tmp_path / "dump" / "flight.json")
        written = life.dump_flight_recorder(path)
        assert written == path
        doc = json.loads(open(path).read())
        assert any(r["trace_id"] == tid for r in doc["requests"])
        assert any(e.get("args", {}).get("trace_id") == tid
                   for e in doc["trace_events"])
        assert "mlp" in doc["slo"] or doc["slo"] == {}
        assert "dl4j_serving_requests_total" in doc["metrics"]

    def test_drain_dumps_flight_recorder(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
        reg = ModelRegistry(manifest_dir=None)
        reg.deploy("mlp", "v1", _mlp(0), example=_x())
        server = ModelServer(reg)
        port = server.start()
        _post(f"http://127.0.0.1:{port}/v1/models/mlp/predict",
              json.dumps({"inputs": _x().tolist()}).encode())
        _wait_until(lambda: len(server.request_ring) >= 1)
        life = GracefulLifecycle(reg, server, drain_timeout_s=10)
        assert life.drain()
        dumps = [p for p in os.listdir(tmp_path)
                 if p.startswith("flight-") and p.endswith(".json")]
        assert len(dumps) == 1
        doc = json.loads((tmp_path / dumps[0]).read_text())
        assert doc["draining"] is True
        assert len(doc["requests"]) >= 1

    def test_disabled_without_dir(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR", "")
        monkeypatch.setenv("DL4J_TPU_CACHE_DIR", "")
        reg = ModelRegistry(manifest_dir=None)
        life = GracefulLifecycle(reg, None)
        assert life.dump_flight_recorder() is None
