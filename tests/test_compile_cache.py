"""AOT compile cache: keying, store round trips, corruption recovery,
warmup concurrency/idempotence, and the warmup manifest.

The cache contract under test (ISSUE 4 acceptance): same config -> hit;
changed dtype / batch bucket / donation / remat-grad_accum knob / mesh
spec -> miss; corrupted cache file -> recompile + warning, never an
exception; DL4J_TPU_CACHE_DIR="" disables everything.
"""
import json
import logging
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.common.environment import (SystemProperties,
                                                   environment)
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.runtime import compile_cache
from deeplearning4j_tpu.runtime.compile_cache import (AOTCompileCache,
                                                      cache_key)
from deeplearning4j_tpu.runtime.inference import InferenceEngine, counted_jit


@pytest.fixture
def fresh_cache(tmp_path):
    """A private cache dir for one test, resolved through the real env
    layering, restored afterwards."""
    env = environment()
    prev = env.property_override(SystemProperties.CACHE_DIR)
    env.set_cache_dir(str(tmp_path))
    compile_cache.reset_cache()
    yield compile_cache.cache()
    if prev is None:
        env.clear_property(SystemProperties.CACHE_DIR)
    else:
        env.set_property(SystemProperties.CACHE_DIR, prev)
    compile_cache.reset_cache()


def _model(p, x):
    for w in p:
        x = jnp.tanh(x @ w)
    return x


def _params(n=3, d=16, dtype=jnp.float32):
    return [jnp.full((d, d), 0.1, dtype) for _ in range(n)]


def _x(b=4, d=16, dtype=jnp.float32):
    return jnp.ones((b, d), dtype)


def _key_of(fn, *args, **jit_kwargs):
    return cache_key(jax.jit(fn, **jit_kwargs).lower(*args), jit_kwargs)


# ---------------------------------------------------------------------------
# cache keying
# ---------------------------------------------------------------------------

class TestCacheKey:
    def test_same_config_same_key(self):
        k1 = _key_of(_model, _params(), _x())
        k2 = _key_of(_model, _params(), _x())
        assert k1 == k2

    def test_changed_dtype_misses(self):
        k1 = _key_of(_model, _params(), _x())
        k2 = _key_of(_model, _params(dtype=jnp.bfloat16),
                     _x(dtype=jnp.bfloat16))
        assert k1 != k2

    def test_changed_batch_bucket_misses(self):
        assert _key_of(_model, _params(), _x(b=4)) != \
            _key_of(_model, _params(), _x(b=8))

    def test_changed_model_structure_misses(self):
        # same input signature, different closure -> different program
        assert _key_of(_model, _params(n=3), _x()) != \
            _key_of(_model, _params(n=4), _x())

    def test_donation_misses(self):
        def addone(x):
            return x + 1.0  # same shape: the donation is actually usable

        k1 = _key_of(addone, _x())
        k2 = _key_of(addone, _x(), donate_argnums=(0,))
        assert k1 != k2

    def test_remat_knob_misses(self):
        env = environment()
        k1 = _key_of(_model, _params(), _x())
        env.set_training_remat("layer")
        try:
            k2 = _key_of(_model, _params(), _x())
        finally:
            env.clear_property(SystemProperties.TRAINING_REMAT)
        assert k1 != k2

    def test_grad_accum_knob_misses(self):
        env = environment()
        k1 = _key_of(_model, _params(), _x())
        env.set_training_grad_accum(4)
        try:
            k2 = _key_of(_model, _params(), _x())
        finally:
            env.clear_property(SystemProperties.TRAINING_GRAD_ACCUM)
        assert k1 != k2

    def test_mesh_spec_misses(self):
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec as P)

        devs = np.asarray(jax.devices()[:2]).reshape(2)
        mesh = Mesh(devs, ("data",))
        repl = NamedSharding(mesh, P())
        sharded = NamedSharding(mesh, P("data"))
        k1 = _key_of(_model, _params(), _x(),
                     in_shardings=(repl, repl))
        k2 = _key_of(_model, _params(), _x(),
                     in_shardings=(repl, sharded))
        assert k1 != k2


# ---------------------------------------------------------------------------
# store round trip through counted_jit
# ---------------------------------------------------------------------------

class TestStoreRoundTrip:
    def test_miss_then_hit_with_identical_result(self, fresh_cache):
        cc = fresh_cache
        f1 = counted_jit(_model, tag="tcc:1")
        ref = np.asarray(f1(_params(), _x()))
        assert cc.stats["misses"] == 1 and cc.stats["puts"] == 1
        assert cc.entry_count() == 1

        jax.clear_caches()  # drop in-memory jax caches: "restart"
        f2 = counted_jit(_model, tag="tcc:2")
        out = np.asarray(f2(_params(), _x()))
        assert cc.stats["hits"] == 1
        np.testing.assert_array_equal(ref, out)

    def test_hit_entry_survives_repeated_calls(self, fresh_cache):
        f1 = counted_jit(_model, tag="tcc:1")
        ref = np.asarray(f1(_params(), _x()))
        jax.clear_caches()
        f2 = counted_jit(_model, tag="tcc:2")
        for _ in range(3):
            np.testing.assert_array_equal(np.asarray(f2(_params(), _x())),
                                          ref)

    def test_pytree_output_round_trip(self, fresh_cache):
        def fn(p, x):
            return {"h": x @ p[0], "n": jnp.sum(x)}

        f1 = counted_jit(fn, tag="tcc:1")
        ref = f1(_params(1), _x())
        jax.clear_caches()
        f2 = counted_jit(fn, tag="tcc:2")
        out = f2(_params(1), _x())
        assert fresh_cache.stats["hits"] == 1
        assert set(out) == {"h", "n"}
        np.testing.assert_array_equal(np.asarray(ref["h"]),
                                      np.asarray(out["h"]))
        np.testing.assert_array_equal(np.asarray(ref["n"]),
                                      np.asarray(out["n"]))

    def test_compile_seconds_histogram_labels(self, fresh_cache):
        f1 = counted_jit(_model, tag="tsec:1")
        f1(_params(), _x())
        jax.clear_caches()
        f2 = counted_jit(_model, tag="tsec:2")
        f2(_params(), _x())
        fam = registry().get("dl4j_compile_seconds")
        assert fam is not None
        labels = {key for key, _ in fam.children()}
        assert ("tsec", "miss") in labels
        assert ("tsec", "hit") in labels

    def test_donated_entries_bypass_the_store(self, fresh_cache):
        cc = fresh_cache
        f = counted_jit(lambda p, x: [w + x.sum() for w in p], tag="tdon:1",
                        donate_argnums=(0,))
        f(_params(), _x())
        assert cc.stats["puts"] == 0  # never serialized
        fam = registry().get("dl4j_compiles_total")
        assert any(key == ("tdon", "bypass") for key, _ in fam.children())

    def test_stale_entry_falls_back_to_live_jit(self, fresh_cache):
        f = counted_jit(lambda p, x: x @ p, tag="tstale:1")
        f(jnp.ones((16, 16)), _x())
        # same data signature (x), params re-initialized with a NEW shape:
        # the AOT entry cannot accept the call and must fall back, not raise
        out = f(jnp.ones((16, 32)), _x())
        assert out.shape == (4, 32)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_x() @ jnp.ones((16, 32))),
                                   rtol=1e-6)

    def test_sharded_predict_hits_store_on_warm_restart(self, fresh_cache):
        # the fleet regression: a mesh-sharded predict executable must be
        # a raw-store HIT after restart (reloaded with its device
        # assignment and in/out shardings), not a silent bypass
        from deeplearning4j_tpu.common.mesh import (MODEL, serving_mesh,
                                                    shard_params)
        from jax.sharding import NamedSharding, PartitionSpec as P

        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices")
        cc = fresh_cache
        mesh = serving_mesh()
        params = shard_params(mesh, _params())
        x = jax.device_put(_x(), NamedSharding(mesh, P()))
        f1 = counted_jit(_model, tag="tshard:1")
        ref = np.asarray(f1(params, x))
        assert cc.stats["misses"] == 1 and cc.stats["puts"] == 1

        jax.clear_caches()  # "restart"
        f2 = counted_jit(_model, tag="tshard:2")
        out = f2(params, x)
        assert cc.stats["hits"] == 1, \
            "sharded executable must round-trip the raw store"
        np.testing.assert_array_equal(ref, np.asarray(out))
        # the reloaded output is still mesh-sharded, not silently gathered
        assert isinstance(out.sharding, NamedSharding)
        assert out.sharding.spec == P(None, MODEL)

    def test_sharded_and_host_args_key_separately(self, fresh_cache):
        from deeplearning4j_tpu.common.mesh import serving_mesh, shard_params

        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices")
        cc = fresh_cache
        mesh = serving_mesh()
        f = counted_jit(_model, tag="tsk:1")
        f(_params(), _x())
        f2 = counted_jit(_model, tag="tsk:2")
        f2(shard_params(mesh, _params()), _x())
        # same shapes, different placement: two distinct entries
        assert cc.stats["puts"] == 2 and cc.entry_count() == 2

    def test_disabled_via_empty_dir(self):
        env = environment()
        prev = env.property_override(SystemProperties.CACHE_DIR)
        env.set_cache_dir("")
        compile_cache.reset_cache()
        try:
            assert compile_cache.cache() is None
            f = counted_jit(_model, tag="toff:1")
            out = f(_params(), _x())
            assert out.shape == (4, 16)
            fam = registry().get("dl4j_compiles_total")
            assert any(key == ("toff", "bypass")
                       for key, _ in fam.children())
        finally:
            if prev is None:
                env.clear_property(SystemProperties.CACHE_DIR)
            else:
                env.set_property(SystemProperties.CACHE_DIR, prev)
            compile_cache.reset_cache()


# ---------------------------------------------------------------------------
# corruption recovery: a bad cache may cost a compile, never an exception
# ---------------------------------------------------------------------------

def _entry_files(cc, ext):
    return [os.path.join(cc.aot_dir, n) for n in os.listdir(cc.aot_dir)
            if n.endswith(ext)]


class TestCorruptionRecovery:
    def _seed_entry(self, cc):
        f = counted_jit(_model, tag="tcor:seed")
        ref = np.asarray(f(_params(), _x()))
        assert cc.entry_count() == 1
        jax.clear_caches()
        return ref

    def _rerun(self):
        f = counted_jit(_model, tag="tcor:rerun")
        return np.asarray(f(_params(), _x()))

    def test_corrupt_payload_recompiles_with_warning(self, fresh_cache,
                                                     caplog):
        ref = self._seed_entry(fresh_cache)
        for p in _entry_files(fresh_cache, ".bin"):
            with open(p, "wb") as fh:
                fh.write(b"garbage")
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.runtime"
                                    ".compile_cache"):
            out = self._rerun()
        np.testing.assert_array_equal(ref, out)
        assert fresh_cache.stats["corrupt"] >= 1
        assert any("recompiling" in r.getMessage() for r in caplog.records)
        # the recompile re-stored a valid entry
        assert fresh_cache.stats["puts"] == 2

    def test_corrupt_meta_recompiles(self, fresh_cache):
        ref = self._seed_entry(fresh_cache)
        for p in _entry_files(fresh_cache, ".json"):
            with open(p, "w") as fh:
                fh.write("{not json")
        out = self._rerun()
        np.testing.assert_array_equal(ref, out)
        assert fresh_cache.stats["corrupt"] >= 1

    def test_format_version_mismatch_recompiles(self, fresh_cache):
        ref = self._seed_entry(fresh_cache)
        for p in _entry_files(fresh_cache, ".json"):
            with open(p) as fh:
                meta = json.load(fh)
            meta["format"] = 999
            with open(p, "w") as fh:
                json.dump(meta, fh)
        out = self._rerun()
        np.testing.assert_array_equal(ref, out)
        assert fresh_cache.stats["corrupt"] >= 1

    def test_undeserializable_payload_recompiles(self, fresh_cache):
        """Payload passes the checksum but is not an executable (stale
        artifact from another backend): deserialize fails -> recompile."""
        ref = self._seed_entry(fresh_cache)
        for p in _entry_files(fresh_cache, ".bin"):
            key = os.path.basename(p)[:-4]
            meta_p = os.path.join(fresh_cache.aot_dir, key + ".json")
            with open(meta_p) as fh:
                meta = json.load(fh)
            fresh_cache.put(key, b"not-an-executable",
                            {"kept_var_idx": meta["kept_var_idx"]})
        out = self._rerun()
        np.testing.assert_array_equal(ref, out)

    def test_truncated_payload_recompiles(self, fresh_cache):
        ref = self._seed_entry(fresh_cache)
        for p in _entry_files(fresh_cache, ".bin"):
            with open(p, "rb") as fh:
                data = fh.read()
            with open(p, "wb") as fh:
                fh.write(data[:len(data) // 2])
        out = self._rerun()
        np.testing.assert_array_equal(ref, out)
        assert fresh_cache.stats["corrupt"] >= 1


# ---------------------------------------------------------------------------
# LRU size capping
# ---------------------------------------------------------------------------

class TestLRUCap:
    def test_oldest_entry_evicted_beyond_cap(self, tmp_path):
        cc = AOTCompileCache(str(tmp_path), max_bytes=100)
        cc.put("k1", b"x" * 80, {"kept_var_idx": [0]})
        old = os.path.join(cc.aot_dir, "k1.bin")
        os.utime(old, (1.0, 1.0))  # force k1 to be the LRU entry
        cc.put("k2", b"y" * 80, {"kept_var_idx": [0]})
        assert cc.stats["evictions"] >= 1
        assert cc.get("k1") is None
        got = cc.get("k2")
        assert got is not None and got[0] == b"y" * 80

    def test_hit_refreshes_recency(self, tmp_path):
        cc = AOTCompileCache(str(tmp_path), max_bytes=180)
        cc.put("k1", b"x" * 80, {"kept_var_idx": [0]})
        cc.put("k2", b"y" * 80, {"kept_var_idx": [0]})
        for p in (os.path.join(cc.aot_dir, "k1.bin"),
                  os.path.join(cc.aot_dir, "k2.bin")):
            os.utime(p, (1.0, 1.0))
        assert cc.get("k1") is not None  # touch k1: k2 becomes LRU
        cc.put("k3", b"z" * 80, {"kept_var_idx": [0]})
        assert cc.get("k1") is not None
        assert cc.get("k2") is None

    def test_uncapped_when_nonpositive(self, tmp_path):
        cc = AOTCompileCache(str(tmp_path), max_bytes=0)
        for i in range(5):
            cc.put(f"k{i}", b"x" * 1000, {"kept_var_idx": [0]})
        assert cc.entry_count() == 5
        assert cc.stats["evictions"] == 0


# ---------------------------------------------------------------------------
# eligibility (what may be wrapped as a raw executable)
# ---------------------------------------------------------------------------

class TestEligibility:
    def test_plain_arrays_eligible(self):
        assert compile_cache._eligible((_params(), _x()), {})

    def test_python_scalars_eligible(self):
        assert compile_cache._eligible((_params(), 3, 0.5, True), {})

    def test_donation_ineligible(self):
        assert not compile_cache._eligible((_params(), _x()),
                                           {"donate_argnums": (0,)})

    def test_shardings_ineligible(self):
        assert not compile_cache._eligible((_params(), _x()),
                                           {"in_shardings": object()})

    def test_prng_key_ineligible(self):
        assert not compile_cache._eligible((_x(), jax.random.key(0)), {})

    def test_multi_device_array_eligible(self):
        # mesh-sharded committed args joined the raw store (their device
        # assignment + shardings fold into the cache key and the entry
        # meta carries the shardings for reload)
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec as P)

        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices")
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        x = jax.device_put(_x(b=4), NamedSharding(mesh, P("data")))
        assert compile_cache._eligible((x,), {})

    def test_placement_fingerprint_distinguishes_shardings(self):
        # the same shapes on different layouts must key differently —
        # a replicated and a sharded executable are not interchangeable
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec as P)

        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices")
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
        host = (_x(b=4),)
        sharded = (jax.device_put(_x(b=4),
                                  NamedSharding(mesh, P("data"))),)
        repl = (jax.device_put(_x(b=4), NamedSharding(mesh, P())),)
        fps = {compile_cache._placement_fingerprint(a)
               for a in (host, sharded, repl)}
        assert len(fps) == 3


# ---------------------------------------------------------------------------
# warmup: concurrency, idempotence, manifest
# ---------------------------------------------------------------------------

def _mlp(n_in=6, hidden=8, n_out=3):
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_in=hidden, n_out=n_out))
            .build())
    return MultiLayerNetwork(conf).init()


def _req(b=1, n_in=6):
    return jnp.zeros((b, n_in), jnp.float32)


class TestWarmupGuard:
    def test_warmup_idempotent(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        assert eng.warmup(_req()) == [1, 2, 4, 8]
        d0 = eng.stats()["dispatches"]
        assert d0 == 4
        assert eng.warmup(_req()) == [1, 2, 4, 8]  # same buckets reported
        assert eng.stats()["dispatches"] == d0     # nothing re-dispatched

    def test_concurrent_warmup_no_double_compile(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        barrier = threading.Barrier(2)
        results, errors = [], []

        def go():
            try:
                barrier.wait(timeout=30)
                results.append(eng.warmup(_req()))
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append(e)

        threads = [threading.Thread(target=go) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors
        assert results == [[1, 2, 4, 8], [1, 2, 4, 8]]
        # each bucket dispatched (and therefore compiled) exactly once
        assert eng.stats()["dispatches"] == 4
        assert all(v == 1
                   for v in eng.stats()["bucket_dispatches"].values())

    def test_warmup_serial_worker_override(self):
        eng = InferenceEngine(_mlp(), max_batch=4)
        assert eng.warmup(_req(), workers=1) == [1, 2, 4]
        assert eng.stats()["dispatches"] == 3


class TestWarmupManifest:
    def test_traffic_records_manifest(self, tmp_path):
        man = str(tmp_path / "warmup.json")
        eng = InferenceEngine(_mlp(), max_batch=8, manifest_path=man)
        eng.infer(_req(b=3))  # bucket 4
        eng.infer(_req(b=1))  # bucket 1
        assert os.path.exists(man)
        with open(man) as f:
            doc = json.load(f)
        assert doc["version"] == 1
        buckets = sorted(b for e in doc["entries"] for b in e["buckets"])
        assert buckets == [1, 4]
        assert doc["entries"][0]["inputs"][0]["shape"] == [6]

    def test_restart_replays_manifest(self, tmp_path):
        man = str(tmp_path / "warmup.json")
        eng = InferenceEngine(_mlp(), max_batch=8, manifest_path=man)
        eng.infer(_req(b=3))
        eng.infer(_req(b=7))  # bucket 8

        # "restart": fresh model + engine, warmup with no example replays
        eng2 = InferenceEngine(_mlp(), max_batch=8, manifest_path=man)
        env = environment()
        c0 = env.compile_count()
        assert eng2.warmup() == [4, 8]
        warm_compiles = env.compile_count() - c0
        assert warm_compiles == 2
        # yesterday's shapes now serve without compiling anything new
        eng2.infer(_req(b=3))
        eng2.infer(_req(b=7))
        assert env.compile_count() - c0 == warm_compiles

    def test_explicit_save_and_replay(self, tmp_path):
        eng = InferenceEngine(_mlp(), max_batch=8)
        eng.infer(_req(b=2))
        path = eng.save_manifest(str(tmp_path / "m.json"))
        entries = InferenceEngine.load_manifest(path)
        assert entries and entries[0]["buckets"] == [2]

    def test_save_without_path_raises(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        with pytest.raises(ValueError):
            eng.save_manifest()

    def test_corrupt_manifest_skipped_with_warning(self, tmp_path, caplog):
        man = tmp_path / "warmup.json"
        man.write_text("{broken")
        eng = InferenceEngine(_mlp(), max_batch=8,
                              manifest_path=str(man))
        with caplog.at_level(logging.WARNING):
            assert eng.warmup() == []  # skipped, no exception
        assert any("unreadable" in r.getMessage() for r in caplog.records)

    def test_warmup_without_example_or_manifest_is_noop(self):
        eng = InferenceEngine(_mlp(), max_batch=8)
        assert eng.warmup() == []
        assert eng.stats()["dispatches"] == 0


# ---------------------------------------------------------------------------
# warm_compile (CI cache pre-baking for train steps)
# ---------------------------------------------------------------------------

class TestWarmCompile:
    def test_warm_compile_populates_backstop_without_stepping(
            self, fresh_cache, monkeypatch):
        # the backstop defaults off on the CPU backend (DL4J_TPU_XLA_CACHE
        # =auto); force it on to exercise the wiring
        monkeypatch.setenv("DL4J_TPU_XLA_CACHE", "on")
        compile_cache.reset_cache()
        try:
            net = _mlp()
            before = jax.tree_util.tree_map(np.asarray, net._params)
            x = np.random.RandomState(0).randn(8, 6).astype(np.float32)
            y = np.zeros((8, 3), np.float32)
            y[np.arange(8), np.arange(8) % 3] = 1.0
            label = net.warm_compile(x, y)
            assert label == "bypass"  # donated train steps: backstop only
            # params untouched (nothing executed, nothing donated)
            after = jax.tree_util.tree_map(np.asarray, net._params)
            for b, a in zip(jax.tree_util.tree_leaves(before),
                            jax.tree_util.tree_leaves(after)):
                np.testing.assert_array_equal(b, a)
            xla_dir = os.path.join(fresh_cache.base_dir, "xla")
            assert os.path.isdir(xla_dir) and os.listdir(xla_dir)
        finally:
            # detach the backstop before the env var reverts to auto —
            # fixture teardown order must not leave it wired for the
            # rest of the suite
            monkeypatch.setenv("DL4J_TPU_XLA_CACHE", "off")
            compile_cache.reset_cache()

    def test_backstop_defaults_off_on_cpu(self, fresh_cache):
        """DL4J_TPU_XLA_CACHE=auto: on the CPU backend the store is
        active but jax's compilation-cache dir stays unwired (XLA:CPU
        deserialized-executable instability; see _backstop_wanted)."""
        assert environment().xla_cache() == "auto"
        assert fresh_cache is not None  # the store itself is on
        assert not compile_cache._backstop_wanted()
        assert jax.config.jax_compilation_cache_dir is None

    def test_warm_buckets_precompiles_direct_output_path(self):
        net = _mlp()
        env = environment()
        c0 = env.compile_count()
        warmed = net.warm_buckets(_req(), batch_sizes=[1, 3])
        assert warmed == [1, 4]
        compiles = env.compile_count() - c0
        assert compiles == 2
        # the direct output() path reuses the warmed executables
        net.output(_req(b=3))
        assert env.compile_count() - c0 == compiles


# ---------------------------------------------------------------------------
# attention auto-dispatch satellite
# ---------------------------------------------------------------------------

class TestAttentionDispatch:
    def test_threshold_default(self):
        from deeplearning4j_tpu.kernels import attention_dispatch

        assert environment().flash_min_seq() == 1024
        assert attention_dispatch(128) == "xla"
        assert attention_dispatch(1024) == "flash"
        assert attention_dispatch(4096) == "flash"

    def test_threshold_env_override(self):
        from deeplearning4j_tpu.kernels import attention_dispatch

        env = environment()
        env.set_flash_min_seq(64)
        try:
            assert attention_dispatch(128) == "flash"
            assert attention_dispatch(32) == "xla"
        finally:
            env.clear_property(SystemProperties.FLASH_MIN_SEQ)

    def test_dispatch_counter(self):
        from deeplearning4j_tpu.kernels import attention_dispatch

        fam = registry().counter("dl4j_attn_dispatch_total",
                                 "Attention path decisions for flash=True "
                                 "configs", labels=("path",))
        x0 = fam.labels(path="xla").value()
        f0 = fam.labels(path="flash").value()
        attention_dispatch(8)
        attention_dispatch(8192)
        assert fam.labels(path="xla").value() == x0 + 1
        assert fam.labels(path="flash").value() == f0 + 1

    def test_bert_flash_below_threshold_takes_xla_path(self):
        """flash=True at short seq must produce bitwise the XLA result —
        proof the dispatch silently switched paths."""
        from deeplearning4j_tpu.models import bert

        config = bert.BertConfig.tiny()
        params = bert.init_params(jax.random.key(0), config)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, config.vocab_size, (2, 16)),
                          jnp.int32)
        out_flash = bert.encode(params, ids, config=config, use_flash=True)
        out_xla = bert.encode(params, ids, config=config, use_flash=False)
        np.testing.assert_array_equal(np.asarray(out_flash),
                                      np.asarray(out_xla))


# ---------------------------------------------------------------------------
# pluggable artifact stores: tiers, concurrent writers, fleet handoff
# ---------------------------------------------------------------------------

@pytest.fixture
def tiered_cache(tmp_path):
    """Local + remote tiered cache over private dirs (the shared-store
    deployment in miniature), env triple restored afterwards."""
    env = environment()
    saved = {p: env.property_override(p)
             for p in (SystemProperties.CACHE_DIR,
                       SystemProperties.REMOTE_CACHE,
                       SystemProperties.CACHE_TIER)}
    env.set_cache_dir(str(tmp_path / "local"))
    env.set_remote_cache(str(tmp_path / "remote"))
    env.set_cache_tier("auto")
    compile_cache.reset_cache()
    yield compile_cache.cache()
    for prop, value in saved.items():
        if value is None:
            env.clear_property(prop)
        else:
            env.set_property(prop, value)
    compile_cache.reset_cache()


def _remote_paths(store, key):
    return store._paths(key)


class TestArtifactStores:
    def test_default_store_is_local_dir(self, fresh_cache):
        """No remote configured -> behavior-identical LocalDirStore with
        today's flat <base>/aot layout."""
        assert isinstance(fresh_cache.store,
                          compile_cache.LocalDirStore)
        assert fresh_cache.aot_dir.endswith(os.path.join("", "aot"))
        fresh_cache.put("k1", b"payload", {"kept_var_idx": [0]})
        assert os.path.exists(os.path.join(fresh_cache.aot_dir, "k1.bin"))
        assert os.path.exists(os.path.join(fresh_cache.aot_dir, "k1.json"))
        tiers = fresh_cache.store.tiers()
        assert [t.tier for t in tiers] == ["local"]
        assert tiers[0].describe()["backend"] == "local-dir"

    def test_tiered_put_populates_both_tiers(self, tiered_cache):
        assert isinstance(tiered_cache.store, compile_cache.TieredStore)
        tiered_cache.put("ab" * 32, b"payload", {"kept_var_idx": [0]})
        store = tiered_cache.store
        assert store.local.contains("ab" * 32)
        assert store.remote.contains("ab" * 32)
        # content-addressed remote layout: objects/<key[:2]>/<key>.bin
        payload_p, _ = _remote_paths(store.remote, "ab" * 32)
        assert os.sep + os.path.join("objects", "ab") + os.sep in payload_p

    def test_local_miss_falls_through_and_backfills(self, tiered_cache):
        tiered_cache.put("cd" * 32, b"payload", {"kept_var_idx": [0]})
        tiered_cache.store.local.clear()
        assert not tiered_cache.store.local.contains("cd" * 32)
        got = tiered_cache.get("cd" * 32)
        assert got is not None and got[0] == b"payload"
        assert tiered_cache.stats["hits"] == 1
        # the remote hit was written back into the local tier
        assert tiered_cache.store.local.contains("cd" * 32)

    def test_corrupt_local_refetches_from_remote(self, tiered_cache,
                                                 caplog):
        """Digest mismatch on the local copy -> delete + transparent
        refetch from the shared store, surfaced on the existing
        corruption warning path."""
        tiered_cache.put("ef" * 32, b"payload", {"kept_var_idx": [0]})
        with open(os.path.join(tiered_cache.aot_dir,
                               "ef" * 32 + ".bin"), "wb") as fh:
            fh.write(b"garbage")
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.runtime"
                                    ".compile_cache"):
            got = tiered_cache.get("ef" * 32)
        assert got is not None and got[0] == b"payload"
        assert tiered_cache.stats["corrupt"] == 1
        assert any("refetched from remote" in r.getMessage()
                   for r in caplog.records)
        # the backfill healed the local copy
        healed = tiered_cache.store.local.get("ef" * 32)
        assert healed is not None and healed[0] == b"payload"

    def test_corrupt_remote_deleted_with_warning(self, tiered_cache,
                                                 caplog):
        """A bad shared-store entry is deleted for the whole fleet and
        reported as a miss via the existing recompiling warning."""
        store = tiered_cache.store
        store.remote.put("12" * 32, b"payload",
                         compile_cache._stamp_meta(b"payload", {}))
        payload_p, _ = _remote_paths(store.remote, "12" * 32)
        with open(payload_p, "wb") as fh:
            fh.write(b"garbage")
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.runtime"
                                    ".compile_cache"):
            assert tiered_cache.get("12" * 32) is None
        assert tiered_cache.stats["corrupt"] == 1
        assert tiered_cache.stats["misses"] == 1
        assert not store.remote.contains("12" * 32)
        assert any("recompiling" in r.getMessage()
                   for r in caplog.records)

    def test_half_written_entry_detected_and_dropped(self, tmp_path):
        """Satellite regression: an interleaved half-written entry (a
        writer that died mid-payload AFTER the meta landed) must fail the
        digest check and be deleted, never served."""
        store = compile_cache.RemoteStore(str(tmp_path))
        meta = compile_cache._stamp_meta(b"full-payload-bytes", {})
        store.put("ab" * 32, b"full-payload-bytes", meta)
        payload_p, _ = _remote_paths(store, "ab" * 32)
        with open(payload_p, "wb") as fh:
            fh.write(b"full-pay")  # torn write: correct prefix, truncated
        with pytest.raises(compile_cache.CorruptEntryError):
            store.get("ab" * 32)
        assert not store.contains("ab" * 32)
        # a crashed writer's leftover tmp file is not an entry either
        with open(payload_p + compile_cache._tmp_suffix(), "wb") as fh:
            fh.write(b"partial")
        assert store.keys() == []
        assert store.stat()["entries"] == 0

    def test_concurrent_same_key_writers_converge(self, tmp_path):
        """N threads racing a put of the same key: unique tmp files +
        atomic rename mean the survivor is always a valid entry."""
        store = compile_cache.RemoteStore(str(tmp_path))
        payload = b"x" * 4096
        meta = compile_cache._stamp_meta(payload, {"kept_var_idx": [0]})
        errs = []

        def writer():
            try:
                for _ in range(20):
                    assert store.put("fe" * 32, payload, meta)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=writer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        got = store.get("fe" * 32)
        assert got is not None and got[0] == payload
        # no tmp litter survived the races
        shard = os.path.dirname(_remote_paths(store, "fe" * 32)[0])
        assert [n for n in os.listdir(shard) if ".tmp" in n] == []

    def test_tmp_suffixes_are_unique(self):
        out = set()

        def grab():
            for _ in range(50):
                out.add(compile_cache._tmp_suffix())

        threads = [threading.Thread(target=grab) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(out) == 200

    def test_remote_only_tier(self, tmp_path):
        env = environment()
        saved = {p: env.property_override(p)
                 for p in (SystemProperties.CACHE_DIR,
                           SystemProperties.REMOTE_CACHE,
                           SystemProperties.CACHE_TIER)}
        try:
            env.set_cache_dir(str(tmp_path / "base"))
            env.set_remote_cache(str(tmp_path / "remote"))
            env.set_cache_tier("remote")
            compile_cache.reset_cache()
            cc = compile_cache.cache()
            assert isinstance(cc.store, compile_cache.RemoteStore)
            assert cc.aot_dir is None
            cc.put("ba" * 32, b"payload", {"kept_var_idx": [0]})
            assert cc.get("ba" * 32)[0] == b"payload"
            assert cc.entry_count() == 1
        finally:
            for prop, value in saved.items():
                if value is None:
                    env.clear_property(prop)
                else:
                    env.set_property(prop, value)
            compile_cache.reset_cache()

    def test_shared_remote_not_lru_capped(self, tmp_path):
        """One replica's byte cap must never evict the fleet's shared
        entries: enforce_cap only prunes the local tier."""
        local = compile_cache.LocalDirStore(str(tmp_path / "l"))
        remote = compile_cache.RemoteStore(str(tmp_path / "r"))
        store = compile_cache.TieredStore(local, remote)
        for i in range(4):
            key = f"{i:02d}" * 32
            store.put(key, b"x" * 80,
                      compile_cache._stamp_meta(b"x" * 80, {}))
        assert store.enforce_cap(100) > 0
        assert local.stat()["bytes"] <= 100
        assert remote.stat()["entries"] == 4


class TestTieredInventory:
    def test_inventory_reports_tiers(self, tiered_cache):
        tiered_cache.put("aa" * 32, b"x" * 100, {"kept_var_idx": [0]})
        tiered_cache.put("bb" * 32, b"y" * 50, {"kept_var_idx": [0]})
        tiered_cache.store.local.delete("bb" * 32)  # remote-only entry
        inv = compile_cache.inventory()
        assert inv["enabled"] and inv["entry_count"] == 1
        by_tier = {t["tier"]: t for t in inv["tiers"]}
        assert set(by_tier) == {"local", "remote"}
        assert by_tier["local"]["backend"] == "local-dir"
        assert by_tier["remote"]["backend"] == "remote-fs"
        assert by_tier["local"]["entry_count"] == 1
        assert by_tier["remote"]["entry_count"] == 2
        assert by_tier["local"]["payload_bytes"] >= 100
        assert by_tier["remote"]["payload_bytes"] >= 150

    def test_debug_endpoint_serves_tier_listing(self, tiered_cache):
        """/debug/compile_cache with a tiered store: per-tier backend,
        entry counts, and bytes ride the existing inventory document."""
        import urllib.request

        from deeplearning4j_tpu.ui.server import UIServer

        tiered_cache.put("cc" * 32, b"z" * 64, {"kept_var_idx": [0]})
        ui = UIServer(port=0)
        port = ui.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/compile_cache",
                    timeout=5) as r:
                doc = json.loads(r.read())
        finally:
            ui.stop()
        assert doc["enabled"] and doc["entry_count"] == 1
        tiers = {t["tier"]: t for t in doc["tiers"]}
        assert tiers["local"]["entry_count"] == 1
        assert tiers["remote"]["entry_count"] == 1
        assert tiers["remote"]["payload_bytes"] >= 64

    def test_store_gauges_track_mutations(self, tiered_cache):
        reg = registry()
        tiered_cache.put("dd" * 32, b"p" * 128, {"kept_var_idx": [0]})
        g_entries = reg.get("dl4j_cache_store_entries")
        g_bytes = reg.get("dl4j_cache_store_bytes")
        assert g_entries.labels(tier="local").value() == 1
        assert g_entries.labels(tier="remote").value() == 1
        assert g_bytes.labels(tier="remote").value() >= 128
        tiered_cache.clear()  # local-only clear: remote keeps the entry
        assert g_entries.labels(tier="local").value() == 0
        assert g_entries.labels(tier="remote").value() == 1


class TestFleetHandoff:
    def test_push_to_remote_publishes_missing_entries(self, tmp_path):
        env = environment()
        saved = {p: env.property_override(p)
                 for p in (SystemProperties.CACHE_DIR,
                           SystemProperties.REMOTE_CACHE,
                           SystemProperties.CACHE_TIER)}
        try:
            # seed executables with NO remote configured (yesterday's
            # replica), then attach the shared store and push on drain
            env.set_cache_dir(str(tmp_path / "local"))
            env.set_remote_cache(None)
            compile_cache.reset_cache()
            cc = compile_cache.cache()
            cc.put("ab" * 32, b"one", {"kept_var_idx": [0]})
            cc.put("cd" * 32, b"two", {"kept_var_idx": [0]})
            mdir = compile_cache.serving_manifest_dir()
            with open(os.path.join(mdir, "toy.warmup.json"), "w") as fh:
                json.dump([{"inputs": [], "buckets": [1]}], fh)
            env.set_remote_cache(str(tmp_path / "remote"))
            compile_cache.reset_cache()
            pushed = compile_cache.push_to_remote()
            assert pushed == {"executables": 2, "manifests": 1}
            remote = compile_cache.RemoteStore(str(tmp_path / "remote"))
            assert remote.stat()["entries"] == 2
            assert os.path.exists(os.path.join(
                remote.manifest_dir(), "toy.warmup.json"))
            # idempotent: nothing new to publish the second time
            assert compile_cache.push_to_remote()["executables"] == 0
        finally:
            for prop, value in saved.items():
                if value is None:
                    env.clear_property(prop)
                else:
                    env.set_property(prop, value)
            compile_cache.reset_cache()

    def test_pull_from_remote_warms_empty_local(self, tmp_path):
        env = environment()
        saved = {p: env.property_override(p)
                 for p in (SystemProperties.CACHE_DIR,
                           SystemProperties.REMOTE_CACHE,
                           SystemProperties.CACHE_TIER)}
        try:
            remote = compile_cache.RemoteStore(str(tmp_path / "remote"))
            for key, payload in (("ab" * 32, b"one"), ("cd" * 32, b"two")):
                remote.put(key, payload,
                           compile_cache._stamp_meta(payload, {}))
            os.makedirs(remote.manifest_dir(), exist_ok=True)
            with open(os.path.join(remote.manifest_dir(),
                                   "toy.warmup.json"), "w") as fh:
                json.dump([{"inputs": [], "buckets": [1]}], fh)
            env.set_cache_dir(str(tmp_path / "local2"))  # empty joiner
            env.set_remote_cache(str(tmp_path / "remote"))
            compile_cache.reset_cache()
            pulled = compile_cache.pull_from_remote()
            assert pulled == {"executables": 2, "manifests": 1}
            cc = compile_cache.cache()
            assert cc.store.local.contains("ab" * 32)
            assert cc.store.local.contains("cd" * 32)
            assert os.path.exists(os.path.join(
                compile_cache.serving_manifest_dir(),
                "toy.warmup.json"))
            # the boot pull landed on the pull-latency histogram
            fam = registry().get("dl4j_cache_pull_seconds")
            hits = sum(child.count()
                       for key, child in fam.children()
                       if key == ("hit",))
            assert hits >= 2
        finally:
            for prop, value in saved.items():
                if value is None:
                    env.clear_property(prop)
                else:
                    env.set_property(prop, value)
            compile_cache.reset_cache()

    def test_handoff_noop_without_remote_store(self, fresh_cache):
        fresh_cache.put("ab" * 32, b"one", {"kept_var_idx": [0]})
        assert compile_cache.push_to_remote() == {"executables": 0,
                                                  "manifests": 0}
        assert compile_cache.pull_from_remote() == {"executables": 0,
                                                    "manifests": 0}
        assert compile_cache.pull_manifests() == 0
