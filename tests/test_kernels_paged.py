"""Pallas fast path round 2: the paged-flash-decode kernel and the fused
int8 dequant-matmul (kernels/paged_flash_decode + quant/transforms +
kernels dispatch plumbing).

Covers the acceptance contract of the kernel PR: the paged-flash kernel
is numerically a drop-in for the block-table gather it replaces (kernel
vs reference math, argmax-identical model logits for both the Q=1 decode
and the Q=k+1 speculative-verify shape, token-identical engine output
through greedy / speculative / prefix-cache warm attach); the fused
dequant-matmul matches the XLA cast-then-dot within the quant
deploy-gate divergence and keeps weights int8 at rest in the jitted HLO
(no full-precision weight tensor materializes); dispatch is decided at
trace time from pool tileability — never from the query length, so
spec-k configs cannot flap between paths (satellite 6) — and every
decision ticks ``dl4j_kernel_dispatch_total{kernel,path}`` and lands in
the ``/debug/decode`` snapshot; and a warm decode loop with the kernel
on performs zero steady-state recompiles.

CPU CI runs the kernel in Pallas interpret mode (``_interpret()`` —
identical math, XLA-inlined), which is exactly the fallback contract
MIGRATING.md documents.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.environment import (SystemProperties,
                                                   environment)
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.kernels import (attention_dispatch,
                                        dispatch_snapshot,
                                        paged_flash_decode)
from deeplearning4j_tpu.kernels.paged_flash_decode import tileable
from deeplearning4j_tpu.models import causal_lm
from deeplearning4j_tpu.quant.transforms import (QuantizedTensor,
                                                 dequant_matmul,
                                                 dequantize,
                                                 quantize_model,
                                                 quantize_tensor)
from deeplearning4j_tpu.runtime.generation import DecodeEngine
from deeplearning4j_tpu.runtime.inference import counted_jit

CFG = causal_lm.CausalLMConfig.tiny()

_KERNEL_HELP = ("Hand-written-kernel vs fallback path decisions per "
                "kernel family, evaluated at trace time")


@pytest.fixture(scope="module")
def model():
    return causal_lm.CausalLM(CFG, seed=0)


def _kernel_counter():
    return registry().counter("dl4j_kernel_dispatch_total", _KERNEL_HELP,
                              labels=("kernel", "path"))


def _paged_mode(mode):
    """Set DL4J_TPU_PAGED_KERNEL; caller restores via the returned fn."""
    env = environment()
    env.set_paged_kernel(mode)
    return lambda: env.clear_property(SystemProperties.PAGED_KERNEL)


def _reference_paged_attention(q, k_pages, v_pages, tables, lengths,
                               scale):
    """The exact XLA block-table-gather math the kernel replaces
    (mirrors models/causal_lm.paged_decode's fallback branch)."""
    S, Q, H, D = q.shape
    MB = tables.shape[1]
    Bs = k_pages.shape[1]
    C = MB * Bs
    ks = jnp.take(k_pages, tables, axis=0).reshape(S, C, H, D)
    vs = jnp.take(v_pages, tables, axis=0).reshape(S, C, H, D)
    att = jnp.einsum("sqhd,schd->shqc", q, ks) * scale
    pos = lengths[:, None] + jnp.arange(Q)[None, :]
    key_mask = jnp.arange(C)[None, None, :] <= pos[:, :, None]
    att = jnp.where(key_mask[:, None, :, :], att,
                    jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(
        q.dtype)
    return jnp.einsum("shqc,schd->sqhd", probs, vs)


def _kernel_inputs(Q, S=3, MB=2, Bs=8, H=2, D=128, seed=0):
    rng = np.random.RandomState(seed)
    N = S * MB + 1  # page 0 left as scratch, like the engine's pool
    q = jnp.asarray(rng.randn(S, Q, H, D).astype(np.float32) * 0.4)
    kp = jnp.asarray(rng.randn(N, Bs, H, D).astype(np.float32) * 0.4)
    vp = jnp.asarray(rng.randn(N, Bs, H, D).astype(np.float32) * 0.4)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, N)).reshape(S, MB).astype(np.int32))
    # committed lengths: empty slot, unaligned, and nearly-full
    lengths = jnp.asarray([0, 5, MB * Bs - Q][:S], jnp.int32)
    return q, kp, vp, tables, lengths


# ---------------------------------------------------------------------------
# tentpole (a): kernel vs the gather reference math
# ---------------------------------------------------------------------------

class TestPagedFlashKernelParity:
    @pytest.mark.parametrize("Q", [1, 3])
    def test_matches_gather_reference(self, Q):
        """Online-softmax block streaming == one-shot gather softmax, for
        the Q=1 decode and Q=3 speculative-verify shapes, across empty /
        unaligned / nearly-full slots."""
        q, kp, vp, tables, lengths = _kernel_inputs(Q)
        scale = 1.0 / np.sqrt(q.shape[-1])
        ref = _reference_paged_attention(q, kp, vp, tables, lengths, scale)
        out = paged_flash_decode(q, kp, vp, tables, lengths, scale=scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_under_jit(self):
        q, kp, vp, tables, lengths = _kernel_inputs(Q=1, seed=7)
        scale = 1.0 / np.sqrt(q.shape[-1])
        ref = _reference_paged_attention(q, kp, vp, tables, lengths, scale)
        out = jax.jit(
            lambda *a: paged_flash_decode(*a, scale=scale))(
                q, kp, vp, tables, lengths)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_tileable_predicate(self):
        """The auto-gate: lane dim must fill the 128-wide VPU lanes and
        the page must tile the 8-row sublane."""
        assert tileable(128, 8)
        assert tileable(256, 16)
        assert not tileable(64, 8)     # head_dim under a lane tile
        assert not tileable(128, 6)    # page not sublane-aligned
        assert not tileable(CFG.head_dim, 16)  # the tiny test config


# ---------------------------------------------------------------------------
# tentpole (a): model-level identity, gather vs kernel
# ---------------------------------------------------------------------------

class TestModelTokenIdentity:
    @pytest.mark.parametrize("Q", [1, 3])
    def test_paged_decode_argmax_identical(self, model, Q):
        """CausalLM.paged_decode produces argmax-identical logits whether
        the read is the XLA gather or the forced (interpret-mode on CPU)
        Pallas kernel — for both the decode and spec-verify shapes."""
        S, MB, Bs = 2, 2, 16
        cache = model.init_paged_kv_cache(S * MB + 1, Bs)
        rng = np.random.RandomState(3)
        k_shape = cache["k"].shape
        cache = {
            "k": jnp.asarray(rng.randn(*k_shape).astype(np.float32) * .3),
            "v": jnp.asarray(rng.randn(*k_shape).astype(np.float32) * .3),
        }
        tables = jnp.asarray(
            np.arange(1, S * MB + 1).reshape(S, MB), np.int32)
        toks = jnp.asarray(rng.randint(0, CFG.vocab_size, (S, Q)),
                           jnp.int32)
        lengths = jnp.asarray([0, 9], jnp.int32)

        outs = {}
        for mode in ("off", "on"):
            restore = _paged_mode(mode)
            try:
                _, lg = model.paged_decode(model.params, cache, tables,
                                           toks, lengths)
                outs[mode] = np.asarray(lg)
            finally:
                restore()
        assert (outs["off"].argmax(-1) == outs["on"].argmax(-1)).all()
        np.testing.assert_allclose(outs["off"], outs["on"], atol=5e-4)


# ---------------------------------------------------------------------------
# tentpole (a): engine-level token identity, gather vs kernel
# ---------------------------------------------------------------------------

def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(
        0, CFG.vocab_size, n).astype(np.int32)


def _engine_tokens(model, mode, prompts, engine_kw=None):
    """Greedy-generate each prompt in sequence under one paged-kernel
    mode; returns the tuple-of-token-tuples."""
    restore = _paged_mode(mode)
    try:
        eng = DecodeEngine(model, slots=2, max_ctx=64, prompt_buckets=[16],
                           **(engine_kw or {}))
        try:
            out = []
            for p in prompts:
                r = eng.generate(p, max_tokens=8,
                                 temperature=0.0).result(timeout=120)
                out.append(tuple(r["tokens"]))
            return tuple(out)
        finally:
            eng.close(10)
    finally:
        restore()


class TestEngineTokenIdentity:
    def test_greedy_identical(self, model):
        prompts = [_prompt(7, seed=11), _prompt(13, seed=12)]
        assert (_engine_tokens(model, "off", prompts)
                == _engine_tokens(model, "on", prompts))

    def test_speculative_identical(self, model):
        """The Q=k+1 verify pass rides the same kernel: a drafted engine
        must emit the same greedy tokens on either read path."""
        kw = {"draft_model": causal_lm.CausalLM(CFG, seed=3), "spec_k": 3}
        prompts = [_prompt(9, seed=21)]
        assert (_engine_tokens(model, "off", prompts, kw)
                == _engine_tokens(model, "on", prompts, kw))

    def test_prefix_warm_attach_identical(self, model):
        """Second request shares a radix-cached prefix (warm attach skips
        prefill for the shared blocks) — still token-identical across
        read paths."""
        base = _prompt(24, seed=31)
        prompts = [base, np.concatenate([base[:16], _prompt(4, seed=32)])]
        assert (_engine_tokens(model, "off", prompts)
                == _engine_tokens(model, "on", prompts))


# ---------------------------------------------------------------------------
# tentpole (b): fused int8 dequant-matmul
# ---------------------------------------------------------------------------

def _fused_mode(mode):
    env = environment()
    env.set_fused_dequant(mode)
    return lambda: env.clear_property(SystemProperties.FUSED_DEQUANT)


class TestFusedDequantMatmul:
    def _w(self, k=256, n=256, seed=0):
        rng = np.random.RandomState(seed)
        return quantize_tensor(
            jnp.asarray(rng.randn(k, n).astype(np.float32) * 0.05))

    @pytest.mark.parametrize("x_shape", [(32, 256), (2, 5, 256), (256,)])
    def test_matches_xla_path(self, x_shape):
        """Forced-on fused kernel == the XLA cast-then-dot fallback, for
        2-D, batched 3-D, and vector activations."""
        w = self._w()
        x = jnp.asarray(
            np.random.RandomState(1).randn(*x_shape).astype(np.float32))
        restore = _fused_mode("off")
        try:
            ref = np.asarray(dequant_matmul(x, w))
        finally:
            restore()
        restore = _fused_mode("on")
        try:
            out = np.asarray(jax.jit(lambda a: dequant_matmul(a, w))(x))
        finally:
            restore()
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_plain_array_passthrough(self):
        """Non-quantized weights bypass both paths entirely — identity
        with a plain jnp.dot, whatever the knob says."""
        w = jnp.asarray(np.random.RandomState(2).randn(16, 8), jnp.float32)
        x = jnp.asarray(np.random.RandomState(3).randn(4, 16), jnp.float32)
        restore = _fused_mode("on")
        try:
            np.testing.assert_allclose(np.asarray(dequant_matmul(x, w)),
                                       np.asarray(jnp.dot(x, w)))
        finally:
            restore()

    def test_no_full_precision_weight_in_hlo(self):
        """int8 at rest: the jitted program holds the 512x512 weight only
        as i8; no full-size f32 copy of it materializes (the in-kernel
        dequant happens tile-by-tile in VMEM). StableHLO types are
        ``tensor<...xi8>``-style."""
        w = self._w(512, 512)
        x = jnp.asarray(
            np.random.RandomState(4).randn(8, 512).astype(np.float32))
        restore = _fused_mode("on")
        try:
            txt = jax.jit(lambda a: dequant_matmul(a, w)).lower(x).as_text()
        finally:
            restore()
        assert "tensor<512x512xi8>" in txt
        assert "tensor<512x512xf32>" not in txt

    def test_quantized_model_twin_within_divergence(self, model):
        """Full-model gate: an int8 twin's logits through the fused path
        stay within DL4J_TPU_QUANT_MAX_DIVERGENCE of the dequant-first
        path, with identical greedy argmax."""
        env = environment()
        qm = quantize_model(causal_lm.CausalLM(CFG, seed=0))
        ids = jnp.asarray(_prompt(12, seed=41)[None, :])
        restore = _fused_mode("off")
        try:
            ref = np.asarray(qm.forward(ids))
        finally:
            restore()
        restore = _fused_mode("on")
        try:
            out = np.asarray(qm.forward(ids))
        finally:
            restore()
        assert float(np.abs(out - ref).max()) <= env.quant_max_divergence()
        assert (out.argmax(-1) == ref.argmax(-1)).all()

    def test_dequantize_unchanged(self):
        """The at-rest representation round-trips independently of the
        matmul path (dequantize() is the scale*q contract)."""
        w = self._w(8, 8, seed=5)
        assert isinstance(w, QuantizedTensor)
        np.testing.assert_allclose(
            np.asarray(dequantize(w)),
            np.asarray(w.q.astype(jnp.float32) * w.scale))


# ---------------------------------------------------------------------------
# satellite 1 + 2: dispatch counters and the /debug/decode join
# ---------------------------------------------------------------------------

class TestKernelDispatchTelemetry:
    def test_paged_decision_ticks_both_counters(self):
        """A paged dispatch ticks the existing per-path attention counter
        AND the new per-kernel-family counter with matching labels."""
        att = registry().counter(
            "dl4j_attn_dispatch_total",
            "Attention path decisions for flash=True configs",
            labels=("path",))
        fam = _kernel_counter()
        b_att = att.labels(path="paged_flash").value()
        b_fam = fam.labels(kernel="paged_decode",
                           path="paged_flash").value()
        restore = _paged_mode("on")
        try:
            assert attention_dispatch(1, paged=True, head_dim=128,
                                      block_size=8) == "paged_flash"
        finally:
            restore()
        assert att.labels(path="paged_flash").value() == b_att + 1
        assert fam.labels(kernel="paged_decode",
                          path="paged_flash").value() == b_fam + 1

    def test_dequant_decision_ticks_kernel_counter(self):
        fam = _kernel_counter()
        before = fam.labels(kernel="dequant_matmul", path="fused").value()
        w = quantize_tensor(jnp.ones((128, 128), jnp.float32))
        x = jnp.ones((4, 128), jnp.float32)
        restore = _fused_mode("on")
        try:
            dequant_matmul(x, w)
        finally:
            restore()
        assert fam.labels(kernel="dequant_matmul",
                          path="fused").value() == before + 1

    def test_dispatch_snapshot_reports_last_decision(self):
        """dispatch_snapshot() (the /debug/decode "kernels" join) records
        kernel name, chosen path, and the human-readable fallback
        reason of the most recent decision per family."""
        restore = _paged_mode("off")
        try:
            attention_dispatch(1, paged=True, head_dim=128, block_size=8)
        finally:
            restore()
        snap = dispatch_snapshot()
        rec = snap["paged_decode"]
        assert rec["kernel"] == "paged_decode"
        assert rec["path"] == "paged"
        assert rec["reason"] == "DL4J_TPU_PAGED_KERNEL=off"
        # snapshot hands out copies, not live references
        rec["path"] = "tampered"
        assert dispatch_snapshot()["paged_decode"]["path"] == "paged"

    def test_debug_snapshot_joins_kernels(self, model):
        """DecodeEngine.debug_snapshot (served at /debug/decode) carries
        the kernels section so operators can see which read path served
        the last compiled dispatch and why."""
        eng = DecodeEngine(model, slots=2, max_ctx=64, prompt_buckets=[16])
        try:
            eng.generate(_prompt(6, seed=51),
                         max_tokens=2).result(timeout=120)
            snap = eng.debug_snapshot()
        finally:
            eng.close(10)
        assert "kernels" in snap
        pd = snap["kernels"].get("paged_decode")
        assert pd is not None and pd["path"] in ("paged", "paged_flash")
        if pd["path"] == "paged":
            assert pd["reason"]  # fallbacks always say why


# ---------------------------------------------------------------------------
# satellite 6: the pin decision comes from tileability, never seq_len
# ---------------------------------------------------------------------------

class TestSpecVerifyPathStability:
    @pytest.mark.parametrize("mode", ["auto", "on", "off"])
    @pytest.mark.parametrize("tile", [(128, 8), (CFG.head_dim, 16)])
    def test_q1_and_qk1_always_same_path(self, mode, tile):
        """Q=1 decode and Q=k+1 spec-verify land on the SAME paged path
        in every mode and for every pool layout: the decision reads only
        kernel tileability, so spec-k configs cannot flap between the
        gather and the kernel across draft lengths."""
        hd, bs = tile
        env = environment()
        prev = env.spec_draft_k() if hasattr(env, "spec_draft_k") else None
        restore = _paged_mode(mode)
        try:
            if prev is not None:
                env.set_property(SystemProperties.SPEC_DRAFT_K, 3)
            paths = {attention_dispatch(q, paged=True, head_dim=hd,
                                        block_size=bs)
                     for q in (1, 4, 9)}  # decode, k=3 verify, k=8 verify
        finally:
            restore()
            if prev is not None:
                env.clear_property(SystemProperties.SPEC_DRAFT_K)
        assert len(paths) == 1
        assert paths <= {"paged", "paged_flash"}

    def test_flash_min_seq_never_moves_paged(self):
        """An adversarial DL4J_TPU_FLASH_MIN_SEQ=1 (flash for everything)
        must not pull the paged read onto the slab flash kernel."""
        env = environment()
        prev = env.flash_min_seq()
        restore = _paged_mode("off")
        try:
            env.set_flash_min_seq(1)
            assert attention_dispatch(512, paged=True, head_dim=128,
                                      block_size=8) == "paged"
        finally:
            restore()
            env.set_flash_min_seq(prev)

    def test_prefill_view_stays_on_gather(self):
        """Callers with no pool tile info (paged_prefill's contiguous
        view) always get the gather path, even when the kernel is forced
        on — the kernel contract is decode-shaped queries only."""
        restore = _paged_mode("on")
        try:
            assert attention_dispatch(32, paged=True) == "paged"
        finally:
            restore()


# ---------------------------------------------------------------------------
# acceptance: zero steady-state recompiles with the kernel on
# ---------------------------------------------------------------------------

class TestSteadyStateCompiles:
    def test_warm_decode_loop_never_retraces(self, model):
        """The path decision is trace-time: after the first compile, a
        growing-lengths greedy loop through the kernel path compiles
        nothing (same invariant the engine's zero-recompile gate holds
        for the gather path)."""
        env = environment()
        S, MB, Bs = 2, 2, 16
        cache = model.init_paged_kv_cache(S * MB + 1, Bs)
        tables = jnp.asarray(
            np.arange(1, S * MB + 1).reshape(S, MB), np.int32)
        toks = jnp.ones((S, 1), jnp.int32)
        lengths = jnp.asarray([0, 3], jnp.int32)
        restore = _paged_mode("on")
        try:
            step = counted_jit(
                lambda c, t, ln: model.paged_decode(model.params, c,
                                                    tables, t, ln),
                "test_paged_kernel_steady_state")
            cache, lg = step(cache, toks, lengths)  # compile + warm
            jax.block_until_ready(lg)
            env.reset_compile_count()
            for _ in range(4):
                cache, lg = step(cache, toks, lengths)
                toks = lg[:, -1].argmax(-1).astype(jnp.int32)[:, None]
                lengths = lengths + 1
            jax.block_until_ready(lg)
            assert env.compile_count() == 0
        finally:
            restore()
            env.reset_compile_count()


# ---------------------------------------------------------------------------
# env knob plumbing
# ---------------------------------------------------------------------------

class TestKnobPlumbing:
    @pytest.mark.parametrize("accessor,prop", [
        ("paged_kernel", SystemProperties.PAGED_KERNEL),
        ("fused_dequant", SystemProperties.FUSED_DEQUANT),
    ])
    def test_tri_state_with_auto_fallback(self, accessor, prop):
        env = environment()
        get = getattr(env, accessor)
        assert get() == "auto"  # shipped default
        try:
            for v in ("on", "off", "auto"):
                env.set_property(prop, v)
                assert get() == v
            env.set_property(prop, "bogus")  # unparseable → auto
            assert get() == "auto"
        finally:
            env.clear_property(prop)
        assert get() == "auto"
