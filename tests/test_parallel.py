"""Distributed tests on the virtual 8-device CPU mesh — the reference's
DummyTransport in-process fake-cluster pattern (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import collectives
from deeplearning4j_tpu.parallel.mesh import (DATA, SEQ, TENSOR, MeshConfig,
                                              make_mesh, shard_batch)
from deeplearning4j_tpu.parallel.ring_attention import (blockwise_attention,
                                                        ring_attention,
                                                        ulysses_attention)


def dense_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(B=2, T=16, H=4, D=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D)) for k in ks)


class TestMesh:
    def test_device_count(self):
        assert jax.device_count() == 8

    def test_make_mesh_shapes(self):
        m = make_mesh(MeshConfig(data=-1, tensor=2))
        assert dict(zip(m.axis_names, m.devices.shape))[TENSOR] == 2
        assert m.devices.size == 8

    def test_bad_mesh_raises(self):
        with pytest.raises(ValueError):
            make_mesh(MeshConfig(data=3, tensor=3))


class TestCollectives:
    def test_psum_over_mesh(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        mesh = make_mesh(MeshConfig())

        def f(x):
            return collectives.all_reduce_sum(jnp.sum(x), DATA)

        fn = shard_map(f, mesh=mesh,
                       in_specs=P((DATA, "fsdp", TENSOR, SEQ, "pipe")),
                       out_specs=P(), check_rep=False)
        x = jnp.ones(8)
        np.testing.assert_allclose(fn(x), 8.0)

    def test_ppermute_ring(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        mesh = make_mesh(MeshConfig())

        def f(x):
            return collectives.ppermute_next(x, DATA)

        fn = shard_map(f, mesh=mesh,
                       in_specs=P((DATA, "fsdp", TENSOR, SEQ, "pipe")),
                       out_specs=P((DATA, "fsdp", TENSOR, SEQ, "pipe")),
                       check_rep=False)
        x = jnp.arange(8.0)
        out = fn(x)
        np.testing.assert_allclose(out, jnp.roll(x, 1))


class TestRingAttention:
    def test_matches_dense(self):
        q, k, v = _qkv()
        mesh = make_mesh(MeshConfig(data=1, seq=8))
        out = ring_attention(q, k, v, mesh)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches_dense(self):
        q, k, v = _qkv(seed=1)
        mesh = make_mesh(MeshConfig(data=1, seq=8))
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_with_data_parallel_axis(self):
        q, k, v = _qkv(B=4, seed=2)
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        out = ring_attention(q, k, v, mesh)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_blockwise_matches_dense(self):
        q, k, v = _qkv(T=20, seed=3)
        out = blockwise_attention(q, k, v, block_size=6)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_blockwise_causal(self):
        q, k, v = _qkv(T=20, seed=4)
        out = blockwise_attention(q, k, v, causal=True, block_size=7)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_flash_ring_matches_dense(self):
        # Pallas-per-KV-block ring (SURVEY §5); interpret mode on CPU
        q, k, v = _qkv(T=256, seed=6)
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        out = ring_attention(q, k, v, mesh, use_flash=True)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_flash_ring_causal_matches_dense(self):
        q, k, v = _qkv(T=256, seed=7)
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        out = ring_attention(q, k, v, mesh, causal=True, use_flash=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_flash_ring_equals_xla_ring_with_mask(self):
        q, k, v = _qkv(B=2, T=128, seed=8)
        mask = jax.random.bernoulli(jax.random.key(9), 0.8,
                                    q.shape[:2])
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        out_f = ring_attention(q, k, v, mesh, mask=mask, use_flash=True)
        out_x = ring_attention(q, k, v, mesh, mask=mask, use_flash=False)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-5)

    def test_all_masked_row_agrees_across_paths(self):
        # a batch element whose key mask is all-False fully masks every one
        # of its query rows: the XLA ring merges l=0 -> out=0, and the
        # flash ring must not leak the kernel's uniform-softmax fallback
        # (mean of V) for those rows
        q, k, v = _qkv(B=2, T=128, seed=11)
        mask = jnp.ones(q.shape[:2], bool).at[0].set(False)
        mesh = make_mesh(MeshConfig(data=2, seq=4))
        out_f = ring_attention(q, k, v, mesh, mask=mask, use_flash=True)
        out_x = ring_attention(q, k, v, mesh, mask=mask, use_flash=False)
        assert np.all(np.asarray(out_f)[0] == 0.0)
        assert np.all(np.asarray(out_x)[0] == 0.0)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                                   rtol=2e-4, atol=2e-5)
        # live rows keep matching dense attention
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out_f)[1],
                                   np.asarray(ref)[1],
                                   rtol=2e-4, atol=2e-5)

    def test_flash_ring_grads_match_xla_ring(self):
        q, k, v = _qkv(T=128, seed=10)
        mesh = make_mesh(MeshConfig(data=2, seq=4))

        def loss(use_flash):
            def f(q, k, v):
                o = ring_attention(q, k, v, mesh, causal=True,
                                   use_flash=use_flash)
                return jnp.sum(o ** 2)
            return f

        gf = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gx):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_ulysses_matches_dense(self):
        q, k, v = _qkv(H=8, seed=5)
        mesh = make_mesh(MeshConfig(data=1, seq=8))
        out = ulysses_attention(q, k, v, mesh)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestBertSharded:
    def test_tiny_bert_dp_tp_sp_step(self):
        """Full train step over a dp=2 x tensor=2 x seq=2 mesh."""
        from deeplearning4j_tpu.models import bert

        config = bert.BertConfig.tiny()
        mesh = make_mesh(MeshConfig(data=2, tensor=2, seq=2))
        params = bert.init_params(jax.random.key(0), config)
        params = bert.place_params(params, config, mesh)
        opt = bert.init_opt_state(params)
        step = bert.make_train_step(config, mesh, seq_parallel=True)

        B, T = 4, 32
        rng = np.random.RandomState(0)
        batch = {
            "input_ids": jnp.asarray(rng.randint(0, config.vocab_size, (B, T))),
            "labels": jnp.asarray(
                np.where(rng.rand(B, T) < 0.15,
                         rng.randint(0, config.vocab_size, (B, T)), -100)),
            "attention_mask": jnp.ones((B, T), jnp.int32),
        }
        params, opt, loss1 = step(params, opt, batch, 0)
        params, opt, loss2 = step(params, opt, batch, 1)
        assert np.isfinite(float(loss1)) and np.isfinite(float(loss2))
        assert float(loss2) < float(loss1)  # learning on repeated batch

    def test_bert_forward_single_device_matches_sharded(self):
        from deeplearning4j_tpu.models import bert

        config = bert.BertConfig.tiny()
        config = bert.BertConfig(**{**config.__dict__, "dtype": jnp.float32})
        params = bert.init_params(jax.random.key(1), config)
        ids = jnp.asarray(np.random.RandomState(1).randint(
            0, config.vocab_size, (2, 16)))
        ref = bert.encode(params, ids, config=config)

        mesh = make_mesh(MeshConfig(data=2, tensor=2, seq=2))
        p_sharded = bert.place_params(params, config, mesh)
        out = bert.encode(p_sharded, ids, config=config, mesh=mesh,
                          seq_parallel=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-3, atol=5e-4)
