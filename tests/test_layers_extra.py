"""Extended layer catalog tests.

Models the reference's per-layer tests in
platform-tests/.../dl4jcore/nn/layers/ (shape inference + forward shape
agreement, plus train-ability for parameterized layers).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf.config import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import layers as L

RNG = np.random.RandomState(0)


def check_layer(layer, input_type, batch=2, training=False):
    """init → forward → assert output shape matches output_type inference."""
    key = jax.random.key(0)
    params = layer.init_params(key, input_type) if layer.has_params() else {}
    x = jnp.asarray(RNG.randn(batch, *input_type).astype(np.float32))
    out = layer.forward(params, x, training=training,
                        key=key if layer.needs_key() else None)
    expect = layer.output_type(input_type)
    assert out.shape == (batch,) + tuple(expect), \
        f"{type(layer).__name__}: {out.shape} != {(batch,) + tuple(expect)}"
    assert bool(jnp.all(jnp.isfinite(out)))
    return params, x, out


class TestConv3DFamily:
    def test_conv3d(self):
        check_layer(L.Convolution3D(n_in=2, n_out=4, kernel_size=(3, 3, 3),
                                    padding="SAME"), (2, 6, 6, 6))

    def test_conv3d_valid(self):
        check_layer(L.Convolution3D(n_in=2, n_out=4, kernel_size=(3, 3, 3),
                                    padding=(0, 0, 0)), (2, 6, 6, 6))

    def test_subsampling3d(self):
        check_layer(L.Subsampling3DLayer(kernel_size=(2, 2, 2)), (3, 4, 4, 4))
        check_layer(L.Subsampling3DLayer(pooling_type="avg"), (3, 4, 4, 4))

    def test_upsampling3d(self):
        check_layer(L.Upsampling3D(size=(2, 2, 2)), (3, 2, 2, 2))

    def test_cropping_padding_3d(self):
        check_layer(L.Cropping3D(cropping=(1, 1, 1, 1, 1, 1)), (2, 4, 4, 4))
        check_layer(L.ZeroPadding3DLayer(padding=(1, 1, 1, 1, 1, 1)),
                    (2, 4, 4, 4))


class TestConv1DFamily:
    def test_subsampling1d(self):
        check_layer(L.Subsampling1DLayer(kernel_size=2), (3, 8))

    def test_upsampling1d(self):
        check_layer(L.Upsampling1D(size=3), (3, 4))

    def test_cropping1d(self):
        check_layer(L.Cropping1D(cropping=(1, 2)), (3, 8))

    def test_zeropadding1d(self):
        check_layer(L.ZeroPadding1DLayer(padding=(2, 1)), (3, 8))

    def test_cropping2d(self):
        check_layer(L.Cropping2D(cropping=(1, 1, 2, 0)), (2, 6, 6))


class TestRecurrent:
    def test_simple_rnn(self):
        check_layer(L.SimpleRnn(n_in=4, n_out=6), (4, 7))

    def test_gru(self):
        check_layer(L.GRU(n_in=4, n_out=6), (4, 7))

    def test_last_time_step(self):
        check_layer(L.LastTimeStep(underlying=L.LSTM(n_in=4, n_out=6)), (4, 7))

    def test_time_distributed(self):
        check_layer(L.TimeDistributed(
            underlying=L.DenseLayer(n_in=4, n_out=6, activation="relu")),
            (4, 7))

    def test_mask_zero(self):
        layer = L.MaskZeroLayer(underlying=L.SimpleRnn(n_in=3, n_out=5))
        key = jax.random.key(0)
        params = layer.init_params(key, (3, 6))
        x = np.ones((2, 3, 6), np.float32)
        x[:, :, 4:] = 0.0  # padding timesteps
        out = layer.forward(params, jnp.asarray(x))
        assert np.allclose(np.asarray(out)[:, :, 4:], 0.0)
        assert not np.allclose(np.asarray(out)[:, :, :4], 0.0)


class TestLocallyConnected:
    def test_lc2d(self):
        check_layer(L.LocallyConnected2D(n_in=2, n_out=4, kernel_size=(3, 3)),
                    (2, 6, 6))

    def test_lc1d(self):
        check_layer(L.LocallyConnected1D(n_in=3, n_out=5, kernel_size=3),
                    (3, 8))

    def test_lc2d_vs_conv_param_count(self):
        # unshared weights: param count = positions * shared-conv params
        lc = L.LocallyConnected2D(n_in=2, n_out=4, kernel_size=(3, 3),
                                  has_bias=False)
        p = lc.init_params(jax.random.key(0), (2, 6, 6))
        assert p["W"].shape == (16, 2 * 9, 4)


class TestElementwiseShape:
    def test_prelu(self):
        layer = L.PReLULayer(n_in=4)
        p, x, out = check_layer(layer, (4,))
        neg = jnp.asarray(-np.ones((2, 4), np.float32))
        assert np.allclose(layer.forward(p, neg), -0.25)

    def test_elementwise_mult(self):
        check_layer(L.ElementWiseMultiplicationLayer(n_in=5), (5,))

    def test_repeat_vector(self):
        check_layer(L.RepeatVector(n=4), (3,))

    def test_space_depth_roundtrip(self):
        s2d = L.SpaceToDepthLayer(block_size=2)
        d2s = L.DepthToSpaceLayer(block_size=2)
        x = jnp.asarray(RNG.randn(2, 3, 4, 4).astype(np.float32))
        y = s2d.forward({}, x)
        assert y.shape == (2, 12, 2, 2)
        z = d2s.forward({}, y)
        assert np.allclose(z, x, atol=1e-6)

    def test_mask_layer(self):
        check_layer(L.MaskLayer(), (4,))


class TestDropoutVariants:
    def test_gaussian_dropout(self):
        check_layer(L.GaussianDropout(rate=0.5), (8,), training=True)

    def test_gaussian_noise(self):
        layer = L.GaussianNoise(stddev=0.1)
        x = jnp.ones((2, 8))
        out_train = layer.forward({}, x, training=True, key=jax.random.key(1))
        out_infer = layer.forward({}, x, training=False)
        assert not np.allclose(out_train, x)
        assert np.allclose(out_infer, x)

    def test_alpha_dropout(self):
        check_layer(L.AlphaDropout(rate=0.3), (8,), training=True)


class TestLossHeads:
    def test_cnn_loss_layer(self):
        layer = L.CnnLossLayer()
        x = jnp.asarray(RNG.randn(2, 3, 4, 4).astype(np.float32))
        out = layer.forward({}, x)
        # softmax over channels
        assert np.allclose(np.asarray(out).sum(1), 1.0, atol=1e-5)
        labels = jax.nn.one_hot(jnp.zeros((2, 4, 4), jnp.int32), 3, axis=1)
        loss = layer.compute_loss(labels, out)
        assert float(loss) > 0

    def test_rnn_loss_layer(self):
        layer = L.RnnLossLayer()
        x = jnp.asarray(RNG.randn(2, 3, 5).astype(np.float32))
        out = layer.forward({}, x)
        labels = jax.nn.one_hot(jnp.zeros((2, 5), jnp.int32), 3, axis=1)
        assert float(layer.compute_loss(labels, out)) > 0

    def test_cnn3d_loss_layer(self):
        layer = L.Cnn3DLossLayer()
        x = jnp.asarray(RNG.randn(2, 3, 2, 4, 4).astype(np.float32))
        out = layer.forward({}, x)
        labels = jax.nn.one_hot(jnp.zeros((2, 2, 4, 4), jnp.int32), 3, axis=1)
        assert float(layer.compute_loss(labels, out)) > 0

    def test_yolo2_loss(self):
        layer = L.Yolo2OutputLayer(anchors=((1.0, 1.0), (2.0, 2.0)))
        B, H, W, C = 2, 4, 4, 3
        x = jnp.asarray(RNG.randn(B, 2 * (5 + C), H, W).astype(np.float32))
        labels = np.zeros((B, 4 + C, H, W), np.float32)
        labels[0, :4, 1, 1] = [0.1, 0.1, 0.3, 0.3]  # one box
        labels[0, 4, 1, 1] = 1.0                     # class 0
        loss = layer.compute_loss(jnp.asarray(labels), layer.forward({}, x))
        assert np.isfinite(float(loss)) and float(loss) > 0

    def test_center_loss_trains(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2)).list()
                .layer(L.DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(L.CenterLossOutputLayer(n_in=8, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.randn(16, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.randint(0, 3, 16)]
        before = net.score(DataSet(x, y))
        net.fit(DataSet(x, y), num_epochs=20)
        assert net.score(DataSet(x, y)) < before
        # centers moved away from zero init
        centers = net._params[1]["state_centers"]
        assert float(jnp.abs(centers).sum()) > 0


class TestAttentionLayers:
    def test_learned_self_attention(self):
        check_layer(L.LearnedSelfAttentionLayer(n_in=6, n_out=8, n_heads=2,
                                                n_queries=3), (6, 10))

    def test_recurrent_attention(self):
        check_layer(L.RecurrentAttentionLayer(n_in=6, n_out=8), (6, 10))


class TestFrozen:
    def test_frozen_params_not_trained(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        inner = L.DenseLayer(n_in=4, n_out=8, activation="relu")
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2)).list()
                .layer(L.FrozenLayer(underlying=inner))
                .layer(L.OutputLayer(n_in=8, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        w_before = np.asarray(net._params[0][L.FrozenLayer.PREFIX + "W"])
        x = RNG.randn(8, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[RNG.randint(0, 3, 8)]
        net.fit(DataSet(x, y), num_epochs=5)
        w_after = np.asarray(net._params[0][L.FrozenLayer.PREFIX + "W"])
        np.testing.assert_array_equal(w_before, w_after)
        # but the output layer did train
        assert net.score(DataSet(x, y)) < 2.0


class TestVAE:
    def test_vae_shapes(self):
        check_layer(L.VariationalAutoencoder(
            n_in=10, n_out=4, encoder_layer_sizes=(16,),
            decoder_layer_sizes=(16,)), (10,))

    def test_vae_elbo_decreases(self):
        vae = L.VariationalAutoencoder(n_in=10, n_out=3,
                                       encoder_layer_sizes=(16,),
                                       decoder_layer_sizes=(16,))
        params = vae.init_params(jax.random.key(0), (10,))
        x = jnp.asarray(RNG.randn(32, 10).astype(np.float32))
        opt = Adam(1e-2)
        state = opt.init(params)

        @jax.jit
        def step(params, state, i, key):
            loss, g = jax.value_and_grad(
                lambda p: vae.elbo_loss(p, x, key))(params)
            upd, state = opt.apply(g, state, i)
            params = jax.tree_util.tree_map(lambda p, u: p - u, params, upd)
            return params, state, loss

        key = jax.random.key(1)
        first = None
        for i in range(60):
            key, k = jax.random.split(key)
            params, state, loss = step(params, state, i, k)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.8

    def test_vae_reconstruct(self):
        vae = L.VariationalAutoencoder(n_in=6, n_out=2)
        params = vae.init_params(jax.random.key(0), (6,))
        x = jnp.ones((3, 6))
        assert vae.reconstruct(params, x).shape == (3, 6)


class TestCapsules:
    def test_primary_capsules(self):
        check_layer(L.PrimaryCapsules(n_in=2, capsules=4,
                                      capsule_dimensions=8,
                                      kernel_size=(3, 3), stride=(2, 2)),
                    (2, 12, 12))

    def test_capsule_layer_routing(self):
        check_layer(L.CapsuleLayer(input_capsules=6, input_capsule_dimensions=4,
                                   capsules=3, capsule_dimensions=8,
                                   routings=2), (6, 4))

    def test_capsule_strength(self):
        layer = L.CapsuleStrengthLayer()
        x = jnp.asarray(RNG.randn(2, 5, 8).astype(np.float32))
        out = layer.forward({}, x)
        assert out.shape == (2, 5)
        # lengths are in [0, inf); squashed capsules give < 1
        assert bool(jnp.all(out >= 0))

    def test_capsnet_end_to_end(self):
        """Mini CapsNet (reference CapsNet zoo-style construction)."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder().updater(Adam(1e-2)).list()
                .layer(L.ConvolutionLayer(n_in=1, n_out=4, kernel_size=(3, 3),
                                          activation="relu"))
                .layer(L.PrimaryCapsules(n_in=4, capsules=2,
                                         capsule_dimensions=4,
                                         kernel_size=(3, 3), stride=(2, 2)))
                .layer(L.CapsuleLayer(capsules=2, capsule_dimensions=4,
                                      routings=2))
                .layer(L.CapsuleStrengthLayer())
                .layer(L.LossLayer(loss="mse", activation="softmax"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = RNG.randn(4, 1, 8, 8).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[RNG.randint(0, 2, 4)]
        net.fit(DataSet(x, y), num_epochs=3)
        out = net.output(x)
        assert out.shape == (4, 2)
