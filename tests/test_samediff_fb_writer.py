"""SameDiff FlatBuffers WRITER (VERDICT r4 #6): emit the reference
FlatGraph format (`SameDiff.java:5465-5727` asFlatBuffers; schemas
`libnd4j/include/graph/scheme/*.fbs`) and round-trip it through the
wire-format reader — identical outputs, loss variables, and updater state.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu import nd
from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.training import TrainingConfig
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.modelimport.samediff_fb import (FlatGraphFile,
                                                        load_samediff_fb)


def _mlp_sd():
    sd = SameDiff.create()
    x = sd.placeholder("input", (None, 8))
    y = sd.placeholder("label", (None, 4))
    rs = np.random.RandomState(0)
    w0 = sd.var("w0", nd.create(rs.randn(8, 16).astype(np.float32) * 0.3))
    b0 = sd.var("b0", nd.create(np.zeros((1, 16), np.float32)))
    w1 = sd.var("w1", nd.create(rs.randn(16, 4).astype(np.float32) * 0.3))
    b1 = sd.var("b1", nd.create(np.zeros((1, 4), np.float32)))
    h = sd.invoke("tanh", x.mmul(w0) + b0)
    logits = h.mmul(w1) + b1
    sm = sd.invoke("softmax", logits)
    diff = sm - y
    sq = sd.invoke("square", diff)
    loss = sd.invoke("reduce_mean", sq)
    sd.set_loss_variables(loss)
    return sd, sm.name, loss.name


def _feeds(n=4):
    rs = np.random.RandomState(7)
    x = rs.randn(n, 8).astype(np.float32)
    y = np.zeros((n, 4), np.float32)
    y[np.arange(n), rs.randint(0, 4, n)] = 1.0
    return {"input": x, "label": y}


class TestWriterRoundTrip:
    def test_outputs_identical(self, tmp_path):
        sd, sm_name, loss_name = _mlp_sd()
        path = str(tmp_path / "g.fb")
        sd.save_flatbuffers(path)
        sd2 = load_samediff_fb(path)

        feeds = _feeds()
        a = sd.output(feeds, [sm_name, loss_name])
        b = sd2.output(feeds, [sm_name, loss_name])
        for k in (sm_name, loss_name):
            np.testing.assert_allclose(np.asarray(a[k].numpy()),
                                       np.asarray(b[k].numpy()),
                                       atol=1e-6, rtol=1e-6)
        assert sd2.fb_loss_variables == [loss_name]

    def test_trained_roundtrip_with_updater_state(self, tmp_path):
        """Train, write with updater state, reload, CONTINUE training —
        the resumed step must equal the uninterrupted one."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

        def it():
            f = _feeds(32)
            return ListDataSetIterator(
                [DataSet(nd.create(f["input"][i:i + 8]),
                         nd.create(f["label"][i:i + 8]))
                 for i in range(0, 32, 8)])

        def configure(s):
            s.set_training_config(TrainingConfig(
                updater=Adam(learning_rate=0.05),
                data_set_feature_mapping=["input"],
                data_set_label_mapping=["label"]))

        sd, sm_name, loss_name = _mlp_sd()
        configure(sd)
        sd.fit(it(), num_epochs=3)

        path = str(tmp_path / "trained.fb")
        sd.save_flatbuffers(path, save_updater_state=True)
        sd2 = load_samediff_fb(path)
        configure(sd2)

        # updater state survived byte-exactly
        assert sd2._updater_state is not None
        for key in sd._updater_state:
            for pname, arr in sd._updater_state[key].items():
                np.testing.assert_array_equal(
                    np.asarray(arr), np.asarray(sd2._updater_state[key][pname]))

        # resumed training matches uninterrupted training step for step
        h1 = sd.fit(it(), num_epochs=1)
        h2 = sd2.fit(it(), num_epochs=1)
        np.testing.assert_allclose(h1.final_loss(), h2.final_loss(),
                                   rtol=1e-5)

    def test_kwarg_packing_roundtrip(self, tmp_path):
        """matmul transpose flags, softmax axis, reduction dims/keep_dims
        survive the i_args/t_args/b_args/dimensions packing."""
        sd = SameDiff.create()
        x = sd.placeholder("x", (3, 5))
        rs = np.random.RandomState(1)
        w = sd.var("w", nd.create(rs.randn(4, 5).astype(np.float32)))
        mm = sd.invoke("matmul", x, w, transpose_b=True)     # [3, 4]
        sm = sd.invoke("softmax", mm, axis=0)
        red = sd.invoke("reduce_sum", sm, dims=[0], keep_dims=True)
        path = str(tmp_path / "kw.fb")
        sd.save_flatbuffers(path)
        sd2 = load_samediff_fb(path)

        feeds = {"x": rs.randn(3, 5).astype(np.float32)}
        for name in (mm.name, sm.name, red.name):
            a = sd.output(feeds, [name])[name].numpy()
            b = sd2.output(feeds, [name])[name].numpy()
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_log_softmax_stays_log_softmax(self, tmp_path):
        # the reader's axis decoder must NOT rewrite log_softmax to
        # softmax (review finding: outputs came back exponentiated)
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 5))
        out = sd.invoke("log_softmax", x, axis=-1)
        sd.save_flatbuffers(str(tmp_path / "ls.fb"))
        sd2 = load_samediff_fb(str(tmp_path / "ls.fb"))
        feeds = {"x": np.random.RandomState(3).randn(2, 5).astype(np.float32)}
        a = np.asarray(sd.output(feeds, [out.name])[out.name].numpy())
        b = np.asarray(sd2.output(feeds, [out.name])[out.name].numpy())
        np.testing.assert_allclose(a, b, atol=1e-6)
        assert (a <= 0).all()  # log-probabilities, not probabilities

    def test_unencodable_kwargs_fail_loudly(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 2, 3))
        sd.invoke("transpose", x, axes=(2, 0, 1))
        with pytest.raises(ValueError, match="no FlatBuffers arg packing"):
            sd.save_flatbuffers(str(tmp_path / "bad.fb"))

    def test_default_kwargs_are_droppable(self, tmp_path):
        # kwargs equal to the op's declared defaults carry no information
        # and must not block serialization
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 3))
        out = sd.invoke("relu", x)
        sd.save_flatbuffers(str(tmp_path / "ok.fb"))
        sd2 = load_samediff_fb(str(tmp_path / "ok.fb"))
        feeds = {"x": np.random.RandomState(2).randn(2, 3).astype(np.float32)}
        np.testing.assert_allclose(
            np.asarray(sd.output(feeds, [out.name])[out.name].numpy()),
            np.asarray(sd2.output(feeds, [out.name])[out.name].numpy()))


REF_FIXTURE = "/root/reference/sameDiffExampleInference.fb"


@pytest.mark.skipif(not os.path.exists(REF_FIXTURE),
                    reason="reference .fb fixture not present")
def test_reference_fixture_rewrites_identically(tmp_path):
    """read(reference .fb) -> write -> read: outputs unchanged."""
    sd = load_samediff_fb(REF_FIXTURE)
    path = str(tmp_path / "rewritten.fb")
    sd.save_flatbuffers(path)
    sd2 = load_samediff_fb(path)
    assert sd2.fb_loss_variables == sd.fb_loss_variables

    rng = np.random.RandomState(7)
    x = rng.randn(4, 784).astype(np.float32)
    lbl = np.zeros((4, 10), np.float32)
    lbl[np.arange(4), rng.randint(0, 10, 4)] = 1.0
    feeds = {"input": x, "label": lbl}
    a = sd.output(feeds, ["prediction"])["prediction"].numpy()
    b = sd2.output(feeds, ["prediction"])["prediction"].numpy()
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
