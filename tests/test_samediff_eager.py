"""SameDiff eager mode (VERDICT r2 weak #6): ops execute as they are
defined (reference SameDiff.java eagerMode, :153,379) while the recorded
graph stays intact for the compiled path."""
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff


def test_eager_values_available_at_definition():
    sd = SameDiff.create(eager=True)
    x = sd.var("x", np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32))
    y = x * 2.0 + 1.0
    arr = y.get_arr()
    assert arr is not None
    np.testing.assert_allclose(arr.numpy(), [[3, 5], [7, 9]])


def test_enable_mid_build():
    sd = SameDiff.create()
    x = sd.var("x", np.asarray([2.0], np.float32))
    a = x + 1.0                    # recorded before eager: no value
    assert sd.eager_arr(a.name) is None
    sd.enable_eager_mode()
    assert sd.is_eager_mode()
    b = a * 3.0                    # a has no eager value -> b skipped too
    assert sd.eager_arr(b.name) is None
    c = x * 5.0                    # direct from a known array: computed
    np.testing.assert_allclose(sd.eager_arr(c.name).numpy(), [10.0])


def test_placeholder_gates_eager_until_set():
    sd = SameDiff.create(eager=True)
    p = sd.placeholder("p", shape=(2,))
    w = sd.var("w", np.asarray([10.0, 20.0], np.float32))
    out1 = p + w
    assert sd.eager_arr(out1.name) is None  # p unset: not computable
    sd.set_array("p", np.asarray([1.0, 2.0], np.float32))
    out2 = p + w                            # defined after the array exists
    np.testing.assert_allclose(sd.eager_arr(out2.name).numpy(), [11, 22])


def test_compiled_path_unchanged():
    """The same graph still compiles/executes define-then-run, matching the
    eager values."""
    sd2 = SameDiff.create(eager=True)
    x2 = sd2.var("x", np.asarray([[1.0, 2.0]], np.float32))
    out = sd2._record("multiply", [x2, sd2.constant(3.0, "k")],
                      out_name="y")
    eager = sd2.eager_arr(out.name).numpy()
    compiled = sd2.output({}, [out.name])[out.name].numpy()
    np.testing.assert_allclose(eager, compiled)


def test_eager_failure_is_nonfatal():
    """A node whose eager execution fails still records; compiled eval with
    proper placeholders works."""
    sd = SameDiff.create(eager=True)
    p = sd.placeholder("p", shape=(3,))
    out = p * 2.0
    assert sd.eager_arr(out.name) is None
    res = out.eval({"p": np.asarray([1.0, 2.0, 3.0], np.float32)})
    np.testing.assert_allclose(res.numpy(), [2, 4, 6])
