import numpy as np
from deeplearning4j_tpu.datasets.iterators import NativeBatchDataSetIterator

def test_native_dataset_iterator():
    import deeplearning4j_tpu.native as native
    import pytest
    if not native.available():
        pytest.skip("no native lib")
    rs = np.random.RandomState(0)
    it = NativeBatchDataSetIterator(
        rs.randn(32, 4).astype(np.float32),
        np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)], batch_size=8)
    assert sum(1 for _ in it) == 4
    it.reset()
    assert sum(1 for _ in it) == 4
    it.close()


def test_native_iterator_trailing_partial_batch():
    """Reference DataSetIterator contract: the final batch may be smaller;
    every sample is seen exactly once per epoch."""
    import deeplearning4j_tpu.native as native
    import pytest
    if not native.available():
        pytest.skip("no native lib")
    x = np.arange(22, dtype=np.float32).reshape(22, 1)
    it = native.NativeBatchIterator(x, None, batch_size=8, shuffle=False,
                                    num_epochs=1)
    sizes, seen = [], []
    for bx, _ in it:
        sizes.append(bx.shape[0])
        seen.extend(bx[:, 0].tolist())
    assert sizes == [8, 8, 6]
    assert sorted(seen) == list(range(22))


def test_native_iterator_drop_last():
    import deeplearning4j_tpu.native as native
    import pytest
    if not native.available():
        pytest.skip("no native lib")
    x = np.arange(22, dtype=np.float32).reshape(22, 1)
    it = native.NativeBatchIterator(x, None, batch_size=8, shuffle=False,
                                    num_epochs=1, drop_last=True)
    assert [bx.shape[0] for bx, _ in it] == [8, 8]
