import numpy as np
from deeplearning4j_tpu.datasets.iterators import NativeBatchDataSetIterator

def test_native_dataset_iterator():
    import deeplearning4j_tpu.native as native
    import pytest
    if not native.available():
        pytest.skip("no native lib")
    rs = np.random.RandomState(0)
    it = NativeBatchDataSetIterator(
        rs.randn(32, 4).astype(np.float32),
        np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)], batch_size=8)
    assert sum(1 for _ in it) == 4
    it.reset()
    assert sum(1 for _ in it) == 4
    it.close()
