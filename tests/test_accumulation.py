"""Gradient accumulation: k micro-batches == one big batch exactly
(EncodedGradientsAccumulator role, minus the wire)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.accumulation import (GradientsAccumulator,
                                                      fit_accumulated)


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Sgd(learning_rate=5e-2)).list()
            .layer(L.DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(L.OutputLayer(n_out=3, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


class TestAccumulation:
    def test_matches_big_batch(self):
        rs = np.random.RandomState(0)
        x = rs.randn(32, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]

        big = _net()
        big.fit(x, y)

        acc = _net()
        micro = [(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
                 for i in range(4)]
        fit_accumulated(acc, micro, accumulation_steps=4)

        np.testing.assert_allclose(acc.params().numpy(),
                                   big.params().numpy(), atol=1e-6)

    def test_multiple_steps(self):
        rs = np.random.RandomState(1)
        net = _net()
        batches = []
        for _ in range(6):
            x = rs.randn(8, 8).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]
            batches.append((x, y))
        losses = fit_accumulated(net, batches, accumulation_steps=2)
        assert len(losses) == 3          # 6 micro / 2 per step
        assert net._iteration == 3

    def test_trailing_partial_window_applies(self):
        rs = np.random.RandomState(2)
        net = _net()
        batches = [(rs.randn(8, 8).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)])
                   for _ in range(5)]
        losses = fit_accumulated(net, batches, accumulation_steps=2)
        assert len(losses) == 3          # 2 + 2 + trailing 1
        assert net._iteration == 3

    def test_gradient_clipping_applied(self):
        """fit_accumulated must honor conf.gradient_normalization like
        net.fit (shared _apply_update)."""
        rs = np.random.RandomState(4)
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=5e-2))
                .gradient_normalization("clip_value", 1e-4)
                .list()
                .layer(L.DenseLayer(n_in=8, n_out=16, activation="tanh"))
                .layer(L.OutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        before = net.params().numpy()
        x = rs.randn(8, 8).astype(np.float32) * 100  # huge grads
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]
        fit_accumulated(net, [(x, y)], accumulation_steps=1)
        delta = np.abs(net.params().numpy() - before).max()
        assert delta <= 5e-2 * 1e-4 * 1.01  # lr * clip bound (+f32 rounding)

    def test_batchnorm_stats_refresh(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Sgd(learning_rate=1e-2)).list()
                .layer(L.DenseLayer(n_in=8, n_out=16, activation="relu"))
                .layer(L.BatchNormalization())
                .layer(L.OutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        mean_before = np.asarray(net._params[1]["state_mean"]).copy()
        rs = np.random.RandomState(6)
        x = rs.randn(16, 8).astype(np.float32) + 3.0
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
        fit_accumulated(net, [(x, y)] * 2, accumulation_steps=2)
        mean_after = np.asarray(net._params[1]["state_mean"])
        assert np.abs(mean_after - mean_before).max() > 1e-3

    def test_threshold_roundtrip_quantizes(self):
        import jax.numpy as jnp
        acc = GradientsAccumulator(threshold=0.1)
        acc.store_update({"w": jnp.asarray([0.25, -0.03, -0.4, 0.0])})
        avg = acc.get_average()
        np.testing.assert_allclose(np.asarray(avg["w"]),
                                   [0.1, 0.0, -0.1, 0.0], atol=1e-7)
