"""SameDiff graph layer tests: define-then-run, eval, grad, fit, serde,
gradient checks — mirroring the reference's SameDiffTests basics."""
import os

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import nd
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.gradcheck import (check_gradients,
                                                   check_samediff_gradients)
from deeplearning4j_tpu.learning import Adam, Sgd


class TestGraphBuilding:
    def test_simple_arithmetic(self):
        sd = SameDiff.create()
        a = sd.constant(nd.create([1.0, 2.0]), "a")
        b = sd.constant(nd.create([3.0, 4.0]), "b")
        c = a + b
        out = c.eval()
        np.testing.assert_allclose(out.numpy(), [4, 6])

    def test_placeholder_eval(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 2))
        w = sd.var("w", nd.create([[1.0], [1.0]]))
        y = x.mmul(w)
        out = y.eval({"x": nd.create([[2.0, 3.0]])})
        np.testing.assert_allclose(out.numpy(), [[5.0]])

    def test_namespaces(self):
        sd = SameDiff.create()
        x = sd.constant(nd.create([[1.0, 1.0]]), "x")
        s = sd.nn.softmax(x)
        np.testing.assert_allclose(s.eval().numpy(), [[0.5, 0.5]])
        m = sd.math.log(sd.constant(nd.create([jnp.e]), "e"))
        assert float(m.eval().numpy()[0]) == pytest.approx(1.0)

    def test_chained_ops(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2, 2))
        y = (x * 2.0 + 1.0).sum()
        out = y.eval({"x": nd.ones(2, 2)})
        assert float(out.numpy()) == 12.0

    def test_reduce_methods(self):
        sd = SameDiff.create()
        x = sd.constant(nd.create([[1.0, 2.0], [3.0, 4.0]]), "x")
        assert float(x.mean().eval().numpy()) == 2.5
        np.testing.assert_allclose(x.sum(0).eval().numpy(), [4, 6])
        assert x.argmax(1).eval().to_list() == [1, 1]

    def test_name_scope(self):
        sd = SameDiff.create()
        with sd.name_scope("layer1"):
            v = sd.var("w", nd.ones(2))
        assert v.name == "layer1/w"

    def test_multi_output_not_recomputed(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2,))
        y = x * 2.0
        z = y + 1.0
        outs = sd.output({"x": nd.ones(2)}, [y.name, z.name])
        np.testing.assert_allclose(outs[y.name].numpy(), [2, 2])
        np.testing.assert_allclose(outs[z.name].numpy(), [3, 3])


class TestGradients:
    def test_calculate_gradients(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (2,))
        w = sd.var("w", nd.create([2.0, 3.0]))
        loss = (x * w).sum()
        sd.set_loss_variables(loss)
        grads = sd.calculate_gradients({"x": nd.create([5.0, 7.0])}, ["w"])
        np.testing.assert_allclose(grads["w"].numpy(), [5, 7])

    def test_gradcheck_util(self):
        check_gradients(lambda x: jnp.sum(jnp.tanh(x) ** 2),
                        [jnp.array([0.3, -0.5, 1.2])])

    def test_samediff_gradcheck(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (3,))
        w = sd.var("w", nd.create([0.5, -0.3, 0.8]))
        loss = sd.invoke("reduce_sum", sd.invoke("sigmoid", x * w))
        sd.set_loss_variables(loss)
        check_samediff_gradients(sd, {"x": nd.create([1.0, 2.0, 3.0])},
                                 loss.name)


class TestTraining:
    def _make_regression_sd(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 3))
        y = sd.placeholder("y", (None, 1))
        w = sd.var("w", nd.zeros(3, 1))
        b = sd.var("b", nd.zeros(1))
        pred = x.mmul(w) + b
        loss = sd.loss.mean_squared_error(pred, None, y)
        sd.set_loss_variables(loss)
        return sd

    def test_fit_linear_regression(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

        nd.set_seed(0)
        true_w = np.array([[1.0], [-2.0], [0.5]])
        X = np.random.RandomState(0).randn(200, 3).astype(np.float32)
        Y = X @ true_w

        sd = self._make_regression_sd()
        sd.set_training_config(TrainingConfig(
            updater=Adam(learning_rate=0.1),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        it = ListDataSetIterator(
            [DataSet(nd.create(X[i:i + 50]), nd.create(Y[i:i + 50]))
             for i in range(0, 200, 50)])
        history = sd.fit(it, num_epochs=30)
        assert history.final_loss() < 1e-2
        w_trained = sd.get_arr_for_var("w").numpy()
        np.testing.assert_allclose(w_trained, true_w, atol=0.1)


class TestSerialization:
    def test_save_load_roundtrip(self, tmp_path):
        sd = SameDiff.create()
        x = sd.placeholder("x", (None, 2))
        w = sd.var("w", nd.create([[1.0], [2.0]]))
        out = sd.invoke("sigmoid", x.mmul(w))
        path = str(tmp_path / "model.zip")
        sd.save(path)

        sd2 = SameDiff.load(path)
        x_val = nd.create([[1.0, 1.0]])
        r1 = out.eval({"x": x_val})
        r2 = sd2.output({"x": x_val}, [out.name])[out.name]
        np.testing.assert_allclose(r1.numpy(), r2.numpy())

    def test_save_preserves_variables(self, tmp_path):
        sd = SameDiff.create()
        w = sd.var("w", nd.create([1.0, 2.0, 3.0]))
        path = str(tmp_path / "vars.zip")
        sd.save(path)
        sd2 = SameDiff.load(path)
        np.testing.assert_allclose(sd2.get_arr_for_var("w").numpy(), [1, 2, 3])
