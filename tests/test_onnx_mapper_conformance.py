"""Golden conformance for the long-tail ONNX mappers.

ONNX protos are hand-encoded with the shared `protoio` writer (no onnx
package in this environment); goldens are numpy reference implementations
of the ONNX operator specs — the onnx-import test-resources role of the
reference (`nd4j/samediff-import/samediff-import-onnx/src/test/`).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport import import_onnx_model
from deeplearning4j_tpu.modelimport import protoio as pio

RS = np.random.RandomState(7)

_DT = {np.dtype("float32"): 1, np.dtype("int32"): 6, np.dtype("int64"): 7}


def _tensor(name, arr):
    arr = np.asarray(arr)
    w = pio.Writer()
    for d in arr.shape:
        w.int_(1, d)
    w.int_(2, _DT[arr.dtype])
    w.str_(8, name)
    w.bytes_(9, arr.tobytes())
    return w


def _vi(name, shape, dt=1):
    dimw = pio.Writer()
    for d in shape:
        dimw.msg(1, pio.Writer().int_(1, d))
    tens = pio.Writer().int_(1, dt).msg(2, dimw)
    return pio.Writer().str_(1, name).msg(2, pio.Writer().msg(1, tens))


def _node(op_type, inputs, outputs, **attrs):
    w = pio.Writer()
    for i in inputs:
        w.str_(1, i)
    for o in outputs:
        w.str_(2, o)
    w.str_(4, op_type)
    for k, v in attrs.items():
        aw = pio.Writer().str_(1, k)
        if isinstance(v, str):
            aw.int_(20, 3).bytes_(4, v.encode())
        elif isinstance(v, float):
            aw.int_(20, 1).float_(2, v)
        elif isinstance(v, int):
            aw.int_(20, 2).int_(3, v)
        elif isinstance(v, (list, tuple)):
            aw.int_(20, 7)
            for x in v:
                aw.int_(8, x)
        w.msg(5, aw)
    return w


def build_model(nodes, initializers, inputs, outputs):
    gw = pio.Writer()
    for n in nodes:
        gw.msg(1, n)
    gw.str_(2, "test")
    for name, arr in initializers.items():
        gw.msg(5, _tensor(name, arr))
    for name, shape, dt in inputs:
        gw.msg(11, _vi(name, shape, dt))
    for name, shape in outputs:
        gw.msg(12, _vi(name, shape))
    model = pio.Writer().int_(1, 8).msg(7, gw)
    model.msg(8, pio.Writer().str_(1, "").int_(2, 17))
    return model.build()


def run1(node, feeds, initializers=None, out_shape=(1,), n_outputs=1):
    """Single-node model: feeds dict name->array; returns output array(s)."""
    inputs = [(k, v.shape, _DT[np.asarray(v).dtype]) for k, v in
              feeds.items()]
    outs = [(f"y{i}" if n_outputs > 1 else "y", out_shape)
            for i in range(n_outputs)]
    data = build_model([node], initializers or {}, inputs, outs)
    imp = import_onnx_model(data)
    names = [o[0] for o in outs]
    res = imp.output(dict(feeds), names)
    arrs = [np.asarray(res[n].numpy()) for n in names]
    return arrs[0] if n_outputs == 1 else arrs


class TestElementwise:
    def test_hard_sigmoid_default_alpha(self):
        x = RS.randn(4, 3).astype(np.float32)
        got = run1(_node("HardSigmoid", ["x"], ["y"]), {"x": x})
        np.testing.assert_allclose(got, np.clip(0.2 * x + 0.5, 0, 1),
                                   atol=1e-6)

    def test_is_nan_inf(self):
        x = np.array([1.0, np.nan, np.inf, -np.inf], np.float32)
        got = run1(_node("IsNaN", ["x"], ["y"]), {"x": x})
        np.testing.assert_array_equal(got, np.isnan(x))
        got = run1(_node("IsInf", ["x"], ["y"]), {"x": x})
        np.testing.assert_array_equal(got, np.isinf(x))

    def test_prelu(self):
        x = RS.randn(2, 3).astype(np.float32)
        slope = np.array([0.1, 0.2, 0.3], np.float32)
        got = run1(_node("PRelu", ["x", "s"], ["y"]), {"x": x},
                   initializers={"s": slope})
        np.testing.assert_allclose(got, np.where(x > 0, x, slope * x),
                                   atol=1e-6)


class TestShape:
    def test_cumsum(self):
        x = RS.randn(3, 4).astype(np.float32)
        got = run1(_node("CumSum", ["x", "ax"], ["y"]), {"x": x},
                   initializers={"ax": np.asarray(1, np.int32)})
        np.testing.assert_allclose(got, np.cumsum(x, 1), atol=1e-6)

    def test_depth_space_roundtrip(self):
        x = RS.randn(1, 8, 2, 2).astype(np.float32)
        d2s = run1(_node("DepthToSpace", ["x"], ["y"], blocksize=2),
                   {"x": x})
        # numpy DCR reference
        n, c, h, w = x.shape
        ref = x.reshape(n, 2, 2, c // 4, h, w).transpose(
            0, 3, 4, 1, 5, 2).reshape(n, c // 4, h * 2, w * 2)
        np.testing.assert_allclose(d2s, ref, atol=1e-6)
        back = run1(_node("SpaceToDepth", ["x"], ["y"], blocksize=2),
                    {"x": ref})
        np.testing.assert_allclose(back, x, atol=1e-6)

    def test_range_size(self):
        got = run1(_node("Range", ["a", "b", "c"], ["y"]), {},
                   initializers={"a": np.asarray(1, np.int32),
                                 "b": np.asarray(9, np.int32),
                                 "c": np.asarray(2, np.int32)})
        np.testing.assert_array_equal(got, np.arange(1, 9, 2))
        x = RS.randn(3, 4).astype(np.float32)
        got = run1(_node("Size", ["x"], ["y"]), {"x": x})
        assert int(got) == 12

    def test_gather_nd(self):
        x = RS.randn(4, 5).astype(np.float32)
        idx = np.array([[0, 1], [3, 4]], np.int32)
        got = run1(_node("GatherND", ["x", "i"], ["y"]), {"x": x},
                   initializers={"i": idx})
        np.testing.assert_allclose(got, x[[0, 3], [1, 4]], atol=1e-6)


class TestReduceNorm:
    def test_reduce_l1_l2_logsumexp(self):
        x = RS.randn(3, 4).astype(np.float32)
        got = run1(_node("ReduceL1", ["x"], ["y"], axes=[1], keepdims=0),
                   {"x": x})
        np.testing.assert_allclose(got, np.abs(x).sum(1), atol=1e-5)
        got = run1(_node("ReduceL2", ["x"], ["y"], axes=[1], keepdims=0),
                   {"x": x})
        np.testing.assert_allclose(got, np.sqrt((x * x).sum(1)), atol=1e-5)
        got = run1(_node("ReduceLogSumExp", ["x"], ["y"], axes=[1],
                         keepdims=0), {"x": x})
        np.testing.assert_allclose(
            got, np.log(np.exp(x).sum(1)), atol=1e-5)

    def test_global_max_pool(self):
        x = RS.randn(2, 3, 4, 4).astype(np.float32)
        got = run1(_node("GlobalMaxPool", ["x"], ["y"]), {"x": x})
        np.testing.assert_allclose(got, x.max((2, 3), keepdims=True),
                                   atol=1e-6)


class TestLinalgScatter:
    def test_det(self):
        x = (RS.randn(3, 3) + 3 * np.eye(3)).astype(np.float32)
        got = run1(_node("Det", ["x"], ["y"]), {"x": x})
        np.testing.assert_allclose(got, np.linalg.det(x), rtol=1e-4)

    def test_scatter_nd(self):
        data = RS.randn(5, 3).astype(np.float32)
        idx = np.array([[0], [2]], np.int64)
        upd = RS.randn(2, 3).astype(np.float32)
        got = run1(_node("ScatterND", ["d", "i", "u"], ["y"]), {"d": data},
                   initializers={"i": idx, "u": upd})
        ref = data.copy()
        ref[[0, 2]] = upd
        np.testing.assert_allclose(got, ref, atol=1e-6)

    def test_scatter_elements_axis1(self):
        data = np.zeros((2, 5), np.float32)
        idx = np.array([[1, 3], [0, 4]], np.int64)
        upd = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        got = run1(_node("ScatterElements", ["d", "i", "u"], ["y"], axis=1),
                   {"d": data}, initializers={"i": idx, "u": upd})
        ref = data.copy()
        for r in range(2):
            for c in range(2):
                ref[r, idx[r, c]] = upd[r, c]
        np.testing.assert_allclose(got, ref, atol=1e-6)


class TestImageSelection:
    def test_lrn(self):
        x = RS.randn(1, 6, 2, 2).astype(np.float32)
        alpha, beta, bias, size = 1e-3, 0.75, 1.0, 3
        got = run1(_node("LRN", ["x"], ["y"], alpha=alpha, beta=beta,
                         bias=bias, size=size), {"x": x})
        # ONNX spec reference: square_sum over centered window along C
        sq = np.zeros_like(x)
        C = x.shape[1]
        for c in range(C):
            lo = max(0, c - (size - 1) // 2)
            hi = min(C - 1, c + int(np.ceil((size - 1) / 2)))
            sq[:, c] = (x[:, lo:hi + 1] ** 2).sum(1)
        ref = x / (bias + alpha / size * sq) ** beta
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_resize_nearest_2x(self):
        x = RS.randn(1, 2, 2, 2).astype(np.float32)
        got = run1(_node("Resize", ["x", "roi", "s"], ["y"],
                         mode="nearest"), {"x": x},
                   initializers={"roi": np.zeros(0, np.float32),
                                 "s": np.array([1, 1, 2, 2], np.float32)})
        ref = x.repeat(2, 2).repeat(2, 3)
        np.testing.assert_allclose(got, ref, atol=1e-6)

    def test_top_k(self):
        x = RS.randn(3, 6).astype(np.float32)
        vals, idx = run1(_node("TopK", ["x", "k"], ["y0", "y1"], axis=-1),
                         {"x": x}, initializers={"k": np.asarray(
                             2, np.int64)}, n_outputs=2)
        ref = np.sort(x, 1)[:, ::-1][:, :2]
        np.testing.assert_allclose(vals, ref, atol=1e-6)
        np.testing.assert_array_equal(idx, np.argsort(-x, 1)[:, :2])

    def test_roi_align_whole_image_mean(self):
        # ROI covering the full map with 1x1 output ≈ the map mean
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        bi = np.zeros(1, np.int64)
        got = run1(_node("RoiAlign", ["x", "r", "b"], ["y"],
                         output_height=1, output_width=1,
                         sampling_ratio=4, spatial_scale=1.0),
                   {"x": x}, initializers={"r": rois, "b": bi})
        assert got.shape == (1, 1, 1, 1)
        assert abs(float(got) - x.mean()) < 1.5


class TestRandom:
    def test_random_moments(self):
        got = run1(_node("RandomNormal", [], ["y"], shape=[256],
                         mean=1.0, scale=2.0), {}, out_shape=(256,))
        assert got.shape == (256,)
        assert abs(got.mean() - 1.0) < 0.5 and abs(got.std() - 2.0) < 0.6
        got = run1(_node("RandomUniform", [], ["y"], shape=[256],
                         low=-1.0, high=1.0), {}, out_shape=(256,))
        assert got.min() >= -1 and got.max() <= 1
        assert abs(got.mean()) < 0.25
