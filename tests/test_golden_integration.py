"""Golden-baseline integration tests (reference `platform-tests/.../
integration/IntegrationTestRunner` pattern): fixed-seed end-to-end runs
compared against committed expected values — regression tripwires for the
whole stack (init -> fit -> serde), with tolerances for cross-version
float drift (SURVEY §7 hard part 6)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _data(rs, b, f, c):
    x = rs.randn(b, f).astype(np.float32)
    y = np.zeros((b, c), np.float32)
    y[np.arange(b), rs.randint(0, c, b)] = 1.0
    return x, y


class TestGoldenMLP:
    """Golden values generated 2026-07-30 (jax 0.9.0, CPU, seed 12345)."""

    GOLDEN_LOSSES = [1.558639, 1.519035, 1.48349, 1.451367, 1.422158]
    GOLDEN_FINAL_SCORE = 1.395449

    @pytest.fixture(scope="class")
    def run_once(self):
        """Deterministic fixed-seed run shared by the class's tests."""
        return self._run()

    def _run(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(12345).updater(Sgd(learning_rate=0.1)).list()
                .layer(L.DenseLayer(n_in=10, n_out=20, activation="tanh"))
                .layer(L.OutputLayer(n_out=4, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.feed_forward(10)).build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(777)
        x, y = _data(rs, 32, 10, 4)
        losses = []
        for _ in range(5):
            net.fit(x, y)
            losses.append(net.score_value)
        return net, x, y, losses

    def test_loss_trajectory_matches_golden(self, run_once):
        _, _, _, losses = run_once
        np.testing.assert_allclose(losses, self.GOLDEN_LOSSES, rtol=2e-3)

    def test_post_training_score(self, run_once):
        net, x, y, _ = run_once
        from deeplearning4j_tpu.datasets.dataset import DataSet
        score = net.score(DataSet(x, y))
        np.testing.assert_allclose(score, self.GOLDEN_FINAL_SCORE,
                                   rtol=2e-3)

    def test_serde_preserves_golden_outputs(self, tmp_path, run_once):
        net, x, _, _ = run_once
        path = str(tmp_path / "golden.zip")
        net.save(path)
        from deeplearning4j_tpu.nn.serde import restore_model
        net2 = restore_model(path)
        np.testing.assert_allclose(net2.output(x).numpy(),
                                   net.output(x).numpy(), atol=1e-6)


class TestGoldenSameDiff:
    GOLDEN = [1.38945, 1.296639, 1.214418, 1.141212, 1.075134]

    def test_samediff_training_trajectory(self):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

        rs = np.random.RandomState(5)
        sd = SameDiff.create()
        x = sd.placeholder("x", (16, 6))
        y = sd.placeholder("y", (16, 3))
        w = sd.var("w", rs.randn(6, 3).astype(np.float32) * 0.5)
        b = sd.var("b", np.zeros(3, np.float32))
        logits = x.mmul(w) + b
        loss = sd.invoke("softmax_cross_entropy_loss_with_logits",
                         logits, sd.nn.softmax(y * 8.0)).mean()
        loss.rename("loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(TrainingConfig(
            updater=Adam(learning_rate=0.05),
            data_set_feature_mapping=["x"], data_set_label_mapping=["y"]))
        xs, ys = _data(rs, 16, 6, 3)
        hist = sd.fit(ListDataSetIterator([DataSet(xs, ys)]), num_epochs=5)
        losses = [round(float(v), 6) for c in hist.loss_curves
                  for v in c.losses]
        np.testing.assert_allclose(losses, self.GOLDEN, rtol=2e-3)
