"""The bench sanity gate must reject physically impossible measurements.

BENCH_r04's judged headline was 69,690 samples/s/chip — 2,989% implied
MFU, ~30x chip peak — produced by the axon tunnel replaying repeated
identical executes from cache. These tests pin the gate that keeps such
an artifact out of the judged record (VERDICT r4 directive #1).
"""
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import bench  # noqa: E402  (repo-root module)


def _bert_base_mfu(samples_per_sec, T=128, peak=197e12):
    from deeplearning4j_tpu.models import bert
    fpt = bert.flops_per_token(bert.BertConfig.base())
    return samples_per_sec * T * fpt / peak


DECREASING = np.linspace(10.4, 9.7, 20)


class TestCheckBertSanity:
    def test_rejects_the_r04_artifact(self):
        # the exact invalid judged number: 69,690 samples/s on a v5e
        mfu = _bert_base_mfu(69690.0)
        assert mfu > 10  # ~30x peak — sanity of the test itself
        ok, reason = bench.check_bert_sanity(DECREASING, mfu)
        assert not ok
        assert "impossible" in reason or "ceiling" in reason

    def test_rejects_anything_over_ceiling(self):
        ok, _ = bench.check_bert_sanity(DECREASING, 0.81)
        assert not ok
        ok, _ = bench.check_bert_sanity(DECREASING, bench.BERT_MFU_CEILING
                                        + 1e-6)
        assert not ok

    def test_accepts_credible_measurement(self):
        # r3's trustworthy headline: 1,427 samples/s ~= 60.6% MFU
        mfu = _bert_base_mfu(1427.0)
        assert 0.4 < mfu < bench.BERT_MFU_CEILING
        ok, reason = bench.check_bert_sanity(DECREASING, mfu)
        assert ok, reason

    def test_rejects_flat_loss_trajectory(self):
        # device never stepped: same loss replayed N times
        ok, reason = bench.check_bert_sanity(np.full(20, 10.38), 0.5)
        assert not ok
        assert "mostly flat" in reason

    def test_accepts_single_plateau_step(self):
        # one bitwise-equal adjacent pair is a legitimately plateaued f32
        # step, not a stuck device (the gate requires >= 80% changing)
        l = DECREASING.copy()
        l[7] = l[6]
        ok, reason = bench.check_bert_sanity(l, 0.5)
        assert ok, reason

    def test_rejects_mostly_stuck_trajectory(self):
        l = DECREASING.copy()
        l[10:] = l[10]  # back half frozen: device stopped stepping
        ok, reason = bench.check_bert_sanity(l, 0.5)
        assert not ok
        assert "mostly flat" in reason

    def test_rejects_nonfinite_loss(self):
        l = DECREASING.copy()
        l[3] = np.nan
        ok, reason = bench.check_bert_sanity(l, 0.5)
        assert not ok
        assert "finite" in reason

    def test_rejects_replayed_dispatch(self):
        # two of three dispatches return byte-identical trajectories:
        # the tunnel served a cached execute instead of running the scan
        t1 = DECREASING
        t3 = DECREASING - 0.8
        ok, reason = bench.check_bert_sanity(np.stack([t1, t1, t3]), 0.5)
        assert not ok
        assert "replayed" in reason

    def test_accepts_distinct_dispatches(self):
        stack = np.stack([DECREASING, DECREASING - 0.7, DECREASING - 1.4])
        ok, reason = bench.check_bert_sanity(stack, 0.5)
        assert ok, reason


class TestSelectHeadline:
    def test_insane_variant_never_wins(self):
        variants = {
            "flash": {"samples_per_sec": 69690.0, "mfu": 29.6, "sane": False,
                      "reason": "implied MFU 29.6 > ceiling"},
            "xla": {"samples_per_sec": 1427.0, "mfu": 0.606, "sane": True,
                    "reason": "ok"},
        }
        name, rec = bench.select_headline(variants)
        assert name == "xla"
        assert rec["samples_per_sec"] == 1427.0

    def test_all_insane_fails_loudly(self):
        variants = {
            "flash": {"samples_per_sec": 69690.0, "mfu": 29.6, "sane": False,
                      "reason": "implied MFU 29.6 > ceiling"},
        }
        with pytest.raises(RuntimeError, match="refusing to emit"):
            bench.select_headline(variants)

    def test_fastest_sane_wins(self):
        variants = {
            "a": {"samples_per_sec": 1000.0, "sane": True, "reason": "ok"},
            "b": {"samples_per_sec": 1400.0, "sane": True, "reason": "ok"},
        }
        name, _ = bench.select_headline(variants)
        assert name == "b"


def _tm_record(default_sps=100.0, remat_sps=80.0, default_peak=4_000_000,
               accum_peak=1_000_000, default_act=10_000_000,
               remat_act=2_000_000):
    return {
        "default": {"samples_per_sec": default_sps,
                    "peak_bytes": default_peak,
                    "activation_bytes": default_act},
        "remat": {"samples_per_sec": remat_sps,
                  "peak_bytes": default_peak,
                  "activation_bytes": remat_act},
        "remat_accum": {"samples_per_sec": remat_sps,
                        "peak_bytes": accum_peak,
                        "activation_bytes": remat_act},
    }


class TestCheckTrainMemory:
    """Gate logic for the train_memory metric (perf trajectory): remat must
    not cost >30% samples/sec at equal batch, and the accumulation path
    must actually lower peak memory at equal effective batch."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_train_memory(_tm_record())
        assert ok, reason

    def test_rejects_slow_remat(self):
        # 69 < 0.7 * 100: recompute ate more than the one-extra-forward
        # budget — the checkpoint boundaries are wrong
        ok, reason = bench.check_train_memory(_tm_record(remat_sps=69.0))
        assert not ok
        assert "remat samples/sec" in reason
        ok, _ = bench.check_train_memory(_tm_record(remat_sps=71.0))
        assert ok

    def test_rejects_accum_without_memory_win(self):
        ok, reason = bench.check_train_memory(
            _tm_record(accum_peak=4_000_000))
        assert not ok
        assert "saved no memory" in reason

    def test_rejects_remat_without_activation_win(self):
        ok, reason = bench.check_train_memory(
            _tm_record(remat_act=10_000_000))
        assert not ok
        assert "saved no activations" in reason

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU: the tiny CNN record must pass
        its own gate — deterministically lower XLA peak for the accum path
        and lower stored residuals for remat (analytic quantities, not
        wall-clock), and the wall-clock gate with the 30% margin."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_train_memory(jax, jnp, tiny=True)
        assert rec["gate_ok"], rec["gate_reason"]
        assert rec["remat_accum"]["peak_bytes"] < rec["default"]["peak_bytes"]
        assert (rec["remat"]["activation_bytes"]
                < rec["default"]["activation_bytes"])
        assert rec["effective_batch"] == rec["batch"]


class TestCheckTelemetryOverhead:
    """Gate logic for the telemetry_overhead metric: metrics-on serving
    throughput may cost at most 3% vs metrics-off (the near-zero-cost
    contract of the telemetry subsystem)."""

    def test_accepts_cheap_telemetry(self):
        ok, reason = bench.check_telemetry_overhead(
            {"metrics_on_sps": 990.0, "metrics_off_sps": 1000.0})
        assert ok, reason

    def test_rejects_expensive_telemetry(self):
        ok, reason = bench.check_telemetry_overhead(
            {"metrics_on_sps": 900.0, "metrics_off_sps": 1000.0})
        assert not ok
        assert "near-zero-cost" in reason

    def test_boundary_at_three_percent(self):
        ok, _ = bench.check_telemetry_overhead(
            {"metrics_on_sps": 970.0, "metrics_off_sps": 1000.0})
        assert ok
        ok, _ = bench.check_telemetry_overhead(
            {"metrics_on_sps": 969.0, "metrics_off_sps": 1000.0})
        assert not ok

    def test_custom_budget(self):
        rec = {"metrics_on_sps": 950.0, "metrics_off_sps": 1000.0}
        ok, _ = bench.check_telemetry_overhead(rec, max_overhead=0.10)
        assert ok

    def test_fleet_pass_gated_when_present(self):
        # records without the fleet pass (older artifacts) still gate
        base = {"metrics_on_sps": 990.0, "metrics_off_sps": 1000.0}
        ok, _ = bench.check_telemetry_overhead(dict(base))
        assert ok
        ok, _ = bench.check_telemetry_overhead(
            dict(base, fleet_on_rps=98.0, fleet_off_rps=100.0))
        assert ok
        ok, reason = bench.check_telemetry_overhead(
            dict(base, fleet_on_rps=90.0, fleet_off_rps=100.0))
        assert not ok
        assert "fleet observability plane" in reason

    def test_tiny_live_measurement_structure(self):
        """The metric end-to-end on CPU: record shape + gate evaluation.
        The 3% wall-clock bound itself is asserted by the bench artifact,
        not here (CI wall-clock is too noisy for a hard 3% unit test) —
        but the measured overhead must at least be far from pathological,
        and the enabled-flag must be restored afterwards."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.common.metrics import registry

        prev = registry().enabled
        rec = bench.bench_telemetry_overhead(jax, jnp, tiny=True)
        assert registry().enabled == prev  # restored
        assert rec["metrics_on_sps"] > 0 and rec["metrics_off_sps"] > 0
        assert "gate_ok" in rec and "gate_reason" in rec
        assert rec["overhead_frac"] == pytest.approx(
            1.0 - rec["metrics_on_sps"] / rec["metrics_off_sps"], abs=1e-3)
        assert rec["overhead_frac"] < 0.5  # sanity: nowhere near 2x
        # request-scoped tracing pass (PR 6): measured and sane
        assert rec["metrics_trace_sps"] > 0
        assert rec["tracing_overhead_frac"] < 0.5
        # fleet observability pass (PR 18): routed path measured with
        # the plane armed vs disarmed, same noise caveat as above
        assert rec["fleet_on_rps"] > 0 and rec["fleet_off_rps"] > 0
        assert rec["fleet_overhead_frac"] < 0.5


def _so_record(unloaded_p99=10.0, on_p99=20.0, on_completed=50, on_shed=40,
               off_p99=200.0):
    return {
        "unloaded_p99_ms": unloaded_p99,
        "shed_on": {"completed": on_completed, "shed": on_shed,
                    "offered": 120, "p50_ms": on_p99 / 2, "p99_ms": on_p99,
                    "throughput_rps": 100.0},
        "shed_off": {"completed": 120, "shed": 0, "offered": 120,
                     "p50_ms": off_p99 / 2, "p99_ms": off_p99,
                     "throughput_rps": 100.0},
    }


class TestCheckServingOverload:
    """Gate logic for the serving_overload metric: under synthetic
    overload the admission controller must actually shed, and the
    requests it DOES admit must keep a p99 within 3x of the unloaded
    p99 — the bounded-queue contract of load shedding."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_serving_overload(_so_record())
        assert ok, reason

    def test_rejects_unbounded_admitted_p99(self):
        ok, reason = bench.check_serving_overload(_so_record(on_p99=31.0))
        assert not ok
        assert "not bounding" in reason

    def test_boundary_at_three_x(self):
        ok, _ = bench.check_serving_overload(_so_record(on_p99=29.9))
        assert ok
        ok, _ = bench.check_serving_overload(_so_record(on_p99=30.1))
        assert not ok

    def test_rejects_record_without_shedding(self):
        # zero shed means the storm never overloaded the controller: the
        # bounded-p99 claim was not actually tested
        ok, reason = bench.check_serving_overload(_so_record(on_shed=0))
        assert not ok
        assert "never tripped" in reason

    def test_rejects_shed_everything(self):
        ok, reason = bench.check_serving_overload(
            _so_record(on_completed=0))
        assert not ok
        assert "shed everything" in reason

    def test_custom_ratio(self):
        rec = _so_record(on_p99=45.0)
        ok, _ = bench.check_serving_overload(rec, max_p99_ratio=5.0)
        assert ok

    def test_tiny_live_measurement(self):
        """The metric end-to-end on CPU: the storm must actually shed
        (deterministic: 4 threads vs max_concurrent=1 with high_water=1)
        and admitted requests must complete. The 3x wall-clock bound is
        evaluated and recorded; the bench artifact asserts it (CI
        wall-clock is too noisy for a hard latency unit test), but the
        measured tail must at least be far from pathological."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_serving_overload(jax, jnp, tiny=True)
        assert rec["shed_on"]["completed"] > 0
        assert rec["shed_on"]["shed"] > 0
        assert rec["shed_off"]["shed"] == 0
        assert rec["shed_on"]["completed"] + rec["shed_on"]["shed"] \
            == rec["shed_on"]["offered"]
        assert rec["unloaded_p99_ms"] > 0
        assert "gate_ok" in rec and "gate_reason" in rec
        # nowhere near unbounded: the no-shedding p99 is the unbounded
        # reference point and the shedding p99 must not exceed it
        assert rec["shed_on"]["p99_ms"] <= rec["shed_off"]["p99_ms"] * 1.5


def _sr_record(ok_rate=0.999, faulted_p99=25.0, fault_free_p99=10.0,
               injected=8, restarts=2, permadeaths=0, survivors=6,
               submitted=6, opened=True, reclosed=True, reclose_s=0.25,
               probe_s=0.2):
    return {
        "threads": 4, "requests_per_phase": 160, "fault_rate": 0.05,
        "fault_free": {"offered": 160, "ok": 160, "quarantined": 0,
                       "failed_other": 0, "ok_rate_of_nonpoison": 1.0,
                       "p50_ms": 2.0, "p99_ms": fault_free_p99},
        "faulted": {"offered": 160, "ok": 155, "quarantined": 2,
                    "failed_other": 0,
                    "ok_rate_of_nonpoison": ok_rate,
                    "p50_ms": 2.5, "p99_ms": faulted_p99,
                    "injected": injected},
        "batcher_crash": {"restarts": restarts, "survivors": survivors,
                          "submitted": submitted,
                          "permadeaths": permadeaths},
        "breaker": {"opened": opened, "reclosed": reclosed,
                    "probe_s": probe_s, "reclose_s": reclose_s,
                    "state": "closed"},
    }


class TestCheckServingResilience:
    """Gate logic for the serving_resilience metric: under 5% injected
    dispatch faults >= 99% of non-quarantined requests must succeed with
    a p99 within 3x of the fault-free run, the supervised batcher must
    restart (and never permadie), and the circuit breaker must open
    under sustained faults and re-close within its probe window."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_serving_resilience(_sr_record())
        assert ok, reason

    def test_rejects_zero_injected_faults(self):
        ok, reason = bench.check_serving_resilience(_sr_record(injected=0))
        assert not ok
        assert "untested" in reason

    def test_rejects_low_success_rate(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(ok_rate=0.98))
        assert not ok
        assert "innocent" in reason
        ok, _ = bench.check_serving_resilience(_sr_record(ok_rate=0.991))
        assert ok

    def test_rejects_unbounded_faulted_p99(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(faulted_p99=31.0))
        assert not ok
        assert "stalling" in reason
        ok, _ = bench.check_serving_resilience(_sr_record(faulted_p99=29.9))
        assert ok

    def test_rejects_permadeath(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(permadeaths=1))
        assert not ok
        assert "permadeath" in reason

    def test_rejects_unexercised_supervisor(self):
        ok, reason = bench.check_serving_resilience(_sr_record(restarts=0))
        assert not ok
        assert "never restarted" in reason

    def test_rejects_lost_queued_work(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(survivors=5))
        assert not ok
        assert "lost" in reason

    def test_rejects_breaker_that_never_opened(self):
        ok, reason = bench.check_serving_resilience(_sr_record(opened=False))
        assert not ok
        assert "never opened" in reason

    def test_rejects_breaker_that_stayed_open(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(reclosed=False))
        assert not ok
        assert "re-close" in reason

    def test_rejects_slow_reclose(self):
        ok, reason = bench.check_serving_resilience(
            _sr_record(reclose_s=2.0, probe_s=0.2))
        assert not ok
        assert "probe" in reason

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. The deterministic legs ARE
        asserted in CI (faults injected, supervisor restarted, zero
        permadeaths, breaker opened and re-closed); the p99 ratio is
        evaluated and recorded, with wide margin at the tiny sizing."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.common import faults as faults_mod

        rec = bench.bench_serving_resilience(jax, jnp, tiny=True)
        assert not faults_mod.active()  # bench disarmed everything
        assert rec["faulted"]["injected"] > 0
        assert rec["batcher_crash"]["restarts"] >= 1
        assert rec["batcher_crash"]["permadeaths"] == 0
        assert rec["batcher_crash"]["survivors"] == \
            rec["batcher_crash"]["submitted"]
        assert rec["breaker"]["opened"] and rec["breaker"]["reclosed"]
        assert rec["breaker"]["state"] == "closed"
        assert rec["faulted"]["ok_rate_of_nonpoison"] >= 0.99
        assert "gate_ok" in rec and "gate_reason" in rec


def _gd_record(kv_speedup=4.0, cb_speedup=2.0, match=True, compiles=0,
               bytes_ratio=0.35, prefill_speedup=1.7, spec_match=True,
               acceptance=0.8):
    return {
        "kv_cached": {"tokens_per_sec": 400.0},
        "recompute": {"tokens_per_sec": 400.0 / kv_speedup},
        "kv_speedup": kv_speedup,
        "decode_match": match,
        "steady_state_compiles": compiles,
        "continuous": {"tokens_per_sec": 1000.0, "requests": 6,
                       "p50_ttft_ms": 5.0, "p99_ttft_ms": 25.0},
        "serial": {"tokens_per_sec": 1000.0 / cb_speedup},
        "cb_speedup": cb_speedup,
        "paged_kv": {"block_size": 16,
                     "paged_bytes_per_token": 10000.0 * bytes_ratio
                     if bytes_ratio is not None else None,
                     "slab_bytes_per_token": 10000.0,
                     "bytes_ratio": bytes_ratio},
        "batched_prefill": {"prompts": 16, "batched_dispatches": 4,
                            "serial_dispatches": 16,
                            "speedup": prefill_speedup,
                            "p99_ttft_ms": 20.0},
        "speculative": {"k": 3, "decode_match": spec_match,
                        "tokens_per_sec": 600.0,
                        "plain_tokens_per_sec": 400.0,
                        "speedup": 1.5, "acceptance_rate": acceptance,
                        "proposed": 90, "accepted": 72},
    }


class TestCheckGenerativeDecode:
    """Gate logic for the generative_decode metric: the KV cache must buy
    >= 3x tokens/sec over prefix recompute, continuous batching >= 1.5x
    over per-request serving, greedy outputs must be token-identical, and
    the steady state must compile nothing after warmup. The paging PR
    added three more: paged KV must hold <= 0.6x the slab layout's bytes
    per active token, batched prefill must ingest prompts >= 1.3x faster
    than per-prompt dispatch, and the speculative run must be
    token-identical to the engine's own plain run with a measured
    acceptance rate."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_generative_decode(_gd_record())
        assert ok, reason

    def test_rejects_insufficient_kv_speedup(self):
        ok, reason = bench.check_generative_decode(
            _gd_record(kv_speedup=2.5))
        assert not ok
        assert "prefix recompute" in reason

    def test_boundary_at_three_x(self):
        ok, _ = bench.check_generative_decode(_gd_record(kv_speedup=3.01))
        assert ok
        ok, _ = bench.check_generative_decode(_gd_record(kv_speedup=2.99))
        assert not ok

    def test_rejects_insufficient_cb_speedup(self):
        ok, reason = bench.check_generative_decode(
            _gd_record(cb_speedup=1.3))
        assert not ok
        assert "sharing decode steps" in reason
        ok, _ = bench.check_generative_decode(_gd_record(cb_speedup=1.51))
        assert ok

    def test_rejects_token_mismatch(self):
        # a fast decode that decodes something else is not a speedup
        ok, reason = bench.check_generative_decode(_gd_record(match=False))
        assert not ok
        assert "token" in reason

    def test_rejects_steady_state_recompiles(self):
        ok, reason = bench.check_generative_decode(_gd_record(compiles=2))
        assert not ok
        assert "retracing" in reason

    def test_rejects_high_kv_bytes_ratio(self):
        # paged footprint near the slab's means blocks aren't tracking
        # actual sequence length — the whole point of paging
        ok, reason = bench.check_generative_decode(
            _gd_record(bytes_ratio=0.7))
        assert not ok
        assert "bytes per active token" in reason
        ok, _ = bench.check_generative_decode(_gd_record(bytes_ratio=0.59))
        assert ok
        ok, _ = bench.check_generative_decode(_gd_record(bytes_ratio=0.61))
        assert not ok

    def test_rejects_missing_paged_section(self):
        rec = _gd_record()
        del rec["paged_kv"]
        ok, reason = bench.check_generative_decode(rec)
        assert not ok
        assert "paged_kv" in reason
        rec = _gd_record(bytes_ratio=None)
        ok, reason = bench.check_generative_decode(rec)
        assert not ok
        assert "paged_kv" in reason

    def test_rejects_insufficient_prefill_speedup(self):
        ok, reason = bench.check_generative_decode(
            _gd_record(prefill_speedup=1.1))
        assert not ok
        assert "sharing a dispatch" in reason
        ok, _ = bench.check_generative_decode(
            _gd_record(prefill_speedup=1.31))
        assert ok

    def test_rejects_missing_prefill_section(self):
        rec = _gd_record()
        del rec["batched_prefill"]
        ok, reason = bench.check_generative_decode(rec)
        assert not ok
        assert "batched_prefill" in reason

    def test_rejects_speculative_token_mismatch(self):
        # a draft that changes the greedy output is a correctness bug,
        # whatever its speed
        ok, reason = bench.check_generative_decode(
            _gd_record(spec_match=False))
        assert not ok
        assert "non-speculative" in reason

    def test_rejects_missing_acceptance_rate(self):
        # no acceptance rate means the draft never proposed — the spec
        # path wasn't actually exercised
        ok, reason = bench.check_generative_decode(
            _gd_record(acceptance=None))
        assert not ok
        assert "acceptance" in reason

    def test_custom_thresholds(self):
        rec = _gd_record(kv_speedup=2.5, cb_speedup=1.2,
                         bytes_ratio=0.7, prefill_speedup=1.1)
        ok, _ = bench.check_generative_decode(rec, min_kv_speedup=2.0,
                                              min_cb_speedup=1.1,
                                              max_kv_bytes_ratio=0.8,
                                              min_prefill_speedup=1.0)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. Unlike the wall-clock-only
        gates, this one IS asserted in CI: token-identity, the
        zero-recompile invariant, and the paged-vs-slab bytes ratio are
        deterministic, and the timed gates have wide margins at the tiny
        sizing (measured ~4.4x KV / ~2.8x cb / ~1.7x prefill against
        3x / 1.5x / 1.3x; the bench retries once on a timing hiccup and
        the prefill burst is a median of three)."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_generative_decode(jax, jnp, tiny=True)
        assert rec["decode_match"]
        assert rec["steady_state_compiles"] == 0
        assert rec["continuous"]["p99_ttft_ms"] > 0
        assert rec["paged_kv"]["bytes_ratio"] < 0.6
        assert rec["batched_prefill"]["batched_dispatches"] < \
            rec["batched_prefill"]["serial_dispatches"]
        assert rec["speculative"]["decode_match"]
        assert rec["speculative"]["acceptance_rate"] is not None
        assert rec["gate_ok"], rec["gate_reason"]


def _qi_record(speedup=1.8, top1=1.0, bytes_ratio=0.26, rejected=True,
               status=200, served="v1", current="v1"):
    return {
        "top1_agreement": top1,
        "max_abs_err": 0.0003,
        "param_bytes_full": 1000000,
        "param_bytes_quant": int(1000000 * bytes_ratio),
        "bytes_ratio": bytes_ratio,
        "f32_sps": 9000.0,
        "bf16_sps": 4000.0,
        "quantized_sps": 4000.0 * speedup,
        "quant_speedup_vs_bf16": speedup,
        "misscale_rejected": rejected,
        "post_reject_predict_status": status,
        "post_reject_served_version": served,
        "current_version": current,
    }


class TestCheckQuantizedInference:
    """Gate logic for the quantized_inference metric: the int8 twin must
    be >= 1.2x the bf16 baseline and >= 99% top-1-consistent with f32,
    and the mis-scaled-spec drill must end with the gate rejecting the
    deploy and the full-precision version still serving."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_quantized_inference(_qi_record())
        assert ok, reason

    def test_rejects_insufficient_speedup(self):
        ok, reason = bench.check_quantized_inference(
            _qi_record(speedup=1.1))
        assert not ok
        assert "bf16 baseline" in reason

    def test_boundary_at_1_2x(self):
        ok, _ = bench.check_quantized_inference(_qi_record(speedup=1.21))
        assert ok
        ok, _ = bench.check_quantized_inference(_qi_record(speedup=1.19))
        assert not ok

    def test_rejects_low_top1_agreement(self):
        ok, reason = bench.check_quantized_inference(
            _qi_record(top1=0.95))
        assert not ok
        assert "top-1" in reason

    def test_rejects_unshrunk_params(self):
        # a "quantized" twin that is still f32-sized never stored int8
        ok, reason = bench.check_quantized_inference(
            _qi_record(bytes_ratio=1.0))
        assert not ok
        assert "at rest" in reason

    def test_rejects_unguarded_misscale_deploy(self):
        ok, reason = bench.check_quantized_inference(
            _qi_record(rejected=False))
        assert not ok
        assert "gate" in reason

    def test_rejects_disturbed_live_version(self):
        # the aborted swap must leave v1 current and answering
        ok, reason = bench.check_quantized_inference(
            _qi_record(status=503))
        assert not ok
        assert "aborted swap" in reason
        ok, _ = bench.check_quantized_inference(_qi_record(current="v2"))
        assert not ok

    def test_custom_thresholds(self):
        rec = _qi_record(speedup=1.1, top1=0.97)
        ok, _ = bench.check_quantized_inference(rec, min_speedup=1.05,
                                                min_top1=0.95)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. The deterministic legs ARE
        asserted in CI (top-1 agreement on the margin-filtered batch,
        int8-at-rest byte shrink, the mis-scale rejection with v1 still
        answering /predict); the 1.2x throughput gate has wide margin at
        the tiny sizing (measured ~1.8x: XLA:CPU emulates bf16, the twin
        computes in f32 with folded dequant)."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_quantized_inference(jax, jnp, tiny=True)
        assert rec["top1_agreement"] >= 0.99
        assert rec["bytes_ratio"] < 0.6
        assert rec["misscale_rejected"]
        assert rec["post_reject_predict_status"] == 200
        assert rec["post_reject_served_version"] == "v1"
        assert rec["current_version"] == "v1"
        assert rec["current_precision"] == "float32"
        assert "gate_ok" in rec and "gate_reason" in rec


def _cs_record(cold_ttfi=0.5, warm_ttfi=0.1, warm_hits=4):
    return {
        "cold": {"ttfi_s": cold_ttfi, "warmup_s": 1.0, "cache_hits": 0},
        "warm": {"ttfi_s": warm_ttfi, "warmup_s": 0.3,
                 "cache_hits": warm_hits},
    }


class TestCheckColdStart:
    """Gate logic for the cold_start metric: a warm-cache restart must be
    >= 2x faster to first inference than a cold compile, and the speedup
    must come from real executable-store hits."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_cold_start(_cs_record())
        assert ok, reason

    def test_rejects_insufficient_speedup(self):
        ok, reason = bench.check_cold_start(
            _cs_record(cold_ttfi=0.15, warm_ttfi=0.1))
        assert not ok
        assert "2.0x" in reason or "2x" in reason or "faster" in reason

    def test_boundary_at_two_x(self):
        ok, _ = bench.check_cold_start(
            _cs_record(cold_ttfi=0.21, warm_ttfi=0.1))
        assert ok
        ok, _ = bench.check_cold_start(
            _cs_record(cold_ttfi=0.19, warm_ttfi=0.1))
        assert not ok

    def test_rejects_speedup_without_cache_hits(self):
        # a fast warm phase with zero store hits is measuring leaked
        # in-memory caches, not the persistent store
        ok, reason = bench.check_cold_start(_cs_record(warm_hits=0))
        assert not ok
        assert "no executable-store hits" in reason

    def test_custom_min_speedup(self):
        rec = _cs_record(cold_ttfi=0.15, warm_ttfi=0.1)
        ok, _ = bench.check_cold_start(rec, min_speedup=1.2)
        assert ok

    def test_tiny_live_measurement(self):
        """The full metric end-to-end on CPU: a fresh cache dir, a cold
        phase that stores executables, a warm phase that loads them. The
        warm phase must actually hit the store; the 2x wall-clock gate is
        evaluated and recorded (and asserted by the bench artifact — CI
        only requires the record to be structurally sound and the hits
        real)."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_cold_start(jax, jnp, tiny=True)
        for phase in ("cold", "warm"):
            assert rec[phase]["ttfi_s"] > 0
            assert rec[phase]["warmup_s"] > 0
            assert rec[phase]["buckets_warmed"] >= 1
        assert rec["cold"]["cache_hits"] == 0
        assert rec["warm"]["cache_hits"] > 0
        assert rec["hit_observations"] > 0
        assert "gate_ok" in rec and "gate_reason" in rec
        assert rec["ttfi_speedup"] == pytest.approx(
            rec["cold"]["ttfi_s"] / rec["warm"]["ttfi_s"], rel=1e-2)


def _ss_record(allclose=True, argmax=1.0, max_err=3e-8, scaleout=2.8,
               hit3=3, failovers=1, nonshed=1.0):
    return {
        "n_devices": 8, "threads": 6, "requests_per_storm": 90,
        "batch_delay_ms": 20.0,
        "parity": {"mesh_shape": {"data": 1, "model": 8},
                   "param_spec": "auto(model)", "allclose": allclose,
                   "argmax_match_rate": argmax, "max_abs_err": max_err},
        "single_replica": {"offered": 90, "ok": 90, "shed": 0,
                           "failed": 0, "throughput_rps": 46.0,
                           "p50_ms": 129.0, "p99_ms": 133.0,
                           "replicas_hit": 1},
        "fleet3": {"offered": 90, "ok": 90, "shed": 0, "failed": 0,
                   "throughput_rps": 46.0 * scaleout, "p50_ms": 45.0,
                   "p99_ms": 53.0, "replicas_hit": hit3},
        "scaleout": scaleout,
        "kill_drill": {"offered": 90, "ok": int(round(88 * nonshed)),
                       "shed": 2, "failed": 90 - 2 - int(round(
                           88 * nonshed)),
                       "throughput_rps": 98.0, "p50_ms": 65.0,
                       "p99_ms": 78.0, "replicas_hit": 3,
                       "failovers": failovers,
                       "nonshed_success_rate": nonshed},
    }


class TestCheckShardedServing:
    """Gate logic for the sharded_serving metric: the mesh-sharded deploy
    must be decision-identical to single-device, the 3-replica router
    must actually spread and buy >= 2x throughput over one replica, and
    killing a replica mid-storm must lose nothing (100% non-shed success
    via one failover retry)."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_sharded_serving(_ss_record())
        assert ok, reason

    def test_rejects_diverging_sharded_logits(self):
        ok, reason = bench.check_sharded_serving(
            _ss_record(allclose=False, max_err=0.3))
        assert not ok
        assert "diverges" in reason

    def test_rejects_changed_decisions(self):
        # logits within tolerance but a flipped argmax is a served
        # wrong answer, whatever the float error
        ok, reason = bench.check_sharded_serving(_ss_record(argmax=0.75))
        assert not ok
        assert "diverges" in reason

    def test_rejects_insufficient_scaleout(self):
        ok, reason = bench.check_sharded_serving(_ss_record(scaleout=1.5))
        assert not ok
        assert "scaling the fleet out" in reason

    def test_boundary_at_two_x(self):
        ok, _ = bench.check_sharded_serving(_ss_record(scaleout=2.01))
        assert ok
        ok, _ = bench.check_sharded_serving(_ss_record(scaleout=1.99))
        assert not ok

    def test_rejects_unspread_storm(self):
        # a ratio measured against a router that piled everything onto
        # one replica proves nothing about scale-out
        ok, reason = bench.check_sharded_serving(_ss_record(hit3=1))
        assert not ok
        assert "never spread" in reason

    def test_rejects_unexercised_kill_drill(self):
        ok, reason = bench.check_sharded_serving(_ss_record(failovers=0))
        assert not ok
        assert "untested" in reason

    def test_rejects_lost_requests_on_failover(self):
        ok, reason = bench.check_sharded_serving(
            _ss_record(nonshed=0.977))
        assert not ok
        assert "losing requests" in reason

    def test_custom_min_scaleout(self):
        ok, _ = bench.check_sharded_serving(_ss_record(scaleout=1.6),
                                            min_scaleout=1.5)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. The deterministic legs ARE
        asserted in CI: sharded-vs-single-device parity, the router
        spreading over all 3 replicas, and the kill drill's zero lost
        requests with a recorded failover. The 2x throughput gate has
        wide margin at this sizing (measured ~2.8x: per-replica service
        time is the micro-batcher's no-CPU coalescing window, so three
        replicas overlap their windows even on one core)."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_sharded_serving(jax, jnp, tiny=True)
        assert rec["parity"]["allclose"]
        assert rec["parity"]["argmax_match_rate"] == 1.0
        assert rec["fleet3"]["replicas_hit"] == 3
        assert rec["kill_drill"]["failovers"] >= 1
        assert rec["kill_drill"]["nonshed_success_rate"] == 1.0
        assert rec["kill_drill"]["failed"] == 0
        assert rec["gate_ok"], rec["gate_reason"]


def _fr_record(baseline_p99=80.0, faulted_p99=160.0, failed=0,
               baseline_failed=0, injected=30, extra=30, launched=10,
               ejections=1, readmissions=1, ratio=0.5, burst=10.0):
    offered = 90
    return {
        "threads": 6, "requests_per_storm": offered,
        "batch_delay_ms": 20.0, "fault_rate": 0.2,
        "outlier_delay_ms": 200.0,
        "budget": {"ratio": ratio, "burst": burst},
        "baseline": {"offered": offered, "ok": offered - baseline_failed,
                     "shed": 0, "failed": baseline_failed,
                     "throughput_rps": 80.0, "p50_ms": 60.0,
                     "p99_ms": baseline_p99, "replicas_hit": 3},
        "faulted": {"offered": offered, "ok": offered - failed,
                    "shed": 0, "failed": failed, "throughput_rps": 60.0,
                    "p50_ms": 70.0, "p99_ms": faulted_p99,
                    "replicas_hit": 3, "injected": injected,
                    "attempts": offered + extra,
                    "extra_dispatches": extra,
                    "hedges": {"launched": launched, "won": 5,
                               "suppressed": 1},
                    "budget_denials": 1},
        "p99_ratio": round(faulted_p99 / baseline_p99, 3),
        "outlier": {"url": "http://127.0.0.1:9999",
                    "ejections": ejections,
                    "readmissions": readmissions},
    }


class TestCheckFleetResilience:
    """Gate logic for the fleet_resilience metric: under a 20% injected
    dispatch-fault rate plus one 10x-latency outlier, the router must
    lose zero non-shed requests, hold p99 <= 3x the fault-free storm,
    keep hedge+retry overhead inside the token budget, and eject then
    probe-re-admit the outlier."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_fleet_resilience(_fr_record())
        assert ok, reason

    def test_rejects_zero_injected_faults(self):
        ok, reason = bench.check_fleet_resilience(_fr_record(injected=0))
        assert not ok
        assert "untested" in reason

    def test_rejects_dirty_baseline(self):
        # a fault-free storm that drops requests invalidates the p99
        # yardstick (and means the fleet is broken without faults)
        ok, reason = bench.check_fleet_resilience(
            _fr_record(baseline_failed=1))
        assert not ok
        assert "yardstick" in reason

    def test_rejects_lost_requests(self):
        ok, reason = bench.check_fleet_resilience(_fr_record(failed=1))
        assert not ok
        assert "dropping traffic" in reason

    def test_rejects_unbounded_p99_and_boundary(self):
        ok, reason = bench.check_fleet_resilience(
            _fr_record(faulted_p99=241.0))
        assert not ok
        assert "tail" in reason
        ok, _ = bench.check_fleet_resilience(_fr_record(faulted_p99=239.0))
        assert ok

    def test_rejects_overbudget_dispatch_and_boundary(self):
        # allowance = 0.5 * 90 offered + 10 burst = 55
        ok, reason = bench.check_fleet_resilience(_fr_record(extra=56))
        assert not ok
        assert "unbounded" in reason
        ok, _ = bench.check_fleet_resilience(_fr_record(extra=55))
        assert ok

    def test_rejects_storm_that_never_hedged(self):
        ok, reason = bench.check_fleet_resilience(_fr_record(launched=0))
        assert not ok
        assert "hedging path is untested" in reason

    def test_rejects_unejected_outlier(self):
        ok, reason = bench.check_fleet_resilience(_fr_record(ejections=0))
        assert not ok
        assert "never ejected" in reason

    def test_rejects_permanent_ejection(self):
        ok, reason = bench.check_fleet_resilience(
            _fr_record(readmissions=0))
        assert not ok
        assert "permanent" in reason

    def test_custom_max_ratio(self):
        rec = _fr_record(faulted_p99=320.0)
        ok, _ = bench.check_fleet_resilience(rec, max_p99_ratio=5.0)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. The deterministic legs ARE
        asserted in CI: faults fired, zero lost requests in both storms,
        hedges launched, the outlier ejected and probe-re-admitted, and
        dispatch overhead inside the configured budget. The 3x p99
        ratio is evaluated and recorded with wide margin at the tiny
        sizing (the hedge answers at ~p95 while the outlier sits on a
        fixed 200 ms connect delay)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.common import faults as faults_mod

        rec = bench.bench_fleet_resilience(jax, jnp, tiny=True)
        assert not faults_mod.active()  # bench disarmed everything
        assert rec["faulted"]["injected"] > 0
        assert rec["baseline"]["failed"] == 0
        assert rec["faulted"]["failed"] == 0
        assert rec["faulted"]["hedges"]["launched"] >= 1
        allowance = (rec["budget"]["ratio"] * rec["faulted"]["offered"]
                     + rec["budget"]["burst"])
        assert rec["faulted"]["extra_dispatches"] <= allowance
        assert rec["outlier"]["ejections"] >= 1
        assert rec["outlier"]["readmissions"] >= 1
        assert rec["gate_ok"], rec["gate_reason"]


def _op_record(storm_ok=40, status=200, echoed="ab" * 16,
               kinds=("hedge", "primary"), subtree=(
                   "inference/dispatch", "inference/ride",
                   "serving/admission", "serving/predict",
                   "serving/request"),
               checked=4, missing=0, max_diff=0.0, rows=3,
               consistent=True):
    return {
        "replicas": 3,
        "storm_requests": 40,
        "storm_ok": storm_ok,
        "percentile_parity": {
            "series_checked": checked,
            "series_missing": missing,
            "max_abs_diff": max_diff,
        },
        "signals": {
            "replica_rows": rows,
            "fleet_ready": rows,
            "rollup_consistent": consistent,
        },
        "stitched": {
            "status": status,
            "trace_id": "ab" * 16,
            "echoed_trace_id": echoed,
            "attempt_kinds": sorted(kinds),
            "outcomes": ["abandoned", "ok"],
            "replicas_stitched": 2,
            "winner_subtree": sorted(subtree),
        },
    }


class TestCheckObservabilityPlane:
    """Gate logic for the observability_plane metric: a hedged predict
    through the real HTTP front door must yield ONE stitched trace
    (both attempt spans + the winner's server-side subtree), fleet
    percentiles must be bucket-exact vs the pooled per-replica data,
    and /fleet/signals must list every replica with a self-consistent
    rollup."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_observability_plane(_op_record())
        assert ok, reason

    def test_rejects_lossy_storm(self):
        ok, reason = bench.check_observability_plane(
            _op_record(storm_ok=39))
        assert not ok
        assert "unhealthy" in reason

    def test_rejects_failed_hedged_predict(self):
        ok, reason = bench.check_observability_plane(
            _op_record(status=503))
        assert not ok
        assert "503" in reason

    def test_rejects_dropped_trace_context(self):
        ok, reason = bench.check_observability_plane(
            _op_record(echoed="cd" * 16))
        assert not ok
        assert "trace context was dropped" in reason

    def test_rejects_missing_attempt_span(self):
        ok, reason = bench.check_observability_plane(
            _op_record(kinds=("primary",)))
        assert not ok
        assert "hedge" in reason
        ok, reason = bench.check_observability_plane(
            _op_record(kinds=("hedge", "retry")))
        assert not ok

    def test_rejects_unstitched_winner_subtree(self):
        ok, reason = bench.check_observability_plane(
            _op_record(subtree=("serving/request", "serving/admission")))
        assert not ok
        assert "inference/dispatch" in reason

    def test_rejects_empty_parity_check(self):
        ok, reason = bench.check_observability_plane(
            _op_record(checked=0))
        assert not ok
        assert "no histogram series" in reason

    def test_rejects_missing_merged_series(self):
        ok, reason = bench.check_observability_plane(
            _op_record(missing=1))
        assert not ok
        assert "missing from the fleet" in reason

    def test_rejects_inexact_percentiles(self):
        # ANY drift fails: the merge is bucket addition, not estimation
        ok, reason = bench.check_observability_plane(
            _op_record(max_diff=1e-9))
        assert not ok
        assert "not exact" in reason

    def test_rejects_incomplete_signals_membership(self):
        ok, reason = bench.check_observability_plane(_op_record(rows=2))
        assert not ok
        assert "expected 3" in reason

    def test_rejects_inconsistent_rollup(self):
        ok, reason = bench.check_observability_plane(
            _op_record(consistent=False))
        assert not ok
        assert "rollup" in reason

    @pytest.mark.slow
    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU over real HTTP: storm,
        percentile parity, signals rollup, and the forced-hedge
        stitched trace are all deterministic legs — the gate is
        asserted, not just recorded. Slow-marked like the other fleet
        acceptance drills: the same measurement gates `python bench.py`
        via main(), and the gate logic itself is pinned by the
        fabricated-record tests above."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.common import faults as faults_mod
        from deeplearning4j_tpu.common.metrics import registry

        prev = registry().enabled
        rec = bench.bench_observability_plane(jax, jnp, tiny=True)
        assert registry().enabled == prev  # restored
        assert not faults_mod.active()     # hedge fault disarmed
        assert rec["storm_ok"] == rec["storm_requests"]
        assert rec["percentile_parity"]["series_checked"] >= 1
        assert rec["percentile_parity"]["max_abs_diff"] == 0.0
        assert rec["signals"]["replica_rows"] == rec["replicas"]
        st = rec["stitched"]
        assert st["echoed_trace_id"] == st["trace_id"]
        assert {"hedge", "primary"} <= set(st["attempt_kinds"])
        assert rec["gate_ok"], rec["gate_reason"]


class TestScannedStepEndToEnd:
    def test_tiny_scan_chain_produces_sane_record(self):
        """The full measurement path on CPU: scanned step, median-of-5,
        gate evaluation — the losses must actually move."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models import bert

        config = bert.BertConfig.tiny()
        B, T = 4, 16
        rng = np.random.RandomState(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.randint(0, config.vocab_size, (B, T)), jnp.int32),
            "labels": jnp.asarray(
                np.where(rng.rand(B, T) < 0.15,
                         rng.randint(0, config.vocab_size, (B, T)), -100),
                jnp.int32),
            "attention_mask": jnp.ones((B, T), jnp.int32),
        }
        fpt = bert.flops_per_token(config)
        rec = bench._measure_bert_variant(
            jax, jnp, bert, config, batch, B, T, 4, {"remat": False},
            fpt, peak=0.0)
        assert rec["sane"], rec["reason"]
        assert rec["loss_last"] < rec["loss_first"]
        assert rec["samples_per_sec"] > 0


def _sa_record(lint_seconds=2.5, findings=0, inversions=0,
               on_sps=990.0, off_sps=1000.0):
    return {
        "lint_seconds": lint_seconds,
        "lint_modules": 168,
        "lint_findings": findings,
        "lint_baselined": 11,
        "lock_off_sps": off_sps,
        "lock_on_sps": on_sps,
        "lock_overhead_frac": round(1.0 - on_sps / off_sps, 4),
        "lock_inversions": inversions,
        "request_count": 32,
    }


class TestCheckStaticAnalysis:
    """Gate logic for the static_analysis metric: the dl4jlint pass must
    fit the CI budget (< 30 s) and come back green, and the DL105
    runtime lock-order tracker must cost < 3% serving throughput when
    armed (and record zero inversions on the healthy serving path)."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_static_analysis(_sa_record())
        assert ok, reason

    def test_rejects_slow_lint(self):
        ok, reason = bench.check_static_analysis(
            _sa_record(lint_seconds=31.0))
        assert not ok
        assert "budget" in reason

    def test_rejects_unbaselined_findings(self):
        ok, reason = bench.check_static_analysis(_sa_record(findings=2))
        assert not ok
        assert "lint-green" in reason

    def test_rejects_recorded_inversions(self):
        ok, reason = bench.check_static_analysis(_sa_record(inversions=1))
        assert not ok
        assert "inversion" in reason

    def test_rejects_expensive_tracker(self):
        ok, reason = bench.check_static_analysis(
            _sa_record(on_sps=960.0, off_sps=1000.0))
        assert not ok
        assert "near-zero-cost" in reason

    def test_boundary_at_three_percent(self):
        ok, _ = bench.check_static_analysis(
            _sa_record(on_sps=970.1, off_sps=1000.0))
        assert ok
        ok, _ = bench.check_static_analysis(
            _sa_record(on_sps=969.0, off_sps=1000.0))
        assert not ok

    def test_custom_budgets(self):
        ok, _ = bench.check_static_analysis(
            _sa_record(lint_seconds=31.0), max_seconds=60.0)
        assert ok
        ok, _ = bench.check_static_analysis(
            _sa_record(on_sps=960.0), max_overhead=0.05)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU: the lint pass runs over
        the real package (green, inside budget) and the tracker on/off
        serving measurement records no inversions. The 3% overhead leg
        is evaluated and recorded; the deterministic legs are hard
        asserts."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.common import locks

        before = locks.lock_check_enabled()
        rec = bench.bench_static_analysis(jax, jnp, tiny=True)
        assert rec["lint_findings"] == 0
        assert rec["lint_modules"] > 150
        assert rec["lint_seconds"] < 30.0
        assert rec["lock_inversions"] == 0
        assert rec["lock_off_sps"] > 0 and rec["lock_on_sps"] > 0
        assert "gate_ok" in rec and "gate_reason" in rec
        # the bench restored the tracker to the suite's state
        assert locks.lock_check_enabled() == before


def _fcs_record(remote_entries=4, live=0, hits=4, buckets=4,
                cold_ttr=0.12, warm_ttr=0.1):
    return {
        "remote_entries": remote_entries, "remote_bytes": 4096,
        "seed": {"ttr_s": 0.9, "buckets_warmed": buckets,
                 "live_compiles": buckets, "hit_compiles": 0,
                 "store_hits": 0},
        "warm_restart": {"ttr_s": warm_ttr, "buckets_warmed": buckets,
                         "live_compiles": 0, "hit_compiles": buckets,
                         "store_hits": buckets},
        "cold_join": {"ttr_s": cold_ttr, "buckets_warmed": buckets,
                      "live_compiles": live, "hit_compiles": hits,
                      "store_hits": hits},
        "ttr_ratio": round(cold_ttr / warm_ttr, 3),
    }


class TestCheckFleetColdStart:
    """Gate logic for the fleet_cold_start metric: a second replica with
    an empty local cache must warm entirely from the shared artifact
    store — zero live compiles — in <= 1.2x a fully-warm local
    restart's time-to-ready."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_fleet_cold_start(_fcs_record())
        assert ok, reason

    def test_rejects_empty_shared_store(self):
        # nothing published by the seed phase -> the cold join would be
        # measuring local recompiles, not the store
        ok, reason = bench.check_fleet_cold_start(
            _fcs_record(remote_entries=0))
        assert not ok
        assert "shared store" in reason

    def test_rejects_live_compiles_on_cold_join(self):
        ok, reason = bench.check_fleet_cold_start(
            _fcs_record(live=1, hits=3))
        assert not ok
        assert "live" in reason

    def test_rejects_partial_store_coverage(self):
        # a full ladder warmed but fewer store hits than buckets means
        # part of it came from somewhere other than the shared store
        ok, reason = bench.check_fleet_cold_start(
            _fcs_record(hits=2, buckets=4))
        assert not ok
        assert "somewhere other than" in reason

    def test_rejects_slow_join_and_boundary(self):
        ok, reason = bench.check_fleet_cold_start(
            _fcs_record(cold_ttr=0.15, warm_ttr=0.1))
        assert not ok
        assert "1.2" in reason
        ok, _ = bench.check_fleet_cold_start(
            _fcs_record(cold_ttr=0.119, warm_ttr=0.1))
        assert ok

    def test_custom_max_ratio(self):
        rec = _fcs_record(cold_ttr=0.15, warm_ttr=0.1)
        ok, _ = bench.check_fleet_cold_start(rec, max_ratio=2.0)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU against a real shared
        filesystem store. The deterministic legs are hard asserts (seed
        publishes, joiner records zero live compiles with every bucket a
        store hit); the 1.2x wall-clock ratio has wide margin on CPU
        since local and remote tiers are the same filesystem."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_fleet_cold_start(jax, jnp, tiny=True)
        for phase in ("seed", "warm_restart", "cold_join"):
            assert rec[phase]["ttr_s"] > 0
            assert rec[phase]["buckets_warmed"] >= 1
        assert rec["remote_entries"] > 0
        assert rec["seed"]["live_compiles"] > 0
        assert rec["cold_join"]["live_compiles"] == 0
        assert rec["cold_join"]["store_hits"] >= \
            rec["cold_join"]["buckets_warmed"]
        assert rec["ttr_ratio"] == pytest.approx(
            rec["cold_join"]["ttr_s"] / rec["warm_restart"]["ttr_s"],
            rel=1e-2)
        assert "gate_ok" in rec and "gate_reason" in rec


def _pr_record(match=True, reused=1120, expected=1120, cold_rows=1416,
               warm_rows=296, hits=5, requests=6, sess_match=True,
               ratio=10.2):
    return {
        "storm": {"decode_match": match, "requests": requests,
                  "reused_rows": reused, "expected_reused_rows": expected,
                  "prefill_rows": warm_rows,
                  "prefill_rows_cold": cold_rows,
                  "prefix_hits": hits},
        "session": {"decode_match": sess_match, "ttft_ratio": ratio,
                    "warm_ttft_s": 0.01, "cold_ttft_s": 0.01 * ratio},
    }


class TestCheckPrefixReuse:
    """Gate logic for the prefix_reuse metric: the radix cache must be
    invisible to the decoded function (token identity both phases), the
    storm must reuse EXACTLY the block-aligned common prefix per
    follower with the computed-row gap to prove single prefill, every
    follower must hit, and warm turn-2 TTFT must beat the cold
    full-history prefill by >= 5x."""

    def test_accepts_good_record(self):
        ok, reason = bench.check_prefix_reuse(_pr_record())
        assert ok, reason

    def test_rejects_storm_token_mismatch(self):
        ok, reason = bench.check_prefix_reuse(_pr_record(match=False))
        assert not ok
        assert "changed the decoded function" in reason

    def test_rejects_wrong_reused_rows(self):
        # a follower that re-prefilled its prefix (reused < expected) or
        # attached beyond the block-aligned run (reused > expected)
        ok, reason = bench.check_prefix_reuse(_pr_record(reused=1100))
        assert not ok
        assert "block-aligned common prefix" in reason
        ok, _ = bench.check_prefix_reuse(_pr_record(reused=1140))
        assert not ok

    def test_rejects_computed_row_gap_mismatch(self):
        # reused counter says 1120 but the engine actually computed the
        # same rows as the cold run: the "reuse" never skipped work
        ok, reason = bench.check_prefix_reuse(
            _pr_record(warm_rows=1416))
        assert not ok
        assert "prefilled exactly once" in reason

    def test_rejects_missed_followers(self):
        ok, reason = bench.check_prefix_reuse(_pr_record(hits=4))
        assert not ok
        assert "hit the cache" in reason

    def test_rejects_session_token_mismatch(self):
        ok, reason = bench.check_prefix_reuse(
            _pr_record(sess_match=False))
        assert not ok
        assert "decodes differently" in reason

    def test_rejects_insufficient_ttft_ratio_and_boundary(self):
        ok, reason = bench.check_prefix_reuse(_pr_record(ratio=4.9))
        assert not ok
        assert "5.0" in reason or "5x" in reason
        ok, _ = bench.check_prefix_reuse(_pr_record(ratio=5.01))
        assert ok

    def test_custom_min_ratio(self):
        ok, _ = bench.check_prefix_reuse(_pr_record(ratio=3.0),
                                         min_ratio=2.5)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU. The deterministic legs ARE
        asserted in CI: token identity in both phases, exact reused-row
        accounting (the storm prefills the common prefix once — the
        cold/warm computed-row gap equals the reused rows), and every
        follower hitting. The 5x TTFT gate has wide margin at the tiny
        sizing (measured ~10x: turn-2 prefills a 2-block tail instead of
        a 45-block history)."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_prefix_reuse(jax, jnp, tiny=True)
        assert rec["storm"]["decode_match"]
        assert rec["storm"]["reused_rows"] == \
            rec["storm"]["expected_reused_rows"]
        assert (rec["storm"]["prefill_rows_cold"]
                - rec["storm"]["prefill_rows"]) == \
            rec["storm"]["reused_rows"]
        assert rec["storm"]["prefix_hits"] == rec["storm"]["requests"] - 1
        assert rec["session"]["decode_match"]
        assert rec["session"]["ttft_ratio"] > 1.0
        assert rec["gate_ok"], rec["gate_reason"]


def _pd_record(identical=True, g_paged=1, g_flash=0, k_paged=0, k_flash=1,
               g_compiles=0, k_compiles=0, fused_dispatch=1, fused_err=2e-6,
               top1=1.0, platform="cpu", interpret=True, speedup=1.4):
    return {
        "platform": platform,
        "interpret": interpret,
        "gather": {"path": "paged", "tokens_per_sec": 100.0,
                   "steady_state_compiles": g_compiles,
                   "dispatch_paged": g_paged,
                   "dispatch_paged_flash": g_flash},
        "kernel": {"path": "paged_flash",
                   "tokens_per_sec": 100.0 * speedup,
                   "steady_state_compiles": k_compiles,
                   "dispatch_paged": k_paged,
                   "dispatch_paged_flash": k_flash},
        "token_identical": identical,
        "speedup_vs_gather": speedup,
        "fused_dequant": {"k": 512, "n": 512, "max_abs_err": fused_err,
                          "top1_agreement": top1,
                          "dispatch_fused": fused_dispatch},
    }


class TestCheckPallasDecode:
    """Gate logic for the pallas_decode metric: token-identical greedy
    streams between the gather and paged-flash phases, dispatch counters
    proving which path compiled each phase, zero steady-state recompiles,
    the fused dequant-matmul within the quant deploy-gate thresholds, and
    (accelerators only) the kernel actually beating the gather."""

    def test_accepts_good_cpu_record(self):
        ok, reason = bench.check_pallas_decode(_pd_record())
        assert ok, reason

    def test_rejects_token_divergence(self):
        ok, reason = bench.check_pallas_decode(_pd_record(identical=False))
        assert not ok
        assert "drop-in" in reason

    def test_rejects_gather_phase_served_by_kernel(self):
        # the "gather baseline" that secretly compiled the kernel
        ok, reason = bench.check_pallas_decode(
            _pd_record(g_paged=1, g_flash=1))
        assert not ok
        assert "gather" in reason
        ok, _ = bench.check_pallas_decode(_pd_record(g_paged=0))
        assert not ok

    def test_rejects_kernel_phase_served_by_gather(self):
        # a kernel phase that silently fell back measures nothing
        ok, reason = bench.check_pallas_decode(
            _pd_record(k_flash=0, k_paged=1))
        assert not ok
        assert "paged-flash" in reason

    def test_rejects_steady_state_recompiles(self):
        ok, reason = bench.check_pallas_decode(_pd_record(k_compiles=2))
        assert not ok
        assert "recompiled" in reason
        ok, _ = bench.check_pallas_decode(_pd_record(g_compiles=1))
        assert not ok

    def test_rejects_fused_leg_that_never_fused(self):
        ok, reason = bench.check_pallas_decode(
            _pd_record(fused_dispatch=0))
        assert not ok
        assert "fallback against itself" in reason

    def test_rejects_fused_divergence_and_top1(self):
        ok, reason = bench.check_pallas_decode(_pd_record(fused_err=0.3))
        assert not ok
        assert "diverges" in reason
        ok, reason = bench.check_pallas_decode(_pd_record(top1=0.9))
        assert not ok
        assert "top-1" in reason

    def test_accelerator_speed_gate_and_boundary(self):
        # on hardware the kernel must pay for itself; CPU (interpret
        # mode) skips the speed leg but must say so
        ok, reason = bench.check_pallas_decode(
            _pd_record(platform="tpu", interpret=False, speedup=1.01))
        assert not ok
        assert "paying for itself" in reason
        ok, _ = bench.check_pallas_decode(
            _pd_record(platform="tpu", interpret=False, speedup=1.06))
        assert ok
        ok, _ = bench.check_pallas_decode(
            _pd_record(speedup=0.5))  # cpu: speed leg skipped
        assert ok
        ok, reason = bench.check_pallas_decode(_pd_record(interpret=False))
        assert not ok
        assert "interpret" in reason

    def test_custom_thresholds(self):
        rec = _pd_record(platform="tpu", interpret=False, speedup=1.02)
        ok, _ = bench.check_pallas_decode(rec, min_speedup=1.01)
        assert ok

    def test_tiny_live_measurement_passes_gate(self):
        """The full metric end-to-end on CPU: the gather phase runs the
        XLA block-table gather, the kernel phase the same greedy loop
        through the interpret-mode Pallas kernel. The deterministic legs
        ARE asserted in CI (token identity, dispatch-counter proof of
        which path compiled each phase, zero steady-state recompiles,
        fused-dequant parity); the throughput leg is informational on
        CPU."""
        import jax
        import jax.numpy as jnp

        rec = bench.bench_pallas_decode(jax, jnp, tiny=True)
        assert rec["token_identical"]
        assert rec["interpret"]
        assert rec["gather"]["dispatch_paged"] >= 1
        assert rec["gather"]["dispatch_paged_flash"] == 0
        assert rec["kernel"]["dispatch_paged_flash"] >= 1
        assert rec["kernel"]["dispatch_paged"] == 0
        assert rec["gather"]["steady_state_compiles"] == 0
        assert rec["kernel"]["steady_state_compiles"] == 0
        assert rec["fused_dequant"]["max_abs_err"] <= 0.25
        assert rec["fused_dequant"]["dispatch_fused"] >= 1
        assert rec["gate_ok"], rec["gate_reason"]
