"""Enforced op-coverage accounting vs the reference's declarable inventory
(VERDICT round-1 item 7) + behavior tests for the new op families.

The coverage test is the OpValidation accounting analog
(`nd4j/.../autodiff/validation/OpValidation.java:117-232`): it enumerates
the reference's 517 DECLARE_* names and FAILS if coverage drops below 95%,
printing the exact diff.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.registry import OpRegistry, exec_op
from deeplearning4j_tpu.ops.reference_inventory import (EXEMPT,
                                                        REFERENCE_OPS,
                                                        all_reference_ops)


class TestCoverage:
    def test_reference_coverage_at_least_95_percent(self):
        reg = OpRegistry.get()
        names = all_reference_ops()
        missing = sorted(n for n in names
                         if not reg.has(n) and n not in EXEMPT)
        covered = len(names) - len(missing) - \
            sum(1 for n in names if n in EXEMPT)
        pct = 100.0 * covered / len(names)
        assert pct >= 95.0, (
            f"op coverage {pct:.1f}% ({covered}/{len(names)}); "
            f"missing: {missing}")

    def test_no_category_fully_missing(self):
        reg = OpRegistry.get()
        for header, names in REFERENCE_OPS.items():
            real = [n for n in names if n not in EXEMPT]
            if not real:
                continue
            present = sum(1 for n in real if reg.has(n))
            assert present > 0, f"entire header {header} unimplemented"

    def test_exempt_list_is_small_and_documented(self):
        assert len(EXEMPT) <= 10


class TestAutoBp:
    def test_tanh_bp_matches_analytic(self):
        x = jnp.asarray([0.3, -1.2, 2.0], jnp.float32)
        g = jnp.asarray([1.0, 1.0, 1.0], jnp.float32)
        got = exec_op("tanh_bp", x, g)
        expected = (1 - jnp.tanh(x) ** 2) * g
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=1e-6)

    def test_matmul_bp_shapes(self):
        a = jnp.ones((3, 4))
        b = jnp.ones((4, 5))
        g = jnp.ones((3, 5))
        ga, gb = exec_op("matmul_bp", a, b, g)
        assert ga.shape == a.shape and gb.shape == b.shape
        np.testing.assert_allclose(np.asarray(ga), np.asarray(g @ b.T))

    def test_add_bp_broadcast(self):
        a = jnp.ones((2, 3))
        b = jnp.ones((3,))
        g = jnp.full((2, 3), 2.0)
        ga, gb = exec_op("add_bp", a, b, g)
        assert ga.shape == (2, 3) and gb.shape == (3,)
        np.testing.assert_allclose(np.asarray(gb), [4.0, 4.0, 4.0])

    def test_softmax_cross_entropy_loss_grad_registered(self):
        reg = OpRegistry.get()
        assert reg.has("softmax_cross_entropy_loss_grad")
        assert reg.has("sigm_cross_entropy_loss_grad")


class TestImageOps:
    def test_color_roundtrips(self):
        rs = np.random.RandomState(0)
        img = jnp.asarray(rs.rand(4, 4, 3).astype(np.float32))
        for fwd, bwd in (("rgb_to_yiq", "yiq_to_rgb"),
                         ("rgb_to_yuv", "yuv_to_rgb"),
                         ("rgb_to_hsv", "hsv_to_rgb")):
            back = exec_op(bwd, exec_op(fwd, img))
            np.testing.assert_allclose(np.asarray(back), np.asarray(img),
                                       atol=1e-4)

    def test_resize(self):
        img = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
        out = exec_op("resize_nearest_neighbor", img, size=(2, 2))
        assert out.shape == (1, 2, 2, 1)
        out = exec_op("resize_bilinear", img, size=(8, 8))
        assert out.shape == (1, 8, 8, 1)

    def test_adjust_contrast(self):
        img = jnp.asarray([[[[1.0], [3.0]], [[5.0], [7.0]]]])
        out = exec_op("adjust_contrast", img, factor=2.0)
        np.testing.assert_allclose(np.asarray(out).ravel(),
                                   [-2.0, 2.0, 6.0, 10.0])

    def test_nms(self):
        boxes = jnp.asarray([[0, 0, 1, 1], [0, 0, 1, 1.01], [0, 2, 1, 3]],
                            jnp.float32)
        scores = jnp.asarray([0.9, 0.8, 0.7], jnp.float32)
        sel = exec_op("non_max_suppression", boxes, scores, 3,
                      iou_threshold=0.5)
        sel = [i for i in np.asarray(sel) if i >= 0]
        assert sel == [0, 2]


class TestListOps:
    def test_write_read_stack(self):
        lst = exec_op("create_list")
        lst = exec_op("write_list", lst, jnp.asarray([1.0, 2.0]), 0)
        lst = exec_op("write_list", lst, jnp.asarray([3.0, 4.0]), 2)
        assert int(exec_op("size_list", lst)) == 3
        stacked = exec_op("stack_list", lst)
        np.testing.assert_allclose(np.asarray(stacked),
                                   [[1, 2], [0, 0], [3, 4]])
        np.testing.assert_allclose(
            np.asarray(exec_op("read_list", lst, 2)), [3, 4])

    def test_unstack_split(self):
        arr = jnp.arange(6.0).reshape(3, 2)
        lst = exec_op("unstack_list", arr)
        assert len(lst) == 3
        parts = exec_op("split_list", arr, [1, 2])
        assert parts[0].shape == (1, 2) and parts[1].shape == (2, 2)


class TestStringOps:
    def test_split_string(self):
        vals, lens = exec_op("split_string",
                             np.asarray(["a b c", "d e"], object))
        assert list(vals) == ["a", "b", "c", "d", "e"]
        assert list(lens) == [3, 2]

    def test_compat_string_split_and_densify(self):
        idx, vals, shape = exec_op("compat_string_split",
                                   np.asarray(["x y", "z"], object))
        assert list(shape) == [2, 2]
        dense = exec_op("compat_sparse_to_dense", idx, shape, vals,
                        default_value="")
        assert dense[0][0] == "x" and dense[1][0] == "z" and dense[1][1] == ""

    def test_hashcode_deterministic(self):
        a = exec_op("hashcode", jnp.asarray([1, 2, 3], jnp.int32))
        b = exec_op("hashcode", jnp.asarray([1, 2, 3], jnp.int32))
        c = exec_op("hashcode", jnp.asarray([1, 2, 4], jnp.int32))
        assert int(a) == int(b) and int(a) != int(c)


class TestNlpOps:
    def test_skipgram_reduces_loss(self):
        rs = np.random.RandomState(0)
        syn0 = jnp.asarray(rs.randn(20, 8).astype(np.float32) * 0.1)
        syn1 = jnp.asarray(rs.randn(20, 8).astype(np.float32) * 0.1)
        target = jnp.asarray([1, 2], jnp.int32)
        context = jnp.asarray([3, 4], jnp.int32)
        neg = jnp.asarray([[5, 6], [7, 8]], jnp.int32)
        losses = []
        for _ in range(30):
            syn0, syn1, loss = exec_op("skipgram", syn0, syn1, target,
                                       context, neg, lr=0.1)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_cbow_reduces_loss(self):
        rs = np.random.RandomState(1)
        syn0 = jnp.asarray(rs.randn(20, 8).astype(np.float32) * 0.1)
        syn1 = jnp.asarray(rs.randn(20, 8).astype(np.float32) * 0.1)
        ctx = jnp.asarray([[1, 2, 0], [3, 4, 5]], jnp.int32)
        mask = jnp.asarray([[1, 1, 0], [1, 1, 1]], jnp.int32)
        target = jnp.asarray([6, 7], jnp.int32)
        neg = jnp.asarray([[8, 9], [10, 11]], jnp.int32)
        losses = []
        for _ in range(30):
            syn0, syn1, loss = exec_op("cbow", syn0, syn1, ctx, mask,
                                       target, neg, lr=0.1)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestRecurrentExtra:
    def test_lstm_block_runs(self):
        rs = np.random.RandomState(0)
        B, T, In, H = 2, 5, 3, 4
        x = jnp.asarray(rs.randn(T, B, In).astype(np.float32))
        w = jnp.asarray(rs.randn(In + H, 4 * H).astype(np.float32) * 0.3)
        b = jnp.zeros((4 * H,), jnp.float32)
        h0 = jnp.zeros((B, H), jnp.float32)
        c0 = jnp.zeros((B, H), jnp.float32)
        h_seq, h_last, c_last = exec_op("lstmBlock", x, h0, c0, w, b)
        assert h_seq.shape == (T, B, H)
        np.testing.assert_allclose(np.asarray(h_seq[-1]),
                                   np.asarray(h_last), atol=1e-6)

    def test_bidirectional_rnn(self):
        rs = np.random.RandomState(1)
        B, T, In, H = 2, 4, 3, 5
        x = jnp.asarray(rs.randn(B, T, In).astype(np.float32))
        args = [jnp.asarray(rs.randn(In, H).astype(np.float32) * 0.3),
                jnp.asarray(rs.randn(H, H).astype(np.float32) * 0.3),
                jnp.zeros((H,), jnp.float32)]
        args2 = [jnp.asarray(rs.randn(In, H).astype(np.float32) * 0.3),
                 jnp.asarray(rs.randn(H, H).astype(np.float32) * 0.3),
                 jnp.zeros((H,), jnp.float32)]
        seq, hf, hb = exec_op("static_bidirectional_rnn", x, *args, *args2)
        assert seq.shape == (B, T, 2 * H)


class TestParityExtra:
    def test_confusion_matrix(self):
        cm = exec_op("confusion_matrix", jnp.asarray([0, 1, 2, 1]),
                     jnp.asarray([0, 2, 2, 1]), num_classes=3)
        np.testing.assert_allclose(np.asarray(cm),
                                   [[1, 0, 0], [0, 1, 1], [0, 0, 1]])

    def test_fake_quant(self):
        x = jnp.asarray([-0.1, 0.0, 0.5, 1.1], jnp.float32)
        q = exec_op("fake_quant_with_min_max_vars", x, 0.0, 1.0)
        assert float(q[0]) >= -1e-6 and float(q[-1]) <= 1.0 + 1e-6

    def test_ctc_beam_greedy_case(self):
        # peaked logits decode to the obvious collapsed sequence
        T, C = 5, 4
        logits = np.full((1, T, C), -5.0, np.float32)
        for t, c in enumerate([1, 1, 0, 2, 2]):  # -> "1 2" after collapse
            logits[0, t, c] = 5.0
        paths, logp = exec_op("ctc_beam", jnp.asarray(logits),
                              beam_width=4, blank_index=0)
        decoded = [int(i) for i in np.asarray(paths)[0, 0] if i >= 0]
        assert decoded == [1, 2]

    def test_broadcastgradientargs(self):
        ra, rb = exec_op("broadcastgradientargs",
                         np.asarray([2, 3]), np.asarray([3]))
        assert list(rb) == [0] and list(ra) == []

    def test_barnes_gains(self):
        g = exec_op("barnes_gains", jnp.ones(3), jnp.asarray([1.0, -1.0, 1.0]),
                    jnp.asarray([1.0, 1.0, -1.0]))
        np.testing.assert_allclose(np.asarray(g), [0.8, 1.2, 1.2])
