"""Spark-API compatibility facade: reference-style distributed training
entry points over the mesh."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh
from deeplearning4j_tpu.parallel.spark_compat import (
    ParameterAveragingTrainingMaster, SharedTrainingMaster,
    SparkDl4jMultiLayer)


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(learning_rate=1e-2)).list()
            .layer(L.DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(L.OutputLayer(n_out=3, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
class TestSparkCompat:
    def test_parameter_averaging_style_fit(self):
        master = (ParameterAveragingTrainingMaster.Builder(16)
                  .averaging_frequency(5).aggregation_depth(2).build())
        mesh = make_mesh(MeshConfig(data=8))
        spark_net = SparkDl4jMultiLayer(mesh, _net(), master)
        rs = np.random.RandomState(0)
        data = []
        for _ in range(4):
            x = rs.randn(16, 8).astype(np.float32)
            y = np.zeros((16, 3), np.float32)
            y[np.arange(16), rs.randint(0, 3, 16)] = 1.0
            data.append(DataSet(x, y))
        spark_net.fit(data, num_epochs=2)
        assert np.isfinite(spark_net.get_score())

    def test_shared_training_master_knobs_accepted(self):
        master = (SharedTrainingMaster.Builder(32)
                  .update_threshold(1e-3)
                  .workers_per_node(4).build())
        assert master.threshold == 1e-3
        mesh = make_mesh(MeshConfig(data=2, tensor=2, fsdp=2))
        spark_net = SparkDl4jMultiLayer(mesh, _net(), master)
        rs = np.random.RandomState(1)
        x = rs.randn(8, 8).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]
        spark_net.fit([DataSet(x, y)])
        assert np.isfinite(spark_net.get_score())


class TestInertKnobWarnings:
    """Accepted-but-inert knobs must announce themselves at runtime
    (VERDICT r2 weak #8)."""

    def test_shared_master_warns_per_ignored_knob(self, caplog):
        import logging
        master = (SharedTrainingMaster.Builder(32)
                  .update_threshold(5e-4)
                  .threshold_algorithm("adaptive")
                  .workers_per_node(4).build())
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.parallel.spark_compat"):
            SparkDl4jMultiLayer(make_mesh(MeshConfig(data=8)), _net(), master)
        text = caplog.text
        assert "threshold=0.0005" in text
        assert "threshold_algorithm" in text
        assert "workers_per_node" in text
        assert text.count("has no effect on TPU") == 3

    def test_parameter_averaging_warns(self, caplog):
        import logging
        master = (ParameterAveragingTrainingMaster.Builder(16)
                  .averaging_frequency(5).build())
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.parallel.spark_compat"):
            SparkDl4jMultiLayer(make_mesh(MeshConfig(data=8)), _net(), master)
        assert "averaging_frequency=5" in caplog.text

    def test_default_knobs_stay_silent(self, caplog):
        import logging
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.parallel.spark_compat"):
            SparkDl4jMultiLayer(make_mesh(MeshConfig(data=8)), _net(),
                                SharedTrainingMaster.Builder(32).build())
        assert "has no effect" not in caplog.text


class TestAssertUnderJit:
    """Assert semantics survive compilation (VERDICT r2 weak #7): the
    condition is checked on host via callback, so a failing Assert inside a
    jitted graph raises instead of silently passing."""

    def test_eager_raises(self):
        from deeplearning4j_tpu.ops.registry import exec_op
        import jax.numpy as jnp
        with pytest.raises(AssertionError, match="boom"):
            exec_op("Assert", jnp.asarray(False), message="boom")
        assert bool(exec_op("Assert", jnp.asarray(True)))

    def test_jitted_failure_propagates(self):
        from deeplearning4j_tpu.ops.registry import OpRegistry
        import jax.numpy as jnp
        fn = OpRegistry.get().lookup("Assert").fn

        @jax.jit
        def guarded(x):
            fn(jnp.all(x > 0), message="nonpositive input")
            return x * 2

        out = guarded(jnp.asarray([1.0, 2.0]))   # passing case
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
        with pytest.raises(Exception, match="nonpositive input"):
            jax.block_until_ready(guarded(jnp.asarray([-1.0, 2.0])))
