"""Excel (.xlsx) and JDBC record readers (VERDICT r4 Missing #5 —
reference datavec-excel / datavec-jdbc parity).

The Excel reader is checked against BOTH our writer's output and a
hand-built workbook using the sharedStrings layout real Excel emits
(which our writer does not use), so the reader is validated against the
foreign format, not just our own round trip.
"""
import sqlite3
import zipfile

import pytest

from deeplearning4j_tpu.etl import (ExcelRecordReader, ExcelRecordWriter,
                                    FileSplit, JDBCRecordReader,
                                    LocalTransformExecutor, Schema,
                                    TransformProcess)


def _foreign_xlsx(path):
    """Workbook in Excel's own style: sharedStrings table, gap cells,
    two sheets."""
    shared = ('<?xml version="1.0"?>'
              '<sst xmlns="http://schemas.openxmlformats.org/'
              'spreadsheetml/2006/main" count="3" uniqueCount="3">'
              '<si><t>alpha</t></si><si><r><t>be</t></r><r><t>ta</t></r>'
              '</si><si><t>sheet2str</t></si></sst>')
    sheet1 = ('<?xml version="1.0"?>'
              '<worksheet xmlns="http://schemas.openxmlformats.org/'
              'spreadsheetml/2006/main"><sheetData>'
              '<row r="1"><c r="A1" t="s"><v>0</v></c>'
              '<c r="B1"><v>1.5</v></c><c r="C1" t="s"><v>1</v></c></row>'
              '<row r="2"><c r="A2"><v>7</v></c>'
              '<c r="C2"><v>9</v></c></row>'   # B2 is a gap cell
              '</sheetData></worksheet>')
    sheet2 = ('<?xml version="1.0"?>'
              '<worksheet xmlns="http://schemas.openxmlformats.org/'
              'spreadsheetml/2006/main"><sheetData>'
              '<row r="1"><c r="A1" t="s"><v>2</v></c>'
              '<c r="B1"><v>42</v></c></row></sheetData></worksheet>')
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("xl/sharedStrings.xml", shared)
        z.writestr("xl/worksheets/sheet1.xml", sheet1)
        z.writestr("xl/worksheets/sheet2.xml", sheet2)


class TestExcel:
    def test_reader_on_foreign_workbook(self, tmp_path):
        p = str(tmp_path / "foreign.xlsx")
        _foreign_xlsx(p)
        rr = ExcelRecordReader().initialize(FileSplit(p))
        rows = list(rr)
        assert rows == [["alpha", "1.5", "beta"],
                        ["7", "", "9"],          # gap cell -> empty
                        ["sheet2str", "42"]]     # second sheet appended

    def test_writer_reader_roundtrip(self, tmp_path):
        p = str(tmp_path / "out.xlsx")
        w = ExcelRecordWriter(p)
        w.write_batch([["name", "score", "flag"],
                       ["a", 1.25, True],
                       ["b <&> c", -3, False]])
        w.close()
        rr = ExcelRecordReader(skip_num_rows=1).initialize(FileSplit(p))
        rows = list(rr)
        assert rows[0] == ["a", "1.25", "1"]
        assert rows[1] == ["b <&> c", "-3", "0"]

    def test_skip_rows_is_per_sheet(self, tmp_path):
        p = str(tmp_path / "foreign.xlsx")
        _foreign_xlsx(p)
        rr = ExcelRecordReader(skip_num_rows=1).initialize(FileSplit(p))
        # first row of EACH sheet skipped
        assert list(rr) == [["7", "", "9"]]

    def test_workbook_order_and_phonetic_runs(self, tmp_path):
        """Sheets iterate in workbook.xml order (not part-number order);
        phonetic <rPh> runs are not part of the cell text."""
        p = str(tmp_path / "reordered.xlsx")
        ns = "http://schemas.openxmlformats.org/spreadsheetml/2006/main"
        shared = (f'<sst xmlns="{ns}"><si><t>first</t>'
                  '<rPh sb="0" eb="2"><t>IGNORED</t></rPh></si>'
                  '<si><t>second</t></si></sst>')
        mk = lambda si: (f'<worksheet xmlns="{ns}"><sheetData><row r="1">'
                         f'<c r="A1" t="s"><v>{si}</v></c></row>'
                         '</sheetData></worksheet>')
        wb = (f'<workbook xmlns="{ns}" xmlns:r="http://schemas.'
              'openxmlformats.org/officeDocument/2006/relationships">'
              '<sheets><sheet name="B" sheetId="1" r:id="rId2"/>'
              '<sheet name="A" sheetId="2" r:id="rId1"/>'
              '</sheets></workbook>')
        rels = ('<Relationships xmlns="http://schemas.openxmlformats.org/'
                'package/2006/relationships">'
                '<Relationship Id="rId1" Type="t" '
                'Target="worksheets/sheet1.xml"/>'
                '<Relationship Id="rId2" Type="t" '
                'Target="worksheets/sheet2.xml"/></Relationships>')
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("xl/workbook.xml", wb)
            z.writestr("xl/_rels/workbook.xml.rels", rels)
            z.writestr("xl/sharedStrings.xml", shared)
            z.writestr("xl/worksheets/sheet1.xml", mk(0))
            z.writestr("xl/worksheets/sheet2.xml", mk(1))
        rr = ExcelRecordReader().initialize(FileSplit(p))
        # workbook lists sheet2 (rId2) first; phonetic run excluded
        assert list(rr) == [["second"], ["first"]]

    def test_writer_quoted_sheet_name_and_nan(self, tmp_path):
        p = str(tmp_path / "q.xlsx")
        w = ExcelRecordWriter(p, sheet_name='my "best" sheet')
        w.write([float("nan"), 1.0])
        w.close()
        rr = ExcelRecordReader().initialize(FileSplit(p))
        rows = list(rr)
        assert rows == [["nan", "1.0"]]  # NaN lands as a string cell

    def test_through_transform_process(self, tmp_path):
        """Excel rows flow into Schema/TransformProcess like CSV rows."""
        p = str(tmp_path / "data.xlsx")
        w = ExcelRecordWriter(p)
        w.write_batch([["x", "y"], [1, 4.0], [2, 5.0], [3, 6.0]])
        w.close()
        rr = ExcelRecordReader(skip_num_rows=1).initialize(FileSplit(p))
        schema = (Schema.Builder().add_column_double("x")
                  .add_column_double("y").build())
        tp = (TransformProcess.Builder(schema)
              .remove_columns("y").build())
        out = LocalTransformExecutor.execute(list(rr), tp)
        assert [float(r[0]) for r in out] == [1.0, 2.0, 3.0]


class TestJdbc:
    def _db(self):
        conn = sqlite3.connect(":memory:")
        conn.execute("CREATE TABLE coffee (id INTEGER, name TEXT, "
                     "strength REAL)")
        conn.executemany("INSERT INTO coffee VALUES (?, ?, ?)",
                         [(1, " espresso ", 9.5), (2, "latte", 3.0),
                          (3, "filter", 5.5)])
        return conn

    def test_query_iteration_and_labels(self):
        rr = JDBCRecordReader("SELECT id, name, strength FROM coffee "
                              "ORDER BY id")
        rr.initialize(self._db())
        rows = list(rr)
        assert rows == [[1, " espresso ", 9.5], [2, "latte", 3.0],
                        [3, "filter", 5.5]]
        assert rr.get_labels() == ["id", "name", "strength"]

    def test_trim_strings(self):
        rr = JDBCRecordReader("SELECT name FROM coffee ORDER BY id",
                              trim_strings=True)
        rr.initialize(self._db())
        assert rr.next() == ["espresso"]

    def test_reset_rewinds_refresh_reexecutes(self):
        conn = self._db()
        rr = JDBCRecordReader("SELECT count(*) FROM coffee")
        rr.initialize(conn)
        assert rr.next() == [3]
        conn.execute("INSERT INTO coffee VALUES (4, 'mocha', 6.0)")
        rr.reset()
        assert rr.next() == [3]   # reset rewinds the fetched rows
        rr.refresh()
        assert rr.next() == [4]   # refresh re-executes the query

    def test_metadata_and_load_from_meta(self):
        rr = JDBCRecordReader(
            "SELECT id, name, strength FROM coffee ORDER BY id",
            metadata_query="SELECT id, name, strength FROM coffee "
                           "WHERE id = ?",
            metadata_indices=[0])
        rr.initialize(self._db())
        rec, meta = rr.next_with_meta()
        assert meta.values == [1]
        again = rr.load_from_meta(meta)
        assert again == rec

    def test_requires_initialize(self):
        rr = JDBCRecordReader("SELECT 1")
        with pytest.raises(RuntimeError, match="initialize"):
            rr.refresh()
