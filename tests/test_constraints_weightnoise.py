"""Layer constraints + weight noise (VERDICT r2 missing #3).

Reference: deeplearning4j-nn/.../nn/conf/constraint/{MaxNorm,MinMaxNorm,
NonNegative,UnitNorm}Constraint.java (applied post-update via
applyConstraint) and .../conf/weightnoise/{DropConnect,WeightNoise}.java
(applied pre-forward via getParameter(train=true)).
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.nn.conf import (
    DropConnect, InputType, MaxNormConstraint, MinMaxNormConstraint,
    NeuralNetConfiguration, NonNegativeConstraint, UnitNormConstraint,
    WeightNoise)
from deeplearning4j_tpu.nn.conf.constraints import apply_constraints
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _col_norms(W):
    return np.sqrt((np.asarray(W) ** 2).sum(axis=0))


class TestConstraintMath:
    def test_max_norm_projects_only_violators(self):
        W = jnp.asarray(np.array([[3.0, 0.1], [4.0, 0.2]]))  # norms 5, ~0.22
        out = np.asarray(MaxNormConstraint(max_norm=1.0).apply(W))
        np.testing.assert_allclose(_col_norms(out)[0], 1.0, atol=1e-4)
        np.testing.assert_allclose(out[:, 1], np.asarray(W)[:, 1], atol=1e-5)

    def test_unit_norm(self):
        W = jnp.asarray(np.random.RandomState(0).randn(6, 4) * 3)
        out = UnitNormConstraint().apply(W)
        np.testing.assert_allclose(_col_norms(out), 1.0, atol=1e-4)

    def test_non_negative(self):
        W = jnp.asarray([[-1.0, 2.0], [3.0, -4.0]])
        out = np.asarray(NonNegativeConstraint().apply(W))
        assert (out >= 0).all()
        np.testing.assert_allclose(out, [[0, 2], [3, 0]])

    def test_min_max_norm_full_rate(self):
        W = jnp.asarray(np.array([[0.1, 5.0], [0.0, 0.0]]))  # norms .1, 5
        out = MinMaxNormConstraint(min_norm=0.5, max_norm=2.0,
                                   rate=1.0).apply(W)
        norms = _col_norms(out)
        assert 0.45 <= norms[0] <= 0.55 and 1.95 <= norms[1] <= 2.05

    def test_explicit_dimensions(self):
        W = jnp.asarray(np.random.RandomState(1).randn(4, 3))
        out = MaxNormConstraint(max_norm=1.0, dimensions=(1,)).apply(W)
        row_norms = np.sqrt((np.asarray(out) ** 2).sum(axis=1))
        assert (row_norms <= 1.0 + 1e-4).all()

    def test_apply_constraints_targets(self):
        params = [{"W": jnp.ones((3, 3)) * 5, "b": jnp.ones((3,)) * -2,
                   "state_mean": jnp.ones((3,)) * -9}]
        out = apply_constraints([("weights", UnitNormConstraint()),
                                 ("bias", NonNegativeConstraint())], params)
        np.testing.assert_allclose(_col_norms(out[0]["W"]), 1.0, atol=1e-4)
        assert (np.asarray(out[0]["b"]) == 0).all()
        # running stats never touched
        np.testing.assert_allclose(out[0]["state_mean"], -9.0)


def _net(constraints=None, weight_noise=None, lr=0.5):
    b = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(lr)))
    if constraints:
        for target, c in constraints:
            getattr(b, f"constrain_{target}")(c)
    if weight_noise is not None:
        b.weight_noise(weight_noise)
    conf = (b.list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(n=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 6).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rs.randint(0, 3, n)] = 1
    return DataSet(x, y)


class TestConstraintInTraining:
    def test_max_norm_enforced_after_fit(self):
        net = _net(constraints=[("weights", MaxNormConstraint(max_norm=0.7))],
                   lr=1.0)  # big LR would push norms way past 0.7
        for _ in range(3):
            net.fit(_batch())
        for i in (0, 1):
            W = np.asarray(net.get_param_table(i)["W"].numpy())
            assert (_col_norms(W) <= 0.7 + 1e-3).all()

    def test_bias_constraint(self):
        net = _net(constraints=[("bias", NonNegativeConstraint())], lr=1.0)
        for _ in range(3):
            net.fit(_batch())
        for i in (0, 1):
            b = np.asarray(net.get_param_table(i)["b"].numpy())
            assert (b >= 0).all()

    def test_constraint_under_mesh(self):
        """Constraint honored when the net is distributed over a mesh."""
        from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh
        net = _net(constraints=[("weights", MaxNormConstraint(max_norm=0.5))],
                   lr=1.0)
        net.distribute(make_mesh(MeshConfig(data=4, tensor=2)))
        for _ in range(2):
            net.fit(_batch())
        W = np.asarray(net.get_param_table(0)["W"].numpy())
        assert (_col_norms(W) <= 0.5 + 1e-3).all()

    def test_serde_round_trip(self):
        net = _net(constraints=[("weights", MaxNormConstraint(max_norm=0.9)),
                                ("bias", NonNegativeConstraint())])
        s = net.conf.to_json()
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        conf2 = MultiLayerConfiguration.from_json(s)
        assert len(conf2.constraints) == 2
        t0, c0 = conf2.constraints[0]
        assert t0 == "weights" and isinstance(c0, MaxNormConstraint)
        assert c0.max_norm == 0.9
        net2 = MultiLayerNetwork(conf2).init()
        net2.fit(_batch())  # constraint live after round-trip
        W = np.asarray(net2.get_param_table(0)["W"].numpy())
        assert (_col_norms(W) <= 0.9 + 1e-3).all()


class TestWeightNoise:
    def test_dropconnect_identity_at_p1(self):
        net_plain = _net()
        net_dc = _net(weight_noise=DropConnect(weight_retain_prob=1.0))
        net_dc.set_params(net_plain.params())
        ds = _batch()
        net_plain.fit(ds)
        net_dc.fit(ds)
        np.testing.assert_allclose(net_plain.params().numpy(),
                                   net_dc.params().numpy(), atol=1e-5)

    def test_weightnoise_zero_std_identity(self):
        net_plain = _net()
        net_wn = _net(weight_noise=WeightNoise(stddev=0.0))
        net_wn.set_params(net_plain.params())
        ds = _batch()
        net_plain.fit(ds)
        net_wn.fit(ds)
        np.testing.assert_allclose(net_plain.params().numpy(),
                                   net_wn.params().numpy(), atol=1e-5)

    def test_dropconnect_changes_training_not_inference(self):
        net = _net(weight_noise=DropConnect(weight_retain_prob=0.5))
        x = _batch().features
        o1 = net.output(x).numpy()
        o2 = net.output(x).numpy()
        np.testing.assert_allclose(o1, o2)  # inference path noise-free
        p0 = net.params().numpy().copy()
        net.fit(_batch())
        assert not np.allclose(p0, net.params().numpy())

    def test_noise_gradients_flow(self):
        """Gradcheck: with a fixed key the noised loss is differentiable and
        jax.grad matches finite differences."""
        net = _net(weight_noise=WeightNoise(stddev=0.05))
        ds = _batch(8)
        x, y = ds.features.numpy(), ds.labels.numpy()
        key = jax.random.key(42)
        trainable = net._trainable(net._params)
        states = net._states(net._params)

        def loss_fn(tr):
            return net._loss_with_bn(tr, states, x, y, key)[0]

        g = jax.grad(loss_fn)(trainable)
        # finite-difference spot-check on a few W entries
        W = np.asarray(trainable[0]["W"])
        eps = 1e-3
        for (i, j) in [(0, 0), (2, 3)]:
            pert = [dict(p) for p in trainable]
            Wp = W.copy(); Wp[i, j] += eps
            pert[0] = {**pert[0], "W": jnp.asarray(Wp)}
            lp = float(loss_fn(pert))
            Wm = W.copy(); Wm[i, j] -= eps
            pert[0] = {**pert[0], "W": jnp.asarray(Wm)}
            lm = float(loss_fn(pert))
            fd = (lp - lm) / (2 * eps)
            assert abs(fd - float(g[0]["W"][i, j])) < 5e-3

    def test_per_layer_weight_noise_serde(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_in=4, n_out=4,
                                  weight_noise=DropConnect(0.8)))
                .layer(OutputLayer(n_in=4, n_out=2))
                .set_input_type(InputType.feed_forward(4))
                .build())
        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        wn = conf2.layers[0].weight_noise
        assert isinstance(wn, DropConnect)
        assert wn.weight_retain_prob == 0.8
        assert conf2.layers[1].weight_noise is None
