"""NDArray core tests — INDArray semantics (view write-through, in-place ops,
dup isolation), mirroring the reference's Nd4jTestsC basics."""
import numpy as np
import pytest

from deeplearning4j_tpu import DataType, NDArray, nd


class TestCreation:
    def test_zeros_ones(self):
        a = nd.zeros(2, 3)
        assert a.shape == (2, 3)
        assert a.sum_number() == 0.0
        b = nd.ones(4)
        assert b.sum_number() == 4.0

    def test_create_from_list(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.shape == (2, 2)
        assert a.get_double(1, 0) == 3.0

    def test_dtypes(self):
        a = nd.zeros(2, 2, dtype="bfloat16")
        assert a.dtype == DataType.BFLOAT16
        b = a.cast_to("float32")
        assert b.dtype == DataType.FLOAT

    def test_arange_linspace(self):
        assert nd.arange(5).to_list() == [0, 1, 2, 3, 4]
        ls = nd.linspace(0, 1, 5)
        np.testing.assert_allclose(ls.numpy(), [0, 0.25, 0.5, 0.75, 1.0])

    def test_rand_deterministic(self):
        nd.set_seed(42)
        a = nd.rand(3, 3)
        nd.set_seed(42)
        b = nd.rand(3, 3)
        assert a.equals(b)

    def test_eye_full(self):
        assert nd.eye(3).get_double(1, 1) == 1.0
        assert nd.full((2, 2), 7.0).get_double(0, 1) == 7.0


class TestArithmetic:
    def test_elementwise(self):
        a = nd.create([1.0, 2.0, 3.0])
        b = nd.create([4.0, 5.0, 6.0])
        np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
        np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
        np.testing.assert_allclose((b - a).numpy(), [3, 3, 3])
        np.testing.assert_allclose((a / 2).numpy(), [0.5, 1.0, 1.5])

    def test_inplace_ops(self):
        a = nd.create([1.0, 2.0])
        a.addi(10)
        np.testing.assert_allclose(a.numpy(), [11, 12])
        a.muli(2)
        np.testing.assert_allclose(a.numpy(), [22, 24])

    def test_mmul(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        b = nd.eye(2)
        assert a.mmul(b).equals(a)

    def test_broadcasting(self):
        a = nd.ones(2, 3)
        row = nd.create([1.0, 2.0, 3.0])
        np.testing.assert_allclose((a + row).numpy(),
                                   [[2, 3, 4], [2, 3, 4]])


class TestViews:
    """The hard part: reference view write-through semantics (SURVEY §7)."""

    def test_view_read(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        row = a.get_row(1)
        np.testing.assert_allclose(row.numpy(), [3, 4])

    def test_view_write_through(self):
        a = nd.zeros(3, 3)
        row = a.get_row(1)
        row.assign(5.0)
        np.testing.assert_allclose(a.numpy()[1], [5, 5, 5])
        np.testing.assert_allclose(a.numpy()[0], [0, 0, 0])

    def test_view_inplace_arithmetic(self):
        a = nd.ones(2, 2)
        col = a.get_column(0)
        col.addi(10)
        np.testing.assert_allclose(a.numpy(), [[11, 1], [11, 1]])

    def test_nested_view(self):
        a = nd.zeros(2, 2, 2)
        v = a[0][1]
        v.assign(3.0)
        np.testing.assert_allclose(a.numpy()[0, 1], [3, 3])
        assert a.numpy()[1].sum() == 0

    def test_dup_detaches(self):
        a = nd.ones(2, 2)
        d = a.get_row(0).dup()
        d.assign(99.0)
        assert a.sum_number() == 4.0

    def test_put_scalar(self):
        a = nd.zeros(2, 2)
        a.put_scalar((0, 1), 5.0)
        assert a.get_double(0, 1) == 5.0
        assert a.sum_number() == 5.0

    def test_setitem_slice(self):
        a = nd.zeros(4)
        a[1:3] = 7.0
        np.testing.assert_allclose(a.numpy(), [0, 7, 7, 0])


class TestReductions:
    def test_basic(self):
        a = nd.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.sum_number() == 10.0
        assert a.mean_number() == 2.5
        assert a.max_number() == 4.0
        np.testing.assert_allclose(a.sum(0).numpy(), [4, 6])
        np.testing.assert_allclose(a.sum(1).numpy(), [3, 7])

    def test_argmax(self):
        a = nd.create([[1.0, 5.0], [3.0, 2.0]])
        assert a.argmax(1).to_list() == [1, 0]

    def test_norms(self):
        a = nd.create([3.0, 4.0])
        assert a.norm2_number() == pytest.approx(5.0)
        assert a.norm1_number() == pytest.approx(7.0)

    def test_std_bias_correction(self):
        a = nd.create([1.0, 2.0, 3.0, 4.0])
        # reference default: bias-corrected (ddof=1)
        assert a.std_number() == pytest.approx(np.std([1, 2, 3, 4], ddof=1))


class TestShape:
    def test_reshape_transpose(self):
        a = nd.arange(6).reshape(2, 3)
        assert a.shape == (2, 3)
        assert a.T.shape == (3, 2)
        assert a.permute(1, 0).shape == (3, 2)

    def test_concat_stack(self):
        a, b = nd.ones(2, 2), nd.zeros(2, 2)
        assert nd.concat([a, b], axis=0).shape == (4, 2)
        assert nd.vstack([a, b]).shape == (4, 2)
        assert nd.stack([a, b]).shape == (2, 2, 2)

    def test_squeeze_expand(self):
        a = nd.ones(1, 3, 1)
        assert a.squeeze().shape == (3,)
        assert a.expand_dims(0).shape == (1, 1, 3, 1)

    def test_equals_tolerance(self):
        a = nd.create([1.0, 2.0])
        b = nd.create([1.0 + 1e-7, 2.0])
        assert a.equals(b)
        assert not a.equals(nd.create([1.1, 2.0]))
