"""Post-training quantization subsystem (quant/) + its serving thread.

Covers the acceptance contract of the quantized-serving PR: the
params->params transforms (per-channel symmetric int8, per-row embedding
scales, fp8 gating), QuantSpec calibration + byte-identical serde, the
max-divergence gate between warmup and cutover (a mis-scaled spec must
abort the swap with the full-precision version still live, end-to-end
over HTTP), deploy metadata (precision + param-bytes in /v1/models and
the /debug/requests ring), the env knobs, and the warm-failure
no-leak satellite (a deploy that dies mid-warmup must close the incoming
engine instead of leaking its worker thread).
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.environment import environment
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.quant import (QuantizationRejectedError, QuantSpec,
                                      QuantizedTensor, calibrate,
                                      dequant_matmul, dequantize,
                                      divergence_report, param_bytes_of,
                                      precision_of, precision_of_model,
                                      quantize_model, quantize_params,
                                      quantize_tensor, take_rows,
                                      tied_logits, validate)
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer

N_IN, N_OUT = 16, 4


def _mlp(seed=0, hidden=32):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=hidden, activation="gelu"))
            .layer(OutputLayer(n_in=hidden, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=8, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _decisive_batch(model, n=16, seed=0):
    """Calibration inputs whose f32 top-2 logit margin is largest, so
    top-1 agreement measures quantization error, not coin flips."""
    cands = np.random.RandomState(seed).randn(4 * n, N_IN) \
        .astype(np.float32)
    logits = np.asarray(model.output(cands).jax())
    part = np.partition(logits, -2, axis=-1)
    margin = part[:, -1] - part[:, -2]
    return cands[np.argsort(margin)[-n:]]


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


def _post(url, data, content_type="application/json", timeout=30):
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": content_type})
    try:
        r = urllib.request.urlopen(req, timeout=timeout)
        return r.status, r.headers, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers, e.read()


# ---------------------------------------------------------------------------
# tensor-level transforms
# ---------------------------------------------------------------------------

class TestQuantizeTensor:
    def test_per_channel_scales_and_error_bound(self):
        w = np.random.RandomState(0).randn(64, 32).astype(np.float32)
        qt = quantize_tensor(jnp.asarray(w))
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (1, 32)  # one scale per output channel
        deq = np.asarray(dequantize(qt))
        # symmetric rounding: per-element error <= scale/2 per channel
        bound = np.asarray(qt.scale)[0] / 2 + 1e-7
        assert (np.abs(deq - w) <= bound[None, :]).all()

    def test_embedding_axes_give_per_row_scales(self):
        w = np.random.RandomState(1).randn(100, 16).astype(np.float32)
        qt = quantize_tensor(jnp.asarray(w), axes=(1,))
        assert qt.scale.shape == (100, 1)
        rows = np.asarray(take_rows(qt, jnp.asarray([3, 7])))
        ref = np.asarray(take_rows(jnp.asarray(w), jnp.asarray([3, 7])))
        assert np.abs(rows - ref).max() < float(np.abs(w).max()) / 100

    def test_dequant_matmul_matches_reference(self):
        rng = np.random.RandomState(2)
        w = rng.randn(32, 8).astype(np.float32)
        x = rng.randn(4, 32).astype(np.float32)
        ref = x @ w
        out = np.asarray(dequant_matmul(jnp.asarray(x),
                                        quantize_tensor(jnp.asarray(w))))
        assert np.abs(ref - out).max() < 0.05 * np.abs(ref).max() + 0.05

    def test_tied_logits_fold_row_scales(self):
        rng = np.random.RandomState(3)
        w = rng.randn(50, 16).astype(np.float32)  # [V, E] table
        h = rng.randn(2, 5, 16).astype(np.float32)
        ref = np.asarray(tied_logits(jnp.asarray(h), jnp.asarray(w)))
        qt = quantize_tensor(jnp.asarray(w), axes=(1,))
        out = np.asarray(tied_logits(jnp.asarray(h), qt))
        assert out.dtype == np.float32
        assert np.abs(ref - out).max() < 0.05 * np.abs(ref).max() + 0.05

    def test_astype_is_a_noop_guarding_mixed_precision_casts(self):
        # the fastpath param-casting helpers call astype on every leaf;
        # quantized storage must pass through uncorrupted
        qt = quantize_tensor(jnp.ones((4, 4)))
        assert qt.astype(jnp.bfloat16) is qt

    def test_pytree_roundtrip_through_jit(self):
        qt = quantize_tensor(jnp.asarray(
            np.random.RandomState(4).randn(16, 8).astype(np.float32)))
        out = jax.jit(lambda p, x: dequant_matmul(x, p))(
            qt, jnp.ones((2, 16)))
        assert out.shape == (2, 8)

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            quantize_tensor(jnp.ones((4, 4)), mode="int4")


class TestQuantizeParams:
    def test_eligibility_rules(self):
        params = {
            "W": jnp.ones((32, 32)),            # eligible
            "b": jnp.ones((32,)),               # 1-D: skipped
            "small": jnp.ones((2, 2)),          # < min_size: skipped
            "state_mean": jnp.ones((32, 32)),   # running stat: skipped
            "position": jnp.ones((32, 32)),     # skip_keys: skipped
            "ints": jnp.ones((32, 32), jnp.int32),  # not floating
        }
        q = quantize_params(params)
        assert isinstance(q["W"], QuantizedTensor)
        for k in ("b", "small", "state_mean", "position", "ints"):
            assert not isinstance(q[k], QuantizedTensor), k

    def test_scale_override_mis_scales_matching_paths(self):
        params = {"layer": {"W": jnp.ones((32, 32))}}
        good = quantize_params(params)
        bad = quantize_params(
            params, QuantSpec(scale_overrides={"layer.W": 8.0}))
        ratio = np.asarray(bad["layer"]["W"].scale) \
            / np.asarray(good["layer"]["W"].scale)
        assert ratio == pytest.approx(8.0)

    def test_precision_and_bytes(self):
        params = {"W": jnp.ones((32, 32)), "b": jnp.ones((32,))}
        assert precision_of(params) == "float32"
        q = quantize_params(params)
        assert precision_of(q) == "int8"
        # int8 payload + f32 scales + the f32 bias < the f32 original
        full = 32 * 32 * 4 + 32 * 4
        quant = 32 * 32 * 1 + 32 * 4 + 32 * 4
        assert param_bytes_of(q) == quant < full == param_bytes_of(params)


# ---------------------------------------------------------------------------
# QuantSpec serde + calibration
# ---------------------------------------------------------------------------

class TestQuantSpec:
    def test_serde_roundtrip_is_identity(self):
        spec = QuantSpec(mode="int8", act_dtype="float32",
                         method="percentile", percentile=99.0,
                         act_ranges={"layer0": 1.5},
                         batch_fingerprint="float32[8, 16]",
                         scale_overrides={"W": 2.0})
        assert QuantSpec.from_json(spec.to_json()) == spec
        # and byte-identical on a second trip (sorted keys)
        assert QuantSpec.from_json(spec.to_json()).to_json() \
            == spec.to_json()

    def test_from_json_ignores_unknown_fields(self):
        s = QuantSpec.from_json(
            '{"mode": "int8", "future_knob": true}')
        assert s.mode == "int8"

    def test_invalid_mode_and_method_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            QuantSpec(mode="int3")
        with pytest.raises(ValueError, match="method"):
            QuantSpec(method="minmax")

    def test_calibrate_records_layer_ranges_and_fingerprint(self):
        m = _mlp()
        xb = _x()
        spec = calibrate(m, xb, method="percentile", percentile=99.0)
        assert spec.batch_fingerprint == "float32[8, 16]"
        assert spec.act_ranges  # one range per observed layer site
        assert all(v > 0 for v in spec.act_ranges.values())
        assert QuantSpec.from_json(spec.to_json()) == spec


# ---------------------------------------------------------------------------
# model-level twins
# ---------------------------------------------------------------------------

class TestQuantizedMLN:
    def test_twin_is_close_small_and_int8_at_rest(self):
        m = _mlp()
        xb = _x(16)
        full = np.asarray(m.output(xb).jax())
        qm = quantize_model(m)
        q_out = np.asarray(qm.output(xb).jax())
        assert np.abs(full - q_out).max() < 0.05
        assert precision_of_model(qm) == "int8"
        assert precision_of_model(m) == "float32"
        assert param_bytes_of(qm) < 0.6 * param_bytes_of(m)
        # weights stayed quantized at rest through the jitted forward
        assert isinstance(qm._params[0]["W"], QuantizedTensor)

    def test_twin_does_not_mutate_the_original(self):
        m = _mlp()
        quantize_model(m)
        assert precision_of_model(m) == "float32"
        assert getattr(m.conf, "dtype", "float32") in ("float32", None)

    def test_decisive_batch_agrees_at_99pct(self):
        m = _mlp()
        batch = _decisive_batch(m, n=32)
        qm = quantize_model(m)
        rep = divergence_report(m, qm, batch)
        assert rep["top1_agreement"] >= 0.99


class TestQuantizedCausalLM:
    def test_twin_generates_and_agrees_per_token(self):
        from deeplearning4j_tpu.models.causal_lm import (CausalLM,
                                                         CausalLMConfig)
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        cfg = CausalLMConfig.tiny()
        m = CausalLM(cfg, seed=0)
        ids = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (2, 12)).astype(np.int32)
        qm = quantize_model(m)
        rep = divergence_report(m, qm, ids)
        assert rep["generative"]
        assert rep["per_token_agreement"] >= 0.99
        assert qm._precision == "int8"
        eng = DecodeEngine(qm, slots=2, max_ctx=32)
        try:
            eng.warmup()
            res = eng.generate([1, 2, 3], max_tokens=4,
                               temperature=0.0).result()
            assert len(res["tokens"]) == 4
        finally:
            eng.close(5.0)

    @staticmethod
    def _twin_and_ref(mode, prompt, n):
        """A quantized twin plus its OWN full-recompute greedy reference
        (the paged/speculative paths must reproduce the twin's function,
        not the f32 original's)."""
        from deeplearning4j_tpu.models.causal_lm import (CausalLM,
                                                         CausalLMConfig)

        cfg = CausalLMConfig.tiny()
        qm = quantize_model(CausalLM(cfg, seed=0), QuantSpec(mode=mode))
        toks = [int(t) for t in prompt]
        ref = []
        for _ in range(n):
            logits = qm.forward(
                jnp.asarray(np.array(toks, np.int32)[None]))
            tok = int(jnp.argmax(logits[0, len(toks) - 1]))
            ref.append(tok)
            toks.append(tok)
        return qm, ref

    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_twin_paged_decode_token_identical(self, mode):
        """Both storage modes decode through the paged KV cache (small
        blocks, block-table gather) token-identically to the twin's own
        full-recompute greedy."""
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        prompt = [3, 1, 4, 1, 5, 9]
        qm, ref = self._twin_and_ref(mode, prompt, 8)
        eng = DecodeEngine(qm, slots=2, max_ctx=32, prompt_buckets=[8],
                           kv_block_size=4)
        try:
            res = eng.generate(prompt, max_tokens=8,
                               eos_token=None).result(timeout=60)
            assert res["tokens"] == ref
            # completed prefixes legitimately stay in the radix cache
            s = eng.stats()
            assert s["kv_blocks_free"] + s["prefix_cached_blocks"] \
                == eng.kv_blocks
        finally:
            eng.close(5.0)

    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_twin_speculative_decode_token_identical(self, mode):
        """The quantized twin drives the speculative path as both target
        and draft: verification keeps the greedy output identical to the
        twin's non-speculative function in either storage mode."""
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        prompt = [2, 7, 1, 8]
        qm, ref = self._twin_and_ref(mode, prompt, 8)
        eng = DecodeEngine(qm, slots=2, max_ctx=32, prompt_buckets=[8],
                           kv_block_size=4, draft_model=qm, spec_k=2)
        try:
            res = eng.generate(prompt, max_tokens=8,
                               eos_token=None).result(timeout=60)
            assert res["tokens"] == ref
            assert eng.stats()["spec_steps"] > 0
        finally:
            eng.close(5.0)


# ---------------------------------------------------------------------------
# the divergence gate + env knobs
# ---------------------------------------------------------------------------

class TestValidateGate:
    def test_good_twin_passes_and_reports(self):
        m = _mlp()
        batch = _decisive_batch(m)
        rep = validate(m, quantize_model(m), batch, min_top1=0.9)
        assert rep["max_abs_err"] < 0.25

    def test_mis_scaled_twin_rejected(self):
        m = _mlp()
        batch = _decisive_batch(m)
        bad = quantize_model(m, QuantSpec(scale_overrides={"": 64.0}))
        with pytest.raises(QuantizationRejectedError,
                           match="full-precision version stays live"):
            validate(m, bad, batch)

    def test_budget_overrides(self):
        m = _mlp()
        batch = _decisive_batch(m)
        qm = quantize_model(m)
        with pytest.raises(QuantizationRejectedError, match="budget"):
            validate(m, qm, batch, max_divergence=0.0, min_top1=0.0)

    def test_env_knobs(self):
        env = environment()
        prev = (env.quant_mode(), env.quant_max_divergence(),
                env.quant_min_top1())
        try:
            assert env.quant_mode() == ""          # opt-in: off by default
            assert env.quant_max_divergence() == pytest.approx(0.25)
            assert env.quant_min_top1() == pytest.approx(0.99)
            env.set_quant_mode("1")
            assert env.quant_mode() == "int8"      # truthy -> default mode
            env.set_quant_mode("fp8")
            assert env.quant_mode() == "fp8"
            env.set_quant_mode("off")
            assert env.quant_mode() == ""
            env.set_quant_min_top1(2.0)
            assert env.quant_min_top1() == 1.0     # clamped to [0, 1]
        finally:
            env.set_quant_mode(prev[0])
            env.set_quant_max_divergence(prev[1])
            env.set_quant_min_top1(prev[2])


# ---------------------------------------------------------------------------
# registry deploy thread
# ---------------------------------------------------------------------------

class TestRegistryQuantizedDeploy:
    def test_deploy_metadata_and_quantized_serving(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            m = _mlp()
            batch = _decisive_batch(m)
            mv1 = reg.deploy("q", "v1", m, example=batch)
            assert mv1.precision == "float32"
            assert mv1.param_bytes and mv1.param_bytes > 0
            mv2 = reg.deploy("q", "v2", _mlp(), example=batch,
                             quantize=True)
            assert mv2.precision == "int8"
            assert mv2.param_bytes < mv1.param_bytes
            assert mv2.divergence["top1_agreement"] >= 0.99
            d = reg.models()["q"]["versions"][1]
            assert d["precision"] == "int8"
            assert d["param_bytes"] == mv2.param_bytes
            assert d["quant_divergence"]["max_abs_err"] >= 0
            out = reg.predict("q", batch[:4])
            assert np.asarray(out.jax()).shape == (4, N_OUT)
            # rollback works unchanged on/around the quantized twin
            assert reg.rollback("q").version == "v1"
            assert reg.get("q").precision == "float32"
        finally:
            reg.drain_all(5.0)

    def test_quantize_requires_gate_batch_fail_closed(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            with pytest.raises(ValueError, match="calibration_batch"):
                reg.deploy("q", "v1", _mlp(), quantize="int8")
            with pytest.raises(KeyError):
                reg.get("q")  # nothing half-deployed
        finally:
            reg.drain_all(5.0)

    def test_mis_scaled_spec_aborts_swap_leaving_v1_live(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            m = _mlp()
            batch = _decisive_batch(m)
            reg.deploy("q", "v1", m, example=batch)
            # prime v1's lazily-started batcher thread so the baseline
            # thread count is the steady serving state
            reg.predict("q", batch[:2])
            before = threading.active_count()
            with pytest.raises(QuantizationRejectedError):
                reg.deploy("q", "v2", _mlp(), example=batch,
                           quantize=QuantSpec(scale_overrides={"": 64.0}))
            assert reg.get("q").version == "v1"
            assert [v["version"] for v in reg.models()["q"]["versions"]] \
                == ["v1"]
            out = reg.predict("q", batch[:2])
            assert np.asarray(out.jax()).shape == (2, N_OUT)
            deadline = time.time() + 5
            while threading.active_count() > before \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert threading.active_count() <= before  # engine closed
        finally:
            reg.drain_all(5.0)

    def test_env_knob_opts_deploys_into_quantization(self):
        env = environment()
        prev = env.quant_mode()
        reg = ModelRegistry(manifest_dir=None)
        try:
            env.set_quant_mode("int8")
            m = _mlp()
            mv = reg.deploy("q", "v1", m, example=_decisive_batch(m))
            assert mv.precision == "int8"
            # explicit False overrides the env opt-in
            mv2 = reg.deploy("q", "v2", _mlp(), example=_x(),
                             quantize=False)
            assert mv2.precision == "float32"
        finally:
            env.set_quant_mode(prev)
            reg.drain_all(5.0)


class TestWarmFailureDoesNotLeakEngine:
    def test_failed_warmup_closes_incoming_engine(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            m = _mlp()
            reg.deploy("w", "v1", m, example=_x())
            reg.predict("w", _x(2))  # prime v1's lazily-started batcher
            before = threading.active_count()
            # an example whose feature width cannot feed the first matmul
            # makes warmup raise mid-compile; the incoming engine was
            # already allocated (worker thread running) at that point
            bad = np.zeros((4, N_IN + 3), np.float32)
            with pytest.raises(Exception):
                reg.deploy("w", "v2", _mlp(), example=bad)
            assert reg.get("w").version == "v1"
            assert [v["version"] for v in reg.models()["w"]["versions"]] \
                == ["v1"]
            deadline = time.time() + 5
            while threading.active_count() > before \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert threading.active_count() <= before, \
                "failed warmup leaked the incoming engine's worker thread"
            out = reg.predict("w", _x(2))
            assert np.asarray(out.jax()).shape == (2, N_OUT)
        finally:
            reg.drain_all(5.0)


# ---------------------------------------------------------------------------
# HTTP end-to-end (satellite: /v1/models + /debug/requests metadata, gate
# abort observable from the outside)
# ---------------------------------------------------------------------------

class TestQuantizedServingHTTP:
    def test_gate_abort_and_metadata_over_http(self):
        reg = ModelRegistry(manifest_dir=None)
        server = ModelServer(reg)
        port = server.start()
        base = f"http://127.0.0.1:{port}"
        try:
            m = _mlp()
            batch = _decisive_batch(m)
            reg.deploy("q", "v1", m, example=batch)
            with pytest.raises(QuantizationRejectedError):
                reg.deploy("q", "v2", _mlp(), example=batch,
                           quantize=QuantSpec(scale_overrides={"": 64.0}))

            # the rejected deploy is invisible: v1 current, f32 metadata
            st, _, body = _get(base + "/v1/models")
            assert st == 200
            doc = json.loads(body)["models"]["q"]
            assert doc["current"] == "v1"
            assert [v["version"] for v in doc["versions"]] == ["v1"]
            assert doc["versions"][0]["precision"] == "float32"
            assert doc["versions"][0]["param_bytes"] > 0

            # /predict still answers from v1, trace id echoed
            st, hdrs, body = _post(
                base + "/v1/models/q/predict",
                json.dumps({"inputs": batch[:2].tolist()}).encode())
            assert st == 200
            assert json.loads(body)["version"] == "v1"
            trace_id = hdrs["X-Trace-Id"]
            assert trace_id

            # the request ring carries the served precision
            st, _, body = _get(
                base + f"/debug/requests?trace_id={trace_id}")
            assert st == 200
            recs = json.loads(body)["requests"]
            assert len(recs) == 1
            assert recs[0]["precision"] == "float32"
            assert recs[0]["version"] == "v1"

            # a PASSING quantized deploy flips the served precision
            reg.deploy("q", "v3", _mlp(), example=batch, quantize="int8")
            st, _, body = _get(base + "/v1/models")
            doc = json.loads(body)["models"]["q"]
            assert doc["current"] == "v3"
            v3 = [v for v in doc["versions"] if v["version"] == "v3"][0]
            assert v3["precision"] == "int8"
            assert v3["quant_divergence"]["top1_agreement"] >= 0.99
            st, hdrs, body = _post(
                base + "/v1/models/q/predict",
                json.dumps({"inputs": batch[:2].tolist()}).encode())
            assert st == 200
            assert json.loads(body)["version"] == "v3"
            st, _, body = _get(
                base + f"/debug/requests?trace_id={hdrs['X-Trace-Id']}")
            assert json.loads(body)["requests"][0]["precision"] == "int8"
        finally:
            server.stop()
            reg.drain_all(5.0)

    def test_divergence_gauge_exported(self):
        from deeplearning4j_tpu.common.metrics import registry as metrics
        reg = ModelRegistry(manifest_dir=None)
        try:
            m = _mlp()
            batch = _decisive_batch(m)
            reg.deploy("g", "v1", m, example=batch, quantize=True)
            text = metrics().prometheus_text()
            assert "dl4j_quant_divergence" in text
            assert 'model="g"' in text
            assert "dl4j_model_bytes" in text
            assert "dl4j_quant_deploys_total" in text
            assert 'mode="int8"' in text
        finally:
            reg.drain_all(5.0)
