"""Pipeline parallelism + ParallelWrapper + early stopping tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import nd
from deeplearning4j_tpu.datasets import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh
from deeplearning4j_tpu.parallel.pipeline import (pipeline_apply,
                                                  stack_stage_params)
from deeplearning4j_tpu.parallel.trainer import (ParallelInference,
                                                 ParallelWrapper)


class TestPipeline:
    def test_pipeline_matches_sequential(self):
        """4-stage pipelined MLP == running the stages sequentially."""
        mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=jax.devices()[:4])
        D = 8
        keys = jax.random.split(jax.random.key(0), 4)
        per_stage = [{"w": jax.random.normal(k, (D, D)) * 0.3,
                      "b": jnp.zeros(D)} for k in keys]
        stacked = stack_stage_params(per_stage)

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        x = jax.random.normal(jax.random.key(9), (8, D))
        out = pipeline_apply(stage_fn, stacked, x, mesh, n_microbatches=4)

        ref = x
        for p in per_stage:
            ref = stage_fn(p, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=1e-5)

    def test_pipeline_differentiable(self):
        mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=jax.devices()[:2])
        D = 4
        per_stage = [{"w": jnp.eye(D) * 0.5} for _ in range(2)]
        stacked = stack_stage_params(per_stage)

        def stage_fn(p, x):
            return x @ p["w"]

        x = jnp.ones((4, D))

        def loss(params):
            return jnp.sum(pipeline_apply(stage_fn, params, x, mesh, 2) ** 2)

        g = jax.grad(loss)(stacked)
        assert np.isfinite(np.asarray(g["w"])).all()
        assert float(jnp.abs(g["w"]).sum()) > 0


class TestParallelWrapper:
    def _net(self):
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=2))
                .build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n=256):
        rng = np.random.RandomState(0)
        X = rng.randn(n, 4).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[(X.sum(axis=1) > 0).astype(np.int64)]
        return X, Y

    def test_dp_training_converges(self):
        X, Y = self._data()
        net = self._net()
        wrapper = (ParallelWrapper.builder(net).workers(8)
                   .averaging_frequency(1).build())
        it = ArrayDataSetIterator(nd.create(X), nd.create(Y), batch_size=64)
        wrapper.fit(it, num_epochs=15)
        e = net.evaluate(it)
        assert e.accuracy() > 0.9

    def test_dp_matches_single_device_step(self):
        """One DP step over the mesh == same step on one device (same math)."""
        X, Y = self._data(64)
        net1 = self._net()
        net2 = net1.clone()
        net1.fit(DataSet(nd.create(X), nd.create(Y)))
        ParallelWrapper.builder(net2).workers(8).build().fit(
            ArrayDataSetIterator(nd.create(X), nd.create(Y), batch_size=64))
        np.testing.assert_allclose(net1.params().numpy(),
                                   net2.params().numpy(), rtol=2e-3,
                                   atol=2e-4)

    def test_parallel_inference(self):
        X, _ = self._data(50)  # deliberately not divisible by 8
        net = self._net()
        pi = ParallelInference(net)
        out = pi.output(nd.create(X))
        assert out.shape == (50, 2)
        np.testing.assert_allclose(out.numpy(),
                                   net.output(nd.create(X)).numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestEarlyStopping:
    def test_early_stopping_patience(self):
        from deeplearning4j_tpu.nn.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition,
            ScoreImprovementEpochTerminationCondition)

        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        Y = np.eye(2, dtype=np.float32)[(X.sum(1) > 0).astype(int)]
        it = ArrayDataSetIterator(nd.create(X), nd.create(Y), batch_size=32)

        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Adam(learning_rate=0.05)).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        esc = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(it))
               .epoch_termination_conditions(
                   MaxEpochsTerminationCondition(30),
                   ScoreImprovementEpochTerminationCondition(5))
               .build())
        result = EarlyStoppingTrainer(esc, net).fit(it)
        assert result.total_epochs <= 30
        assert result.best_model is not None
        assert result.best_model_score < 1.0
