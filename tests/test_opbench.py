"""Per-op microbenchmark suite (VERDICT r2 missing #7): the JMH /
FullBenchmarkSuit.cpp role — enumerate ops, time eager+jit, persist JSON,
diff run-over-run with a >2x regression gate."""
import json

from deeplearning4j_tpu.benchmarks.opbench import compare_runs, run_opbench


def test_sweep_small_categories():
    out = run_opbench(filter_category="activations", n_iter=2)
    assert out["n_benched"] >= 15
    rec = next(iter(out["results"].values()))
    assert set(rec) >= {"category", "eager_us", "jit_us", "args"}
    assert rec["jit_us"] > 0 and rec["eager_us"] > 0


def test_pairwise_and_reduce_covered():
    out = run_opbench(filter_category="pairwise", n_iter=2)
    assert out["n_benched"] >= 30
    out2 = run_opbench(filter_category="reduce", n_iter=2)
    assert out2["n_benched"] >= 15


def test_matmul_benched():
    out = run_opbench(filter_category="blas", filter_name="matmul", n_iter=2)
    assert "matmul" in out["results"]


def test_regression_gate(tmp_path):
    out = run_opbench(filter_category="blas", n_iter=2)
    # identical run: clean
    assert compare_runs(out, out) == []
    # simulate a 3x regression on one op above the jitter floor
    cur = json.loads(json.dumps(out))
    name = next(iter(cur["results"]))
    cur["results"][name]["jit_us"] = max(
        out["results"][name]["jit_us"] * 3, 200.0)
    regs = compare_runs(out, cur)
    assert len(regs) == 1 and regs[0]["op"] == name
    # below the min_us floor: jitter never flags
    tiny = json.loads(json.dumps(out))
    tiny["results"][name]["jit_us"] = 40.0
    base_tiny = json.loads(json.dumps(out))
    base_tiny["results"][name]["jit_us"] = 10.0
    assert compare_runs(base_tiny, tiny) == []


def test_json_roundtrip(tmp_path):
    out = run_opbench(filter_category="blas", n_iter=2)
    p = tmp_path / "ops.json"
    p.write_text(json.dumps(out))
    loaded = json.loads(p.read_text())
    assert compare_runs(loaded, out) == []


def test_excluded_and_skipped_reported():
    """No silent caps: everything not benched is named."""
    out = run_opbench(filter_category="controlflow", n_iter=2)
    assert out["n_benched"] == 0
    assert len(out["excluded"]) >= 8
