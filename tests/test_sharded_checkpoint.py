"""Sharded checkpoint/resume, incl. restore onto a RESHAPED mesh
(VERDICT round-1 item 10 'done' criterion)."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.checkpoint import (ShardedCheckpointer,
                                              ShardedCheckpointListener)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(learning_rate=1e-2))
            .list()
            .layer(L.DenseLayer(n_in=16, n_out=32, activation="relu"))
            .layer(L.OutputLayer(n_out=4, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(16))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(rs, n, b=8):
    xs = [rs.randn(b, 16).astype(np.float32) for _ in range(n)]
    ys = []
    for _ in range(n):
        y = np.zeros((b, 4), np.float32)
        y[np.arange(b), rs.randint(0, 4, b)] = 1.0
        ys.append(y)
    return xs, ys


class TestShardedCheckpoint:
    def test_save_restore_same_placement(self, tmp_path):
        rs = np.random.RandomState(0)
        xs, ys = _data(rs, 3)
        net = _net()
        for x, y in zip(xs, ys):
            net.fit(x, y)
        ck = ShardedCheckpointer(str(tmp_path / "ck"))
        ck.save(net._iteration, net)

        net2 = _net()
        ck.restore(net2)
        assert net2._iteration == net._iteration
        np.testing.assert_allclose(net2.params().numpy(),
                                   net.params().numpy(), atol=1e-7)

    @pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
    def test_restore_on_reshaped_mesh_continues_identically(self, tmp_path):
        """Train 5 steps on mesh A, checkpoint, restore on mesh B with a
        different shape, keep training — losses match the uninterrupted
        run step for step."""
        rs = np.random.RandomState(1)
        xs, ys = _data(rs, 10)

        # uninterrupted reference run (single device)
        ref = _net()
        ref_losses = []
        for x, y in zip(xs, ys):
            ref.fit(x, y)
            ref_losses.append(ref.score_value)

        # run A: dp=4, tensor=2 for 5 steps -> checkpoint
        mesh_a = make_mesh(MeshConfig(data=4, tensor=2))
        a = _net().distribute(mesh_a)
        for x, y in zip(xs[:5], ys[:5]):
            a.fit(x, y)
        ck = ShardedCheckpointer(str(tmp_path / "elastic"))
        ck.save(5, a)

        # run B: RESHAPED mesh dp=2, fsdp=2, tensor=2 -> restore + continue
        mesh_b = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        b = _net().distribute(mesh_b)
        ck.restore(b)
        losses_b = []
        for x, y in zip(xs[5:], ys[5:]):
            b.fit(x, y)
            losses_b.append(b.score_value)
        np.testing.assert_allclose(losses_b, ref_losses[5:], atol=2e-4)
        np.testing.assert_allclose(b.params().numpy(), ref.params().numpy(),
                                   atol=1e-3)

    def test_listener_retention(self, tmp_path):
        rs = np.random.RandomState(2)
        xs, ys = _data(rs, 6)
        net = _net()
        lst = ShardedCheckpointListener(str(tmp_path / "ckl"),
                                        save_every_n_iterations=1,
                                        keep_last=2)
        net._listeners.append(lst)
        for x, y in zip(xs, ys):
            net.fit(x, y)
        steps = lst.ckpt.all_steps()
        assert len(steps) == 2  # keep-last-K retention
        assert steps[-1] == 5
