"""Importer coverage accounting vs the reference mapping rulesets.

The `tests/test_op_parity.py` pattern applied to the importers: every
`inputFrameworkOpName` in the reference's declarative rulesets must be
mapped, handled structurally, or carry a documented exemption — and the
covered fraction is enforced so mapper regressions fail loudly.

Reference rulesets:
  nd4j/samediff-import/samediff-import-tensorflow/src/main/resources/
    tensorflow-mapping-ruleset.pbtxt (306 unique framework ops)
  nd4j/samediff-import/samediff-import-onnx/src/main/resources/
    onnx-mapping-ruleset.pbtxt (121 unique framework ops)
"""
import os

import pytest

from deeplearning4j_tpu.modelimport import coverage

pytestmark = pytest.mark.skipif(
    not os.path.exists(coverage.TF_RULESET),
    reason="reference rulesets not present")


class TestTFCoverage:
    def test_every_ruleset_op_accounted(self):
        r = coverage.report("tensorflow")
        print(f"\nTF ruleset coverage: {r['covered_pct']}% mapped/"
              f"structural, {r['accounted_pct']}% accounted "
              f"({len(r['mapped'])} mapped, {len(r['structural'])} "
              f"structural, {len(r['exempt'])} exempt of "
              f"{r['ruleset_total']})")
        assert not r["missing"], (
            f"unaccounted TF ruleset ops (map them or add a documented "
            f"exemption in modelimport/coverage.py): {r['missing']}")

    def test_covered_fraction_enforced(self):
        r = coverage.report("tensorflow")
        assert r["covered_pct"] >= 85.0, r["covered_pct"]
        assert r["accounted_pct"] == 100.0

    def test_exemptions_are_bounded_and_reasoned(self):
        r = coverage.report("tensorflow")
        # exemptions must stay a small, explained tail — not a dumping
        # ground (TensorArray family alone is 20 names)
        assert len(r["exempt"]) <= 35
        assert all(len(reason) > 10 for reason in r["exempt"].values())


class TestOnnxCoverage:
    def test_every_ruleset_op_accounted(self):
        r = coverage.report("onnx")
        print(f"\nONNX ruleset coverage: {r['covered_pct']}% mapped/"
              f"structural, {r['accounted_pct']}% accounted "
              f"({len(r['mapped'])} mapped, {len(r['exempt'])} exempt of "
              f"{r['ruleset_total']})")
        assert not r["missing"], (
            f"unaccounted ONNX ruleset ops: {r['missing']}")

    def test_covered_fraction_enforced(self):
        r = coverage.report("onnx")
        assert r["covered_pct"] >= 85.0, r["covered_pct"]
        assert r["accounted_pct"] == 100.0

    def test_exemptions_are_bounded_and_reasoned(self):
        r = coverage.report("onnx")
        assert len(r["exempt"]) <= 12
        assert all(len(reason) > 10 for reason in r["exempt"].values())
