"""Seq2Seq LSTM (BASELINE config 4): teacher-forcing training converges on
a synthetic reverse task; greedy lax.scan decode reproduces the targets."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import seq2seq


class TestSeq2Seq:
    def test_trains_and_decodes_reverse_task(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params, losses = seq2seq.fit_copy_task(c, steps=400, B=32, S=6,
                                               seed=0)
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

        rs = np.random.RandomState(99)
        src = rs.randint(2, c.vocab_size, (16, 6)).astype(np.int32)
        decoded = np.asarray(seq2seq.greedy_decode(params,
                                                   jnp.asarray(src), 6, c))
        acc = float((decoded == src[:, ::-1]).mean())
        assert acc > 0.8, acc

    def test_shapes(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params = seq2seq.init_params(jax.random.key(0), c)
        src = jnp.zeros((4, 5), jnp.int32)
        tgt_in = jnp.zeros((4, 7), jnp.int32)
        logits = seq2seq.teacher_forcing_logits(params, src, tgt_in)
        assert logits.shape == (4, 7, c.vocab_size)
        out = seq2seq.greedy_decode(params, src, 9, c)
        assert out.shape == (4, 9)
