"""Seq2Seq LSTM (BASELINE config 4): teacher-forcing training converges on
a synthetic reverse task; greedy lax.scan decode reproduces the targets."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models import seq2seq


class TestSeq2Seq:
    def test_trains_and_decodes_reverse_task(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params, losses = seq2seq.fit_copy_task(c, steps=400, B=32, S=6,
                                               seed=0)
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

        rs = np.random.RandomState(99)
        src = rs.randint(2, c.vocab_size, (16, 6)).astype(np.int32)
        decoded = np.asarray(seq2seq.greedy_decode(params,
                                                   jnp.asarray(src), 6, c))
        acc = float((decoded == src[:, ::-1]).mean())
        assert acc > 0.8, acc

    def test_shapes(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params = seq2seq.init_params(jax.random.key(0), c)
        src = jnp.zeros((4, 5), jnp.int32)
        tgt_in = jnp.zeros((4, 7), jnp.int32)
        logits = seq2seq.teacher_forcing_logits(params, src, tgt_in)
        assert logits.shape == (4, 7, c.vocab_size)
        out = seq2seq.greedy_decode(params, src, 9, c)
        assert out.shape == (4, 9)


class TestCachedDecodeRegression:
    """The KV-cached-style decode (recurrent state carried through
    lax.scan, one lstm_cell per token) must be token-identical to the
    naive loop that re-runs the decoder over the whole prefix each token
    — the O(T) fast path may never change outputs."""

    def test_cached_decode_matches_recompute_loop(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params = seq2seq.init_params(jax.random.key(3), c)
        src = jnp.asarray(np.random.RandomState(7)
                          .randint(2, c.vocab_size, (8, 6)), jnp.int32)
        cached = np.asarray(seq2seq.greedy_decode(params, src, 12, c))
        naive = np.asarray(
            seq2seq.greedy_decode_recompute(params, src, 12, c))
        np.testing.assert_array_equal(cached, naive)

    def test_cached_decode_matches_on_trained_model(self):
        c = seq2seq.Seq2SeqConfig.tiny()
        params, _ = seq2seq.fit_copy_task(c, steps=40, B=16, S=5, seed=1)
        src = jnp.asarray(np.random.RandomState(11)
                          .randint(2, c.vocab_size, (4, 5)), jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(seq2seq.greedy_decode(params, src, 8, c)),
            np.asarray(seq2seq.greedy_decode_recompute(params, src, 8, c)))

    def test_decode_step_is_incremental(self):
        # one decode_step from the encoder state equals the first column
        # of the full teacher-forcing forward fed BOS
        c = seq2seq.Seq2SeqConfig.tiny()
        params = seq2seq.init_params(jax.random.key(5), c)
        src = jnp.asarray(np.random.RandomState(2)
                          .randint(2, c.vocab_size, (3, 4)), jnp.int32)
        cache = seq2seq._encode(params, src)
        bos = jnp.full((3,), c.bos_token, jnp.int32)
        _, logits = seq2seq.decode_step(params, cache, bos)
        tf = seq2seq.teacher_forcing_logits(params, src, bos[:, None])
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(tf[:, 0]), rtol=1e-5,
                                   atol=1e-5)
