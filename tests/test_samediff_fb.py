"""SameDiff FlatBuffers (.fb) reader: load a reference-produced graph and
execute it under jit, golden-checked against a numpy forward pass built from
the same file's raw weights.

Reference writer: nd4j/.../autodiff/samediff/SameDiff.java:5465-5727
(asFlatGraph); fixture shipped by the reference repo itself.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.samediff_fb import (
    FlatGraphFile, load_samediff_fb)

FIXTURE = "/root/reference/sameDiffExampleInference.fb"

pytestmark = pytest.mark.skipif(
    not os.path.exists(FIXTURE), reason="reference .fb fixture not present")


def _weights():
    flat = FlatGraphFile(open(FIXTURE, "rb").read())
    return flat, {v.name: np.asarray(v.array)
                  for v in flat.variables if v.array is not None}


def test_parse_structure():
    flat, w = _weights()
    assert set(flat.placeholders) == {"input", "label"}
    assert flat.loss_variables == ["reduce_mean"]
    assert w["w0"].shape == (784, 128)
    assert w["b0"].shape == (1, 128)
    assert w["w1"].shape == (128, 10)
    assert w["b1"].shape == (1, 10)
    names = {n.op_name for n in flat.nodes}
    assert {"matmul", "add", "tanh", "softmax", "squaredsubtract"} <= names


def test_load_and_execute_golden():
    flat, w = _weights()
    sd = load_samediff_fb(FIXTURE)
    assert sd.fb_loss_variables == ["reduce_mean"]

    rng = np.random.RandomState(7)
    x = rng.randn(4, 784).astype(np.float32)
    lbl = np.zeros((4, 10), np.float32)
    lbl[np.arange(4), rng.randint(0, 10, 4)] = 1.0

    out = sd.output({"input": x, "label": lbl},
                    ["prediction", "softmax", "reduce_mean"])

    h = np.tanh(x @ w["w0"] + w["b0"])
    logits = h @ w["w1"] + w["b1"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    loss = np.mean((sm - lbl) ** 2)

    np.testing.assert_allclose(np.asarray(out["prediction"].numpy()), logits,
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["softmax"].numpy()), sm,
                               atol=1e-5)
    assert abs(float(out["reduce_mean"].numpy()) - loss) < 1e-6


def test_executes_as_one_jitted_program():
    """The rebuilt graph compiles to a single XLA computation."""
    sd = load_samediff_fb(FIXTURE)
    fn = sd.make_function(["prediction"], ("input", "label"))
    import jax
    x = np.zeros((2, 784), np.float32)
    lbl = np.zeros((2, 10), np.float32)
    (res,) = fn(sd._arrays, {"input": x, "label": lbl})
    jax.block_until_ready(res)
    assert res.shape == (2, 10)


def test_trainable_variables_preserved():
    sd = load_samediff_fb(FIXTURE)
    trainable = {v.name for v in sd.trainable_variables()}
    assert {"w0", "w1"} <= trainable
