"""SameDiff FlatBuffers (.fb) reader: load a reference-produced graph and
execute it under jit, golden-checked against a numpy forward pass built from
the same file's raw weights.

Reference writer: nd4j/.../autodiff/samediff/SameDiff.java:5465-5727
(asFlatGraph); fixture shipped by the reference repo itself.
"""
import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.samediff_fb import (
    FlatGraphFile, load_samediff_fb)

FIXTURE = "/root/reference/sameDiffExampleInference.fb"

pytestmark = pytest.mark.skipif(
    not os.path.exists(FIXTURE), reason="reference .fb fixture not present")


def _weights():
    flat = FlatGraphFile(open(FIXTURE, "rb").read())
    return flat, {v.name: np.asarray(v.array)
                  for v in flat.variables if v.array is not None}


def test_parse_structure():
    flat, w = _weights()
    assert set(flat.placeholders) == {"input", "label"}
    assert flat.loss_variables == ["reduce_mean"]
    assert w["w0"].shape == (784, 128)
    assert w["b0"].shape == (1, 128)
    assert w["w1"].shape == (128, 10)
    assert w["b1"].shape == (1, 10)
    names = {n.op_name for n in flat.nodes}
    assert {"matmul", "add", "tanh", "softmax", "squaredsubtract"} <= names


def test_load_and_execute_golden():
    flat, w = _weights()
    sd = load_samediff_fb(FIXTURE)
    assert sd.fb_loss_variables == ["reduce_mean"]

    rng = np.random.RandomState(7)
    x = rng.randn(4, 784).astype(np.float32)
    lbl = np.zeros((4, 10), np.float32)
    lbl[np.arange(4), rng.randint(0, 10, 4)] = 1.0

    out = sd.output({"input": x, "label": lbl},
                    ["prediction", "softmax", "reduce_mean"])

    h = np.tanh(x @ w["w0"] + w["b0"])
    logits = h @ w["w1"] + w["b1"]
    e = np.exp(logits - logits.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    loss = np.mean((sm - lbl) ** 2)

    np.testing.assert_allclose(np.asarray(out["prediction"].numpy()), logits,
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["softmax"].numpy()), sm,
                               atol=1e-5)
    assert abs(float(out["reduce_mean"].numpy()) - loss) < 1e-6


def test_executes_as_one_jitted_program():
    """The rebuilt graph compiles to a single XLA computation."""
    sd = load_samediff_fb(FIXTURE)
    fn = sd.make_function(["prediction"], ("input", "label"))
    import jax
    x = np.zeros((2, 784), np.float32)
    lbl = np.zeros((2, 10), np.float32)
    (res,) = fn(sd._arrays, {"input": x, "label": lbl})
    jax.block_until_ready(res)
    assert res.shape == (2, 10)


def test_trainable_variables_preserved():
    sd = load_samediff_fb(FIXTURE)
    trainable = {v.name for v in sd.trainable_variables()}
    assert {"w0", "w1"} <= trainable


# --- decode + multi-output paths not exercised by the reference fixture ----

def test_flat_array_f_order():
    """shapeInfo order char 102 ('f'): buffer is Fortran-laid-out.

    The reference writes the raw buffer in the array's own ordering
    (BaseNDArray.toFlatArray), so an 'f'-ordered VARIABLE must decode to the
    same logical values as its 'c'-ordered twin."""
    from deeplearning4j_tpu.modelimport.samediff_fb import _decode_flat_array
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    # [rank, *shape, *strides, extras, ews, order]
    info_c = [2, 2, 3, 3, 1, 0, 1, 99]
    info_f = [2, 2, 3, 1, 2, 0, 1, 102]
    got_c = _decode_flat_array(info_c, a.tobytes(order="C"), 5, 0)
    got_f = _decode_flat_array(info_f, a.tobytes(order="F"), 5, 0)
    np.testing.assert_array_equal(got_c, a)
    np.testing.assert_array_equal(got_f, a)
    assert got_f.flags["C_CONTIGUOUS"]
    with pytest.raises(ValueError, match="order char"):
        _decode_flat_array([2, 2, 3, 3, 1, 0, 1, 77],
                           a.tobytes(order="C"), 5, 0)


def _synthetic_graph(nodes, variables, placeholders):
    from deeplearning4j_tpu.modelimport.samediff_fb import FlatGraphFile
    g = FlatGraphFile.__new__(FlatGraphFile)
    g.graph_id = 0
    g.variables = variables
    g.nodes = nodes
    g.placeholders = placeholders
    g.loss_variables = []
    g.training_config = None
    return g


def _node(nid, name, op_name, inputs, output_names):
    from deeplearning4j_tpu.modelimport.samediff_fb import FlatNodeRec
    n = FlatNodeRec.__new__(FlatNodeRec)
    n.id, n.name, n.op_type, n.op_num = nid, name, 0, 0
    n.inputs = inputs
    n.t_args, n.i_args, n.b_args, n.dimensions = [], [], [], []
    n.output_names = output_names
    n.op_name = op_name
    n.scalar = None
    return n


def _var(vid, name, var_type, array=None, shape=None):
    from deeplearning4j_tpu.modelimport.samediff_fb import FlatVariableRec
    v = FlatVariableRec.__new__(FlatVariableRec)
    v.id, v.name, v.dtype = (vid, 0), name, 5
    v.shape = list(shape or (array.shape if array is not None else ()))
    v.array = array
    v.var_type = var_type
    return v


def test_multi_output_node_all_indices_consumable():
    """A two-output op ('moments') registers (id,0) AND (id,1); a downstream
    node can consume output index 1, and output names come from the file."""
    from deeplearning4j_tpu.modelimport.samediff_fb import SameDiffFbImport
    nodes = [
        _node(2, "mom", "moments", [(1, 0)], ["mom_mean", "mom_var"]),
        _node(3, "out", "sqrt", [(2, 1)], ["std"]),
    ]
    variables = [_var(1, "x", 3, shape=(2, 3))]
    sd = SameDiffFbImport(_synthetic_graph(nodes, variables, ["x"])).convert()
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = sd.output({"x": x}, ["mom_mean", "mom_var", "std"])
    np.testing.assert_allclose(float(out["mom_mean"].numpy()), x.mean(),
                               rtol=1e-6)
    np.testing.assert_allclose(float(out["mom_var"].numpy()), x.var(),
                               rtol=1e-6)
    np.testing.assert_allclose(float(out["std"].numpy()), x.std(), rtol=1e-6)


def test_gru_cell_four_outputs():
    """Reference gruCell declares 4 outputs (r, u, c, h); the converter
    must route to the full-output gru_block_cell, not the h-only port."""
    from deeplearning4j_tpu.modelimport.samediff_fb import SameDiffFbImport
    In, H, B = 3, 4, 2
    rs = np.random.RandomState(3)
    nodes = [_node(5, "g", "gruCell", [(1, 0), (2, 0), (3, 0), (4, 0)],
                   ["g_r", "g_u", "g_c", "g_h"])]
    w_ru = rs.randn(In + H, 2 * H).astype(np.float32)
    w_c = rs.randn(In + H, H).astype(np.float32)
    variables = [_var(1, "x", 3, shape=(B, In)),
                 _var(2, "h0", 3, shape=(B, H)),
                 _var(3, "w_ru", 1, array=w_ru),
                 _var(4, "w_c", 1, array=w_c)]
    sd = SameDiffFbImport(
        _synthetic_graph(nodes, variables, ["x", "h0"])).convert()
    x = rs.randn(B, In).astype(np.float32)
    h0 = np.zeros((B, H), np.float32)
    out = sd.output({"x": x, "h0": h0}, ["g_r", "g_u", "g_c", "g_h"])
    sig = lambda v: 1 / (1 + np.exp(-v))
    ru = np.concatenate([x, h0], -1) @ w_ru
    r, u = sig(ru[:, :H]), sig(ru[:, H:])
    c = np.tanh(np.concatenate([x, r * h0], -1) @ w_c)
    h = u * h0 + (1 - u) * c
    np.testing.assert_allclose(np.asarray(out["g_r"].numpy()), r, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["g_u"].numpy()), u, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["g_c"].numpy()), c, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["g_h"].numpy()), h, atol=1e-5)


def test_multi_output_arity_mismatch_is_loud():
    """A node claiming 2 outputs from a 1-output op fails with a clear
    error instead of silently slicing rows."""
    from deeplearning4j_tpu.modelimport.samediff_fb import SameDiffFbImport
    nodes = [_node(2, "t", "tanh", [(1, 0)], ["t0", "t1"])]
    variables = [_var(1, "x", 3, shape=(2, 3))]
    sd = SameDiffFbImport(_synthetic_graph(nodes, variables, ["x"])).convert()
    x = np.ones((2, 3), np.float32)
    with pytest.raises(ValueError, match="declares 2 outputs"):
        sd.output({"x": x}, ["t0"])
