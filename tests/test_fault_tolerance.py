"""Fault-tolerant training: checkpoint auto-resume + injected failures
(reference FailureTestingListener pattern, MeshOrganizer remap role)."""
import numpy as np
import pytest

from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.fault_tolerance import (FaultTolerantTrainer,
                                                         rebuild_mesh)


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(5).updater(Adam(learning_rate=1e-2)).list()
            .layer(L.DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(L.OutputLayer(n_out=3, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def _data():
    rs = np.random.RandomState(0)
    x = rs.randn(16, 8).astype(np.float32)
    y = np.zeros((16, 3), np.float32)
    y[np.arange(16), rs.randint(0, 3, 16)] = 1.0
    return x, y


class TestFaultTolerantTrainer:
    def test_auto_resume_after_injected_failure(self, tmp_path):
        """Training crashes mid-run (FailureTestingListener-style injected
        fault); the trainer restores the last checkpoint and completes."""
        x, y = _data()
        net = _net()
        crashed = {"done": False}
        restarts = []

        def fit_fn(n, epoch):
            if epoch == 3 and not crashed["done"]:
                crashed["done"] = True
                raise RuntimeError("injected device failure")
            n.fit(x, y)

        trainer = FaultTolerantTrainer(
            net, str(tmp_path / "ft"), checkpoint_every_epochs=1,
            max_restarts=2,
            on_restart=lambda e, n: restarts.append(str(e)))
        trainer.fit(fit_fn, num_epochs=6)
        assert crashed["done"]
        assert restarts == ["injected device failure"]
        assert net._epoch == 6
        assert np.isfinite(net.score_value)

    def test_gives_up_after_max_restarts(self, tmp_path):
        net = _net()

        def always_fail(n, epoch):
            raise RuntimeError("permanent failure")

        trainer = FaultTolerantTrainer(net, str(tmp_path / "ft2"),
                                       max_restarts=2)
        with pytest.raises(RuntimeError, match="permanent"):
            trainer.fit(always_fail, num_epochs=3)
        assert trainer.restarts == 3

    def test_resume_fresh_process(self, tmp_path):
        """A new trainer over the same checkpoint dir resumes where the
        previous run stopped (process-restart recovery)."""
        x, y = _data()
        d = str(tmp_path / "ft3")
        net1 = _net()
        t1 = FaultTolerantTrainer(net1, d, checkpoint_every_epochs=1)
        t1.fit(lambda n, e: n.fit(x, y), num_epochs=3)

        net2 = _net()
        t2 = FaultTolerantTrainer(net2, d, checkpoint_every_epochs=1)
        seen = []
        t2.fit(lambda n, e: seen.append(e) or n.fit(x, y), num_epochs=5)
        assert seen == [3, 4]   # resumed at epoch 3, not 0
        np.testing.assert_allclose(net2._epoch, 5)


class TestSharedRetryPolicy:
    """The trainer's supervised retry now rides the shared
    ``common.faults.RetryPolicy`` (the same backoff + max-restart budget
    the serving engine supervisors use)."""

    def test_trainer_backs_off_between_restarts(self, tmp_path):
        from deeplearning4j_tpu.common.faults import RetryPolicy

        x, y = _data()
        net = _net()
        sleeps = []
        policy = RetryPolicy(max_restarts=3, base_s=0.05, jitter=0.0,
                             sleep=sleeps.append)
        fails = [0]

        def fit_fn(n, epoch):
            if epoch == 1 and fails[0] < 2:
                fails[0] += 1
                raise RuntimeError("flaky device")
            n.fit(x, y)

        trainer = FaultTolerantTrainer(net, str(tmp_path / "bo"),
                                       retry_policy=policy)
        trainer.fit(fit_fn, num_epochs=3)
        # exponential: the second restart waited twice the first
        assert sleeps == [pytest.approx(0.05), pytest.approx(0.1)]
        assert trainer.restarts == 2
        assert trainer.max_restarts == 3  # budget surfaced from policy

    def test_explicit_policy_budget_wins(self, tmp_path):
        from deeplearning4j_tpu.common.faults import RetryPolicy

        net = _net()
        policy = RetryPolicy(max_restarts=1, base_s=0.001,
                             sleep=lambda s: None)
        trainer = FaultTolerantTrainer(net, str(tmp_path / "bp"),
                                       max_restarts=99,  # overridden
                                       retry_policy=policy)

        def always_fail(n, epoch):
            raise RuntimeError("permanent failure")

        with pytest.raises(RuntimeError, match="permanent"):
            trainer.fit(always_fail, num_epochs=2)
        assert trainer.restarts == 2  # initial + budget of 1


class TestRebuildMesh:
    def test_uses_live_devices(self):
        import jax
        mesh = rebuild_mesh()
        assert mesh.devices.size == jax.device_count()

    def test_shrunken_device_set(self):
        import jax
        if jax.device_count() < 4:
            pytest.skip("needs 4 devices")
        devs = jax.devices()[:4]
        mesh = rebuild_mesh(devices=devs)
        assert mesh.devices.size == 4
