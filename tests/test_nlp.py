"""NLP tests: tokenizers, vocab/huffman, Word2Vec, ParagraphVectors,
FastText, DeepWalk — models the reference's
`platform-tests/.../nlp/` Word2VecTests / ParagraphVectorsTest and
`deeplearning4j-graph` DeepWalk tests, on small synthetic corpora.
"""
import numpy as np
import pytest

from deeplearning4j_tpu import nlp


def synthetic_corpus(n=300, seed=0):
    """Two topic clusters: (cat, dog, pet) and (car, road, drive) — words
    inside a topic co-occur, across topics never."""
    rng = np.random.RandomState(seed)
    animals = ["cat", "dog", "pet", "fur", "paw"]
    cars = ["car", "road", "drive", "wheel", "fuel"]
    out = []
    for _ in range(n):
        topic = animals if rng.rand() < 0.5 else cars
        out.append(" ".join(rng.choice(topic, size=8)))
    return out


class TestTokenization:
    def test_default_tokenizer(self):
        tf = nlp.DefaultTokenizerFactory()
        assert tf.create("Hello world foo").get_tokens() == \
            ["Hello", "world", "foo"]

    def test_common_preprocessor(self):
        tf = nlp.DefaultTokenizerFactory()
        tf.set_token_pre_processor(nlp.CommonPreprocessor())
        assert tf.create("Hello, World!").get_tokens() == ["hello", "world!"] \
            or tf.create("Hello, World.").get_tokens() == ["hello", "world"]

    def test_ngram_tokenizer(self):
        tf = nlp.NGramTokenizerFactory(1, 2)
        toks = tf.create("a b c").get_tokens()
        assert "a" in toks and "a b" in toks and "b c" in toks


class TestVocab:
    def test_build_and_frequency_order(self):
        streams = [["a", "a", "b"], ["a", "b", "c"]]
        v = nlp.build_vocab(streams, min_word_frequency=1)
        assert v.word_at(0) == "a" and v.word_frequency("a") == 3
        assert v.index_of("zzz") == -1

    def test_min_frequency_filter(self):
        v = nlp.build_vocab([["a", "a", "b"]], min_word_frequency=2)
        assert "b" not in v and "a" in v

    def test_huffman_codes(self):
        v = nlp.build_vocab([["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]],
                            min_word_frequency=1)
        nlp.assign_huffman_codes(v)
        # most frequent word gets the shortest code
        assert len(v.word_for("a").codes) <= len(v.word_for("d").codes)
        codes, points, mask = nlp.huffman_arrays(v)
        assert codes.shape == points.shape == mask.shape
        assert mask[v.index_of("a")].sum() == len(v.word_for("a").codes)

    def test_unigram_table(self):
        v = nlp.build_vocab([["a", "a", "a", "b"]], min_word_frequency=1)
        p = nlp.unigram_table(v)
        assert p.sum() == pytest.approx(1.0)
        assert p[v.index_of("a")] > p[v.index_of("b")]


class TestWord2Vec:
    @pytest.fixture(scope="class")
    def model(self):
        m = (nlp.Word2Vec.builder()
             .min_word_frequency(1).layer_size(32).window_size(3)
             .negative_sample(5).epochs(3).batch_size(512).seed(42)
             .iterate(synthetic_corpus())
             .tokenizer_factory(nlp.DefaultTokenizerFactory())
             .build())
        m.fit()
        return m

    def test_topics_cluster(self, model):
        within = model.similarity("cat", "dog")
        across = model.similarity("cat", "road")
        assert within > across

    def test_words_nearest(self, model):
        near = model.words_nearest("car", 3)
        assert set(near) <= {"road", "drive", "wheel", "fuel"}

    def test_vector_shape(self, model):
        assert model.get_word_vector("cat").shape == (32,)
        assert model.get_word_vector("notaword") is None

    def test_serialization_roundtrip(self, model, tmp_path):
        p = str(tmp_path / "w2v.zip")
        nlp.write_word_vectors(model, p)
        m2 = nlp.read_word_vectors(p)
        np.testing.assert_allclose(m2.get_word_vector("cat"),
                                   model.get_word_vector("cat"))
        assert m2.similarity("cat", "dog") == \
            pytest.approx(model.similarity("cat", "dog"))

    def test_cbow(self):
        m = (nlp.Word2Vec.builder()
             .min_word_frequency(1).layer_size(16).window_size(3)
             .use_cbow(True).epochs(2).batch_size(256).seed(1)
             .iterate(synthetic_corpus(150))
             .build())
        m.fit()
        assert m.similarity("cat", "pet") > m.similarity("cat", "fuel")


class TestParagraphVectors:
    def test_doc_clusters(self):
        rng = np.random.RandomState(3)
        docs = []
        for i in range(40):
            topic = ["cat", "dog", "pet"] if i % 2 == 0 else \
                ["car", "road", "drive"]
            docs.append((f"doc{i}", " ".join(rng.choice(topic, size=10))))
        pv = (nlp.ParagraphVectors.builder()
              .min_word_frequency(1).layer_size(24).epochs(5)
              .batch_size(256).seed(5).iterate_labeled(docs).build())
        pv.fit()
        a, b = pv.get_paragraph_vector("doc0"), pv.get_paragraph_vector("doc2")
        c = pv.get_paragraph_vector("doc1")
        cos = lambda x, y: float(x @ y / (np.linalg.norm(x) *
                                          np.linalg.norm(y) + 1e-12))
        assert cos(a, b) > cos(a, c)

    def test_infer_vector(self):
        docs = [("animals", "cat dog pet cat dog pet cat dog"),
                ("vehicles", "car road drive car road drive car road")] * 10
        docs = [(f"{l}{i}", t) for i, (l, t) in enumerate(docs)]
        pv = (nlp.ParagraphVectors.builder()
              .min_word_frequency(1).layer_size(16).epochs(8)
              .batch_size(128).seed(7).iterate_labeled(docs).build())
        pv.fit()
        v = pv.infer_vector("cat dog pet")
        assert v.shape == (16,)
        sim_animal = pv.similarity_to_label("cat dog pet", "animals0")
        sim_vehicle = pv.similarity_to_label("cat dog pet", "vehicles1")
        assert sim_animal > sim_vehicle


class TestFastText:
    def test_oov_from_subwords(self):
        ft = nlp.FastText(layer_size=16, epochs=2, min_n=3, max_n=4,
                          buckets=1000, batch_size=256)
        ft.fit(synthetic_corpus(100))
        # OOV word shares subwords with an in-vocab word
        v = ft.get_word_vector("catt")
        assert v.shape == (16,)
        assert ft.similarity("cat", "catt") > ft.similarity("cat", "fuel")


class TestDeepWalk:
    def test_two_cliques(self):
        # two 6-cliques joined by one bridge edge
        g = nlp.Graph(12)
        for base in (0, 6):
            for i in range(base, base + 6):
                for j in range(i + 1, base + 6):
                    g.add_edge(i, j)
        g.add_edge(5, 6)
        dw = (nlp.DeepWalk.builder().vector_size(16).window_size(3)
              .epochs(5).seed(0).build())
        it = nlp.RandomWalkIterator(g, walk_length=12, seed=0)
        dw.fit(it)
        assert dw.similarity(0, 1) > dw.similarity(0, 11)

    def test_weighted_walks(self):
        g = nlp.Graph(3)
        g.add_edge(0, 1, weight=100.0)
        g.add_edge(0, 2, weight=0.001)
        it = nlp.RandomWalkIterator(g, walk_length=2, seed=0, weighted=True)
        nxt = [w[1] for w in it.walks() if w[0] == 0]
        assert nxt == [1]


class TestAdvisorRegressions:
    """Regressions for the round-1 advisor findings (ADVICE.md)."""

    def _small_pv(self, tmp_path=None):
        docs = [("animals", "cat dog pet cat dog pet cat dog"),
                ("vehicles", "car road drive car road drive car road")] * 5
        docs = [(f"{l}{i}", t) for i, (l, t) in enumerate(docs)]
        pv = (nlp.ParagraphVectors.builder()
              .min_word_frequency(1).layer_size(8).epochs(2)
              .batch_size(64).seed(7).iterate_labeled(docs).build())
        pv.fit()
        return pv

    def test_pv_words_nearest_excludes_doc_rows(self):
        pv = self._small_pv()
        near = pv.words_nearest("cat", n=5)
        assert near  # used to raise IndexError via doc-row indices
        assert all(pv.has_word(w) for w in near)
        near_sum = pv.words_nearest_sum(["cat"], [], n=3)
        assert all(pv.has_word(w) for w in near_sum)

    def test_small_batch_size_trains(self):
        # batch_size < MICRO(64) used to ZeroDivisionError in the scan step
        m = (nlp.Word2Vec.builder()
             .min_word_frequency(1).layer_size(8).epochs(1).batch_size(16)
             .seed(3).iterate(synthetic_corpus(30)).build())
        loss = m.fit()
        assert np.isfinite(loss)
        c = (nlp.Word2Vec.builder()
             .min_word_frequency(1).layer_size(8).epochs(1).batch_size(16)
             .use_cbow(True).seed(3).iterate(synthetic_corpus(30)).build())
        assert np.isfinite(c.fit())
        ft = nlp.FastText(layer_size=8, epochs=1, batch_size=16, seed=3)
        assert np.isfinite(ft.fit(synthetic_corpus(30)))

    def test_fasttext_oov_no_ngrams_returns_zeros(self):
        ft = nlp.FastText(layer_size=8, epochs=1, batch_size=64,
                          min_n=5, max_n=6, seed=0)
        ft.fit(synthetic_corpus(30))
        v = ft.get_word_vector("ab")  # too short for any 5-gram of "<ab>"
        assert v.shape == (8,)
        assert not np.any(np.isnan(v))
        assert np.isfinite(ft.similarity("ab", "cat"))

    def test_pv_serde_roundtrip(self, tmp_path):
        pv = self._small_pv()
        p = str(tmp_path / "pv.zip")
        nlp.write_word_vectors(pv, p)
        m2 = nlp.read_word_vectors(p)
        assert isinstance(m2, nlp.ParagraphVectors)
        assert m2.labels == pv.labels
        np.testing.assert_allclose(m2.get_paragraph_vector("animals0"),
                                   pv.get_paragraph_vector("animals0"))
        np.testing.assert_allclose(m2.get_word_vector("cat"),
                                   pv.get_word_vector("cat"))
        assert all(m2.has_word(w) for w in m2.words_nearest("cat", n=3))
