"""Layer-API sharding: MLN/ComputationGraph training on dp x tp x fsdp
meshes matches single-device numerics (VERDICT round-1 item 5).

Runs on the virtual 8-device CPU mesh (conftest).
"""
import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.config import (InputType,
                                               NeuralNetConfiguration)
from deeplearning4j_tpu.nn.graph.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import MeshConfig, make_mesh


def _mln():
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(learning_rate=1e-2))
            .list()
            .layer(L.DenseLayer(n_in=16, n_out=32, activation="relu"))
            .layer(L.DenseLayer(n_out=24, activation="tanh"))
            .layer(L.OutputLayer(n_out=4, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.feed_forward(16))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _batches(rs, n, b=8, f=16, c=4):
    xs = [rs.randn(b, f).astype(np.float32) for _ in range(n)]
    ys = []
    for _ in range(n):
        lab = np.zeros((b, c), np.float32)
        lab[np.arange(b), rs.randint(0, c, b)] = 1.0
        ys.append(lab)
    return xs, ys


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
class TestMLNSharding:
    def test_dp_tp_fsdp_matches_single_device(self):
        rs = np.random.RandomState(0)
        xs, ys = _batches(rs, 4)

        ref = _mln()
        for x, y in zip(xs, ys):
            ref.fit(x, y)
        ref_losses = ref.score_value
        ref_params = ref.params().numpy()

        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        net = _mln()
        net.distribute(mesh)
        for x, y in zip(xs, ys):
            net.fit(x, y)
        np.testing.assert_allclose(net.score_value, ref_losses, atol=1e-5)
        np.testing.assert_allclose(net.params().numpy(), ref_params,
                                   atol=1e-4)

    def test_params_actually_sharded(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        net = _mln().distribute(mesh)
        w = net._params[0]["W"]  # (16, 32) -> fsdp x tensor
        assert isinstance(w.sharding, NamedSharding)
        shard_shape = w.sharding.shard_shape(w.shape)
        assert shard_shape == (8, 16)  # 16/fsdp2, 32/tensor2

    def test_output_matches_after_distribute(self):
        rs = np.random.RandomState(1)
        x = rs.randn(8, 16).astype(np.float32)
        ref = _mln()
        out_ref = ref.output(x).numpy()
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        out_sh = _mln().distribute(mesh).output(x).numpy()
        np.testing.assert_allclose(out_sh, out_ref, atol=1e-5)


def _cg():
    builder = (NeuralNetConfiguration.builder()
               .seed(11)
               .updater(Sgd(learning_rate=5e-2))
               .graph_builder())
    builder.add_inputs("in")
    builder.set_input_types(InputType.feed_forward(12))
    builder.add_layer("fa", L.DenseLayer(n_in=12, n_out=16,
                                         activation="relu"), "in")
    builder.add_layer("fb", L.DenseLayer(n_in=12, n_out=16,
                                         activation="tanh"), "in")
    builder.add_vertex("merge", MergeVertex(), "fa", "fb")
    builder.add_layer("out", L.OutputLayer(n_in=32, n_out=4,
                                           activation="softmax",
                                           loss="mcxent"), "merge")
    builder.set_outputs("out")
    net = ComputationGraph(builder.build())
    net.init()
    return net


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
class TestComputationGraphSharding:
    def test_dp_tp_fsdp_matches_single_device(self):
        """VERDICT item 5 'done' criterion: a ComputationGraph at
        dp=2,tp=2,fsdp=2 matches the single-device step numerically."""
        rs = np.random.RandomState(3)
        xs, ys = _batches(rs, 4, b=8, f=12, c=4)

        ref = _cg()
        for x, y in zip(xs, ys):
            ref.fit(x, y)
        ref_params = ref.params().numpy()

        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        net = _cg().distribute(mesh)
        for x, y in zip(xs, ys):
            net.fit(x, y)
        np.testing.assert_allclose(net.score_value, ref.score_value,
                                   atol=1e-5)
        np.testing.assert_allclose(net.params().numpy(), ref_params,
                                   atol=1e-4)

    def test_conv_net_tp(self):
        """Conv layers shard in/out channels; training still matches."""
        def build():
            conf = (NeuralNetConfiguration.builder()
                    .seed(5)
                    .updater(Sgd(learning_rate=1e-2))
                    .list()
                    .layer(L.ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                              activation="relu",
                                              convolution_mode="same"))
                    .layer(L.SubsamplingLayer(kernel_size=(2, 2)))
                    .layer(L.OutputLayer(n_out=3, activation="softmax",
                                         loss="mcxent"))
                    .set_input_type(InputType.convolutional(8, 8, 4))
                    .build())
            n = MultiLayerNetwork(conf)
            n.init()
            return n

        rs = np.random.RandomState(4)
        x = rs.randn(8, 4, 8, 8).astype(np.float32)
        y = np.zeros((8, 3), np.float32)
        y[np.arange(8), rs.randint(0, 3, 8)] = 1.0

        ref = build()
        ref.fit(x, y)
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
        net = build().distribute(mesh)
        net.fit(x, y)
        np.testing.assert_allclose(net.score_value, ref.score_value,
                                   atol=1e-5)
        np.testing.assert_allclose(net.params().numpy(), ref.params().numpy(),
                                   atol=1e-4)
