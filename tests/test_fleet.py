"""Sharded serving fleet (serving/fleet + mesh-sharded engines).

Covers the acceptance contract of the fleet PRs: a sharded deploy on a
(1, N) CPU mesh serves predictions numerically matching single-device
(bitwise on a 1x1 mesh), with mesh metadata surfaced on /v1/models and
engine snapshots; the FleetRouter picks the least-loaded ready replica
under skew, fails over exactly once on connection refusal and on 503,
refuses nothing silently (NoReplicaError / front-door 503 otherwise);
and a joining replica warmed from the shared manifest takes traffic only
after its /readyz flips.

The tail-tolerance layer is pinned here too: the RetryBudget token
bucket (with the budget at zero, dispatch attempts == requests —
hedging is provably bounded), hedged requests for idempotent predicts
only, outlier ejection over actual dispatch outcomes with probe
re-admission, replica 503 Retry-After pass-through, mid-stream
non-retryability for generate, poll hardening against junk payloads,
brownout priority shedding, and a SIGTERM chaos drill through the
front door.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.common import faults
from deeplearning4j_tpu.common.mesh import (MODEL, mesh_shape, serving_mesh,
                                            spec_fits, validate_mesh)
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.serving.fleet import (FleetRouter, FleetServer,
                                              MidStreamError,
                                              NoReplicaError, Replica,
                                              RetryBudget)
from deeplearning4j_tpu.serving.fleet.router import _parse_metrics_json

N_IN, N_OUT = 6, 3


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=4, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _counter_value(fam_name, **labels):
    fam = registry().get(fam_name)
    if fam is None:
        return 0.0
    want = tuple(labels[k] for k in fam.label_names)
    return sum(child.value() for key, child in fam.children()
               if key == want)


def _attempts_total():
    """Real HTTP dispatch attempts: every dl4j_router_dispatch_total
    outcome except no_replica (which records a request that never
    reached a replica)."""
    fam = registry().get("dl4j_router_dispatch_total")
    if fam is None:
        return 0.0
    i = fam.label_names.index("outcome")
    return sum(child.value() for key, child in fam.children()
               if key[i] != "no_replica")


@pytest.fixture(autouse=True)
def _no_armed_faults():
    """Fault rules must never leak across tests."""
    yield
    faults.clear()


@pytest.fixture
def unsharded_ref():
    reg = ModelRegistry(manifest_dir=None)
    reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
    ref = np.asarray(reg.predict("toy", _x()).jax())
    yield ref
    reg.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# scale-up: mesh-sharded serving
# ---------------------------------------------------------------------------

class TestMeshHelpers:
    def test_serving_mesh_defaults_all_devices_on_model_axis(self):
        mesh = serving_mesh()
        assert mesh_shape(mesh) == {"data": 1,
                                    "model": jax.device_count()}

    def test_validate_mesh_requires_axes(self):
        mesh = serving_mesh()
        validate_mesh(mesh)  # data axis present: fine
        with pytest.raises(ValueError, match="nope"):
            validate_mesh(mesh, required=("nope",))

    def test_spec_fits(self):
        from jax.sharding import PartitionSpec as P
        mesh = serving_mesh()
        n = jax.device_count()
        w = np.zeros((4, 2 * n), np.float32)
        assert spec_fits(w, P(None, MODEL), mesh)
        assert not spec_fits(np.zeros((4, 3), np.float32),
                             P(None, MODEL), mesh)


class TestShardedServing:
    def test_1x1_mesh_bitwise_identical(self, unsharded_ref):
        mesh = serving_mesh(model_parallel=1, devices=jax.devices()[:1])
        reg = ModelRegistry(manifest_dir=None)
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            out = np.asarray(reg.predict("toy", _x()).jax())
            np.testing.assert_array_equal(unsharded_ref, out)
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_predict_matches_unsharded(self, unsharded_ref):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                            mesh=serving_mesh())
            out = np.asarray(reg.predict("toy", _x()).jax())
            # cross-device contractions reorder the reduction: logits
            # match to float tolerance and the decisions exactly
            np.testing.assert_allclose(unsharded_ref, out, rtol=1e-5,
                                       atol=1e-6)
            assert (unsharded_ref.argmax(-1) == out.argmax(-1)).all()
            assert mv.engine.stats()["mesh_shape"] == mesh_shape(
                serving_mesh())
        finally:
            reg.drain_all(save_manifests=False)

    def test_v1_models_reports_mesh_metadata(self):
        mesh = serving_mesh()
        reg = ModelRegistry(manifest_dir=None)
        srv = ModelServer(reg)
        port = srv.start()
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            status, doc = _get(f"http://127.0.0.1:{port}/v1/models")
            assert status == 200
            ver = doc["models"]["toy"]["versions"][0]
            assert ver["mesh_shape"] == mesh_shape(mesh)
            assert ver["param_spec"] == "auto(model)"
        finally:
            srv.stop()
            reg.drain_all(save_manifests=False)

    def test_unsharded_versions_omit_mesh_metadata(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            assert "mesh_shape" not in mv.describe()
            assert "mesh_shape" not in mv.engine.stats()
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_decode_tokens_identical(self):
        from deeplearning4j_tpu.models import causal_lm
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        cfg = causal_lm.CausalLMConfig.tiny()
        prompt = list(range(1, 9))
        e0 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm0")
        e1 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm1", mesh=serving_mesh())
        try:
            r0 = e0.generate_sync(prompt, max_tokens=8, temperature=0.0)
            r1 = e1.generate_sync(prompt, max_tokens=8, temperature=0.0)
            assert r0["tokens"] == r1["tokens"]
            snap = e1.debug_snapshot()
            assert snap["mesh_shape"] == mesh_shape(serving_mesh())
            assert snap["param_spec"] == "auto(model)"
        finally:
            e0.close(10)
            e1.close(10)


# ---------------------------------------------------------------------------
# scale-out: the replica router
# ---------------------------------------------------------------------------

def _stub_replica(router, url, model="toy", ewma=0.01, waiters=0,
                  ready=True):
    """Inject a polled view without HTTP (pure routing-policy tests)."""
    rep = Replica(url)
    rep.ready = ready
    rep.models = [model]
    rep.load = {model: {"ewma_s": ewma, "queue_depth": 0.0,
                        "active": 0.0, "waiters": float(waiters)}}
    router._replicas[rep.url] = rep
    return rep


class TestLeastLoaded:
    def test_skewed_load_prefers_idle_replica(self):
        router = FleetRouter(poll_s=3600, retries=1)
        _stub_replica(router, "http://busy:1", ewma=0.5, waiters=20)
        idle = _stub_replica(router, "http://idle:1", ewma=0.01, waiters=0)
        cands = router._candidates("toy")
        assert cands[0] is idle

    def test_router_side_inflight_breaks_ties(self):
        # between polls, dispatched-but-unpolled work must count: a burst
        # spreads instead of piling onto the replica that looked idle
        router = FleetRouter(poll_s=3600, retries=1)
        a = _stub_replica(router, "http://a:1", ewma=0.1, waiters=0)
        b = _stub_replica(router, "http://b:1", ewma=0.1, waiters=0)
        a.inflight = 5
        assert router._candidates("toy")[0] is b

    def test_not_ready_replica_excluded(self):
        router = FleetRouter(poll_s=3600)
        _stub_replica(router, "http://down:1", ready=False)
        up = _stub_replica(router, "http://up:1")
        assert router._candidates("toy") == [up]

    def test_no_replica_raises(self):
        router = FleetRouter(poll_s=3600)
        with pytest.raises(NoReplicaError, match="no ready replica"):
            router.route("POST", "/v1/models/toy/predict", b"{}",
                         model="toy")


class _Fleet:
    """N live single-model replicas + a router, torn down in reverse."""

    def __init__(self, n, manifest_dir=None, **router_kw):
        self.members = []
        urls = []
        for i in range(n):
            reg = ModelRegistry(manifest_dir=manifest_dir)
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            srv = ModelServer(reg)
            port = srv.start()
            self.members.append((reg, srv))
            urls.append(f"http://127.0.0.1:{port}")
        router_kw.setdefault("poll_s", 0.2)
        router_kw.setdefault("timeout_s", 30)
        self.router = FleetRouter(urls, **router_kw)
        self.router.poll_once()

    def close(self):
        self.router.stop_polling()
        for reg, srv in self.members:
            try:
                srv.stop()
            except Exception:
                pass
            try:
                reg.drain_all(save_manifests=False)
            except Exception:
                pass


class TestFailover:
    def test_conn_refused_fails_over_once(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            # kill the replica the router would pick first
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            fleet.members[idx][1].stop()
            pre = _counter_value("dl4j_router_dispatch_total",
                                 replica=victim.url, outcome="failover")
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert _counter_value("dl4j_router_dispatch_total",
                                  replica=victim.url,
                                  outcome="failover") == pre + 1
            assert not victim.ready  # out of rotation until a poll
        finally:
            fleet.close()

    def test_503_fails_over(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            # draining answers 503 on predict while the socket stays up
            fleet.members[idx][1].begin_drain()
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert not victim.ready
        finally:
            fleet.close()

    def test_exhausted_budget_raises(self):
        fleet = _Fleet(2, retries=1)
        try:
            for _, srv in fleet.members:
                srv.stop()
            with pytest.raises(NoReplicaError, match="all routed attempts"):
                fleet.router.predict("toy", _x().tolist())
        finally:
            fleet.close()

    def test_fleet_gauge_tracks_ready_replicas(self):
        fleet = _Fleet(2)
        try:
            fam = registry().get("dl4j_fleet_replicas")
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 2
            fleet.members[0][1].stop()
            fleet.router.poll_once()
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 1
        finally:
            fleet.close()


class TestFrontDoor:
    def test_proxies_predict_with_replica_header(self):
        fleet = _Fleet(2)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=json.dumps({"inputs": _x().tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            r = urllib.request.urlopen(req, timeout=30)
            assert r.status == 200
            assert r.headers.get("X-Fleet-Replica") in \
                [rep.url for rep in fleet.router.replicas()]
            doc = json.loads(r.read())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 200 and doc["ready"]
            status, doc = _get(f"http://127.0.0.1:{port}/fleet")
            assert status == 200 and len(doc["replicas"]) == 2
        finally:
            front.stop()
            fleet.close()

    def test_empty_fleet_answers_503(self):
        router = FleetRouter(poll_s=3600)
        front = FleetServer(router)
        port = front.start()
        try:
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 503
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=b'{"inputs": []}',
                headers={"Content-Type": "application/json"})
            try:
                r = urllib.request.urlopen(req, timeout=10)
                status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 503
        finally:
            front.stop()


class TestJoiningReplica:
    def test_manifest_warmed_joiner_serves_after_readyz(self, tmp_path):
        mdir = str(tmp_path)
        # replica 1 serves traffic, then persists its observed shapes
        reg1 = ModelRegistry(manifest_dir=mdir)
        reg1.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
        srv1 = ModelServer(reg1)
        port1 = srv1.start()
        reg1.predict("toy", _x(2))
        written = reg1.save_manifests()
        assert written, "manifest must be written for the joiner"

        router = FleetRouter([f"http://127.0.0.1:{port1}"], poll_s=0.2)
        router.poll_once()

        # the joiner deploys UNWARMED against the shared manifest dir:
        # registered with the router immediately, but /readyz is false
        # until the manifest-driven warmup compiles the ladder
        reg2 = ModelRegistry(manifest_dir=mdir)
        reg2.deploy("toy", "v1", _mlp(), warm=False)
        srv2 = ModelServer(reg2)
        port2 = srv2.start()
        joiner_url = f"http://127.0.0.1:{port2}"
        router.add_replica(joiner_url)
        router.poll_once()
        try:
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert not joiner.ready
            # every routed request lands on replica 1 only
            for _ in range(3):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                assert url != joiner_url

            # manifest-driven warmup (no example, no live traffic to
            # replay) flips the joiner ready; the router then routes to it
            buckets = reg2.warm("toy")
            assert buckets, "joiner must warm from the shared manifest"
            status, _ = _get(joiner_url + "/readyz")
            assert status == 200
            router.poll_once()
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert joiner.ready
            hit = set()
            for _ in range(8):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                hit.add(url)
            assert joiner_url in hit
        finally:
            router.stop_polling()
            srv2.stop()
            srv1.stop()
            reg2.drain_all(save_manifests=False)
            reg1.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# poll jitter: N replicas must not thundering-herd the same tick
# ---------------------------------------------------------------------------

class TestPollJitter:
    def test_offsets_distinct_deterministic_in_range(self):
        router = FleetRouter(poll_s=5.0)
        urls = [f"http://10.0.0.{i}:8080" for i in range(1, 9)]
        offsets = [router.poll_offset(u) for u in urls]
        assert all(0.0 <= o < 5.0 for o in offsets)
        # distinct scheduled offsets: the herd is actually spread
        assert len(set(offsets)) == len(offsets)
        # deterministic: same url -> same phase, every call
        assert offsets == [router.poll_offset(u) for u in urls]
        # and normalization-stable (trailing slash is the same replica)
        assert router.poll_offset(urls[0] + "/") == offsets[0]

    def test_offsets_scale_with_poll_period(self):
        u = "http://10.0.0.1:8080"
        assert FleetRouter(poll_s=8.0).poll_offset(u) == pytest.approx(
            4 * FleetRouter(poll_s=2.0).poll_offset(u))

    def test_poll_thread_staggers_first_polls(self):
        import threading
        import time as _time

        polled = []
        lock = threading.Lock()

        class _Recorder(FleetRouter):
            def _poll_replica(self, rep):
                with lock:
                    polled.append((rep.url, _time.monotonic()))

        router = _Recorder(poll_s=0.6)
        # pick two urls whose hashed phases are far apart, so the
        # assertion below is about scheduling, not luck
        base, other = "http://10.0.0.1:8080", None
        for i in range(2, 200):
            candidate = f"http://10.0.0.{i}:8080"
            if abs(router.poll_offset(candidate)
                   - router.poll_offset(base)) > 0.25:
                other = candidate
                break
        assert other is not None
        router.add_replica(base, poll=False)
        router.add_replica(other, poll=False)
        router.start_polling()
        try:
            deadline = _time.monotonic() + 3.0
            while _time.monotonic() < deadline:
                with lock:
                    if len(polled) >= 2:
                        break
                _time.sleep(0.02)
            with lock:
                first = {}
                for url, t in polled:
                    first.setdefault(url, t)
            assert set(first) == {base, other}
            # distinct phases -> the first polls did not share a tick
            assert abs(first[base] - first[other]) > 0.1
        finally:
            router.stop_polling()


# ---------------------------------------------------------------------------
# shared-store cold join: download, don't compile
# ---------------------------------------------------------------------------

def _compile_events(cache_labels):
    fam = registry().get("dl4j_compiles_total")
    return sum(int(child.value()) for key, child in
               (fam.children() if fam else [])
               if len(key) == 2 and key[1] in cache_labels)


class TestSharedStoreJoiner:
    def test_cold_joiner_warms_from_shared_store(self, tmp_path):
        """The fleet cold-start contract end-to-end: replica 1 serves,
        drains (push-on-drain), then a joiner with an EMPTY local cache
        restores on boot and reaches a fully warmed deploy with zero
        live compiles — every bucket a store hit."""
        import os

        from deeplearning4j_tpu.common.environment import (
            SystemProperties, environment)
        from deeplearning4j_tpu.runtime import compile_cache
        from deeplearning4j_tpu.serving import lifecycle

        env = environment()
        saved = {p: env.property_override(p)
                 for p in (SystemProperties.CACHE_DIR,
                           SystemProperties.REMOTE_CACHE,
                           SystemProperties.CACHE_TIER)}
        keep = []  # nets stay alive: compile tags are id()-keyed
        reg1 = reg2 = None
        try:
            env.set_cache_dir(str(tmp_path / "replica1"))
            env.set_remote_cache(str(tmp_path / "shared"))
            env.set_cache_tier("auto")
            compile_cache.reset_cache()
            net1 = _mlp()
            keep.append(net1)
            reg1 = ModelRegistry()
            reg1.deploy("toy", "v1", net1, example=_x(), warm=True)
            ref = np.asarray(reg1.predict("toy", _x()).jax())
            assert lifecycle.GracefulLifecycle(reg1).drain()
            reg1 = None
            shared = compile_cache.RemoteStore(str(tmp_path / "shared"))
            assert shared.stat()["entries"] > 0
            assert os.path.exists(os.path.join(
                shared.manifest_dir(), "toy.warmup.json"))

            # ---- the joiner: fresh local dir, nothing compiled yet ----
            env.set_cache_dir(str(tmp_path / "replica2"))
            compile_cache.reset_cache()
            jax.clear_caches()
            pulled = lifecycle.restore_on_boot()
            assert pulled["executables"] > 0
            assert pulled["manifests"] >= 1
            live0 = _compile_events(("miss", "bypass"))
            hit0 = _compile_events(("hit",))
            net2 = _mlp()
            keep.append(net2)
            reg2 = ModelRegistry()  # "auto" syncs fleet manifests
            reg2.deploy("toy", "v1", net2, warm=False)
            buckets = reg2.warm("toy")
            assert buckets, "joiner must warm from the pulled manifest"
            assert _compile_events(("miss", "bypass")) - live0 == 0, \
                "cold join must download executables, not compile them"
            assert _compile_events(("hit",)) - hit0 >= len(buckets)
            out = np.asarray(reg2.predict("toy", _x()).jax())
            np.testing.assert_allclose(ref, out, rtol=1e-6, atol=1e-7)
        finally:
            for reg in (reg1, reg2):
                if reg is not None:
                    reg.drain_all(save_manifests=False)
            for prop, value in saved.items():
                if value is None:
                    env.clear_property(prop)
                else:
                    env.set_property(prop, value)
            compile_cache.reset_cache()


# ---------------------------------------------------------------------------
# tail tolerance: retry budget
# ---------------------------------------------------------------------------

class TestRetryBudget:
    def test_tokens_accrue_per_dispatch_and_cap_at_burst(self):
        b = RetryBudget(0.5, burst=2.0)
        assert b.tokens == 2.0
        assert b.try_spend() and b.try_spend()
        assert not b.try_spend()
        b.record_dispatch()  # +0.5 -> below one whole token
        assert not b.try_spend()
        b.record_dispatch()
        assert b.try_spend()
        for _ in range(100):
            b.record_dispatch()
        assert b.tokens == 2.0  # never exceeds burst

    def test_zero_ratio_disables_every_extra_dispatch(self):
        b = RetryBudget(0.0)
        assert b.burst == 0.0
        for _ in range(50):
            b.record_dispatch()
        assert not b.try_spend()

    def test_ratio_clamped_to_unit_interval(self):
        assert RetryBudget(3.0).ratio == 1.0
        assert RetryBudget(-1.0).ratio == 0.0
        assert RetryBudget(0.2).burst == 10.0  # default: ratio * 50


# ---------------------------------------------------------------------------
# tail tolerance: poll hardening (malformed replica payloads)
# ---------------------------------------------------------------------------

def _stub_http_server(metrics_body):
    """A fake replica: healthy /readyz, arbitrary /metrics.json bytes."""
    import http.server
    import threading

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/readyz":
                body = json.dumps({"ready": True,
                                   "models": {"toy": {}}}).encode()
            else:
                body = metrics_body
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestPollHardening:
    def test_non_object_metrics_payload_raises(self):
        with pytest.raises(ValueError, match="non-object"):
            _parse_metrics_json([1, 2, 3])

    def test_junk_entries_degrade_to_neutral_and_count(self):
        doc = {
            "dl4j_serving_ewma_service_seconds": {"series": [
                {"labels": {"model": "toy"}, "value": "0.25"},
                {"labels": {"model": "bad"}, "value": "wat"},
                {"labels": {"model": "nan"}, "value": float("nan")},
                {"labels": "junk"},
                "junk",
            ]},
            "dl4j_serving_waiters": "junk",
            "dl4j_serving_queue_depth": {"series": "junk"},
        }
        load, malformed = _parse_metrics_json(doc)
        assert load["toy"]["ewma_s"] == 0.25
        assert load["bad"]["ewma_s"] == 0.0  # unparseable -> neutral
        assert load["nan"]["ewma_s"] == 0.0  # non-finite -> neutral
        assert malformed == 6

    def test_junk_metrics_keeps_replica_in_rotation(self):
        # garbage /metrics.json costs the replica its load view, never
        # its place in rotation (its readiness is known)
        srv = _stub_http_server(b'"garbage"')
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        router = FleetRouter([url], poll_s=3600)
        try:
            pre = _counter_value("dl4j_fleet_poll_errors_total",
                                 replica=url, reason="malformed")
            router.poll_once()
            rep = router.replicas()[0]
            assert rep.ready and rep.models == ["toy"]
            assert rep.load == {}
            assert router._candidates("toy") == [rep]
            assert _counter_value("dl4j_fleet_poll_errors_total",
                                  replica=url,
                                  reason="malformed") == pre + 1
        finally:
            srv.shutdown()

    def test_poll_fault_counts_unreachable_and_unreadies(self):
        fleet = _Fleet(1, poll_s=3600)
        url = fleet.router.replicas()[0].url
        try:
            faults.inject("fleet.poll", kind="error", rate=1.0)
            pre = _counter_value("dl4j_fleet_poll_errors_total",
                                 replica=url, reason="unreachable")
            fleet.router.poll_once()
            assert not fleet.router.replicas()[0].ready
            assert _counter_value("dl4j_fleet_poll_errors_total",
                                  replica=url,
                                  reason="unreachable") == pre + 1
        finally:
            faults.clear()
            fleet.close()


# ---------------------------------------------------------------------------
# tail tolerance: hedged requests
# ---------------------------------------------------------------------------

_CT = [("Content-Type", "application/json")]


class TestHedging:
    def test_hedge_beats_slow_replica_and_settles_both_attempts(self):
        fleet = _Fleet(2, poll_s=3600, retries=1, hedge_pctl=50,
                       hedge_min_samples=4, retry_budget=1.0,
                       retry_burst=8)
        try:
            for _ in range(8):
                fleet.router._note_latency("toy", 0.01)
            slow = fleet.router._candidates("toy")[0]
            pre_att = _attempts_total()
            pre_won = _counter_value("dl4j_fleet_hedges_total",
                                     model="toy", outcome="won")
            faults.inject(
                "fleet.dispatch", kind="delay", rate=1.0, delay_s=0.8,
                predicate=lambda ctx: ctx.get("url") == slow.url
                and ctx.get("phase") == "connect")
            t0 = time.perf_counter()
            doc = fleet.router.predict("toy", _x().tolist())
            dt = time.perf_counter() - t0
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert dt < 0.8  # the hedge answered before the primary
            assert _counter_value("dl4j_fleet_hedges_total", model="toy",
                                  outcome="won") == pre_won + 1
            # the abandoned loser still settles: exactly 2 attempts
            deadline = time.monotonic() + 5
            while (_attempts_total() < pre_att + 2
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert _attempts_total() == pre_att + 2
        finally:
            faults.clear()
            fleet.close()

    def test_non_idempotent_request_never_hedges(self):
        fleet = _Fleet(2, poll_s=3600, retries=1, hedge_pctl=50,
                       hedge_min_samples=4, retry_budget=1.0,
                       retry_burst=8)
        try:
            for _ in range(8):
                fleet.router._note_latency("toy", 0.01)
            slow = fleet.router._candidates("toy")[0]
            pre = _counter_value("dl4j_fleet_hedges_total", model="toy",
                                 outcome="launched")
            faults.inject(
                "fleet.dispatch", kind="delay", rate=1.0, delay_s=0.3,
                predicate=lambda ctx: ctx.get("url") == slow.url
                and ctx.get("phase") == "connect")
            t0 = time.perf_counter()
            status, _, _, url = fleet.router.route(
                "POST", "/v1/models/toy/predict",
                json.dumps({"inputs": _x().tolist()}).encode(),
                headers=_CT, model="toy", idempotent=False)
            dt = time.perf_counter() - t0
            assert status == 200 and url == slow.url
            assert dt >= 0.3  # waited the slow replica out, no hedge
            assert _counter_value("dl4j_fleet_hedges_total", model="toy",
                                  outcome="launched") == pre
        finally:
            faults.clear()
            fleet.close()

    def test_exhausted_budget_bounds_dispatch_to_request_count(self):
        """The acceptance criterion: with the retry budget at zero,
        total dispatch attempts == request count even while faults make
        hedges and retries desirable."""
        fleet = _Fleet(2, poll_s=3600, retries=2, retry_budget=0.0,
                       hedge_pctl=50, hedge_min_samples=1)
        try:
            fleet.router._note_latency("toy", 0.001)  # hedge wants to fire
            faults.inject(
                "fleet.dispatch", kind="error", rate=0.4, seed=3,
                predicate=lambda ctx: ctx.get("phase") == "connect")
            pre = _attempts_total()
            pre_denied = (
                _counter_value("dl4j_fleet_budget_denials_total",
                               reason="retry")
                + _counter_value("dl4j_fleet_budget_denials_total",
                                 reason="hedge"))
            n, served = 12, 0
            for _ in range(n):
                try:
                    fleet.router.predict("toy", _x(1).tolist())
                    served += 1
                except (NoReplicaError, RuntimeError):
                    pass
            assert _attempts_total() - pre == n
            assert served > 0  # the fleet degraded, not died
            denied = (
                _counter_value("dl4j_fleet_budget_denials_total",
                               reason="retry")
                + _counter_value("dl4j_fleet_budget_denials_total",
                                 reason="hedge"))
            assert denied > pre_denied  # extras were wanted and refused
        finally:
            faults.clear()
            fleet.close()


# ---------------------------------------------------------------------------
# tail tolerance: outlier ejection + probe re-admission
# ---------------------------------------------------------------------------

class TestOutlierEjection:
    def _router(self, **kw):
        kw.setdefault("poll_s", 3600)
        kw.setdefault("eject_min_samples", 4)
        kw.setdefault("eject_window", 8)
        kw.setdefault("eject_backoff_s", 0.05)
        return FleetRouter(**kw)

    def test_error_rate_ejects_and_excludes_from_rotation(self):
        router = self._router()
        bad = _stub_replica(router, "http://bad:1")
        good = _stub_replica(router, "http://good:1")
        pre = _counter_value("dl4j_fleet_ejections_total",
                             replica=bad.url, reason="error_rate")
        for _ in range(4):
            router._settle_attempt(bad, ok=False, latency_s=0.01,
                                   probe=False)
        assert bad.ejected and bad.ejections == 1
        assert _counter_value("dl4j_fleet_ejections_total",
                              replica=bad.url,
                              reason="error_rate") == pre + 1
        assert router._candidates("toy") == [good]

    def test_latency_zscore_ejects_zombie(self):
        # the zombie answers 200 every time — only its latency is wrong
        router = self._router()
        slow = _stub_replica(router, "http://slow:1")
        p1 = _stub_replica(router, "http://p1:1")
        p2 = _stub_replica(router, "http://p2:1")
        for rep, lat in ((p1, 0.010), (p2, 0.012)):
            for _ in range(4):
                router._settle_attempt(rep, ok=True, latency_s=lat,
                                       probe=False)
        assert not p1.ejected and not p2.ejected
        for _ in range(4):
            router._settle_attempt(slow, ok=True, latency_s=0.5,
                                   probe=False)
        assert slow.ejected
        assert _counter_value("dl4j_fleet_ejections_total",
                              replica=slow.url, reason="latency") == 1

    def test_tight_peer_agreement_does_not_hair_trigger(self):
        # when peers agree to the microsecond the peer std collapses and
        # a replica 0.2 ms slower would score z > 3 on significance
        # alone — the 2x practical-significance floor must hold it in
        from deeplearning4j_tpu.serving.resilience import latency_zscore
        assert latency_zscore(0.00825, [0.00800, 0.00805]) == 0.0
        assert latency_zscore(0.248, [0.00800, 0.00805]) >= 3.0
        router = self._router()
        slowish = _stub_replica(router, "http://slowish:1")
        p1 = _stub_replica(router, "http://peer1:1")
        p2 = _stub_replica(router, "http://peer2:1")
        for rep, lat in ((p1, 0.00800), (p2, 0.00805)):
            for _ in range(4):
                router._settle_attempt(rep, ok=True, latency_s=lat,
                                       probe=False)
        for _ in range(4):
            router._settle_attempt(slowish, ok=True, latency_s=0.00825,
                                   probe=False)
        assert not slowish.ejected and slowish.ejections == 0

    def test_max_ejection_fraction_keeps_last_replica(self):
        router = self._router()
        a = _stub_replica(router, "http://a:1")
        b = _stub_replica(router, "http://b:1")
        for _ in range(4):
            router._settle_attempt(a, ok=False, latency_s=0.01,
                                   probe=False)
        assert a.ejected
        # b misbehaves too, but ejecting it would empty the fleet
        for _ in range(6):
            router._settle_attempt(b, ok=False, latency_s=0.01,
                                   probe=False)
        assert not b.ejected and b.ejections == 0

    def test_probe_readmits_after_backoff(self):
        router = self._router()
        bad = _stub_replica(router, "http://bad:1")
        good = _stub_replica(router, "http://good:1")
        for _ in range(4):
            router._settle_attempt(bad, ok=False, latency_s=0.01,
                                   probe=False)
        assert bad.ejected
        rep, is_probe = router._pick("toy", ())
        assert rep is good and not is_probe  # backoff still running
        time.sleep(0.08)
        rep, is_probe = router._pick("toy", ())
        assert rep is bad and is_probe  # exactly one probe slot
        rep2, is_probe2 = router._pick("toy", ())
        assert rep2 is good and not is_probe2  # slot already taken
        pre = _counter_value("dl4j_fleet_readmissions_total",
                             replica=bad.url)
        router._settle_attempt(bad, ok=True, latency_s=0.01, probe=True)
        assert not bad.ejected
        assert len(bad.stats) == 0  # history wiped on re-admission
        assert _counter_value("dl4j_fleet_readmissions_total",
                              replica=bad.url) == pre + 1

    def test_failed_probe_reejects_with_doubled_backoff(self):
        router = self._router()
        bad = _stub_replica(router, "http://bad:1")
        _stub_replica(router, "http://good:1")
        for _ in range(4):
            router._settle_attempt(bad, ok=False, latency_s=0.01,
                                   probe=False)
        assert bad.eject_backoff_s == pytest.approx(0.05)
        time.sleep(0.08)
        rep, is_probe = router._pick("toy", ())
        assert rep is bad and is_probe
        router._settle_attempt(bad, ok=False, latency_s=0.01, probe=True)
        assert bad.ejected
        assert bad.eject_backoff_s == pytest.approx(0.10)
        assert _counter_value("dl4j_fleet_ejections_total",
                              replica=bad.url, reason="probe_failed") == 1

    def test_live_zombie_ejected_while_polling_healthy(self):
        """A replica whose /readyz and /metrics.json look perfect but
        whose dispatches crawl must still be ejected — health polls
        cannot see it, dispatch outcomes can. Needs >= 2 healthy peers:
        the z-score refuses to judge against a single peer."""
        fleet = _Fleet(3, poll_s=3600, retries=1, hedge_pctl=0,
                       eject_min_samples=3, eject_window=6,
                       eject_backoff_s=30)
        try:
            zombie = fleet.router._candidates("toy")[0]
            faults.inject(
                "fleet.dispatch", kind="delay", rate=1.0, delay_s=0.25,
                predicate=lambda ctx: ctx.get("url") == zombie.url
                and ctx.get("phase") == "connect")
            for _ in range(20):
                fleet.router.predict("toy", _x(1).tolist())
                if zombie.ejected:
                    break
            assert zombie.ejected
            fleet.router.poll_once()
            assert zombie.ready  # the poll still says healthy...
            assert zombie not in fleet.router._candidates("toy")  # ...but
        finally:
            faults.clear()
            fleet.close()


# ---------------------------------------------------------------------------
# tail tolerance: Retry-After pass-through + mid-stream non-retryability
# ---------------------------------------------------------------------------

class TestRetryAfterPassthrough:
    def test_route_returns_replica_503_with_retry_after(self):
        fleet = _Fleet(2, poll_s=3600, retries=2)
        try:
            for _, srv in fleet.members:
                srv.begin_drain()
            status, hdrs, payload, url = fleet.router.route(
                "POST", "/v1/models/toy/predict",
                json.dumps({"inputs": _x().tolist()}).encode(),
                headers=_CT, model="toy")
            assert status == 503
            retry_after = {k.lower(): v for k, v in hdrs.items()}.get(
                "retry-after")
            assert retry_after == "1"  # the replica's own hint, intact
            assert b"draining" in payload
        finally:
            fleet.close()

    def test_front_door_forwards_retry_after(self):
        fleet = _Fleet(2, poll_s=3600, retries=2)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            for _, srv in fleet.members:
                srv.begin_drain()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=json.dumps({"inputs": _x().tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") == "1"
            ei.value.read()
        finally:
            front.stop()
            fleet.close()


class TestMidStream:
    def test_non_idempotent_mid_stream_raises_and_never_retries(self):
        fleet = _Fleet(2, poll_s=3600, retries=2)
        try:
            faults.inject(
                "fleet.dispatch", kind="error", rate=1.0,
                predicate=lambda ctx: ctx.get("phase") == "body")
            pre = _attempts_total()
            with pytest.raises(MidStreamError,
                               match="not retried") as ei:
                fleet.router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=_CT, model="toy", idempotent=False)
            assert _attempts_total() - pre == 1  # exactly one attempt
            assert ei.value.replica_url.startswith("http://")
            assert ei.value.trace_id  # replica's X-Trace-Id carried out
        finally:
            faults.clear()
            fleet.close()

    def test_idempotent_mid_stream_retries_to_success(self):
        fleet = _Fleet(2, poll_s=3600, retries=2)
        try:
            victim = fleet.router._candidates("toy")[0]
            faults.inject(
                "fleet.dispatch", kind="error", rate=1.0,
                predicate=lambda ctx: ctx.get("url") == victim.url
                and ctx.get("phase") == "body")
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
        finally:
            faults.clear()
            fleet.close()

    def test_front_door_maps_mid_stream_to_502_with_trace(self):
        fleet = _Fleet(2, poll_s=3600, retries=2)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            faults.inject(
                "fleet.dispatch", kind="error", rate=1.0,
                predicate=lambda ctx: ctx.get("phase") == "body")
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/generate",
                data=json.dumps({"prompt": [1, 2, 3]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 502
            doc = json.loads(ei.value.read() or b"{}")
            assert "mid-stream" in doc["error"]
            assert doc.get("trace_id")
        finally:
            faults.clear()
            front.stop()
            fleet.close()


# ---------------------------------------------------------------------------
# tail tolerance: brownout degradation
# ---------------------------------------------------------------------------

class TestBrownout:
    def test_brownout_state_tracks_capacity_deficit(self):
        router = FleetRouter(poll_s=3600, brownout_frac=0.5)
        _stub_replica(router, "http://up:1", ready=True)
        for i in range(3):
            _stub_replica(router, f"http://down:{i}", ready=False)
        st = router.brownout_state()
        assert st["active"] and st["ready_fraction"] == 0.25
        assert st["cutoff"] == 5  # half the deficit -> half the ladder
        assert st["timeout_scale"] == 0.5
        assert st["retry_after_s"] >= 1

    def test_brownout_off_at_or_above_the_limit(self):
        router = FleetRouter(poll_s=3600, brownout_frac=0.5)
        _stub_replica(router, "http://a:1")
        _stub_replica(router, "http://b:1")
        st = router.brownout_state()
        assert not st["active"]
        assert st["cutoff"] == 0 and st["timeout_scale"] == 1.0

    def test_ejected_replicas_count_against_ready_capacity(self):
        router = FleetRouter(poll_s=3600, brownout_frac=0.75)
        a = _stub_replica(router, "http://a:1")
        _stub_replica(router, "http://b:1")
        assert not router.brownout_state()["active"]
        a.ejected = True
        st = router.brownout_state()
        assert st["active"] and st["ready_fraction"] == 0.5

    def test_front_door_sheds_low_priority_first(self):
        fleet = _Fleet(1, poll_s=3600, brownout_frac=0.5)
        for i in range(3):
            _stub_replica(fleet.router, f"http://down:{i}", ready=False)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            pre = _counter_value("dl4j_fleet_shed_total", model="toy",
                                 priority="1")
            body = json.dumps({"inputs": _x().tolist()}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=body, headers={"Content-Type": "application/json",
                                    "X-Priority": "1"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert ei.value.headers.get("X-Fleet-Brownout") == "1"
            assert ei.value.headers.get("Retry-After")
            ei.value.read()
            assert _counter_value("dl4j_fleet_shed_total", model="toy",
                                  priority="1") == pre + 1
            # important traffic still flows to the surviving replica
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=body, headers={"Content-Type": "application/json",
                                    "X-Priority": "9"})
            r = urllib.request.urlopen(req, timeout=30)
            assert r.status == 200
            r.read()
        finally:
            front.stop()
            fleet.close()


# ---------------------------------------------------------------------------
# chaos: SIGTERM-drain one replica mid-storm through the front door
# ---------------------------------------------------------------------------

class TestFleetChaos:
    @pytest.mark.slow
    def test_sigterm_drain_mid_storm_loses_nothing(self, tmp_path,
                                                   monkeypatch):
        """One replica takes a SIGTERM graceful drain mid-storm while
        dispatch faults are armed; every non-shed request through the
        FleetServer front door must still answer 200, and the drained
        replica's flight recorder must be written and parseable."""
        import signal
        import threading

        from deeplearning4j_tpu.serving.lifecycle import GracefulLifecycle

        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
        # brownout off: this drill asserts the ROUTING contract (every
        # request survives via failover); the shedding contract has its
        # own tests above
        fleet = _Fleet(3, poll_s=0.2, retries=4, retry_budget=0.5,
                       retry_burst=10, hedge_pctl=95, brownout_frac=0.0)
        fleet.router.start_polling()
        vreg, vsrv = fleet.members[0]
        lc = GracefulLifecycle(vreg, vsrv, drain_timeout_s=15)
        lc.install()
        front = FleetServer(fleet.router)
        port = front.start()
        statuses = []
        lock = threading.Lock()
        body = json.dumps({"inputs": _x().tolist()}).encode()

        def client():
            for _ in range(12):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models/toy/predict",
                    data=body,
                    headers={"Content-Type": "application/json",
                             "X-Priority": "9"})
                try:
                    r = urllib.request.urlopen(req, timeout=30)
                    st = r.status
                    r.read()
                except urllib.error.HTTPError as e:
                    st = e.code
                    e.read()
                except OSError as e:
                    st = f"conn:{type(e).__name__}"
                with lock:
                    statuses.append(st)

        faults.inject(
            "fleet.dispatch", kind="error", rate=0.1, seed=9,
            predicate=lambda ctx: ctx.get("phase") == "connect")
        threads = [threading.Thread(target=client) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.3)
            signal.raise_signal(signal.SIGTERM)
            for t in threads:
                t.join()
            assert lc.wait_drained(30)
        finally:
            faults.clear()
            lc.uninstall()
            front.stop()
            fleet.close()
        assert len(statuses) == 48
        assert all(st == 200 for st in statuses), statuses
        flights = sorted(tmp_path.glob("flight-*.json"))
        assert flights, "the drained replica must dump a flight record"
        doc = json.loads(flights[0].read_text())
        assert doc["draining"]
        for key in ("requests", "breakers", "engine_health", "faults"):
            assert key in doc
        served = [r for r in doc["requests"]
                  if r.get("kind") == "predict" and r.get("status") == 200]
        assert served, "the victim served storm traffic before draining"
        for r in served:
            # clean flight: nothing quarantined / breaker-opened, and
            # the X-Priority header survived front door -> replica ring
            assert r["disposition"] is None
            assert r["priority"] == 9


# ---------------------------------------------------------------------------
# session affinity: consistent-hash ring + degrade-to-least-loaded
# ---------------------------------------------------------------------------

class TestSessionAffinity:
    def _router(self, n=3, **kw):
        kw.setdefault("poll_s", 3600)
        router = FleetRouter(**kw)
        urls = [f"http://r{i}:1" for i in range(n)]
        for u in urls:
            router.add_replica(u, poll=False)
        return router, urls

    def test_ring_deterministic_balanced_and_stable_under_churn(self):
        router, urls = self._router(3)
        assert router.snapshot()["affinity"]["ring_size"] \
            == 3 * router.affinity_vnodes
        owners = {u: 0 for u in urls}
        keys = [f"sess-{i}" for i in range(300)]
        first = {k: router.affine_url(k) for k in keys}
        for k in keys:
            assert router.affine_url(k) == first[k]  # deterministic
            owners[first[k]] += 1
        # ~64 vnodes/replica spread the key space: nobody starves
        assert all(c > 30 for c in owners.values()), owners
        # removing one replica remaps ONLY the keys it owned
        victim = urls[0]
        router.remove_replica(victim)
        moved = sum(1 for k in keys if router.affine_url(k) != first[k])
        assert moved == owners[victim]
        # re-adding restores the original ownership exactly
        router.add_replica(victim, poll=False)
        assert all(router.affine_url(k) == first[k] for k in keys)

    def test_owner_usable_iff_ready_not_ejected_serving_model(self):
        router = FleetRouter(poll_s=3600)
        reps = [_stub_replica(router, f"http://r{i}:1") for i in range(2)]
        router._rebuild_ring_locked()
        key = "chat-7"
        owner = next(r for r in reps if r.url == router.affine_url(key))
        assert router._affine_replica("toy", key) is owner
        owner.ready = False
        assert router._affine_replica("toy", key) is None
        owner.ready = True
        owner.ejected = True
        assert router._affine_replica("toy", key) is None
        owner.ejected = False
        owner.models = ["other"]
        assert router._affine_replica("toy", key) is None
        owner.models = []          # unknown model list still counts
        assert router._affine_replica("toy", key) is owner

    def test_brownout_disables_affinity(self):
        # 1 ready of 2 known < 0.9 threshold: capacity beats locality
        router = FleetRouter(poll_s=3600, brownout_frac=0.9)
        _stub_replica(router, "http://up:1")
        _stub_replica(router, "http://down:1", ready=False)
        router._rebuild_ring_locked()
        key = next(f"k{i}" for i in range(64)
                   if router.affine_url(f"k{i}") == "http://up:1")
        assert router.brownout_state()["active"]
        assert router._affine_replica("toy", key) is None

    def test_session_header_pins_requests_to_one_replica(self):
        """Live fleet: every predict carrying the same X-Session-Id
        answers from the ring owner (outcome=hit); dropping the owner
        mid-session degrades to least-loaded (outcome=fallback) with
        zero lost requests."""
        fleet = _Fleet(2, retries=2)
        front = FleetServer(fleet.router)
        port = front.start()
        body = json.dumps({"inputs": _x().tolist()}).encode()
        key = "chat-affinity-1"
        owner = fleet.router.affine_url(key)

        def ask():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=body,
                headers={"Content-Type": "application/json",
                         "X-Session-Id": key})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                return r.headers["X-Fleet-Replica"]

        hits0 = _counter_value("dl4j_fleet_affinity_total", outcome="hit")
        fb0 = _counter_value("dl4j_fleet_affinity_total",
                             outcome="fallback")
        try:
            for _ in range(6):
                assert ask() == owner
            assert _counter_value("dl4j_fleet_affinity_total",
                                  outcome="hit") == hits0 + 6
            # kill the owner: the session degrades, nothing is lost
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in owner)
            fleet.members[idx][1].stop()
            fleet.router.poll_once()
            survivors = {ask() for _ in range(4)}
            assert survivors and owner not in survivors
            assert _counter_value("dl4j_fleet_affinity_total",
                                  outcome="fallback") == fb0 + 4
        finally:
            front.stop()
            fleet.close()


class _GenFleet:
    """Two live generative replicas (same weights) + router + front."""

    def __init__(self, **router_kw):
        from deeplearning4j_tpu.models import causal_lm

        cfg = causal_lm.CausalLMConfig.tiny()
        self.model = causal_lm.CausalLM(cfg, seed=0)
        self.cfg = cfg
        self.members = []
        urls = []
        for _ in range(2):
            reg = ModelRegistry(manifest_dir=None, retain=1)
            reg.deploy("lm", "v1", self.model, decode_slots=3,
                       decode_max_ctx=64, decode_prompt_buckets=[32, 48],
                       decode_kv_block_size=8)
            srv = ModelServer(reg)
            port = srv.start()
            self.members.append((reg, srv))
            urls.append(f"http://127.0.0.1:{port}")
        router_kw.setdefault("poll_s", 0.2)
        router_kw.setdefault("timeout_s", 60)
        router_kw.setdefault("retries", 2)
        self.router = FleetRouter(urls, **router_kw)
        self.router.poll_once()
        self.front = FleetServer(self.router)
        self.port = self.front.start()

    def close(self):
        self.router.stop_polling()
        self.front.stop()
        for reg, srv in self.members:
            try:
                srv.stop()
            except Exception:
                pass
            try:
                reg.drain_all(save_manifests=False)
            except Exception:
                pass


class TestGenerateAffinity:
    def _gen(self, port, prompt, headers=()):
        body = json.dumps({"prompt": [int(t) for t in prompt],
                           "max_tokens": 4}).encode()
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/models/lm/generate",
            data=body, headers=hdrs)
        with urllib.request.urlopen(req, timeout=60) as r:
            doc = json.loads(r.read())
            return doc["tokens"], r.headers["X-Fleet-Replica"]

    def test_fingerprint_pins_shared_prefix_storm(self):
        """Generates WITHOUT a session header still pin: the front door
        fingerprints the prompt head, so a storm sharing a system
        prompt lands on one replica and reuses its radix cache."""
        fleet = _GenFleet()
        rng = np.random.RandomState(3)
        # the fingerprint hashes the first 32 tokens: the shared system
        # prompt must fill that whole window for the storm to pin
        common = rng.randint(0, fleet.cfg.vocab_size, 32).astype(np.int32)
        prompts = [np.concatenate(
            [common, rng.randint(0, fleet.cfg.vocab_size,
                                 4).astype(np.int32)])
            for _ in range(4)]
        try:
            served = {self._gen(fleet.port, p)[1] for p in prompts}
            assert len(served) == 1
        finally:
            fleet.close()

    @pytest.mark.slow
    def test_midstorm_ejection_degrades_zero_lost_no_leaks(self):
        """The acceptance drill: a multi-turn session storm pinned by
        X-Session-Id loses its affine replica mid-storm; every request
        must still answer (failover to least-loaded), and the decode
        engines' refcount/leak counters must read 0 afterwards."""
        block_leaks = registry().counter("dl4j_kv_block_leaks_total")
        slot_leaks = registry().counter("dl4j_decode_slot_leaks_total")
        b0, s0 = block_leaks.value(), slot_leaks.value()
        fleet = _GenFleet()
        fleet.router.start_polling()
        rng = np.random.RandomState(5)
        base = rng.randint(0, fleet.cfg.vocab_size, 20).astype(np.int32)
        key = "storm-session"
        owner = fleet.router.affine_url(key)
        hdr = {"X-Session-Id": key}
        try:
            history = list(base)
            toks, url = self._gen(fleet.port, history, hdr)
            assert url == owner
            history += toks
            # drop the affine owner mid-session
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in owner)
            fleet.members[idx][1].stop()
            served = []
            for turn in range(4):
                toks, url = self._gen(fleet.port, history, hdr)
                history += toks
                served.append(url)
            # zero lost: every turn answered, all from the survivor
            assert all(u != owner for u in served)
            # and the replay decodes exactly what one engine would:
            # the survivor's cache rebuilt the session from turn 2 on
            eng_ref = fleet.members[1 - idx][0].generate(
                "lm", np.asarray(history[:len(base) + 4], np.int32),
                max_tokens=4)
            assert eng_ref["tokens"] == history[
                len(base) + 4:len(base) + 8]
            assert block_leaks.value() == b0
            assert slot_leaks.value() == s0
        finally:
            fleet.close()
