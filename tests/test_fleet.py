"""Sharded serving fleet (serving/fleet + mesh-sharded engines).

Covers the acceptance contract of the fleet PR: a sharded deploy on a
(1, N) CPU mesh serves predictions numerically matching single-device
(bitwise on a 1x1 mesh), with mesh metadata surfaced on /v1/models and
engine snapshots; the FleetRouter picks the least-loaded ready replica
under skew, fails over exactly once on connection refusal and on 503,
refuses nothing silently (NoReplicaError / front-door 503 otherwise);
and a joining replica warmed from the shared manifest takes traffic only
after its /readyz flips.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.common.mesh import (MODEL, mesh_shape, serving_mesh,
                                            spec_fits, validate_mesh)
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.serving.fleet import (FleetRouter, FleetServer,
                                              NoReplicaError, Replica)

N_IN, N_OUT = 6, 3


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=4, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _counter_value(fam_name, **labels):
    fam = registry().get(fam_name)
    if fam is None:
        return 0.0
    want = tuple(labels[k] for k in fam.label_names)
    return sum(child.value() for key, child in fam.children()
               if key == want)


@pytest.fixture
def unsharded_ref():
    reg = ModelRegistry(manifest_dir=None)
    reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
    ref = np.asarray(reg.predict("toy", _x()).jax())
    yield ref
    reg.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# scale-up: mesh-sharded serving
# ---------------------------------------------------------------------------

class TestMeshHelpers:
    def test_serving_mesh_defaults_all_devices_on_model_axis(self):
        mesh = serving_mesh()
        assert mesh_shape(mesh) == {"data": 1,
                                    "model": jax.device_count()}

    def test_validate_mesh_requires_axes(self):
        mesh = serving_mesh()
        validate_mesh(mesh)  # data axis present: fine
        with pytest.raises(ValueError, match="nope"):
            validate_mesh(mesh, required=("nope",))

    def test_spec_fits(self):
        from jax.sharding import PartitionSpec as P
        mesh = serving_mesh()
        n = jax.device_count()
        w = np.zeros((4, 2 * n), np.float32)
        assert spec_fits(w, P(None, MODEL), mesh)
        assert not spec_fits(np.zeros((4, 3), np.float32),
                             P(None, MODEL), mesh)


class TestShardedServing:
    def test_1x1_mesh_bitwise_identical(self, unsharded_ref):
        mesh = serving_mesh(model_parallel=1, devices=jax.devices()[:1])
        reg = ModelRegistry(manifest_dir=None)
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            out = np.asarray(reg.predict("toy", _x()).jax())
            np.testing.assert_array_equal(unsharded_ref, out)
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_predict_matches_unsharded(self, unsharded_ref):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                            mesh=serving_mesh())
            out = np.asarray(reg.predict("toy", _x()).jax())
            # cross-device contractions reorder the reduction: logits
            # match to float tolerance and the decisions exactly
            np.testing.assert_allclose(unsharded_ref, out, rtol=1e-5,
                                       atol=1e-6)
            assert (unsharded_ref.argmax(-1) == out.argmax(-1)).all()
            assert mv.engine.stats()["mesh_shape"] == mesh_shape(
                serving_mesh())
        finally:
            reg.drain_all(save_manifests=False)

    def test_v1_models_reports_mesh_metadata(self):
        mesh = serving_mesh()
        reg = ModelRegistry(manifest_dir=None)
        srv = ModelServer(reg)
        port = srv.start()
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            status, doc = _get(f"http://127.0.0.1:{port}/v1/models")
            assert status == 200
            ver = doc["models"]["toy"]["versions"][0]
            assert ver["mesh_shape"] == mesh_shape(mesh)
            assert ver["param_spec"] == "auto(model)"
        finally:
            srv.stop()
            reg.drain_all(save_manifests=False)

    def test_unsharded_versions_omit_mesh_metadata(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            assert "mesh_shape" not in mv.describe()
            assert "mesh_shape" not in mv.engine.stats()
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_decode_tokens_identical(self):
        from deeplearning4j_tpu.models import causal_lm
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        cfg = causal_lm.CausalLMConfig.tiny()
        prompt = list(range(1, 9))
        e0 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm0")
        e1 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm1", mesh=serving_mesh())
        try:
            r0 = e0.generate_sync(prompt, max_tokens=8, temperature=0.0)
            r1 = e1.generate_sync(prompt, max_tokens=8, temperature=0.0)
            assert r0["tokens"] == r1["tokens"]
            snap = e1.debug_snapshot()
            assert snap["mesh_shape"] == mesh_shape(serving_mesh())
            assert snap["param_spec"] == "auto(model)"
        finally:
            e0.close(10)
            e1.close(10)


# ---------------------------------------------------------------------------
# scale-out: the replica router
# ---------------------------------------------------------------------------

def _stub_replica(router, url, model="toy", ewma=0.01, waiters=0,
                  ready=True):
    """Inject a polled view without HTTP (pure routing-policy tests)."""
    rep = Replica(url)
    rep.ready = ready
    rep.models = [model]
    rep.load = {model: {"ewma_s": ewma, "queue_depth": 0.0,
                        "active": 0.0, "waiters": float(waiters)}}
    router._replicas[rep.url] = rep
    return rep


class TestLeastLoaded:
    def test_skewed_load_prefers_idle_replica(self):
        router = FleetRouter(poll_s=3600, retries=1)
        _stub_replica(router, "http://busy:1", ewma=0.5, waiters=20)
        idle = _stub_replica(router, "http://idle:1", ewma=0.01, waiters=0)
        cands = router._candidates("toy")
        assert cands[0] is idle

    def test_router_side_inflight_breaks_ties(self):
        # between polls, dispatched-but-unpolled work must count: a burst
        # spreads instead of piling onto the replica that looked idle
        router = FleetRouter(poll_s=3600, retries=1)
        a = _stub_replica(router, "http://a:1", ewma=0.1, waiters=0)
        b = _stub_replica(router, "http://b:1", ewma=0.1, waiters=0)
        a.inflight = 5
        assert router._candidates("toy")[0] is b

    def test_not_ready_replica_excluded(self):
        router = FleetRouter(poll_s=3600)
        _stub_replica(router, "http://down:1", ready=False)
        up = _stub_replica(router, "http://up:1")
        assert router._candidates("toy") == [up]

    def test_no_replica_raises(self):
        router = FleetRouter(poll_s=3600)
        with pytest.raises(NoReplicaError, match="no ready replica"):
            router.route("POST", "/v1/models/toy/predict", b"{}",
                         model="toy")


class _Fleet:
    """N live single-model replicas + a router, torn down in reverse."""

    def __init__(self, n, manifest_dir=None, **router_kw):
        self.members = []
        urls = []
        for i in range(n):
            reg = ModelRegistry(manifest_dir=manifest_dir)
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            srv = ModelServer(reg)
            port = srv.start()
            self.members.append((reg, srv))
            urls.append(f"http://127.0.0.1:{port}")
        router_kw.setdefault("poll_s", 0.2)
        router_kw.setdefault("timeout_s", 30)
        self.router = FleetRouter(urls, **router_kw)
        self.router.poll_once()

    def close(self):
        self.router.stop_polling()
        for reg, srv in self.members:
            try:
                srv.stop()
            except Exception:
                pass
            try:
                reg.drain_all(save_manifests=False)
            except Exception:
                pass


class TestFailover:
    def test_conn_refused_fails_over_once(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            # kill the replica the router would pick first
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            fleet.members[idx][1].stop()
            pre = _counter_value("dl4j_router_dispatch_total",
                                 replica=victim.url, outcome="failover")
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert _counter_value("dl4j_router_dispatch_total",
                                  replica=victim.url,
                                  outcome="failover") == pre + 1
            assert not victim.ready  # out of rotation until a poll
        finally:
            fleet.close()

    def test_503_fails_over(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            # draining answers 503 on predict while the socket stays up
            fleet.members[idx][1].begin_drain()
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert not victim.ready
        finally:
            fleet.close()

    def test_exhausted_budget_raises(self):
        fleet = _Fleet(2, retries=1)
        try:
            for _, srv in fleet.members:
                srv.stop()
            with pytest.raises(NoReplicaError, match="all routed attempts"):
                fleet.router.predict("toy", _x().tolist())
        finally:
            fleet.close()

    def test_fleet_gauge_tracks_ready_replicas(self):
        fleet = _Fleet(2)
        try:
            fam = registry().get("dl4j_fleet_replicas")
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 2
            fleet.members[0][1].stop()
            fleet.router.poll_once()
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 1
        finally:
            fleet.close()


class TestFrontDoor:
    def test_proxies_predict_with_replica_header(self):
        fleet = _Fleet(2)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=json.dumps({"inputs": _x().tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            r = urllib.request.urlopen(req, timeout=30)
            assert r.status == 200
            assert r.headers.get("X-Fleet-Replica") in \
                [rep.url for rep in fleet.router.replicas()]
            doc = json.loads(r.read())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 200 and doc["ready"]
            status, doc = _get(f"http://127.0.0.1:{port}/fleet")
            assert status == 200 and len(doc["replicas"]) == 2
        finally:
            front.stop()
            fleet.close()

    def test_empty_fleet_answers_503(self):
        router = FleetRouter(poll_s=3600)
        front = FleetServer(router)
        port = front.start()
        try:
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 503
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=b'{"inputs": []}',
                headers={"Content-Type": "application/json"})
            try:
                r = urllib.request.urlopen(req, timeout=10)
                status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 503
        finally:
            front.stop()


class TestJoiningReplica:
    def test_manifest_warmed_joiner_serves_after_readyz(self, tmp_path):
        mdir = str(tmp_path)
        # replica 1 serves traffic, then persists its observed shapes
        reg1 = ModelRegistry(manifest_dir=mdir)
        reg1.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
        srv1 = ModelServer(reg1)
        port1 = srv1.start()
        reg1.predict("toy", _x(2))
        written = reg1.save_manifests()
        assert written, "manifest must be written for the joiner"

        router = FleetRouter([f"http://127.0.0.1:{port1}"], poll_s=0.2)
        router.poll_once()

        # the joiner deploys UNWARMED against the shared manifest dir:
        # registered with the router immediately, but /readyz is false
        # until the manifest-driven warmup compiles the ladder
        reg2 = ModelRegistry(manifest_dir=mdir)
        reg2.deploy("toy", "v1", _mlp(), warm=False)
        srv2 = ModelServer(reg2)
        port2 = srv2.start()
        joiner_url = f"http://127.0.0.1:{port2}"
        router.add_replica(joiner_url)
        router.poll_once()
        try:
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert not joiner.ready
            # every routed request lands on replica 1 only
            for _ in range(3):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                assert url != joiner_url

            # manifest-driven warmup (no example, no live traffic to
            # replay) flips the joiner ready; the router then routes to it
            buckets = reg2.warm("toy")
            assert buckets, "joiner must warm from the shared manifest"
            status, _ = _get(joiner_url + "/readyz")
            assert status == 200
            router.poll_once()
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert joiner.ready
            hit = set()
            for _ in range(8):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                hit.add(url)
            assert joiner_url in hit
        finally:
            router.stop_polling()
            srv2.stop()
            srv1.stop()
            reg2.drain_all(save_manifests=False)
            reg1.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# poll jitter: N replicas must not thundering-herd the same tick
# ---------------------------------------------------------------------------

class TestPollJitter:
    def test_offsets_distinct_deterministic_in_range(self):
        router = FleetRouter(poll_s=5.0)
        urls = [f"http://10.0.0.{i}:8080" for i in range(1, 9)]
        offsets = [router.poll_offset(u) for u in urls]
        assert all(0.0 <= o < 5.0 for o in offsets)
        # distinct scheduled offsets: the herd is actually spread
        assert len(set(offsets)) == len(offsets)
        # deterministic: same url -> same phase, every call
        assert offsets == [router.poll_offset(u) for u in urls]
        # and normalization-stable (trailing slash is the same replica)
        assert router.poll_offset(urls[0] + "/") == offsets[0]

    def test_offsets_scale_with_poll_period(self):
        u = "http://10.0.0.1:8080"
        assert FleetRouter(poll_s=8.0).poll_offset(u) == pytest.approx(
            4 * FleetRouter(poll_s=2.0).poll_offset(u))

    def test_poll_thread_staggers_first_polls(self):
        import threading
        import time as _time

        polled = []
        lock = threading.Lock()

        class _Recorder(FleetRouter):
            def _poll_replica(self, rep):
                with lock:
                    polled.append((rep.url, _time.monotonic()))

        router = _Recorder(poll_s=0.6)
        # pick two urls whose hashed phases are far apart, so the
        # assertion below is about scheduling, not luck
        base, other = "http://10.0.0.1:8080", None
        for i in range(2, 200):
            candidate = f"http://10.0.0.{i}:8080"
            if abs(router.poll_offset(candidate)
                   - router.poll_offset(base)) > 0.25:
                other = candidate
                break
        assert other is not None
        router.add_replica(base, poll=False)
        router.add_replica(other, poll=False)
        router.start_polling()
        try:
            deadline = _time.monotonic() + 3.0
            while _time.monotonic() < deadline:
                with lock:
                    if len(polled) >= 2:
                        break
                _time.sleep(0.02)
            with lock:
                first = {}
                for url, t in polled:
                    first.setdefault(url, t)
            assert set(first) == {base, other}
            # distinct phases -> the first polls did not share a tick
            assert abs(first[base] - first[other]) > 0.1
        finally:
            router.stop_polling()


# ---------------------------------------------------------------------------
# shared-store cold join: download, don't compile
# ---------------------------------------------------------------------------

def _compile_events(cache_labels):
    fam = registry().get("dl4j_compiles_total")
    return sum(int(child.value()) for key, child in
               (fam.children() if fam else [])
               if len(key) == 2 and key[1] in cache_labels)


class TestSharedStoreJoiner:
    def test_cold_joiner_warms_from_shared_store(self, tmp_path):
        """The fleet cold-start contract end-to-end: replica 1 serves,
        drains (push-on-drain), then a joiner with an EMPTY local cache
        restores on boot and reaches a fully warmed deploy with zero
        live compiles — every bucket a store hit."""
        import os

        from deeplearning4j_tpu.common.environment import (
            SystemProperties, environment)
        from deeplearning4j_tpu.runtime import compile_cache
        from deeplearning4j_tpu.serving import lifecycle

        env = environment()
        saved = {p: env.property_override(p)
                 for p in (SystemProperties.CACHE_DIR,
                           SystemProperties.REMOTE_CACHE,
                           SystemProperties.CACHE_TIER)}
        keep = []  # nets stay alive: compile tags are id()-keyed
        reg1 = reg2 = None
        try:
            env.set_cache_dir(str(tmp_path / "replica1"))
            env.set_remote_cache(str(tmp_path / "shared"))
            env.set_cache_tier("auto")
            compile_cache.reset_cache()
            net1 = _mlp()
            keep.append(net1)
            reg1 = ModelRegistry()
            reg1.deploy("toy", "v1", net1, example=_x(), warm=True)
            ref = np.asarray(reg1.predict("toy", _x()).jax())
            assert lifecycle.GracefulLifecycle(reg1).drain()
            reg1 = None
            shared = compile_cache.RemoteStore(str(tmp_path / "shared"))
            assert shared.stat()["entries"] > 0
            assert os.path.exists(os.path.join(
                shared.manifest_dir(), "toy.warmup.json"))

            # ---- the joiner: fresh local dir, nothing compiled yet ----
            env.set_cache_dir(str(tmp_path / "replica2"))
            compile_cache.reset_cache()
            jax.clear_caches()
            pulled = lifecycle.restore_on_boot()
            assert pulled["executables"] > 0
            assert pulled["manifests"] >= 1
            live0 = _compile_events(("miss", "bypass"))
            hit0 = _compile_events(("hit",))
            net2 = _mlp()
            keep.append(net2)
            reg2 = ModelRegistry()  # "auto" syncs fleet manifests
            reg2.deploy("toy", "v1", net2, warm=False)
            buckets = reg2.warm("toy")
            assert buckets, "joiner must warm from the pulled manifest"
            assert _compile_events(("miss", "bypass")) - live0 == 0, \
                "cold join must download executables, not compile them"
            assert _compile_events(("hit",)) - hit0 >= len(buckets)
            out = np.asarray(reg2.predict("toy", _x()).jax())
            np.testing.assert_allclose(ref, out, rtol=1e-6, atol=1e-7)
        finally:
            for reg in (reg1, reg2):
                if reg is not None:
                    reg.drain_all(save_manifests=False)
            for prop, value in saved.items():
                if value is None:
                    env.clear_property(prop)
                else:
                    env.set_property(prop, value)
            compile_cache.reset_cache()
