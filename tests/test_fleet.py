"""Sharded serving fleet (serving/fleet + mesh-sharded engines).

Covers the acceptance contract of the fleet PR: a sharded deploy on a
(1, N) CPU mesh serves predictions numerically matching single-device
(bitwise on a 1x1 mesh), with mesh metadata surfaced on /v1/models and
engine snapshots; the FleetRouter picks the least-loaded ready replica
under skew, fails over exactly once on connection refusal and on 503,
refuses nothing silently (NoReplicaError / front-door 503 otherwise);
and a joining replica warmed from the shared manifest takes traffic only
after its /readyz flips.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.common.mesh import (MODEL, mesh_shape, serving_mesh,
                                            spec_fits, validate_mesh)
from deeplearning4j_tpu.common.metrics import registry
from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.serving.fleet import (FleetRouter, FleetServer,
                                              NoReplicaError, Replica)

N_IN, N_OUT = 6, 3


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_OUT))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=4, seed=0):
    return np.random.RandomState(seed).randn(n, N_IN).astype(np.float32)


def _get(url, timeout=10):
    try:
        r = urllib.request.urlopen(url, timeout=timeout)
        return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _counter_value(fam_name, **labels):
    fam = registry().get(fam_name)
    if fam is None:
        return 0.0
    want = tuple(labels[k] for k in fam.label_names)
    return sum(child.value() for key, child in fam.children()
               if key == want)


@pytest.fixture
def unsharded_ref():
    reg = ModelRegistry(manifest_dir=None)
    reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
    ref = np.asarray(reg.predict("toy", _x()).jax())
    yield ref
    reg.drain_all(save_manifests=False)


# ---------------------------------------------------------------------------
# scale-up: mesh-sharded serving
# ---------------------------------------------------------------------------

class TestMeshHelpers:
    def test_serving_mesh_defaults_all_devices_on_model_axis(self):
        mesh = serving_mesh()
        assert mesh_shape(mesh) == {"data": 1,
                                    "model": jax.device_count()}

    def test_validate_mesh_requires_axes(self):
        mesh = serving_mesh()
        validate_mesh(mesh)  # data axis present: fine
        with pytest.raises(ValueError, match="nope"):
            validate_mesh(mesh, required=("nope",))

    def test_spec_fits(self):
        from jax.sharding import PartitionSpec as P
        mesh = serving_mesh()
        n = jax.device_count()
        w = np.zeros((4, 2 * n), np.float32)
        assert spec_fits(w, P(None, MODEL), mesh)
        assert not spec_fits(np.zeros((4, 3), np.float32),
                             P(None, MODEL), mesh)


class TestShardedServing:
    def test_1x1_mesh_bitwise_identical(self, unsharded_ref):
        mesh = serving_mesh(model_parallel=1, devices=jax.devices()[:1])
        reg = ModelRegistry(manifest_dir=None)
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            out = np.asarray(reg.predict("toy", _x()).jax())
            np.testing.assert_array_equal(unsharded_ref, out)
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_predict_matches_unsharded(self, unsharded_ref):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                            mesh=serving_mesh())
            out = np.asarray(reg.predict("toy", _x()).jax())
            # cross-device contractions reorder the reduction: logits
            # match to float tolerance and the decisions exactly
            np.testing.assert_allclose(unsharded_ref, out, rtol=1e-5,
                                       atol=1e-6)
            assert (unsharded_ref.argmax(-1) == out.argmax(-1)).all()
            assert mv.engine.stats()["mesh_shape"] == mesh_shape(
                serving_mesh())
        finally:
            reg.drain_all(save_manifests=False)

    def test_v1_models_reports_mesh_metadata(self):
        mesh = serving_mesh()
        reg = ModelRegistry(manifest_dir=None)
        srv = ModelServer(reg)
        port = srv.start()
        try:
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True,
                       mesh=mesh)
            status, doc = _get(f"http://127.0.0.1:{port}/v1/models")
            assert status == 200
            ver = doc["models"]["toy"]["versions"][0]
            assert ver["mesh_shape"] == mesh_shape(mesh)
            assert ver["param_spec"] == "auto(model)"
        finally:
            srv.stop()
            reg.drain_all(save_manifests=False)

    def test_unsharded_versions_omit_mesh_metadata(self):
        reg = ModelRegistry(manifest_dir=None)
        try:
            mv = reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            assert "mesh_shape" not in mv.describe()
            assert "mesh_shape" not in mv.engine.stats()
        finally:
            reg.drain_all(save_manifests=False)

    @pytest.mark.skipif(jax.device_count() < 2, reason="needs >= 2 devices")
    def test_sharded_decode_tokens_identical(self):
        from deeplearning4j_tpu.models import causal_lm
        from deeplearning4j_tpu.runtime.generation import DecodeEngine

        cfg = causal_lm.CausalLMConfig.tiny()
        prompt = list(range(1, 9))
        e0 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm0")
        e1 = DecodeEngine(causal_lm.CausalLM(cfg, seed=3), slots=2,
                          max_ctx=64, prompt_buckets=[32],
                          model_name="fleetlm1", mesh=serving_mesh())
        try:
            r0 = e0.generate_sync(prompt, max_tokens=8, temperature=0.0)
            r1 = e1.generate_sync(prompt, max_tokens=8, temperature=0.0)
            assert r0["tokens"] == r1["tokens"]
            snap = e1.debug_snapshot()
            assert snap["mesh_shape"] == mesh_shape(serving_mesh())
            assert snap["param_spec"] == "auto(model)"
        finally:
            e0.close(10)
            e1.close(10)


# ---------------------------------------------------------------------------
# scale-out: the replica router
# ---------------------------------------------------------------------------

def _stub_replica(router, url, model="toy", ewma=0.01, waiters=0,
                  ready=True):
    """Inject a polled view without HTTP (pure routing-policy tests)."""
    rep = Replica(url)
    rep.ready = ready
    rep.models = [model]
    rep.load = {model: {"ewma_s": ewma, "queue_depth": 0.0,
                        "active": 0.0, "waiters": float(waiters)}}
    router._replicas[rep.url] = rep
    return rep


class TestLeastLoaded:
    def test_skewed_load_prefers_idle_replica(self):
        router = FleetRouter(poll_s=3600, retries=1)
        _stub_replica(router, "http://busy:1", ewma=0.5, waiters=20)
        idle = _stub_replica(router, "http://idle:1", ewma=0.01, waiters=0)
        cands = router._candidates("toy")
        assert cands[0] is idle

    def test_router_side_inflight_breaks_ties(self):
        # between polls, dispatched-but-unpolled work must count: a burst
        # spreads instead of piling onto the replica that looked idle
        router = FleetRouter(poll_s=3600, retries=1)
        a = _stub_replica(router, "http://a:1", ewma=0.1, waiters=0)
        b = _stub_replica(router, "http://b:1", ewma=0.1, waiters=0)
        a.inflight = 5
        assert router._candidates("toy")[0] is b

    def test_not_ready_replica_excluded(self):
        router = FleetRouter(poll_s=3600)
        _stub_replica(router, "http://down:1", ready=False)
        up = _stub_replica(router, "http://up:1")
        assert router._candidates("toy") == [up]

    def test_no_replica_raises(self):
        router = FleetRouter(poll_s=3600)
        with pytest.raises(NoReplicaError, match="no ready replica"):
            router.route("POST", "/v1/models/toy/predict", b"{}",
                         model="toy")


class _Fleet:
    """N live single-model replicas + a router, torn down in reverse."""

    def __init__(self, n, manifest_dir=None, **router_kw):
        self.members = []
        urls = []
        for i in range(n):
            reg = ModelRegistry(manifest_dir=manifest_dir)
            reg.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
            srv = ModelServer(reg)
            port = srv.start()
            self.members.append((reg, srv))
            urls.append(f"http://127.0.0.1:{port}")
        router_kw.setdefault("poll_s", 0.2)
        router_kw.setdefault("timeout_s", 30)
        self.router = FleetRouter(urls, **router_kw)
        self.router.poll_once()

    def close(self):
        self.router.stop_polling()
        for reg, srv in self.members:
            try:
                srv.stop()
            except Exception:
                pass
            try:
                reg.drain_all(save_manifests=False)
            except Exception:
                pass


class TestFailover:
    def test_conn_refused_fails_over_once(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            # kill the replica the router would pick first
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            fleet.members[idx][1].stop()
            pre = _counter_value("dl4j_router_dispatch_total",
                                 replica=victim.url, outcome="failover")
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert _counter_value("dl4j_router_dispatch_total",
                                  replica=victim.url,
                                  outcome="failover") == pre + 1
            assert not victim.ready  # out of rotation until a poll
        finally:
            fleet.close()

    def test_503_fails_over(self):
        fleet = _Fleet(2, retries=1)
        try:
            victim = fleet.router._candidates("toy")[0]
            idx = next(i for i, (_, s) in enumerate(fleet.members)
                       if f":{s.port}" in victim.url)
            # draining answers 503 on predict while the socket stays up
            fleet.members[idx][1].begin_drain()
            doc = fleet.router.predict("toy", _x().tolist())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            assert not victim.ready
        finally:
            fleet.close()

    def test_exhausted_budget_raises(self):
        fleet = _Fleet(2, retries=1)
        try:
            for _, srv in fleet.members:
                srv.stop()
            with pytest.raises(NoReplicaError, match="all routed attempts"):
                fleet.router.predict("toy", _x().tolist())
        finally:
            fleet.close()

    def test_fleet_gauge_tracks_ready_replicas(self):
        fleet = _Fleet(2)
        try:
            fam = registry().get("dl4j_fleet_replicas")
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 2
            fleet.members[0][1].stop()
            fleet.router.poll_once()
            val = {key: child.value() for key, child in fam.children()}
            assert val[("toy",)] == 1
        finally:
            fleet.close()


class TestFrontDoor:
    def test_proxies_predict_with_replica_header(self):
        fleet = _Fleet(2)
        front = FleetServer(fleet.router)
        port = front.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=json.dumps({"inputs": _x().tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            r = urllib.request.urlopen(req, timeout=30)
            assert r.status == 200
            assert r.headers.get("X-Fleet-Replica") in \
                [rep.url for rep in fleet.router.replicas()]
            doc = json.loads(r.read())
            assert np.asarray(doc["outputs"]).shape == (4, N_OUT)
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 200 and doc["ready"]
            status, doc = _get(f"http://127.0.0.1:{port}/fleet")
            assert status == 200 and len(doc["replicas"]) == 2
        finally:
            front.stop()
            fleet.close()

    def test_empty_fleet_answers_503(self):
        router = FleetRouter(poll_s=3600)
        front = FleetServer(router)
        port = front.start()
        try:
            status, doc = _get(f"http://127.0.0.1:{port}/readyz")
            assert status == 503
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/models/toy/predict",
                data=b'{"inputs": []}',
                headers={"Content-Type": "application/json"})
            try:
                r = urllib.request.urlopen(req, timeout=10)
                status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 503
        finally:
            front.stop()


class TestJoiningReplica:
    def test_manifest_warmed_joiner_serves_after_readyz(self, tmp_path):
        mdir = str(tmp_path)
        # replica 1 serves traffic, then persists its observed shapes
        reg1 = ModelRegistry(manifest_dir=mdir)
        reg1.deploy("toy", "v1", _mlp(), example=_x(), warm=True)
        srv1 = ModelServer(reg1)
        port1 = srv1.start()
        reg1.predict("toy", _x(2))
        written = reg1.save_manifests()
        assert written, "manifest must be written for the joiner"

        router = FleetRouter([f"http://127.0.0.1:{port1}"], poll_s=0.2)
        router.poll_once()

        # the joiner deploys UNWARMED against the shared manifest dir:
        # registered with the router immediately, but /readyz is false
        # until the manifest-driven warmup compiles the ladder
        reg2 = ModelRegistry(manifest_dir=mdir)
        reg2.deploy("toy", "v1", _mlp(), warm=False)
        srv2 = ModelServer(reg2)
        port2 = srv2.start()
        joiner_url = f"http://127.0.0.1:{port2}"
        router.add_replica(joiner_url)
        router.poll_once()
        try:
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert not joiner.ready
            # every routed request lands on replica 1 only
            for _ in range(3):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                assert url != joiner_url

            # manifest-driven warmup (no example, no live traffic to
            # replay) flips the joiner ready; the router then routes to it
            buckets = reg2.warm("toy")
            assert buckets, "joiner must warm from the shared manifest"
            status, _ = _get(joiner_url + "/readyz")
            assert status == 200
            router.poll_once()
            joiner = next(r for r in router.replicas()
                          if r.url == joiner_url)
            assert joiner.ready
            hit = set()
            for _ in range(8):
                _, _, _, url = router.route(
                    "POST", "/v1/models/toy/predict",
                    json.dumps({"inputs": _x().tolist()}).encode(),
                    headers=[("Content-Type", "application/json")],
                    model="toy")
                hit.add(url)
            assert joiner_url in hit
        finally:
            router.stop_polling()
            srv2.stop()
            srv1.stop()
            reg2.drain_all(save_manifests=False)
            reg1.drain_all(save_manifests=False)
